"""GL003: lock-order and blocking-under-lock discipline.

Builds the lock-acquisition graph over every ``threading.Lock`` /
``RLock`` / ``Condition`` site in the tree (``with`` statements plus a
transitive walk through resolvable callees).  Two findings:

- **order**: lock pair acquired in both orders somewhere in the tree — a
  potential ABBA deadlock.
- **blocking**: a blocking call (``block_until_ready``, ``asnumpy``,
  socket ``recv``/``accept``, zero-arg ``queue.get()`` without timeout,
  ``time.sleep``, zero-arg ``join()``) made while holding a
  telemetry/engine/serving/health lock — those locks sit on hot paths
  (every metric bump, every engine push, every serving request) and must
  never wait on the device or the network.

Lock identity is static: ``module.Class.attr`` for instance locks,
``module.name`` for module globals.  ``Condition(lock)`` aliases the
wrapped lock; ``Condition.wait`` releases it, so ``wait`` is deliberately
not in the blocking set.  Unresolvable lock expressions (dict-of-locks,
``with self._lock_for(k)``) are skipped, never guessed.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Project, _dotted, fn_qual

CODE = "GL003"
TITLE = "lock discipline: consistent order, no blocking under hot locks"

_BLOCKING_ATTRS = {
    "asnumpy": ".asnumpy() host sync",
    "block_until_ready": "block_until_ready device sync",
    "wait_to_read": "wait_to_read device sync",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "recvfrom": "socket recv",
    "recv_msg": "socket recv",
    "recv_msg_full": "socket recv",
    "accept": "socket accept",
}

# default: modules whose locks guard hot paths; overridable for fixtures
_DEFAULT_SCOPE = ("telemetry", "engine", "serving", "health")

_MAX_DEPTH = 8


def _blocking_kind(site) -> Optional[str]:
    chain, canon, call = site.chain, site.canon or "", site.node
    if not chain:
        return None
    last = chain[-1]
    if last in _BLOCKING_ATTRS:
        return _BLOCKING_ATTRS[last]
    if canon == "time.sleep":
        return "time.sleep"
    if last == "get" and len(chain) > 1 and not call.args and \
            not any(kw.arg in ("timeout", "block") for kw in call.keywords):
        return "queue.get() without timeout"
    if last == "join" and len(chain) > 1 and not call.args and \
            not call.keywords:
        return "join() without timeout"
    return None


class _Summary:
    __slots__ = ("acquires", "blocking")

    def __init__(self):
        self.acquires: Set[str] = set()
        # (kind, rel, line, qual) of blocking sites in fn + callees
        self.blocking: List[Tuple[str, str, int, str]] = []


class _Analysis:
    def __init__(self, project: Project):
        self.project = project
        self.locks: Dict[str, str] = {}           # lock id -> kind
        self.cond_alias: Dict[str, str] = {}      # condition id -> lock id
        self.summaries: Dict[int, _Summary] = {}
        self.in_progress: Set[int] = set()
        # (a, b) -> (rel, line, qual) first site acquiring b while holding a
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.blocking_findings: List[Finding] = []
        self.scope = tuple(project.config.get(
            "lock_scope_modules", _DEFAULT_SCOPE))

    # -- lock definition table -------------------------------------------
    def collect_locks(self):
        pending_conds = []
        for mod in self.project.modules.values():
            # module-level globals
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    kind = self._ctor_kind(mod, node.value)
                    if kind:
                        lid = "%s.%s" % (mod.name, node.targets[0].id)
                        self._add(lid, kind, mod, node.value, pending_conds)
            # self.X = threading.Lock() inside methods
            for fn in mod.functions.values():
                scope = fn._gl
                if scope.cls is None:
                    continue
                for node in _own_nodes(fn):
                    if not isinstance(node, ast.Assign) or \
                            len(node.targets) != 1:
                        continue
                    tgt = node.targets[0]
                    if not (isinstance(tgt, ast.Attribute) and
                            isinstance(tgt.value, ast.Name) and
                            tgt.value.id == "self"):
                        continue
                    kind = self._ctor_kind(mod, node.value)
                    if kind:
                        lid = "%s.%s.%s" % (mod.name, scope.cls, tgt.attr)
                        self._add(lid, kind, mod, node.value, pending_conds)
        # resolve Condition(self.X) aliases now the lock table is complete
        for lid, mod, call in pending_conds:
            if call.args:
                arg = call.args[0]
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    owner = lid.rsplit(".", 1)[0]
                    target = "%s.%s" % (owner, arg.attr)
                    if target in self.locks:
                        self.cond_alias[lid] = target
                        continue
            self.locks.setdefault(lid, "Condition")

    def _add(self, lid, kind, mod, value, pending_conds):
        if kind == "Condition":
            pending_conds.append((lid, mod, value))
        else:
            self.locks[lid] = kind

    def _ctor_kind(self, mod, value) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        chain = _dotted(value.func)
        if not chain or chain[-1] not in ("Lock", "RLock", "Condition"):
            return None
        canon = self.project.canonical(mod, chain) or ""
        if "threading" in canon or chain[0] in ("threading", "_threading") \
                or len(chain) == 1:
            return chain[-1]
        return None

    # -- acquisition resolution ------------------------------------------
    def acquire_id(self, mod, scope, expr) -> Optional[str]:
        lid = None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and scope is not None and scope.cls is not None:
            lid = "%s.%s.%s" % (mod.name, scope.cls, expr.attr)
        elif isinstance(expr, ast.Name):
            if expr.id in mod.from_imports:
                src, attr = mod.from_imports[expr.id]
                lid = "%s.%s" % (src, attr)
            else:
                lid = "%s.%s" % (mod.name, expr.id)
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base in mod.imports:
                lid = "%s.%s" % (mod.imports[base], expr.attr)
        if lid is None:
            return None
        lid = self.cond_alias.get(lid, lid)
        return lid if lid in self.locks else None

    def in_scope(self, lock_id: str) -> bool:
        modpart = lock_id.lower()
        return any(s in modpart for s in self.scope)

    # -- per-function summaries ------------------------------------------
    def summarize(self, fn, depth=0) -> _Summary:
        cached = self.summaries.get(id(fn))
        if cached is not None:
            return cached
        s = _Summary()
        if depth > _MAX_DEPTH or id(fn) in self.in_progress:
            return s
        self.in_progress.add(id(fn))
        self._walk_fn(fn, s, depth)
        self.in_progress.discard(id(fn))
        self.summaries[id(fn)] = s
        return s

    def _walk_fn(self, fn, summary: _Summary, depth):
        scope = getattr(fn, "_gl", None)
        if scope is None:
            return
        mod = scope.mod
        qual = fn_qual(fn)
        project = self.project

        def record_blocking(kind, line, held):
            site = (kind, mod.rel, line, qual)
            if len(summary.blocking) < 50:
                summary.blocking.append(site)
            self._maybe_flag(site, held)

        def handle_call(node, held):
            chain = _dotted(node.func)
            canon = project.canonical(mod, chain) if chain else None
            site = _FakeSite(node, chain, canon)
            kind = _blocking_kind(site)
            if kind:
                record_blocking(kind, node.lineno, held)
            if not chain:
                return
            for tgt in project.resolve_chain(mod, scope, chain):
                sub = self.summarize(tgt, depth + 1)
                summary.acquires |= sub.acquires
                for h in held:
                    for a in sub.acquires:
                        if a != h:
                            self.edges.setdefault(
                                (h, a), (mod.rel, node.lineno, qual))
                for bsite in sub.blocking:
                    if len(summary.blocking) < 50:
                        summary.blocking.append(bsite)
                    self._maybe_flag(bsite, held)

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            handle_call(sub, held)
                    lid = self.acquire_id(mod, scope, item.context_expr)
                    if lid is not None:
                        for h in held:
                            if h != lid:
                                self.edges.setdefault(
                                    (h, lid),
                                    (mod.rel, node.lineno, qual))
                        acquired.append(lid)
                        summary.acquires.add(lid)
                new_held = held + tuple(a for a in acquired
                                        if a not in held)
                for b in node.body:
                    visit(b, new_held)
                return
            if isinstance(node, ast.Call):
                handle_call(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            visit(stmt, ())

    def _maybe_flag(self, bsite, held):
        if not held:
            return
        kind, rel, line, qual = bsite
        for h in held:
            if self.in_scope(h):
                self.blocking_findings.append(Finding(
                    CODE, rel, line,
                    "%s in %s while holding %s — a hot-path lock must "
                    "never wait on the device or the network"
                    % (kind, qual, h),
                    "blocking:%s:%s:%s" % (kind.split()[0], qual, h)))
                return


class _FakeSite:
    __slots__ = ("node", "chain", "canon")

    def __init__(self, node, chain, canon):
        self.node = node
        self.chain = chain
        self.canon = canon


def _own_nodes(fn):
    """All AST nodes of ``fn`` excluding nested function bodies."""
    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield child
            yield from rec(child)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        yield stmt
        yield from rec(stmt)


def run(project: Project):
    an = _Analysis(project)
    an.collect_locks()
    for mod in project.modules.values():
        for fn in mod.functions.values():
            an.summarize(fn)

    findings = list(an.blocking_findings)
    # deduplicate blocking findings (same site reached via several callers)
    uniq = {}
    for f in findings:
        uniq.setdefault(f.fingerprint, f)
    findings = list(uniq.values())

    reported = set()
    for (a, b), (rel, line, qual) in sorted(an.edges.items()):
        if (b, a) not in an.edges:
            continue
        pair = tuple(sorted((a, b)))
        if pair in reported:
            continue
        reported.add(pair)
        rel2, line2, qual2 = an.edges[(b, a)]
        findings.append(Finding(
            CODE, rel, line,
            "inconsistent lock order: %s -> %s in %s (%s:%d) but "
            "%s -> %s in %s (%s:%d) — potential ABBA deadlock"
            % (a, b, qual, rel, line, b, a, qual2, rel2, line2),
            "order:%s<->%s" % pair))
    return findings
