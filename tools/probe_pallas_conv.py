#!/usr/bin/env python
"""Probe: Pallas implicit-GEMM conv (ops/pallas_conv.py) vs lax.conv on
the ResNet-50 3x3 shapes, chained-block TFLOPS per shape as JSON.

Round 3 prototyped the implicit-GEMM framing in this file and measured
87-171 TF standalone (vs the 35-45 TF in-graph conv aggregate and
150-195 TF isolated XLA convs).  Round 6 moved the kernels into
``mxnet_tpu/ops/pallas_conv.py`` with a full Pallas VJP; this probe now
drives the LIBRARY kernels — the exact code the ``MXNET_TPU_PALLAS_CONV``
dispatch runs — so probe numbers and production numbers cannot drift.

Protocol (same as tools/probe_wgrad.py): windowed timing with a
data-feedback chain — each jitted call folds a loss-dependent epsilon
back into its input so neither XLA nor the runtime can overlap, reorder
or dead-code the kernels; per-call time is the median of paired
(2N - N) window differences; DEPTH convs chain inside one executable to
amortize dispatch.  TFLOPS uses 2 flops/MAC over KH*KW*C contractions
(the consistent-currency convention bench.py fixed in round 3).

Run:  python tools/probe_pallas_conv.py            (needs the TPU chip)
      python tools/probe_pallas_conv.py --smoke    (CPU: tiny shapes in
          interpret mode, numerics only — the CI guard for this probe)

Output: one JSON object on stdout, {"shapes": [{shape, *_tf | *_err}]}.
"""
import json
import statistics
import sys
import time

import numpy as np

REPS = 5
WINDOW = 12
DEPTH = 4          # convs chained inside one executable

# ResNet-50/224 3x3 conv shapes, batch 128: (name, N, C, O, HW, stride).
# stage1 is lane-starved (C=64 < 128 lanes; r3 measured 10 TF) and
# gated OFF by conv3x3_same_available — probed anyway for the record.
SHAPES = [
    ("stage1_56px", 128, 64, 64, 56, 1),
    ("stage2_28px", 128, 128, 128, 28, 1),
    ("stage3_14px", 128, 256, 256, 14, 1),
    ("stage4_7px", 128, 512, 512, 7, 1),
    ("s2_28to14px", 128, 128, 256, 28, 2),
]
SMOKE_SHAPES = [
    ("smoke_s1", 2, 8, 8, 6, 1),
    ("smoke_s2", 2, 8, 8, 6, 2),
]


def _win_time(fn, fetch, n):
    """One window: n async dispatches, one hard D2H fetch."""
    t0 = time.perf_counter()
    r = None
    for _ in range(n):
        r = fn()
    fetch(r)
    return time.perf_counter() - t0


def _per_call(fn, fetch):
    """Median of paired (2N - N) window differences -> seconds/call."""
    _win_time(fn, fetch, 2)                    # warm
    diffs = []
    for _ in range(REPS):
        d1 = _win_time(fn, fetch, WINDOW)
        d2 = _win_time(fn, fetch, 2 * WINDOW)
        diffs.append(d2 - d1)
    med = statistics.median(diffs)
    return med / WINDOW if med > 0 else None


def probe_shape(name, N, C, O, HW, stride, smoke):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import pallas_conv as pc

    dtype = jnp.float32 if smoke else jnp.bfloat16
    r = np.random.default_rng(0)
    x = jnp.asarray(r.standard_normal((N, C, HW, HW)) * 0.1, dtype)
    w = jnp.asarray(r.standard_normal((O, C, 3, 3)) * 0.1, dtype)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))

    def lax_conv(d, w_):
        return jax.lax.conv_general_dilated(
            d, w_, (stride, stride), [(1, 1), (1, 1)],
            dimension_numbers=dn)

    def pl_conv(d, w_):
        if stride == 1:
            return pc.conv3x3_same(d, w_)
        return pc.conv3x3_s2(d, w_)

    Ho = HW // stride
    flops = 2 * N * O * C * 9 * Ho * Ho          # 2 flops/MAC, 9 taps
    row = {"shape": name, "N": N, "C": C, "O": O, "hw": HW,
           "stride": stride}

    if smoke:
        # numerics guard: forward and both grads vs the lax lowering
        got = np.asarray(pl_conv(x, w).astype(jnp.float32))
        ref = np.asarray(lax_conv(x, w).astype(jnp.float32))
        row["pallas_fwd_err"] = float(np.max(np.abs(got - ref)))

        def loss(conv):
            return lambda d, w_: jnp.sum(conv(d, w_).astype(jnp.float32)
                                         ** 2)
        gp = jax.grad(loss(pl_conv), (0, 1))(x, w)
        gr = jax.grad(loss(lax_conv), (0, 1))(x, w)
        row["pallas_grad_err"] = float(max(
            np.max(np.abs(np.asarray(a) - np.asarray(b)))
            / (np.max(np.abs(np.asarray(b))) + 1e-9)
            for a, b in zip(gp, gr)))
        return row

    def chain_fwd(conv):
        @jax.jit
        def f(d):
            for _ in range(DEPTH):
                y = conv(d, w)
                d = d + (jnp.mean(y.astype(jnp.float32))
                         * 1e-12).astype(d.dtype)
            return d
        return f

    def chain_train(conv):
        def loss(d, w_):
            return 0.5 * jnp.sum(conv(d, w_).astype(jnp.float32) ** 2)

        @jax.jit
        def f(d):
            for _ in range(DEPTH):
                gd, gw = jax.grad(loss, (0, 1))(d, w)
                eps = jnp.mean(gw.astype(jnp.float32)) * 1e-12
                d = d + gd.astype(d.dtype) * 1e-12 + eps.astype(d.dtype)
            return d
        return f

    def fetch(d):
        np.asarray(jax.device_get(d[0, 0, 0, :1]))

    for impl, conv in (("pallas", pl_conv), ("lax", lax_conv)):
        for pass_, mk, nflops in (("fwd", chain_fwd, flops),
                                  ("train", chain_train, 3 * flops)):
            f = mk(conv)
            state = {"d": x}

            def call(f=f):
                state["d"] = f(state["d"])
                return state["d"]
            try:
                t = _per_call(call, fetch)
            except Exception as e:                     # noqa: BLE001
                row["%s_%s_error" % (impl, pass_)] = repr(e)[:200]
                continue
            if t:
                per_conv = t / DEPTH
                row["%s_%s_ms" % (impl, pass_)] = round(per_conv * 1e3, 3)
                row["%s_%s_tf" % (impl, pass_)] = round(
                    nflops / per_conv / 1e12, 1)
    return row


def main(argv):
    smoke = "--smoke" in argv
    import jax
    from mxnet_tpu.ops import pallas_conv as pc

    out = {"metric": "pallas_conv_probe", "smoke": smoke,
           "backend": jax.default_backend(), "depth": DEPTH}
    if smoke:
        pc.INTERPRET = True
    elif out["backend"] != "tpu":
        out["error"] = ("requires the TPU chip; use --smoke for the "
                        "CPU interpret-mode numerics guard")
        print(json.dumps(out))
        return 2
    rows = []
    for spec in (SMOKE_SHAPES if smoke else SHAPES):
        rows.append(probe_shape(*spec, smoke=smoke))
    out["shapes"] = rows
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main(sys.argv[1:]))
