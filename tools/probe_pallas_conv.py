#!/usr/bin/env python
"""Probe: where does ResNet-50 conv time actually go on this chip?

Round-2's docs/perf_analysis.md measured an aggregate 35-45 TF "conv
ceiling"; this probe decomposes it per conv class (3x3 vs 1x1, per stage)
and tests Pallas implicit-GEMM replacements where XLA is below roofline.
Reports both TFLOPS (compute roofline: ~125 TF measured matmul) and
effective GB/s (bandwidth roofline: ~660 GB/s measured).

Timing: chained applications inside one jit, differential (2N)-(N) to
cancel the ~100 ms tunnel round trip.

Run on the TPU:  python tools/probe_pallas_conv.py [--quick]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

REPS = 4


def _align(v, m):
    return (v + m - 1) // m * m


# ----------------------------------------------------------------- kernels
def conv3x3_kernel_factory(TILE, WP):
    def kern(x_ref, w_ref, o_ref):
        acc = None
        for dh in range(3):
            for dw in range(3):
                xs = x_ref[pl.ds(dh * WP + dw, TILE), :]
                p = jax.lax.dot_general(
                    xs, w_ref[dh * 3 + dw],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc = p if acc is None else acc + p
        o_ref[:] = acc.astype(o_ref.dtype)
    return kern


def make_pallas_conv(N, H, W, C, K, TH, interpret=False):
    """Compact-H framing: out frame (H, W+2) per image; x halo-padded."""
    WP = W + 2
    assert H % TH == 0
    T = H // TH
    TILE = TH * WP
    assert TILE % 8 == 0, (TH, WP)
    SLAB = _align(TILE + 2 * WP + 2, 8)
    Lx = _align((H + 2) * WP, 8)
    total_x = _align((N - 1) * Lx + (T - 1) * TILE + SLAB, 8)

    kern = conv3x3_kernel_factory(TILE, WP)

    def conv(x, w):  # x: (N, H, W, C), w: (3, 3, C, K)
        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        xf = xp.reshape(N, (H + 2) * WP, C)
        xf = jnp.pad(xf, ((0, 0), (0, Lx - (H + 2) * WP), (0, 0)))
        xf = xf.reshape(N * Lx, C)
        xf = jnp.pad(xf, ((0, total_x - N * Lx), (0, 0)))
        w9 = w.reshape(9, C, K)
        out = pl.pallas_call(
            kern,
            grid=(N, T),
            in_specs=[
                # (n*Lx + t*TILE) written so Mosaic can prove 8-divisibility
                pl.BlockSpec((pl.Element(SLAB), pl.Element(C)),
                             lambda n, t: ((n * (Lx // 8) + t * (TILE // 8)) * 8, 0)),
                pl.BlockSpec((9, C, K), lambda n, t: (0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((TILE, K), lambda n, t: (n * T + t, 0)),
            out_shape=jax.ShapeDtypeStruct((N * H * WP, K), x.dtype),
            interpret=interpret,
        )(xf, w9)
        return out.reshape(N, H, WP, K)[:, :, :W, :]

    return conv


def make_pallas_conv_stacked(N, H, W, C, K, NB, interpret=False):
    """Halo framing with NB images stacked per grid step (small spatial)."""
    WP = W + 2
    F = (H + 2) * WP              # frame rows per image (halo frame)
    assert N % NB == 0
    TILE = NB * F
    assert TILE % 8 == 0, (NB, F)
    SLAB = _align(TILE + 2 * WP + 2, 8)
    LEAD = WP + 1                 # so tap offsets stay the same dh*WP+dw
    total_x = _align(LEAD + N * F + 2 * WP + 2 + 8, 8)

    kern = conv3x3_kernel_factory(TILE, WP)

    def conv(x, w):
        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        xf = xp.reshape(N * F, C)
        xf = jnp.pad(xf, ((LEAD, total_x - N * F - LEAD), (0, 0)))
        w9 = w.reshape(9, C, K)
        out = pl.pallas_call(
            kern,
            grid=(N // NB,),
            in_specs=[
                pl.BlockSpec((pl.Element(SLAB), pl.Element(C)),
                             lambda g: (g * TILE, 0)),
                pl.BlockSpec((9, C, K), lambda g: (0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((TILE, K), lambda g: (g, 0)),
            out_shape=jax.ShapeDtypeStruct((N * F, K), x.dtype),
            interpret=interpret,
        )(xf, w9)
        return out.reshape(N, H + 2, WP, K)[:, 1:H + 1, 1:W + 1, :]

    return conv


def make_pallas_1x1(N, H, W, C, K, TR=2048, interpret=False):
    """1x1 conv = row-tiled GEMM (R, C) @ (C, K)."""
    R = N * H * W
    Rp = _align(R, TR)

    def kern(x_ref, w_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)

    def conv(x, w):  # w: (1,1,C,K) or (C,K)
        w2 = w.reshape(C, K)
        xf = x.reshape(R, C)
        if Rp != R:
            xf = jnp.pad(xf, ((0, Rp - R), (0, 0)))
        out = pl.pallas_call(
            kern,
            grid=(Rp // TR,),
            in_specs=[pl.BlockSpec((TR, C), lambda i: (i, 0)),
                      pl.BlockSpec((C, K), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((TR, K), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((Rp, K), x.dtype),
            interpret=interpret,
        )(xf, w2)
        return out[:R].reshape(N, H, W, K)

    return conv


def xla_conv(stride=1):
    def f(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return f


# ----------------------------------------------------------------- timing
def time_chain(step, x0, chain):
    """step: x -> x (same shape). Differential timing over `chain` reps."""
    def build(n):
        @jax.jit
        def f(x):
            def body(c, _):
                return step(c) * jnp.bfloat16(0.25), None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(y.astype(jnp.float32))
        return f

    f1, f2 = build(chain), build(2 * chain)
    float(f1(x0)); float(f2(x0))
    best1 = best2 = 1e9
    for _ in range(REPS):
        t0 = time.perf_counter(); float(f1(x0))
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter(); float(f2(x0))
        best2 = min(best2, time.perf_counter() - t0)
    return max(best2 - best1, 1e-9) / chain


# ----------------------------------------------------------------- main
def report(tag, name, t, flops, gbytes, extra=""):
    print(f"{tag:>20} {name:>13} {t*1e3:8.3f}ms {flops/t/1e12:7.1f}TF "
          f"{gbytes/t:6.0f}GB/s {extra}", flush=True)


def main():
    quick = "--quick" in sys.argv
    N = 128
    rng = np.random.default_rng(0)

    # ---- 3x3 stride-1 shapes (C == K) --------------------------------
    for (H, W, C) in [(56, 56, 64), (28, 28, 128), (14, 14, 256), (7, 7, 512)]:
        K = C
        x = jnp.asarray(rng.standard_normal((N, H, W, C)) * 0.1, jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((3, 3, C, K)) * 0.1, jnp.bfloat16)
        flops = 2 * N * H * W * C * K * 9
        gbytes = (2 * N * H * W * C * 2) / 1e9   # read x + write out, bf16
        tag = f"3x3 {H}x{W}x{C}"
        chain = max(64, min(512, int(0.25 / (flops / 45e12))))

        ref = np.asarray(xla_conv()(x, w).astype(jnp.float32))
        t = time_chain(lambda c: xla_conv()(c, w), x, chain)
        report(tag, "xla", t, flops, gbytes, f"chain={chain}")

        variants = []
        if H >= 14:
            for th in (H, H // 2):
                if H % th == 0 and (th * (W + 2)) % 8 == 0:
                    variants.append((f"pl th={th}",
                                     make_pallas_conv(N, H, W, C, K, th)))
        for nb in (8, 4):
            F = (H + 2) * (W + 2)
            if N % nb == 0 and (nb * F) % 8 == 0 and nb * F * max(C, 128) * 6 < 12e6:
                variants.append((f"pl nb={nb}",
                                 make_pallas_conv_stacked(N, H, W, C, K, nb)))
        if quick:
            variants = variants[:1]
        for name, impl in variants:
            try:
                got = np.asarray(impl(x, w).astype(jnp.float32))
                err = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-6)
                t = time_chain(lambda c: impl(c, w), x, chain)
                report(tag, name, t, flops, gbytes,
                       f"err={err:.0e}{'' if err < 2e-2 else ' BAD'}")
            except Exception as e:
                print(f"{tag:>20} {name:>13}    FAIL "
                      f"{str(e).splitlines()[0][:80]}", flush=True)

    # ---- 1x1 shapes: chain expand+reduce pairs -----------------------
    for (H, W, Cs, Cl) in [(56, 56, 64, 256), (28, 28, 128, 512),
                           (14, 14, 256, 1024), (7, 7, 512, 2048)]:
        xs = jnp.asarray(rng.standard_normal((N, H, W, Cs)) * 0.1, jnp.bfloat16)
        w_up = jnp.asarray(rng.standard_normal((1, 1, Cs, Cl)) * 0.1, jnp.bfloat16)
        w_dn = jnp.asarray(rng.standard_normal((1, 1, Cl, Cs)) * 0.1, jnp.bfloat16)
        R = N * H * W
        flops = 2 * R * Cs * Cl * 2              # up + down
        gbytes = (R * Cs * 2 + R * Cl * 2) * 2 / 1e9
        tag = f"1x1 {H}x{W} {Cs}<->{Cl}"
        chain = max(32, min(256, int(0.25 / (flops / 30e12))))

        conv = xla_conv()
        t = time_chain(lambda c: conv(conv(c, w_up), w_dn), xs, chain)
        report(tag, "xla", t, flops, gbytes, f"chain={chain}")

        pu = make_pallas_1x1(N, H, W, Cs, Cl)
        pd = make_pallas_1x1(N, H, W, Cl, Cs)
        ref = np.asarray(conv(conv(xs, w_up), w_dn).astype(jnp.float32))
        try:
            got = np.asarray(pd(pu(xs, w_up), w_dn).astype(jnp.float32))
            err = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-6)
            t = time_chain(lambda c: pd(pu(c, w_up), w_dn), xs, chain)
            report(tag, "pl gemm", t, flops, gbytes,
                   f"err={err:.0e}{'' if err < 2e-2 else ' BAD'}")
        except Exception as e:
            print(f"{tag:>20} {'pl gemm':>13}    FAIL "
                  f"{str(e).splitlines()[0][:80]}", flush=True)


if __name__ == "__main__":
    main()
