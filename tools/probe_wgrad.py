#!/usr/bin/env python
"""Probe: decompose the ResNet-50 train step into fwd / dgrad / wgrad,
and isolate wgrad conv performance (VERDICT r4 item 2b).

Round-4 left wgrad as the last unprobed region of the "platform-bound at
~2,450 img/s" claim: forward convs run 150-195 TF isolated but the whole
step aggregates ~45 TF (in consistent 2-flops/MAC terms — see bench.py),
and prior probes only chained fwd or fwd+dgrad.  Two parts:

1. Three-way split of the real training step (resnet50_v1, batch 128,
   bf16, the same _Plan the bench's FusedTrainer compiles):
     t_fwd            — loss only
     t_fwd_dgrad      — grad wrt DATA (runs the full dgrad chain,
                        no weight gradients)
     t_full           — grad wrt PARAMS (fwd + dgrad + wgrad)
   differences give the per-pass share.  Windowed timing (python loop of
   the jitted step with a donated data-feedback chain, one D2H at the
   end) — the same protocol bench.py validated against the tunnel.

2. Isolated wgrad at the four 3x3 bottleneck shapes (56/28/14/7 px), via
   jax.linear_transpose of the conv in w — the pure wgrad XLA program,
   no fwd needed (conv is linear in w).  Also a hand 9-shifted-GEMM
   formulation (dw[tap] = x_tap^T @ dy) to see whether a different
   lowering beats XLA's chosen one (>=10% -> wire it, VERDICT).

Run: python tools/probe_wgrad.py          (needs the TPU chip)
"""
import json
import statistics
import sys
import time

import numpy as np

REPS = 5
WINDOW = 12


def _win_time(fn, fetch, n):
    """One window: n async dispatches, one hard D2H fetch."""
    t0 = time.perf_counter()
    r = None
    for _ in range(n):
        r = fn()
    fetch(r)
    return time.perf_counter() - t0


def _per_call(fn, fetch):
    """Median of paired (2N - N) window differences -> seconds/call."""
    _win_time(fn, fetch, 2)                    # warm
    diffs = []
    for _ in range(REPS):
        d1 = _win_time(fn, fetch, WINDOW)
        d2 = _win_time(fn, fetch, 2 * WINDOW)
        diffs.append(d2 - d1)
    med = statistics.median(diffs)
    return med / WINDOW if med > 0 else None


def three_way_split():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import symbol as sym_mod
    from mxnet_tpu.executor import _Plan
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.ops.nn import streaming_ce

    batch, px = 128, 224
    net = vision.resnet50_v1()
    net.initialize(ctx=mx.tpu(0) if mx.context.num_tpus() else mx.cpu(0))
    x0 = mx.nd.random.uniform(shape=(batch, 3, px, px))
    net(x0).wait_to_read()
    net.hybridize()
    out_sym = net(sym_mod.var("data"))
    plan = _Plan(out_sym, train=True)
    params = net.collect_params()
    args = {n: jnp.asarray(params[n].data()._data, jnp.float32)
            for n in plan.arg_names if n != "data"}
    auxs = {n: jnp.asarray(params[n].data()._data, jnp.float32)
            for n in plan.aux_names}
    keys = jnp.zeros((max(1, plan.n_rng), 2), jnp.uint32)
    labels = jnp.asarray(np.random.randint(0, 1000, (batch,)))
    data = jnp.asarray(np.asarray(x0._data), jnp.bfloat16)

    def loss_of(a, d):
        a = {k: v.astype(jnp.bfloat16) for k, v in a.items()}
        outs, _ = plan.execute({**a, "data": d}, auxs, keys)
        return jnp.mean(streaming_ce(outs[0], labels))

    # each variant feeds a loss-dependent epsilon back into data so the
    # window's steps chain (nothing can be dead-code'd or reordered out)
    @jax.jit
    def f_fwd(d):
        return d + (loss_of(args, d) * 1e-12).astype(d.dtype)

    @jax.jit
    def f_dgrad(d):
        g = jax.grad(loss_of, 1)(args, d)
        return d + g.astype(d.dtype) * 1e-12

    @jax.jit
    def f_full(d):
        gs = jax.grad(loss_of, 0)(args, d)
        acc = sum(jnp.sum(v.astype(jnp.float32)) for v in gs.values())
        return d + (acc * 1e-12).astype(d.dtype)

    def fetch(d):
        np.asarray(jax.device_get(d[0, 0, 0, :1]))

    res = {}
    state = {"d": data}
    for name, f in (("fwd", f_fwd), ("fwd_dgrad", f_dgrad),
                    ("full", f_full)):
        def call(f=f):
            state["d"] = f(state["d"])
            return state["d"]
        t = _per_call(call, fetch)
        res[name + "_ms"] = round(t * 1e3, 2) if t else None
    if all(res.get(k) for k in ("fwd_ms", "fwd_dgrad_ms", "full_ms")):
        res["dgrad_ms"] = round(res["fwd_dgrad_ms"] - res["fwd_ms"], 2)
        res["wgrad_ms"] = round(res["full_ms"] - res["fwd_dgrad_ms"], 2)
        res["img_per_sec_full"] = round(batch / (res["full_ms"] / 1e3), 1)
    return res


# the four 3x3 bottleneck conv shapes of ResNet-50 at 224px (batch 128)
SHAPES = [
    ("stage1_56px", 128, 64, 64, 56),
    ("stage2_28px", 128, 128, 128, 28),
    ("stage3_14px", 128, 256, 256, 14),
    ("stage4_7px", 128, 512, 512, 7),
]


def isolated_wgrad():
    import jax
    import jax.numpy as jnp

    rows = []
    r = np.random.default_rng(0)
    for name, N, C, K, HW in SHAPES:
        x = jnp.asarray(r.standard_normal((N, C, HW, HW)) * 0.1,
                        jnp.bfloat16)
        dy = jnp.asarray(r.standard_normal((N, K, HW, HW)) * 0.1,
                         jnp.bfloat16)
        dn = jax.lax.conv_dimension_numbers(x.shape, (K, C, 3, 3),
                                            ("NCHW", "OIHW", "NCHW"))

        def conv_w(w):
            # bf16 out so the transpose takes the bf16 dy cotangent
            # (MXU still accumulates f32 internally)
            return jax.lax.conv_general_dilated(
                x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=dn)

        wt = jax.linear_transpose(
            conv_w, jax.ShapeDtypeStruct((K, C, 3, 3), jnp.bfloat16))

        @jax.jit
        def f_xla(g, wt=wt):
            (dw,) = wt(g)
            return g + jnp.mean(dw.astype(jnp.float32)).astype(g.dtype) \
                * 1e-12

        # hand formulation: dw for tap (dy,dx) = x_shifted^T @ dy as one
        # GEMM over (N*H*W) — nine of them, f32 accumulation
        xp = jnp.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))

        @jax.jit
        def f_gemm(g, xp=xp, C=C, K=K, HW=HW, N=N):
            g2 = g.transpose(0, 2, 3, 1).reshape(N * HW * HW, K)
            acc = jnp.mean(g.astype(jnp.float32)) * 0.0
            for dy_ in range(3):
                for dx_ in range(3):
                    tap = xp[:, :, dy_:dy_ + HW, dx_:dx_ + HW] \
                        .transpose(0, 2, 3, 1).reshape(N * HW * HW, C)
                    dw = jax.lax.dot_general(
                        tap, g2, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    acc = acc + jnp.mean(dw)
            return g + acc.astype(g.dtype) * 1e-12

        def fetch(g):
            np.asarray(jax.device_get(g[0, 0, 0, :1]))

        flops = 2 * N * K * C * 9 * HW * HW
        row = {"shape": name}
        for nm, f in (("xla", f_xla), ("gemm9", f_gemm)):
            state = {"g": dy}

            def call(f=f):
                state["g"] = f(state["g"])
                return state["g"]
            t = _per_call(call, fetch)
            if t:
                row[nm + "_ms"] = round(t * 1e3, 3)
                row[nm + "_tf"] = round(flops / t / 1e12, 1)
        rows.append(row)
    return rows


def main():
    out = {"metric": "wgrad_probe"}
    if "--isolated-only" not in sys.argv:
        out["three_way_split"] = three_way_split()
    if "--split-only" not in sys.argv:
        out["isolated_wgrad"] = isolated_wgrad()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
