#!/usr/bin/env python
"""Deploy prefill for the persistent compiled-program cache.

Compiles a model's serving bucket ladder and (optionally) its fused
training step ONCE into ``MXNET_PROGRAM_CACHE_DIR``, so the cache
directory can ship with the model artifact and every replica restarts
warm: ready-to-serve / step-1 with **zero** XLA compiles, just disk
reads (see mxnet_tpu/program_cache.py and docs/serving.md "Deploy
prefill").

Modes:

- default        — prefill: run the workload cold in a subprocess with
                   the cache enabled; artifacts land in ``--cache-dir``.
- ``--verify``   — after prefill, restart the same workload warm in a
                   fresh subprocess and assert zero fresh XLA compiles
                   (``program_cache`` puts == misses == 0); reports
                   cold/warm seconds and the speedup.
- ``--smoke``    — CI probe: tiny MLP, throwaway cache dir under /tmp,
                   CPU pinned, prefill + verify + assertions; prints
                   ``{"probe": "cache_prefill", "ok": true, ...}``.
- ``--worker``   — internal: the subprocess entry that actually runs the
                   workload and prints one JSON result line.

The cold/warm boundary is a real process boundary (subprocess re-exec),
so the numbers are what a deploy sees, not an in-process approximation.

Run:  python tools/cache_prefill.py --cache-dir /models/m1/pcache --verify
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_symbol(args, mode):
    """(symbol, params, example_shapes, n_classes) for --model.

    ``mode="serve"`` heads with a plain softmax (no label input, what a
    Predictor binds); ``mode="train"`` heads with SoftmaxOutput so the
    Module path drives the fused whole-step program.  Both share the
    same backbone parameter names.
    """
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    S = mx.symbol
    if args.model == "resnet50":
        from mxnet_tpu.gluon.model_zoo import vision
        net = vision.resnet50_v1()
        body = net(S.var("data"))
        example = {"data": (3, args.image_size, args.image_size)}
        classes = 1000
    else:
        x = S.var("data")
        h = S.Activation(S.FullyConnected(x, num_hidden=args.hidden,
                                          name="fc1"), act_type="relu")
        h = S.Activation(S.FullyConnected(h, num_hidden=args.hidden,
                                          name="fc2"), act_type="relu")
        body = S.FullyConnected(h, num_hidden=args.classes, name="fc3")
        example = {"data": (args.in_dim,)}
        classes = args.classes
    if mode == "serve":
        sym = S.softmax(body, axis=1, name="prob")
    else:
        sym = S.SoftmaxOutput(body, S.var("softmax_label"),
                              name="softmax")
    rng = np.random.RandomState(0)
    feed = {"data": (1,) + example["data"]}
    if mode != "serve":
        feed["softmax_label"] = (1,)
    shapes, _, aux_shapes = sym.infer_shape(**feed)
    params = {n: nd.array(rng.uniform(-0.1, 0.1, s).astype(np.float32))
              for n, s in zip(sym.list_arguments(), shapes)
              if n not in ("data", "softmax_label")}
    for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
        # BN moving stats: identity-ish init keeps activations finite;
        # "aux:" prefix is the checkpoint convention Predictor parses
        fill = np.ones if n.endswith(("_var", "_running_var")) \
            else np.zeros
        params["aux:" + n] = nd.array(fill(s, np.float32))
    return sym, params, example, classes


def _serve_ladder(args):
    """Compile every declared bucket (ModelServer.warmup); returns the
    measured warmup seconds."""
    from mxnet_tpu.serving import ModelServer
    sym, params, example, _ = build_symbol(args, "serve")
    server = ModelServer(sym.tojson(), params, example_shapes=example,
                         batch_buckets=args.bucket_list,
                         max_batch_size=max(args.bucket_list))
    server.warmup()
    return server.warmup_seconds


def _train_step(args):
    """Fused whole-step program: first-step (compile/restore) seconds +
    op_jit miss delta across a REPEAT step (steady-state restore proof)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    sym, _, example, classes = build_symbol(args, "train")
    batch = args.batch
    data_shape = (batch,) + example["data"]
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",),
                        context=[mx.cpu()])
    mod.bind(data_shapes=[("data", data_shape)],
             label_shapes=[("softmax_label", (batch,))])
    mx.random.seed(7)
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    rs = np.random.RandomState(3)
    x = mx.nd.array(rs.uniform(size=data_shape).astype(np.float32))
    y = mx.nd.array(rs.randint(0, classes, (batch,)).astype(np.float32))

    class _B:
        data = [x]
        label = [y]

    def step():
        mod.forward_backward(_B)
        mod.update()
        return float(mod.get_outputs()[0].asnumpy().ravel()[0])

    t0 = time.perf_counter()
    step()
    first = time.perf_counter() - t0

    def misses():
        fams = telemetry.registry().get("op_jit_cache_misses_total")
        if fams is None:
            return 0
        return sum(c.get() for c in fams._children.values())

    m0 = misses()
    t0 = time.perf_counter()
    step()
    repeat = time.perf_counter() - t0
    return first, max(0.0, first - repeat), misses() - m0


def run_worker(args):
    """Subprocess entry: run the workload with the cache (maybe) enabled
    and print one JSON line of measurements + cache stats."""
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from mxnet_tpu import program_cache, telemetry
    telemetry.enable()
    out = {"cache_dir": os.environ.get(program_cache.ENV_DIR)}
    if args.serve:
        out["serving_warmup_seconds"] = round(_serve_ladder(args), 6)
    if args.train:
        first, compile_s, repeat_misses = _train_step(args)
        out["step_first_seconds"] = round(first, 6)
        # compile/restore component: first-step wall minus a repeat step
        out["step_first_compile_seconds"] = round(compile_s, 6)
        out["repeat_step_op_jit_misses"] = int(repeat_misses)
    s = program_cache.stats()
    out["program_cache"] = s
    # fresh XLA compiles while enabled == persistent-cache misses (every
    # call-path compile request flows through the installed cache)
    out["fresh_compiles"] = int(s.get("puts", 0))
    print(json.dumps(out))


def _spawn(args, extra_env, tag):
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--model", args.model, "--buckets", args.buckets,
           "--batch", str(args.batch), "--in-dim", str(args.in_dim),
           "--hidden", str(args.hidden), "--classes", str(args.classes),
           "--image-size", str(args.image_size)]
    if args.platform:
        cmd += ["--platform", args.platform]
    if not args.serve:
        cmd += ["--no-serve"]
    if not args.train:
        cmd += ["--no-train"]
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=args.timeout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout + proc.stderr)
        raise SystemExit("cache_prefill: %s worker failed (rc=%d)"
                         % (tag, proc.returncode))
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cache-dir",
                    default=os.environ.get("MXNET_PROGRAM_CACHE_DIR"),
                    help="program-cache directory to prefill "
                         "(default: $MXNET_PROGRAM_CACHE_DIR)")
    ap.add_argument("--model", choices=("mlp", "resnet50"), default="mlp")
    ap.add_argument("--buckets", default="1,2,4,8",
                    help="serving bucket ladder (comma-separated)")
    ap.add_argument("--batch", type=int, default=8,
                    help="training-step batch size")
    ap.add_argument("--in-dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--platform", default=None,
                    help="jax platform override (smoke pins cpu)")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-subprocess timeout (s)")
    ap.add_argument("--no-serve", dest="serve", action="store_false",
                    help="skip the serving bucket ladder")
    ap.add_argument("--no-train", dest="train", action="store_false",
                    help="skip the fused training step")
    ap.add_argument("--verify", action="store_true",
                    help="after prefill, restart warm and assert zero "
                         "fresh compiles")
    ap.add_argument("--smoke", action="store_true",
                    help="CI probe: tiny model, /tmp cache, cpu, "
                         "prefill+verify+assert")
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--out", help="write the JSON document here too")
    args = ap.parse_args(argv)
    args.bucket_list = tuple(sorted({int(b) for b in
                                     args.buckets.split(",") if b.strip()}))

    if args.worker:
        run_worker(args)
        return 0

    tmp = None
    if args.smoke:
        args.verify = True
        args.platform = args.platform or "cpu"
        args.model, args.batch = "mlp", 4
        args.in_dim, args.hidden, args.classes = 16, 32, 8
        args.buckets, args.bucket_list = "1,2", (1, 2)
        tmp = tempfile.mkdtemp(prefix="mxpc_smoke_")
        args.cache_dir = tmp
    if not args.cache_dir:
        ap.error("--cache-dir (or $MXNET_PROGRAM_CACHE_DIR) is required")
    os.makedirs(args.cache_dir, exist_ok=True)

    wenv = {"MXNET_PROGRAM_CACHE_DIR": args.cache_dir}
    try:
        cold = _spawn(args, wenv, "prefill")
        doc = {"tool": "cache_prefill", "model": args.model,
               "buckets": list(args.bucket_list),
               "cache_dir": args.cache_dir, "cold": cold}
        if args.verify:
            warm = _spawn(args, wenv, "verify")
            doc["warm"] = warm
            doc["fresh_compiles_warm"] = warm["fresh_compiles"]
            doc["zero_compile_restart"] = (
                warm["fresh_compiles"] == 0
                and warm["program_cache"].get("misses", 1) == 0)
            for k in ("serving_warmup_seconds", "step_first_seconds",
                      "step_first_compile_seconds"):
                if k in cold and k in warm and warm[k] > 0:
                    doc.setdefault("speedup", {})[k] = round(
                        cold[k] / warm[k], 2)
        if args.smoke:
            ok = (cold["fresh_compiles"] > 0
                  and doc.get("zero_compile_restart") is True
                  and doc["warm"].get("repeat_step_op_jit_misses", 1) == 0)
            doc = {"probe": "cache_prefill", "ok": bool(ok),
                   "cold_compiles": cold["fresh_compiles"],
                   "warm_compiles": doc["warm"]["fresh_compiles"],
                   "speedup": doc.get("speedup", {})}
            print(json.dumps(doc))
            return 0 if ok else 1
        text = json.dumps(doc, indent=2)
        print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
        return 0 if doc.get("zero_compile_restart", True) else 1
    finally:
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
