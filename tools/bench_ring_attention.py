#!/usr/bin/env python
"""Ring-attention microbench (VERDICT r2 item 7).

Two parts:
1. single chip: long-context blockwise attention, XLA-scan formulation
   vs the Pallas flash kernel (ops/pallas_attention.py) — ms/call,
   tokens/s, achieved TF (differential chained timing).
2. 8-device virtual CPU mesh (subprocess, like __graft_entry__):
   ring_attention and ulysses_attention vs the single-device reference —
   max abs error, proving the sp decomposition is exact.

Run:  python tools/bench_ring_attention.py [--mesh-only|--chip-only]
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPS = 7
CHAIN = 30


def _time_chain(step, x0, chain):
    import jax
    import jax.numpy as jnp
    import statistics

    def build(n):
        @jax.jit
        def f(x):
            def body(c, _):
                o = step(c)
                eps = (jnp.sum(o.astype(jnp.float32)) * 1e-12)
                return c + eps.astype(c.dtype), None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(y.astype(jnp.float32))
        return f

    f1, f2 = build(chain), build(2 * chain)
    float(f1(x0)); float(f2(x0))
    # median of PAIRED (2N - N) differences: resists the tunnel's
    # per-call latency swings, which made min-of-mins go negative
    diffs = []
    for _ in range(REPS):
        t0 = time.perf_counter(); float(f1(x0))
        d1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(f2(x0))
        d2 = time.perf_counter() - t0
        diffs.append(d2 - d1)
    med = statistics.median(diffs)
    if med <= 0:
        # tunnel bimodality swamped the differential: flag instead of
        # clamping (a clamp fabricates astronomical TF rows)
        return None
    return med / chain


def chip_bench():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel.ring_attention import blockwise_attention


    results = []
    r = np.random.default_rng(0)
    B, H, D = 1, 8, 128
    for T in (4096, 8192, 16384):
        q = jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                        jnp.bfloat16)
        k = jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                        jnp.bfloat16)
        v = jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                        jnp.bfloat16)
        # causal attention FLOPs: 2 matmuls, half the score matrix
        flops = 2 * 2 * B * H * T * T * D / 2
        row = {"T": T}
        for name, use_pallas in (("xla_scan", False), ("pallas", True)):
            fn = lambda c, up=use_pallas: blockwise_attention(
                c, k, v, block_size=256, causal=True, use_pallas=up)
            # correctness cross-check once
            t = _time_chain(fn, q, CHAIN)
            if t is None:
                row[name + "_timing_suspect"] = True
                continue
            row[name + "_ms"] = round(t * 1e3, 3)
            row[name + "_tf"] = round(flops / t / 1e12, 1)
            row[name + "_tokens_per_sec"] = round(T / t, 0)
        ref = np.asarray(blockwise_attention(
            q, k, v, block_size=256, causal=True,
            use_pallas=False).astype(jnp.float32))
        got = np.asarray(blockwise_attention(
            q, k, v, block_size=256, causal=True,
            use_pallas=True).astype(jnp.float32))
        row["max_err"] = float(np.max(np.abs(got - ref)))
        if "xla_scan_ms" in row and "pallas_ms" in row:
            row["pallas_speedup"] = round(
                row["xla_scan_ms"] / max(row["pallas_ms"], 1e-6), 3)
        results.append(row)
    return results


def ring_chip_bench():
    """The RING path itself on the real chip (r03 verdict item 4): a
    1-device mesh runs the actual per-shard ring code — flash kernel
    emitting (acc, m, l) stats + the exact cross-shard combine — vs the
    scan formulation.  The per-shard VMEM gate sees T/n, so the ring
    decomposition is what keeps the kernel applicable at long T."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.ring_attention import ring_attention


    mesh = make_mesh({"sp": 1})
    r = np.random.default_rng(0)
    B, H, D = 1, 8, 128
    results = []
    import jax.numpy as _jnp

    def train_step_fn(use_pallas, k, v):
        # fwd+bwd wrt (q,k,v): the real training cost (VERDICT r4 #1 —
        # the ring backward now runs Pallas dq/dk/dv kernels)
        def loss(q, k, v):
            o = ring_attention(q, k, v, mesh, axis="sp", causal=True,
                               block_size=256, use_pallas=use_pallas)
            return _jnp.sum(o.astype(_jnp.float32) ** 2)

        def step(q):
            dq, dk, dv = jax.grad(loss, (0, 1, 2))(q, k, v)
            return dq + dk + dv
        return step
    # T here is the PER-SHARD sequence (the 1-device mesh runs one ring
    # step); an 8-way ring at global T = 8*T_loc runs exactly this per
    # step, so the T_loc=1024 row is the per-step cost of ring attention
    # at global T=8192.  T_loc=8192 single-chip exceeds the kernel's
    # resident-KV VMEM envelope and documents the scan fallback edge.
    for T in (1024, 2048, 4096, 8192):
        q, k, v = (jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                               jnp.bfloat16) for _ in range(3))
        flops = 2 * 2 * B * H * T * T * D / 2
        row = {"T_loc": T, "T_global_8way": 8 * T}
        for name, up in (("ring_scan", False), ("ring_flash", True)):
            fn = lambda c, u=up: ring_attention(
                c, k, v, mesh, axis="sp", causal=True, block_size=256,
                use_pallas=u)
            t = _time_chain(fn, q, CHAIN)
            if t is None:
                row[name + "_timing_suspect"] = True
                continue
            row[name + "_ms"] = round(t * 1e3, 3)
            row[name + "_tf"] = round(flops / t / 1e12, 1)
        # train step (fwd+bwd): 7 matmul-pairs vs the forward's 2
        tflops = 3.5 * flops
        for name, up in (("train_scan", False), ("train_flash", True)):
            t = _time_chain(train_step_fn(up, k, v), q, CHAIN)
            if t is None:
                row[name + "_timing_suspect"] = True
                continue
            row[name + "_ms"] = round(t * 1e3, 3)
            row[name + "_tf"] = round(tflops / t / 1e12, 1)
        ref = np.asarray(ring_attention(q, k, v, mesh, axis="sp",
                                        causal=True, block_size=256,
                                        use_pallas=False)
                         .astype(jnp.float32))
        got = np.asarray(ring_attention(q, k, v, mesh, axis="sp",
                                        causal=True, block_size=256)
                         .astype(jnp.float32))
        row["max_err"] = float(np.max(np.abs(got - ref)))
        if "ring_scan_ms" in row and "ring_flash_ms" in row:
            row["flash_speedup"] = round(
                row["ring_scan_ms"] / max(row["ring_flash_ms"], 1e-6), 3)
        if "train_scan_ms" in row and "train_flash_ms" in row:
            row["train_flash_speedup"] = round(
                row["train_scan_ms"] / max(row["train_flash_ms"], 1e-6), 3)
        results.append(row)
    return results


def mesh_check():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    code = r"""
import json
import numpy as np
import jax, jax.numpy as jnp
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.ring_attention import (
    blockwise_attention, ring_attention, ulysses_attention)

mesh = make_mesh({"sp": 8})
r = np.random.default_rng(0)
B, H, T, D = 2, 8, 256, 32
q, k, v = (jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3, jnp.float32)
           for _ in range(3))
from jax.sharding import NamedSharding, PartitionSpec as P
sh = NamedSharding(mesh, P(None, None, "sp", None))
qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
ref = np.asarray(blockwise_attention(q, k, v, causal=True,
                                     use_pallas=False))
ring = np.asarray(ring_attention(qs, ks, vs, mesh, axis="sp",
                                 causal=True, block_size=32))
uly = np.asarray(ulysses_attention(qs, ks, vs, mesh, axis="sp",
                                   causal=True))

# the PALLAS ring path (interpret mode) on the 8-way mesh: per-shard
# flash kernel + cross-shard stats combine must be exact too
from mxnet_tpu.ops import pallas_attention as pa
T2 = 1024                      # T_loc = 128 satisfies the lane gate
q2, k2, v2 = (jnp.asarray(r.standard_normal((B, H, T2, D)) * 0.3,
                          jnp.float32) for _ in range(3))
ref2 = np.asarray(blockwise_attention(q2, k2, v2, causal=True,
                                      use_pallas=False))
pa.INTERPRET = True
try:
    ring_fl = np.asarray(ring_attention(
        *(jax.device_put(a, sh) for a in (q2, k2, v2)),
        mesh, axis="sp", causal=True, block_size=128))
finally:
    pa.INTERPRET = False
print(json.dumps({
    "devices": 8,
    "ring_max_err": float(np.max(np.abs(ring - ref))),
    "ulysses_max_err": float(np.max(np.abs(uly - ref))),
    "ring_flash_max_err": float(np.max(np.abs(ring_fl - ref2))),
}))
"""
    out = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                         capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        return {"error": out.stderr[-500:]}
    return json.loads(out.stdout.strip().splitlines()[-1])


def main():
    result = {"metric": "ring_attention_microbench"}
    if "--mesh-only" not in sys.argv:
        result["single_chip"] = chip_bench()
        result["ring_path_chip"] = ring_chip_bench()
    if "--chip-only" not in sys.argv:
        result["virtual_mesh"] = mesh_check()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
