#!/usr/bin/env python
"""Ring-attention microbench (VERDICT r2 item 7).

Two parts:
1. single chip: long-context blockwise attention, XLA-scan formulation
   vs the Pallas flash kernel (ops/pallas_attention.py) — ms/call,
   tokens/s, achieved TF (differential chained timing).
2. 8-device virtual CPU mesh (subprocess, like __graft_entry__):
   ring_attention and ulysses_attention vs the single-device reference —
   max abs error, proving the sp decomposition is exact.

Run:  python tools/bench_ring_attention.py [--mesh-only|--chip-only]
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPS = 4
CHAIN = 30


def chip_bench():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel.ring_attention import blockwise_attention

    def time_chain(step, x0, chain):
        def build(n):
            @jax.jit
            def f(x):
                def body(c, _):
                    o = step(c)
                    eps = (jnp.sum(o.astype(jnp.float32)) * 1e-12)
                    return c + eps.astype(c.dtype), None
                y, _ = jax.lax.scan(body, x, None, length=n)
                return jnp.sum(y.astype(jnp.float32))
            return f
        f1, f2 = build(chain), build(2 * chain)
        float(f1(x0)); float(f2(x0))
        b1 = b2 = 1e9
        for _ in range(REPS):
            t0 = time.perf_counter(); float(f1(x0))
            b1 = min(b1, time.perf_counter() - t0)
            t0 = time.perf_counter(); float(f2(x0))
            b2 = min(b2, time.perf_counter() - t0)
        return max(b2 - b1, 1e-9) / chain

    results = []
    r = np.random.default_rng(0)
    B, H, D = 1, 8, 128
    for T in (4096, 8192, 16384):
        q = jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                        jnp.bfloat16)
        k = jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                        jnp.bfloat16)
        v = jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3,
                        jnp.bfloat16)
        # causal attention FLOPs: 2 matmuls, half the score matrix
        flops = 2 * 2 * B * H * T * T * D / 2
        row = {"T": T}
        for name, use_pallas in (("xla_scan", False), ("pallas", True)):
            fn = lambda c, up=use_pallas: blockwise_attention(
                c, k, v, block_size=256, causal=True, use_pallas=up)
            # correctness cross-check once
            t = time_chain(fn, q, CHAIN)
            row[name + "_ms"] = round(t * 1e3, 3)
            row[name + "_tf"] = round(flops / t / 1e12, 1)
            row[name + "_tokens_per_sec"] = round(T / t, 0)
        ref = np.asarray(blockwise_attention(
            q, k, v, block_size=256, causal=True,
            use_pallas=False).astype(jnp.float32))
        got = np.asarray(blockwise_attention(
            q, k, v, block_size=256, causal=True,
            use_pallas=True).astype(jnp.float32))
        row["max_err"] = float(np.max(np.abs(got - ref)))
        row["pallas_speedup"] = round(row["xla_scan_ms"]
                                      / row["pallas_ms"], 3)
        results.append(row)
    return results


def mesh_check():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    code = r"""
import json
import numpy as np
import jax, jax.numpy as jnp
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.ring_attention import (
    blockwise_attention, ring_attention, ulysses_attention)

mesh = make_mesh({"sp": 8})
r = np.random.default_rng(0)
B, H, T, D = 2, 8, 256, 32
q, k, v = (jnp.asarray(r.standard_normal((B, H, T, D)) * 0.3, jnp.float32)
           for _ in range(3))
from jax.sharding import NamedSharding, PartitionSpec as P
sh = NamedSharding(mesh, P(None, None, "sp", None))
qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
ref = np.asarray(blockwise_attention(q, k, v, causal=True,
                                     use_pallas=False))
ring = np.asarray(ring_attention(qs, ks, vs, mesh, axis="sp",
                                 causal=True, block_size=32))
uly = np.asarray(ulysses_attention(qs, ks, vs, mesh, axis="sp",
                                   causal=True))
print(json.dumps({
    "devices": 8,
    "ring_max_err": float(np.max(np.abs(ring - ref))),
    "ulysses_max_err": float(np.max(np.abs(uly - ref))),
}))
"""
    out = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                         capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        return {"error": out.stderr[-500:]}
    return json.loads(out.stdout.strip().splitlines()[-1])


def main():
    result = {"metric": "ring_attention_microbench"}
    if "--mesh-only" not in sys.argv:
        result["single_chip"] = chip_bench()
    if "--chip-only" not in sys.argv:
        result["virtual_mesh"] = mesh_check()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
