#!/usr/bin/env python
"""Probe: the multi-model SLO serving gateway, end to end, in-process.

Exercises the whole ISSUE-14 surface on virtual devices: a
:class:`ModelRegistry` hosting two models — one single-chip, one
mesh-sharded (``tp=2`` over virtual CPU devices) — with two SLO classes
under deterministic saturation.  Asserts the contracts the gateway
exists for:

1. **mesh parity** — the tp=2 model's outputs are bit-identical to a
   single-chip Predictor over the same (integer-valued) weights;
2. **shed before deadline-miss** — with the queue saturated past the
   shed thresholds, ``batch`` traffic is rejected with
   :class:`AdmissionError` (the 429 path) while every admitted
   ``realtime`` request completes within its deadline: zero ``deadline``
   outcomes, nonzero ``shed`` outcomes;
3. **zero post-warmup compiles** — mixed traffic across both models and
   every bucket never compiles after warmup (per-server verdict AND the
   global Executor::Forward miss counter);
4. **per-model attribution** — each model's bucket programs appear
   under its own ``serving:<model>:b<bucket>:`` namespace on /programz;
5. **bf16 params serve cleanly** — a model registered with bf16 weights
   (integer-valued, so promotion is exact) answers bit-identically to
   its fp32 twin and never compiles after warmup: the param dtype joins
   the serving program cache key, so bf16 and fp32 registrations of the
   same architecture are distinct programs, each compiled exactly once.

Usage:
    python tools/serving_probe.py --smoke    # CI-sized (same coverage)
    python tools/serving_probe.py
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# virtual devices BEFORE jax import: the mesh model needs >= 2 chips
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build_int_mlp(seed):
    """FC16-relu-FC4 with small integer-valued float32 weights: every
    matmul partial sum is exact, so mesh vs single-chip must be
    bit-identical."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    S = mx.symbol
    x = S.var("data")
    h = S.Activation(S.FullyConnected(x, num_hidden=16, name="fc1"),
                     act_type="relu")
    out = S.FullyConnected(h, num_hidden=4, name="fc2")
    rng = np.random.RandomState(seed)
    shapes, _, _ = out.infer_shape(data=(1, 8))
    params = {n: nd.array(rng.randint(-2, 3, s).astype(np.float32))
              for n, s in zip(out.list_arguments(), shapes) if n != "data"}
    return out, params


def main(argv):
    smoke = "--smoke" in argv
    import jax
    from mxnet_tpu import health, telemetry
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.serving import (AdmissionError, ModelRegistry,
                                   QueueFullError)

    telemetry.enable()
    health.enable()
    health.reset()

    devices = jax.devices()
    assert len(devices) >= 2, "need >=2 (virtual) devices, have %d" \
        % len(devices)
    mesh = make_mesh({"tp": 2}, devices=devices[:2])

    reg = ModelRegistry()
    sym1, p1 = build_int_mlp(seed=11)
    sym2, p2 = build_int_mlp(seed=22)
    # rt: plain single-chip; bulk: the SAME architecture sharded tp=2
    reg.register("rt", sym1.tojson(), p1, {"data": (8,)},
                 max_batch_size=4, batch_timeout_ms=1, queue_depth=8,
                 start=False)
    reg.register("bulk", sym2.tojson(), p2, {"data": (8,)}, mesh=mesh,
                 max_batch_size=4, batch_timeout_ms=1)
    rt = reg.get("rt")
    rt.warmup()                      # compiled, but no workers yet
    result = {"probe": "serving", "smoke": smoke}

    try:
        # -- 1. mesh parity ------------------------------------------------
        rng = np.random.RandomState(0)
        rounds = 4 if smoke else 16
        for n in (1, 2, 4):
            X = rng.randint(-2, 3, (n, 8)).astype(np.float32)
            want = Predictor(sym2.tojson(), p2,
                             input_shapes={"data": (n, 8)}) \
                .forward(data=X)[0].asnumpy()
            got = reg.predict({"data": X}, model="bulk")[0]
            assert np.array_equal(got, want), \
                "mesh output diverged from single-chip at rows=%d" % n
        result["mesh_parity"] = True
        result["mesh"] = reg.get("bulk").stats()["mesh"]

        # -- 2. deterministic saturation: shed before deadline-miss --------
        X1 = np.zeros((1, 8), np.float32)
        admitted = []
        for _ in range(4):           # 4/8 occupancy -> shed level 1
            admitted.append(rt.submit({"data": X1}, deadline_ms=30000,
                                      slo_class="realtime"))
        shed = 0
        try:
            rt.submit({"data": X1}, slo_class="batch")
        except AdmissionError:
            shed += 1
        assert shed == 1, "batch traffic was admitted past the shed level"
        for _ in range(4):           # realtime rides to a full queue
            try:
                admitted.append(rt.submit({"data": X1}, deadline_ms=30000,
                                          slo_class="realtime"))
            except QueueFullError:
                break
        rt.start(warmup=False)       # workers drain the saturated queue
        for r in admitted:
            r.result(timeout=60.0)
        assert all(r.outcome == "ok" for r in admitted)
        misses = telemetry.value("serving_requests_total",
                                 outcome="deadline")
        assert misses == 0, "deadline misses under saturation: %r" % misses
        assert telemetry.value("serving_shed_total", slo_class="batch") >= 1
        result["shed_before_deadline_miss"] = True
        result["admitted_realtime"] = len(admitted)
        result["shed_batch"] = int(telemetry.value(
            "serving_shed_total", slo_class="batch"))

        # -- 3. zero post-warmup compiles across the registry --------------
        warm = telemetry.value("op_jit_cache_misses_total",
                               op="Executor::Forward")
        for i in range(rounds):
            n = int(rng.choice([1, 2, 3, 4]))
            X = rng.randint(-2, 3, (n, 8)).astype(np.float32)
            reg.predict({"data": X}, model=("rt", "bulk")[i % 2],
                        slo_class=("realtime", "standard")[i % 2])
        after = telemetry.value("op_jit_cache_misses_total",
                                op="Executor::Forward")
        assert after == warm, "post-warmup compiles: %d" % (after - warm)
        for name in ("rt", "bulk"):
            hc = reg.get(name).health()
            assert hc["post_warmup_compiles"] == 0, (name, hc)
        result["post_warmup_compiles"] = 0

        # -- 4. per-model /programz attribution ----------------------------
        progs = health.programs()
        for m in ("rt", "bulk"):
            for b in (1, 2, 4):
                key = "serving:%s:b%d:forward" % (m, b)
                assert key in progs, "missing %s on /programz" % key
        result["programs"] = sorted(
            n for n in progs if n.startswith("serving:"))

        # -- 5. bf16 params: exact parity, zero post-warmup compiles -------
        from mxnet_tpu import amp
        p1_bf16 = {n: v.astype(amp.compute_dtype()) for n, v in p1.items()}
        reg.register("rt16", sym1.tojson(), p1_bf16, {"data": (8,)},
                     max_batch_size=4, batch_timeout_ms=1)
        for n in (1, 2, 4):
            X = rng.randint(-2, 3, (n, 8)).astype(np.float32)
            want = reg.predict({"data": X}, model="rt")[0]
            got = reg.predict({"data": X}, model="rt16")[0]
            assert np.array_equal(got, want), \
                "bf16 integer weights diverged from fp32 at rows=%d" % n
        warm = telemetry.value("op_jit_cache_misses_total",
                               op="Executor::Forward")
        for i in range(rounds):
            n = int(rng.choice([1, 2, 4]))
            X = rng.randint(-2, 3, (n, 8)).astype(np.float32)
            reg.predict({"data": X}, model="rt16")
        after = telemetry.value("op_jit_cache_misses_total",
                                op="Executor::Forward")
        assert after == warm, \
            "bf16 post-warmup compiles: %d" % (after - warm)
        assert reg.get("rt16").health()["post_warmup_compiles"] == 0
        result["bf16_parity"] = True
        result["bf16_post_warmup_compiles"] = 0
    finally:
        reg.stop_all()
        health.disable()

    result["ok"] = True
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
