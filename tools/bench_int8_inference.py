#!/usr/bin/env python
"""int8 vs bf16 ResNet-50 inference on the real chip (VERDICT r2 item 6).

Reference analog: docs/faq/perf.md:163-177 publishes fp16 inference at
1.9x fp32 on V100; the TPU equivalent claim is the MXU's native
s8xs8->s32 path.  This bench quantizes the model zoo ResNet-50 with the
calibration pass (contrib/quantization.py) and times both variants with
an in-jit data-dependent chain (each forward feeds a perturbation of the
previous logits back into the input, so steps serialize on-device),
measured differentially (2N vs N chains cancels the ~100 ms tunnel RTT).
Inference has no donated-state chain, so bench.py's window protocol
cannot serialize it — this is the honest timing for forward-only
workloads.  Each dtype variant runs in its own subprocess (full-model
chains at batch 128 exhaust HBM when both live in one process).

Run:  python tools/bench_int8_inference.py
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPS = 3
CHAIN = 24


def chain_time(plan_fn, x0, chain=CHAIN):
    import jax
    import jax.numpy as jnp

    def build(n):
        @jax.jit
        def f(x):
            def body(c, _):
                out = plan_fn(c)
                eps = (jnp.sum(out.astype(jnp.float32)) * 1e-12).astype(
                    c.dtype)
                return c + eps, None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(y.astype(jnp.float32))
        return f

    f1, f2 = build(chain), build(2 * chain)
    float(f1(x0)); float(f2(x0))
    b1 = b2 = 1e9
    for _ in range(REPS):
        t0 = time.perf_counter(); float(f1(x0))
        b1 = min(b1, time.perf_counter() - t0)
        t0 = time.perf_counter(); float(f2(x0))
        b2 = min(b2, time.perf_counter() - t0)
    return max(b2 - b1, 1e-9) / chain


def run_variant(variant):
    """Executed in a subprocess: print one JSON line for the variant."""
    import numpy as np

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import symbol as S
    from mxnet_tpu.executor import _Plan
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.io import NDArrayIter
    import jax.numpy as jnp

    ctx = mx.tpu(0) if mx.context.num_tpus() else mx.cpu(0)
    batch = 128 if ctx.device_type == "tpu" else 8
    size = 224 if ctx.device_type == "tpu" else 32

    net = vision.resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize()
    x = mx.nd.random.uniform(0, 1, shape=(batch, 3, size, size), ctx=ctx)
    net(x).wait_to_read()

    sym = net(S.var("data"))
    params = net.collect_params()
    args = {n: params[n].data()._data for n in sym.list_arguments()
            if n != "data"}
    auxs = {n: params[n].data()._data
            for n in sym.list_auxiliary_states()}

    if variant == "bf16":
        plan = _Plan(sym, train=False)
        vals = {n: v.astype(jnp.bfloat16) for n, v in args.items()}
        avals = {n: v.astype(jnp.bfloat16) for n, v in auxs.items()}
        keys = jnp.zeros((max(1, plan.n_rng), 2), jnp.uint32)

        def fwd(data):
            outs, _ = plan.execute({**vals, "data": data}, avals, keys)
            return outs[0]

        xb = x._data.astype(jnp.bfloat16)
        t = chain_time(fwd, xb)
        t2 = chain_time(fwd, xb)       # same-session repeat
        worst = max(t, t2)
        print(json.dumps({"variant": "bf16", "ms": worst * 1e3,
                          "ms_first": t * 1e3, "ms_repeat": t2 * 1e3,
                          "img_per_sec": batch / worst, "batch": batch}))
        return 0

    # int8
    import numpy as np
    # small calib batch: the calibration pass materializes every
    # conv/FC output at once (53 layers x batch) — batch 32 at 224px
    # exhausts HBM
    calib = NDArrayIter(data=x.asnumpy()[:8], batch_size=8)
    # fuse=True: the static-scale pipeline — BN folded into conv weights,
    # requantize+ReLU epilogues fused per conv, int8 residual adds
    # (round-3 verdict item 1: the unfused dynamic-range form measured
    # 0.80x bf16 because of per-layer min/max + f32 glue)
    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        sym, {n: mx.nd.array(np.asarray(v, np.float32))
              for n, v in args.items()},
        {n: mx.nd.array(np.asarray(v, np.float32))
         for n, v in auxs.items()},
        ctx=ctx, calib_mode="naive", calib_data=calib,
        num_calib_examples=8, fuse=True)
    qplan = _Plan(qsym, train=False)
    qvals = {n: (v._data if hasattr(v, "_data") else jnp.asarray(v))
             for n, v in qargs.items()}
    qaux = {n: (v._data if hasattr(v, "_data") else jnp.asarray(v))
            for n, v in qauxs.items()}
    qkeys = jnp.zeros((max(1, qplan.n_rng), 2), jnp.uint32)

    def fwdq(data):
        outs, _ = qplan.execute({**qvals, "data": data}, qaux, qkeys)
        return outs[0]

    t = chain_time(fwdq, x._data)
    t2 = chain_time(fwdq, x._data)   # same-session repeat: within-process
    worst = max(t, t2)
    ref = net(x).asnumpy().argmax(1)
    # jit: the eager per-op replay would hold every layer's s32
    # activations live at once and exhaust HBM at batch 128
    q_top1 = np.asarray(jax.jit(fwdq)(x._data)).argmax(1)
    agree = float((q_top1 == ref).mean())
    print(json.dumps({"variant": "int8", "ms": worst * 1e3,
                      "ms_first": t * 1e3, "ms_repeat": t2 * 1e3,
                      "img_per_sec": batch / worst,
                      "top1_agreement_vs_fp32": agree, "batch": batch}))
    return 0


def main():
    if len(sys.argv) > 1 and sys.argv[1] in ("bf16", "int8"):
        return run_variant(sys.argv[1])

    env = dict(os.environ)
    extra = [REPO]
    if os.path.isdir("/root/.axon_site"):   # axon PJRT sitecustomize
        extra.append("/root/.axon_site")
    env["PYTHONPATH"] = os.pathsep.join(
        extra + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    n_runs = {"bf16": 3, "int8": 3}    # both variants: 3 processes x 2
    rows = {}                          # measurements — the bimodal
    for variant in ("bf16", "int8"):   # lowering lands on either side
        runs = []
        for _ in range(n_runs[variant]):
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), variant],
                env=env, capture_output=True, text=True, timeout=1500)
            if p.returncode != 0:
                runs.append({"error": p.stderr[-400:]})
                continue
            runs.append(json.loads(p.stdout.strip().splitlines()[-1]))
        ok = [r for r in runs if "error" not in r]
        if not ok:
            rows[variant] = runs[0]
            continue
        # headline = the CONSERVATIVE (slowest) clean observation,
        # consistent across ms and img_per_sec; all clean runs kept for
        # the variance story, failures counted
        rows[variant] = dict(max(ok, key=lambda r: r["ms"]))
        if len(runs) > 1:
            rows[variant]["all_ms_first"] = [r.get("ms_first") for r in ok]
            rows[variant]["all_ms_repeat"] = [r.get("ms_repeat")
                                              for r in ok]
            rows[variant]["failed_runs"] = len(runs) - len(ok)

    out = {"metric": "resnet50_int8_vs_bf16_inference"}
    out.update(rows)
    if "error" not in rows.get("bf16", {}) and \
            "error" not in rows.get("int8", {}):
        out["int8_speedup"] = round(rows["bf16"]["ms"]
                                    / rows["int8"]["ms"], 3)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
