#!/usr/bin/env python
"""Probe: run ``bench.py`` with the health monitor on and validate the
exported health evidence.

``--smoke`` shrinks the bench (tiny batch/image, few iters, no LSTM /
phase-breakdown satellites) so the probe finishes in a couple of minutes
on a CPU dev box; without it the full resnet50 bench runs.  Asserts the
acceptance contract of the health PR: the bench JSON carries a nested
``health`` object with live XLA-counted ``program_flops`` /
``program_hbm_bytes``, a ``step_mfu_pct`` gauge value, a verdict cause,
and the measured monitor-overhead A/B.

Usage:
    python tools/probe_health.py --smoke
    python tools/probe_health.py            # full resnet50 bench
"""
import json
import os
import subprocess
import sys

REQUIRED_KEYS = ("step_mfu_pct", "verdict", "step_seconds_ewma",
                 "monitor_overhead_pct", "program_flops",
                 "program_hbm_bytes", "donation_leaks")
HBM_KINDS = ("args", "output", "temp")


def main(argv):
    smoke = "--smoke" in argv
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["BENCH_HEALTH"] = "1"
    if smoke:
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update({"BENCH_BATCH": "8", "BENCH_IMAGE": "64",
                    "BENCH_ITERS": "3", "BENCH_WARMUP": "2",
                    "BENCH_LSTM": "0", "BENCH_PHASES": "0"})
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        env=env, cwd=repo, capture_output=True, text=True,
        timeout=900 if smoke else 3000)
    if proc.returncode != 0:
        print("bench failed (rc=%d)\n--- stdout ---\n%s\n--- stderr ---\n%s"
              % (proc.returncode, proc.stdout[-4000:], proc.stderr[-4000:]))
        return proc.returncode
    rec = json.loads(proc.stdout.strip().splitlines()[-1])

    health = rec.get("health")
    assert isinstance(health, dict), "bench JSON carries no health block"
    missing = [k for k in REQUIRED_KEYS if k not in health]
    assert not missing, "health block missing keys %s: %r" \
        % (missing, health)
    assert health["step_mfu_pct"] is not None and health["step_mfu_pct"] > 0
    assert health["verdict"] in ("compute_bound", "input_bound",
                                 "sync_bound", "compile_bound")
    assert health["program_flops"], "no program registered its cost"
    for name, flops in health["program_flops"].items():
        assert flops > 0, "program %s reports zero flops" % name
        hbm = health["program_hbm_bytes"][name]
        assert all(k in hbm for k in HBM_KINDS), hbm
        assert hbm["args"] > 0, "program %s reports empty arguments" % name
    assert health["donation_leaks"] == [], \
        "donation chain broke: %s" % health["donation_leaks"]
    print(json.dumps({"probe": "health", "smoke": smoke, "ok": True,
                      "step_mfu_pct": health["step_mfu_pct"],
                      "verdict": health["verdict"],
                      "monitor_overhead_pct":
                          health["monitor_overhead_pct"],
                      "programs": sorted(health["program_flops"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
