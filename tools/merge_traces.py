#!/usr/bin/env python
"""Merge per-process Chrome traces from a dist run into one clock-aligned
trace, or schema-check trace files (``--validate``).

Each process of a ``dist_async`` run under ``MXNET_TRACING=1`` +
``MXNET_TRACE_DIR=<dir>`` dumps its own ``trace_worker<r>.json`` /
``trace_server.json`` (see ``mxnet_tpu.tracing.dump_process_trace``).
Timestamps are relative to each process's own perf_counter origin, so the
files cannot be overlaid as-is; ``profiler.dump`` records that origin as
unix epoch in ``metadata.t0_unix_us``, and this tool shifts every event by
the per-file offset to the earliest origin.  Rows are keyed by rank: the
server becomes pid 1 (sorted first), worker r becomes pid 100+r, each with
a ``process_name`` metadata event Perfetto displays.  Span/flow ids embed
the producing pid, so cross-process flow links (a worker's ``s`` ending at
a server handler's ``f``) survive the merge without remapping.

Usage:
    python tools/merge_traces.py -o merged.json trace_worker0.json \\
        trace_worker1.json trace_server.json
    python tools/merge_traces.py --validate merged.json

stdlib-only on purpose: usable on any machine holding the trace files.
"""
from __future__ import annotations

import argparse
import json
import sys

# phases we emit plus common Chrome-trace ones a hand-built file may use
_KNOWN_PHASES = frozenset("XBEiIsftMCbenO")
_FLOW_PHASES = frozenset("stf")


def load_trace(path):
    with open(path) as f:
        return json.load(f)


def _events_of(trace):
    if isinstance(trace, list):  # bare-array Chrome trace form
        return trace
    if isinstance(trace, dict):
        return trace.get("traceEvents")
    return None


def validate_trace(trace):
    """Schema-check one loaded trace; returns a list of error strings.

    Checks: traceEvents is a list of objects with known ``ph``, string
    names, numeric ``ts`` (and ``dur`` for X spans); flow events carry an
    ``id``; flow-start ids are unique; every flow step/end has a matching
    start.
    """
    errors = []
    events = _events_of(trace)
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    start_ids = set()
    continuations = []  # (index, ph, id) for t/f events
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            errors.append("event #%d: not an object" % i)
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PHASES:
            errors.append("event #%d: unknown phase %r" % (i, ph))
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            errors.append("event #%d (%s): missing name" % (i, ph))
        if ph != "M" and not isinstance(e.get("ts"), (int, float)):
            errors.append("event #%d (%s): missing numeric ts" % (i, ph))
        if ph == "X" and not isinstance(e.get("dur"), (int, float)):
            errors.append("event #%d (X): missing numeric dur" % i)
        if ph in _FLOW_PHASES:
            fid = e.get("id")
            if not isinstance(fid, (str, int)):
                errors.append("event #%d (%s): flow event without id"
                              % (i, ph))
                continue
            if ph == "s":
                if fid in start_ids:
                    errors.append("event #%d (s): duplicate flow-start id %r"
                                  % (i, fid))
                start_ids.add(fid)
            else:
                continuations.append((i, ph, fid))
    for i, ph, fid in continuations:
        if fid not in start_ids:
            errors.append("event #%d (%s): flow id %r has no matching start"
                          % (i, ph, fid))
    return errors


def merge(traces):
    """Merge loaded per-process traces into one Chrome trace dict."""
    bases = []
    for tr in traces:
        meta = tr.get("metadata", {}) if isinstance(tr, dict) else {}
        bases.append(float(meta.get("t0_unix_us", 0.0) or 0.0))
    known = [b for b in bases if b > 0]
    base0 = min(known) if known else 0.0
    out = []
    used_pids = set()
    for idx, tr in enumerate(traces):
        meta = tr.get("metadata", {}) if isinstance(tr, dict) else {}
        role = str(meta.get("role", "worker"))
        rank = int(meta.get("rank", idx) or 0)
        pid = 1 if role == "server" else 100 + rank
        while pid in used_pids:  # duplicate role/rank inputs still merge
            pid += 1000
        used_pids.add(pid)
        label = "server" if role == "server" else "%s %d" % (role, rank)
        out.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "ts": 0, "args": {"name": label}})
        out.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                    "tid": 0, "ts": 0,
                    "args": {"sort_index": -1 if role == "server" else rank}})
        shift = (bases[idx] - base0) if bases[idx] > 0 else 0.0
        for e in _events_of(tr) or []:
            e = dict(e)
            e["pid"] = pid
            if e.get("ph") != "M" and isinstance(e.get("ts"), (int, float)):
                e["ts"] = e["ts"] + shift
            out.append(e)
    return {"traceEvents": out, "displayTimeUnit": "us"}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-process mxnet_tpu traces / validate a trace")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the input files instead of merging")
    ap.add_argument("-o", "--output", default="merged_trace.json",
                    help="merged trace path (merge mode)")
    ap.add_argument("inputs", nargs="+", help="trace json files")
    args = ap.parse_args(argv)

    if args.validate:
        ok = True
        for path in args.inputs:
            try:
                errs = validate_trace(load_trace(path))
            except (OSError, ValueError) as e:
                errs = ["unreadable: %s" % e]
            for err in errs:
                print("%s: %s" % (path, err))
            print("%s: %s" % (path, "OK" if not errs else "INVALID"))
            ok = ok and not errs
        return 0 if ok else 1

    merged = merge([load_trace(p) for p in args.inputs])
    with open(args.output, "w") as f:
        json.dump(merged, f)
    print("wrote %s (%d events from %d files)"
          % (args.output, len(merged["traceEvents"]), len(args.inputs)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
