#!/usr/bin/env python
"""Merge per-process Chrome traces from a dist run into one clock-aligned
trace, or schema-check trace files (``--validate``).  Validate mode also
recognizes flight-recorder dumps (``reason`` + ``events``) and checks
their ``programs`` / ``atlas`` / ``timeseries`` post-mortem blocks.

Each process of a ``dist_async`` run under ``MXNET_TRACING=1`` +
``MXNET_TRACE_DIR=<dir>`` dumps its own ``trace_worker<r>.json`` /
``trace_server.json`` (see ``mxnet_tpu.tracing.dump_process_trace``).
Timestamps are relative to each process's own perf_counter origin, so the
files cannot be overlaid as-is; ``profiler.dump`` records that origin as
unix epoch in ``metadata.t0_unix_us``, and this tool shifts every event by
the per-file offset to the earliest origin.  Rows are keyed by rank: the
server becomes pid 1 (sorted first), worker r becomes pid 100+r, each with
a ``process_name`` metadata event Perfetto displays.  Span/flow ids embed
the producing pid, so cross-process flow links (a worker's ``s`` ending at
a server handler's ``f``) survive the merge without remapping.

Usage:
    python tools/merge_traces.py -o merged.json trace_worker0.json \\
        trace_worker1.json trace_server.json
    python tools/merge_traces.py --validate merged.json

stdlib-only on purpose: usable on any machine holding the trace files.
"""
from __future__ import annotations

import argparse
import json
import sys

# phases we emit plus common Chrome-trace ones a hand-built file may use
_KNOWN_PHASES = frozenset("XBEiIsftMCbenO")
_FLOW_PHASES = frozenset("stf")


def load_trace(path):
    with open(path) as f:
        return json.load(f)


def _events_of(trace):
    if isinstance(trace, list):  # bare-array Chrome trace form
        return trace
    if isinstance(trace, dict):
        return trace.get("traceEvents")
    return None


def validate_trace(trace):
    """Schema-check one loaded trace; returns a list of error strings.

    Checks: traceEvents is a list of objects with known ``ph``, string
    names, numeric ``ts`` (and ``dur`` for X spans); flow events carry an
    ``id``; flow-start ids are unique; every flow step/end has a matching
    start.
    """
    errors = []
    events = _events_of(trace)
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    start_ids = set()
    continuations = []  # (index, ph, id) for t/f events
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            errors.append("event #%d: not an object" % i)
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PHASES:
            errors.append("event #%d: unknown phase %r" % (i, ph))
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            errors.append("event #%d (%s): missing name" % (i, ph))
        if ph != "M" and not isinstance(e.get("ts"), (int, float)):
            errors.append("event #%d (%s): missing numeric ts" % (i, ph))
        if ph == "X" and not isinstance(e.get("dur"), (int, float)):
            errors.append("event #%d (X): missing numeric dur" % i)
        if ph in _FLOW_PHASES:
            fid = e.get("id")
            if not isinstance(fid, (str, int)):
                errors.append("event #%d (%s): flow event without id"
                              % (i, ph))
                continue
            if ph == "s":
                if fid in start_ids:
                    errors.append("event #%d (s): duplicate flow-start id %r"
                                  % (i, fid))
                start_ids.add(fid)
            else:
                continuations.append((i, ph, fid))
    for i, ph, fid in continuations:
        if fid not in start_ids:
            errors.append("event #%d (%s): flow id %r has no matching start"
                          % (i, ph, fid))
    return errors


def is_flight_dump(doc):
    """A FlightRecorder dump (tracing.FlightRecorder.dump), not a Chrome
    trace: ring events plus post-mortem blocks."""
    return isinstance(doc, dict) and "reason" in doc and "events" in doc \
        and "traceEvents" not in doc


def validate_flight_dump(doc):
    """Schema-check one flight-recorder dump; returns error strings.

    Covers the ring events and every post-mortem block the recorder has
    grown since PR 3: ``programs`` (health cost records), ``atlas``
    (per-scope attribution tables), ``timeseries`` (the trailing metric
    window) and ``fleet`` (the collector's merged target table, derived
    aggregates and alert state) — so a merged multi-process dump set
    fails loudly on a malformed block instead of silently dropping
    evidence."""
    errors = []
    if not isinstance(doc.get("events"), list):
        errors.append("events missing or not a list")
    else:
        for i, e in enumerate(doc["events"]):
            if not isinstance(e, dict):
                errors.append("events[%d]: not an object" % i)
                continue
            if not isinstance(e.get("name"), str) or not e["name"]:
                errors.append("events[%d]: missing name" % i)
            for k in ("ts_us", "dur_us"):
                if not isinstance(e.get(k), (int, float)):
                    errors.append("events[%d]: missing numeric %s" % (i, k))
    for k in ("reason", "role"):
        if not isinstance(doc.get(k), str):
            errors.append("%s missing or not a string" % k)
    if not isinstance(doc.get("unix_time"), (int, float)):
        errors.append("unix_time missing or not numeric")

    progs = doc.get("programs")
    if progs is not None:
        if not isinstance(progs, dict):
            errors.append("programs: not an object")
        else:
            for name, pc in progs.items():
                if not isinstance(pc, dict):
                    errors.append("programs[%s]: not an object" % name)
                    continue
                for k in ("flops", "arg_bytes", "out_bytes"):
                    if not isinstance(pc.get(k), (int, float)):
                        errors.append("programs[%s]: missing numeric %s"
                                      % (name, k))
                if pc.get("env") is not None \
                        and not isinstance(pc["env"], dict):
                    errors.append("programs[%s]: env not an object" % name)

    atlas = doc.get("atlas")
    if atlas is not None:
        if not isinstance(atlas, dict):
            errors.append("atlas: not an object")
        else:
            for name, a in atlas.items():
                if not isinstance(a, dict):
                    errors.append("atlas[%s]: not an object" % name)
                    continue
                if not isinstance(a.get("coverage_pct"), (int, float)):
                    errors.append("atlas[%s]: missing numeric coverage_pct"
                                  % name)
                if not isinstance(a.get("scopes"), list):
                    errors.append("atlas[%s]: scopes not a list" % name)
                else:
                    for j, row in enumerate(a["scopes"]):
                        if not isinstance(row, dict) or \
                                not isinstance(row.get("flops"),
                                               (int, float)):
                            errors.append(
                                "atlas[%s].scopes[%d]: bad row"
                                % (name, j))

    ts = doc.get("timeseries")
    if ts is not None:
        if not isinstance(ts, dict):
            errors.append("timeseries: not an object")
        else:
            if not isinstance(ts.get("window_seconds"), (int, float)):
                errors.append("timeseries: missing numeric window_seconds")
            series = ts.get("series")
            if not isinstance(series, dict):
                errors.append("timeseries: series not an object")
            else:
                for key, s in series.items():
                    pts = s.get("points") if isinstance(s, dict) else None
                    if not isinstance(pts, list):
                        errors.append("timeseries[%s]: points not a list"
                                      % key)
                        continue
                    for j, p in enumerate(pts):
                        if (not isinstance(p, list) or len(p) != 2
                                or not isinstance(p[0], (int, float))
                                or not (p[1] is None
                                        or isinstance(p[1],
                                                      (int, float)))):
                            errors.append(
                                "timeseries[%s].points[%d]: expected "
                                "[t, value|null]" % (key, j))
                            break

    fleet = doc.get("fleet")
    if fleet is not None:
        if not isinstance(fleet, dict):
            errors.append("fleet: not an object")
        else:
            targets = fleet.get("targets")
            if not isinstance(targets, dict):
                errors.append("fleet: targets not an object")
            else:
                for tid, t in targets.items():
                    if not isinstance(t, dict):
                        errors.append("fleet.targets[%s]: not an object"
                                      % tid)
                        continue
                    for k in ("role", "port"):
                        if t.get(k) is None:
                            errors.append("fleet.targets[%s]: missing %s"
                                          % (tid, k))
            if not isinstance(fleet.get("aggregates"), dict):
                errors.append("fleet: aggregates not an object")
            alerts = fleet.get("alerts")
            if not isinstance(alerts, dict) \
                    or not isinstance(alerts.get("active"), list):
                errors.append("fleet: alerts.active not a list")
            else:
                for j, a in enumerate(alerts["active"]):
                    if not isinstance(a, dict) \
                            or not isinstance(a.get("rule"), str):
                        errors.append("fleet.alerts.active[%d]: missing "
                                      "rule" % j)
    return errors


def merge(traces):
    """Merge loaded per-process traces into one Chrome trace dict."""
    bases = []
    for tr in traces:
        meta = tr.get("metadata", {}) if isinstance(tr, dict) else {}
        bases.append(float(meta.get("t0_unix_us", 0.0) or 0.0))
    known = [b for b in bases if b > 0]
    base0 = min(known) if known else 0.0
    out = []
    used_pids = set()
    for idx, tr in enumerate(traces):
        meta = tr.get("metadata", {}) if isinstance(tr, dict) else {}
        role = str(meta.get("role", "worker"))
        rank = int(meta.get("rank", idx) or 0)
        pid = 1 if role == "server" else 100 + rank
        while pid in used_pids:  # duplicate role/rank inputs still merge
            pid += 1000
        used_pids.add(pid)
        label = "server" if role == "server" else "%s %d" % (role, rank)
        out.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "ts": 0, "args": {"name": label}})
        out.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                    "tid": 0, "ts": 0,
                    "args": {"sort_index": -1 if role == "server" else rank}})
        shift = (bases[idx] - base0) if bases[idx] > 0 else 0.0
        for e in _events_of(tr) or []:
            e = dict(e)
            e["pid"] = pid
            if e.get("ph") != "M" and isinstance(e.get("ts"), (int, float)):
                e["ts"] = e["ts"] + shift
            out.append(e)
    return {"traceEvents": out, "displayTimeUnit": "us"}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-process mxnet_tpu traces / validate a trace")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the input files instead of merging")
    ap.add_argument("-o", "--output", default="merged_trace.json",
                    help="merged trace path (merge mode)")
    ap.add_argument("inputs", nargs="+", help="trace json files")
    args = ap.parse_args(argv)

    if args.validate:
        ok = True
        for path in args.inputs:
            try:
                doc = load_trace(path)
                errs = (validate_flight_dump(doc) if is_flight_dump(doc)
                        else validate_trace(doc))
            except (OSError, ValueError) as e:
                errs = ["unreadable: %s" % e]
            for err in errs:
                print("%s: %s" % (path, err))
            print("%s: %s" % (path, "OK" if not errs else "INVALID"))
            ok = ok and not errs
        return 0 if ok else 1

    merged = merge([load_trace(p) for p in args.inputs])
    with open(args.output, "w") as f:
        json.dump(merged, f)
    print("wrote %s (%d events from %d files)"
          % (args.output, len(merged["traceEvents"]), len(args.inputs)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
