#!/usr/bin/env python
"""Serving load generator: dynamic batching vs the serial Predictor.

Measures what the serving layer is *for*: request throughput and tail
latency under concurrency.  Four scenarios over the same model:

- **serial** — one thread calling ``Predictor.forward`` per request: the
  baseline an embedder gets without the serving layer.
- **closed** — N closed-loop clients issuing back-to-back requests into a
  :class:`ModelServer` (each client waits for its response before sending
  the next): measures coalescing gain at saturation (and doubles as the
  capacity estimate the sweep scales from).
- **open** — Poisson arrivals at a target rate submitted asynchronously:
  measures tail latency and rejection behaviour at a fixed offered load
  (closed-loop self-throttles and can't show overload).
- **sweep** — open-loop Poisson points at multiples of measured capacity,
  up to >10x, with a mixed SLO-class workload (realtime with a deadline,
  standard, batch): the saturation curve (offered vs achieved QPS) plus
  per-class p50/p99 and shed rate at every point.  The story it must
  tell: past saturation the scheduler sheds ``batch``/``standard`` with
  429s while realtime latency stays bounded — overload degrades the
  cheap traffic, not the tail.

Reports p50/p90/p99/mean end-to-end latency (ms), throughput (req/s and
rows/s), realized mean batch size, padding overhead, and the compiled
program count (``op_jit_cache_misses_total`` for ``Executor::Forward``) —
one JSON document on stdout (or ``--out``).  ``--history-out`` also
writes the canonical sentinel round (``serving_p99_ms_realtime``,
``serving_shed_rate_overload``, ...) for ``bench_history/``.

Run:  python tools/bench_serving.py [--smoke] [--out results.json]
      python tools/bench_serving.py --smoke \\
          --history-out bench_history/serving_r14.canonical.json
"""
import argparse
import json
import os
import queue
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd, telemetry  # noqa: E402
from mxnet_tpu.predictor import Predictor  # noqa: E402
from mxnet_tpu.serving import (AdmissionError, ModelServer,  # noqa: E402
                               QueueFullError, ServingError)

S = mx.symbol


def build_model(in_dim, hidden, classes):
    """data (n, in_dim) -> FC(hidden) relu x2 -> FC(classes) softmax."""
    x = S.var("data")
    h = S.Activation(S.FullyConnected(x, num_hidden=hidden, name="fc1"),
                     act_type="relu")
    h = S.Activation(S.FullyConnected(h, num_hidden=hidden, name="fc2"),
                     act_type="relu")
    out = S.softmax(S.FullyConnected(h, num_hidden=classes, name="fc3"),
                    axis=1, name="prob")
    rng = np.random.RandomState(0)
    shapes, _, _ = out.infer_shape(data=(1, in_dim))
    params = {n: nd.array(rng.uniform(-0.1, 0.1, s).astype(np.float32))
              for n, s in zip(out.list_arguments(), shapes) if n != "data"}
    return out, params


def percentiles(lat_s):
    if not lat_s:
        return {}
    a = np.asarray(lat_s) * 1e3
    return {"p50_ms": float(np.percentile(a, 50)),
            "p90_ms": float(np.percentile(a, 90)),
            "p99_ms": float(np.percentile(a, 99)),
            "mean_ms": float(a.mean()),
            "max_ms": float(a.max())}


def bench_serial(sym, params, in_dim, requests):
    """One request at a time through a batch-1 Predictor."""
    pred = Predictor(sym.tojson(), params, input_shapes={"data": (1, in_dim)})
    rng = np.random.RandomState(1)
    X = rng.uniform(-1, 1, (requests, 1, in_dim)).astype(np.float32)
    pred.forward(data=X[0])[0].asnumpy()          # compile outside timing
    lat = []
    t0 = time.perf_counter()
    for i in range(requests):
        t = time.perf_counter()
        pred.forward(data=X[i])[0].asnumpy()
        lat.append(time.perf_counter() - t)
    wall = time.perf_counter() - t0
    return {"requests": requests, "wall_s": round(wall, 4),
            "throughput_rps": round(requests / wall, 1), **percentiles(lat)}


def _serving_counters():
    def misses():
        return telemetry.value("op_jit_cache_misses_total",
                               op="Executor::Forward")
    batch_hist = telemetry.registry().get("serving_batch_rows")
    pad = lambda: telemetry.value("serving_padding_rows_total")  # noqa: E731
    return misses, batch_hist, pad


def bench_closed(server, in_dim, clients, requests_per_client):
    """Closed loop: each client waits for its response before the next."""
    misses, batch_hist, pad = _serving_counters()
    h0, m0, p0 = batch_hist.get(), misses(), pad()
    rng = np.random.RandomState(2)
    X = rng.uniform(-1, 1, (clients, in_dim)).astype(np.float32)
    lat, errors, lock = [], [], threading.Lock()

    def client(i):
        mine = []
        for _ in range(requests_per_client):
            t = time.perf_counter()
            try:
                server.predict({"data": X[i]}, timeout=120.0)
            except ServingError as e:
                with lock:
                    errors.append(repr(e))
                return
            mine.append(time.perf_counter() - t)
        with lock:
            lat.extend(mine)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    h1 = batch_hist.get()
    batches = h1["count"] - h0["count"]
    rows = h1["sum"] - h0["sum"]
    total = clients * requests_per_client
    return {"clients": clients, "requests": total,
            "errors": len(errors), "wall_s": round(wall, 4),
            "throughput_rps": round(total / wall, 1),
            "batches": int(batches),
            "mean_batch_rows": round(rows / max(batches, 1), 2),
            "padding_rows": int(p0 is not None and pad() - p0),
            "new_compiles": misses() - m0, **percentiles(lat)}


def bench_open(server, in_dim, rate_rps, duration_s, deadline_ms):
    """Open loop: Poisson arrivals at ``rate_rps`` regardless of
    completions; waits happen on collector threads so arrivals never
    self-throttle."""
    misses, batch_hist, pad = _serving_counters()
    h0, p0 = batch_hist.get(), pad()
    rng = np.random.RandomState(3)
    X = rng.uniform(-1, 1, (64, in_dim)).astype(np.float32)
    lat, lock = [], threading.Lock()
    outcomes = {"ok": 0, "rejected": 0, "deadline": 0, "error": 0}
    pending = []

    def collect(req, t_submit):
        try:
            req.result(120.0)
            with lock:
                outcomes["ok"] += 1
                lat.append(time.perf_counter() - t_submit)
        except ServingError:
            with lock:
                outcomes[req.outcome if req.outcome in outcomes
                         else "error"] += 1

    t0 = time.perf_counter()
    end = t0 + duration_s
    n = 0
    next_t = t0
    while True:
        now = time.perf_counter()
        if now >= end:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.001))
            continue
        next_t += rng.exponential(1.0 / rate_rps)
        t_submit = time.perf_counter()
        try:
            req = server.submit({"data": X[n % len(X)]},
                                deadline_ms=deadline_ms or None)
        except ServingError as e:
            with lock:
                outcomes["rejected" if "queue full" in str(e)
                         else "error"] += 1
            continue
        finally:
            n += 1
        t = threading.Thread(target=collect, args=(req, t_submit))
        t.start()
        pending.append(t)
    for t in pending:
        t.join(120.0)
    wall = time.perf_counter() - t0
    h1 = batch_hist.get()
    batches = h1["count"] - h0["count"]
    rows = h1["sum"] - h0["sum"]
    return {"offered_rps": rate_rps, "duration_s": duration_s,
            "submitted": n, "outcomes": dict(outcomes),
            "achieved_rps": round(outcomes["ok"] / wall, 1),
            "batches": int(batches),
            "mean_batch_rows": round(rows / max(batches, 1), 2),
            "padding_rows": int(pad() - p0), **percentiles(lat)}


#: SLO-class workload mix for the saturation sweep: (class, share of
#: arrivals, carries the realtime deadline?).  30/40/30 is the classic
#: "interactive + default + offline backfill" blend.
CLASS_MIX = (("realtime", 0.30, True),
             ("standard", 0.40, False),
             ("batch", 0.30, False))


def bench_open_slo(server, in_dim, rate_rps, duration_s, rt_deadline_ms,
                   collectors_per_class=8):
    """One open-loop Poisson point with the CLASS_MIX workload.

    Arrivals never self-throttle (submission is non-blocking; waiting
    happens on small collector pools — at most queue_depth + one batch
    of requests are ever in flight, so the pools keep up and a thread
    per request at 12x capacity is avoided).  One pool **per SLO class**:
    the scheduler executes classes out of submission order, so a shared
    pool would head-of-line block on a deprioritized batch request while
    completed realtime responses queue behind it, inflating the measured
    realtime tail.  Within one class completion order tracks submission
    order (EDF with a uniform deadline offset == FIFO), so per-class
    pools measure true latency.  Returns offered/achieved QPS,
    shed/reject rates, and per-class outcome counts + p50/p99.
    """
    rng = np.random.RandomState(int(rate_rps) % 7919 + 5)
    X = rng.uniform(-1, 1, (64, in_dim)).astype(np.float32)
    classes = [c for c, _, _ in CLASS_MIX]
    shares = np.asarray([s for _, s, _ in CLASS_MIX])
    shares = shares / shares.sum()
    rt_deadline = {c: (rt_deadline_ms if dl else None)
                   for c, _, dl in CLASS_MIX}
    lock = threading.Lock()
    lat = {c: [] for c in classes}
    outcomes = {c: {"ok": 0, "shed": 0, "rejected": 0, "deadline": 0,
                    "error": 0} for c in classes}
    done_q = {c: queue.Queue() for c in classes}

    def collect(q):
        while True:
            item = q.get()
            if item is None:
                return
            req, t_submit, cls = item
            try:
                req.result(120.0)
                dt = time.perf_counter() - t_submit
                with lock:
                    outcomes[cls]["ok"] += 1
                    lat[cls].append(dt)
            except ServingError:
                out = req.outcome if req.outcome in outcomes[cls] \
                    else "error"
                with lock:
                    outcomes[cls][out] += 1

    pool = [threading.Thread(target=collect, args=(done_q[c],), daemon=True)
            for c in classes for _ in range(collectors_per_class)]
    for t in pool:
        t.start()
    t0 = time.perf_counter()
    end = t0 + duration_s
    n = 0
    next_t = t0
    while True:
        now = time.perf_counter()
        if now >= end:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.001))
            continue
        next_t += rng.exponential(1.0 / rate_rps)
        cls = classes[int(rng.choice(len(classes), p=shares))]
        t_submit = time.perf_counter()
        try:
            req = server.submit({"data": X[n % len(X)]},
                                deadline_ms=rt_deadline[cls],
                                slo_class=cls)
        except AdmissionError:
            with lock:
                outcomes[cls]["shed"] += 1
            continue
        except QueueFullError:
            with lock:
                outcomes[cls]["rejected"] += 1
            continue
        except ServingError:
            with lock:
                outcomes[cls]["error"] += 1
            continue
        finally:
            n += 1
        done_q[cls].put((req, t_submit, cls))
    for c in classes:
        for _ in range(collectors_per_class):
            done_q[c].put(None)
    for t in pool:
        t.join(120.0)
    wall = time.perf_counter() - t0
    ok = sum(o["ok"] for o in outcomes.values())
    shed = sum(o["shed"] for o in outcomes.values())
    rejected = sum(o["rejected"] for o in outcomes.values())
    per_class = {}
    for c in classes:
        per_class[c] = {"outcomes": dict(outcomes[c]), **percentiles(lat[c])}
    return {"offered_rps": round(rate_rps, 1), "duration_s": duration_s,
            "submitted": n,
            "achieved_rps": round(ok / wall, 1),
            "shed_rate": round(shed / max(n, 1), 4),
            "reject_rate": round(rejected / max(n, 1), 4),
            "classes": per_class}


def bench_sweep(server, in_dim, capacity_rps, multiples, point_duration_s,
                rt_deadline_ms):
    """The saturation curve: one open-loop SLO point per capacity
    multiple (the last well past 10x), worst-case offered load last so
    earlier points aren't polluted by a saturated queue."""
    points = []
    for mult in multiples:
        rate = max(capacity_rps * mult, 1.0)
        pt = bench_open_slo(server, in_dim, rate, point_duration_s,
                            rt_deadline_ms)
        pt["capacity_multiple"] = mult
        points.append(pt)
        # let the queue fully drain between points: each point measures
        # its own offered load, not the previous point's backlog
        while len(server._batcher):
            time.sleep(0.01)
    return points


def canonical_round(doc, round_name, source):
    """The sentinel-canonical round document for ``bench_history/``."""
    sat = doc["sweep"][-1]
    rt = sat["classes"]["realtime"]
    metrics = {}
    if rt.get("p99_ms") is not None:
        metrics["serving_p99_ms_realtime"] = round(rt["p99_ms"], 2)
    metrics["serving_shed_rate_overload"] = sat["shed_rate"]
    metrics["serving_throughput_rps"] = doc["closed"]["throughput_rps"]
    if doc.get("warmup_seconds") is not None:
        metrics["serving_warmup_seconds"] = round(doc["warmup_seconds"], 3)
    metrics["post_warmup_compiles"] = doc.get("post_warmup_compiles", 0)
    return {
        "round": round_name,
        "source": source,
        "kind": "serving_gateway",
        "metrics": metrics,
        "context": {
            "platform": "cpu",
            "capacity_rps": doc["closed"]["throughput_rps"],
            "overload_offered_rps": sat["offered_rps"],
            "overload_achieved_rps": sat["achieved_rps"],
            "capacity_multiple": sat["capacity_multiple"],
            "class_mix": {c: s for c, s, _ in CLASS_MIX},
            "rt_deadline_ms": doc["config"].get("rt_deadline_ms"),
            "note": "realtime p99 + shed rate at the >10x-capacity "
                    "open-loop point; shedding (429) is the designed "
                    "overload response — shed_rate collapsing to 0 "
                    "under 12x load means admission control broke",
        },
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--in-dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--timeout-ms", type=float, default=2.0,
                    help="batch window (MXNET_SERVING_BATCH_TIMEOUT_MS)")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=200,
                    help="serial total; also per-client closed-loop count")
    ap.add_argument("--rate", type=float, default=300.0,
                    help="open-loop offered load (req/s); 0 skips open loop")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="open-loop duration (s)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="open-loop per-request deadline (0 = none)")
    ap.add_argument("--queue-depth", type=int, default=512)
    ap.add_argument("--sweep-multiples", default="0.5,1,2,5,10,12",
                    help="capacity multiples for the saturation sweep "
                         "('' skips the sweep)")
    ap.add_argument("--sweep-duration", type=float, default=4.0,
                    help="open-loop duration per sweep point (s)")
    ap.add_argument("--rt-deadline-ms", type=float, default=200.0,
                    help="realtime-class deadline in the sweep")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny counts: CI-sized sanity run")
    ap.add_argument("--out", help="write the JSON document here too")
    ap.add_argument("--history-out",
                    help="write the canonical sentinel round here "
                         "(e.g. bench_history/serving_r14.canonical.json)")
    ap.add_argument("--round", default="r14",
                    help="round name stamped on --history-out")
    args = ap.parse_args()
    if args.smoke:
        args.requests, args.clients = 20, 4
        args.rate, args.duration = 100.0, 1.0
        args.sweep_multiples = "1,12"
        args.sweep_duration = 1.0

    telemetry.enable()
    sym, params = build_model(args.in_dim, args.hidden, args.classes)

    doc = {"bench": "serving",
           "model": {"in_dim": args.in_dim, "hidden": args.hidden,
                     "classes": args.classes},
           "config": {"max_batch": args.max_batch,
                      "batch_timeout_ms": args.timeout_ms,
                      "clients": args.clients,
                      "queue_depth": args.queue_depth,
                      "rt_deadline_ms": args.rt_deadline_ms}}

    doc["serial"] = bench_serial(sym, params, args.in_dim, args.requests)

    server = ModelServer(sym.tojson(), params,
                         example_shapes={"data": (args.in_dim,)},
                         max_batch_size=args.max_batch,
                         batch_timeout_ms=args.timeout_ms,
                         queue_depth=args.queue_depth)
    m0 = telemetry.value("op_jit_cache_misses_total", op="Executor::Forward")
    server.start()
    doc["warmup_compiles"] = telemetry.value(
        "op_jit_cache_misses_total", op="Executor::Forward") - m0
    doc["warmup_seconds"] = server.warmup_seconds
    doc["buckets"] = list(server.config.batch_buckets)
    try:
        doc["closed"] = bench_closed(server, args.in_dim, args.clients,
                                     args.requests)
        if args.rate > 0:
            doc["open"] = bench_open(server, args.in_dim, args.rate,
                                     args.duration, args.deadline_ms)
        multiples = [float(m) for m in args.sweep_multiples.split(",")
                     if m.strip()]
        if multiples:
            capacity = max(doc["closed"]["throughput_rps"], 1.0)
            doc["sweep"] = bench_sweep(server, args.in_dim, capacity,
                                       multiples, args.sweep_duration,
                                       args.rt_deadline_ms)
        doc["post_warmup_compiles"] = telemetry.value(
            "op_jit_cache_misses_total",
            op="Executor::Forward") - m0 - doc["warmup_compiles"]
    finally:
        server.stop()

    if doc["serial"].get("throughput_rps") and \
            doc["closed"].get("throughput_rps"):
        doc["closed_vs_serial_speedup"] = round(
            doc["closed"]["throughput_rps"]
            / doc["serial"]["throughput_rps"], 2)

    text = json.dumps(doc, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.history_out:
        if "sweep" not in doc:
            raise SystemExit("--history-out needs the sweep "
                             "(--sweep-multiples was empty)")
        rnd = canonical_round(doc, args.round,
                              "tools/bench_serving.py --smoke" if args.smoke
                              else "tools/bench_serving.py")
        with open(args.history_out, "w") as f:
            json.dump(rnd, f, indent=1, sort_keys=True)
            f.write("\n")


if __name__ == "__main__":
    main()
