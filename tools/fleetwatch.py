#!/usr/bin/env python
"""Fleet dashboard CLI: live view, snapshot, diff and smoke-check the
fleet control plane (mxnet_tpu/telemetry/fleet.py).

Modes:

live (default)
    Render the merged fleet view as an ASCII dashboard — one row per
    rank (step rate, MFU, HBM, health verdict, active alerts) plus
    fleet-aggregate sparklines from the merged multi-resolution tiers.
    ``--url`` points at a running collector's ``/fleetz``; with
    ``--fleet-dir`` (or ``MXNET_FLEET_DIR``) an *embedded* collector is
    started instead, so the dashboard works with no extra process.
    ``--watch SECS`` refreshes in place; default renders once.

``--snapshot [FILE]``
    Save the raw ``/fleetz`` JSON (``-`` = stdout) for a later
    ``--diff``.

``--diff A B``
    Two saved snapshots -> aggregate and per-rank deltas (who got
    slower, whose HBM grew, which alerts appeared).

``--format json``
    Print the raw fleet document instead of the dashboard.

``--smoke``
    Self-contained in-process acceptance check (<15 s CPU, no separate
    processes): start a telemetry endpoint, register it in a temp fleet
    dir, scrape it with an embedded collector, assert rank-attributed
    merged series, a histogram overflow rendered as ``>max`` (never 0),
    one synthetic page-severity alert firing exactly once with its
    flight dump captured, and a collector flight dump carrying a valid
    ``fleet`` block.  Exit 0/1.

Scraped-quantile convention: a p50/p99 that falls in the histogram's
+Inf overflow bucket arrives as JSON ``null`` and renders ``>max`` —
an off-scale tail must never read as a healthy zero.

Usage:
    python tools/fleetwatch.py --url http://127.0.0.1:9102
    python tools/fleetwatch.py --fleet-dir /tmp/fleet --watch 5
    python tools/fleetwatch.py --url ... --snapshot before.json
    python tools/fleetwatch.py --diff before.json after.json
    python tools/fleetwatch.py --smoke
"""
import argparse
import json
import os
import sys
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_mx():
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)


def _fmt_bytes(n):
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return "%.1f%s" % (n, unit) if unit != "B" else "%dB" % int(n)
        n /= 1024.0


def _fmt_val(v, stat="value", fmt="%.4g"):
    """None is overflow for quantile stats (render >max, never 0) and
    plain no-data otherwise."""
    if v is None:
        return ">max" if stat in ("p50", "p90", "p99", "p999") else "-"
    return fmt % v


def _finest_points(series_entry):
    tiers = series_entry.get("tiers") or []
    pts = (tiers[0].get("points") or []) if tiers else []
    return [p[1] for p in pts]


def render(doc, width=48):
    """ASCII dashboard of one /fleetz document."""
    from mxnet_tpu.telemetry.timeseries import sparkline
    agg = doc.get("aggregates") or {}
    per_rank = agg.get("per_rank") or {}
    targets = doc.get("targets") or {}
    alerts = (doc.get("alerts") or {}).get("active") or []
    pages = sum(1 for a in alerts if a.get("severity") == "page")
    p99 = agg.get("serving_p99_seconds")
    p99_txt = (">max" if agg.get("serving_p99_off_scale")
               else _fmt_val(p99))
    lines = []
    lines.append("fleet %s  targets=%d  alerts=%d active (%d page)"
                 % (doc.get("fleet_dir") or doc.get("url", ""),
                    len(targets), len(alerts), pages))
    lines.append("  step_rate=%s/s  mfu=%s%%  skew=%sx  "
                 "hbm_frac=%s  serving_p99=%s"
                 % (_fmt_val(agg.get("step_rate")),
                    _fmt_val(agg.get("mfu_pct")),
                    _fmt_val(agg.get("straggler_skew")),
                    _fmt_val(agg.get("hbm_used_frac")), p99_txt))
    hdr = "%-10s %-7s %9s %7s %10s %-12s %s" % (
        "rank", "role", "step/s", "mfu%", "hbm", "health", "alerts")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    ids = sorted(set(targets) | set(per_rank))
    for tid in ids:
        pr = per_rank.get(tid) or {}
        t = targets.get(tid) or {}
        step_s = pr.get("step_seconds")
        rate = (1.0 / step_s) if step_s else None
        mine = [a for a in alerts
                if a.get("group") == tid or tid == a.get("offender")]
        stale = t.get("last_ok_age_seconds") is None
        health = ("unscraped" if stale
                  else (pr.get("verdict") or pr.get("status") or "ok"))
        lines.append("%-10s %-7s %9s %7s %10s %-12s %s" % (
            tid, pr.get("role") or t.get("role") or "?",
            _fmt_val(rate, fmt="%.3g"), _fmt_val(pr.get("mfu_pct"),
                                                 fmt="%.3g"),
            _fmt_bytes(pr.get("hbm_bytes")), health[:12],
            ",".join("%s(%s)" % (a["rule"], a["severity"])
                     for a in mine) or "-"))
    series = doc.get("series") or {}
    spark_rows = []
    for key in sorted(series):
        s = series[key]
        metric, stat = s.get("metric"), s.get("stat")
        rank = (s.get("labels") or {}).get("rank")
        if metric == "step_seconds_ewma" and stat == "value":
            spark_rows.append(("step_s %s" % rank, key))
        elif rank == "fleet" and metric in (
                "fleet_step_rate", "fleet_straggler_skew",
                "fleet_mfu_pct", "fleet_serving_p99_seconds"):
            spark_rows.append((metric, key))
    if spark_rows:
        lines.append("")
        for label, key in spark_rows:
            vals = _finest_points(series[key])
            last = next((v for v in reversed(vals) if v is not None),
                        None)
            stat = series[key].get("stat", "value")
            overflow = (stat in ("p50", "p99")
                        and any(v is None for v in vals))
            lines.append("%-28s %s last=%s" % (
                label[:28], sparkline(vals, width),
                ">max" if overflow and last is None
                else _fmt_val(last, stat)))
    return "\n".join(lines) + "\n"


def _fetch(url, window=None):
    full = url.rstrip("/") + "/fleetz"
    if window is not None:
        full += "?window=%g" % window
    with urllib.request.urlopen(full, timeout=10) as resp:
        doc = json.loads(resp.read().decode())
    doc["url"] = url
    return doc


def _embedded(fleet_dir, interval):
    """Start an in-process collector over the fleet dir; returns a
    zero-argument fetcher."""
    _import_mx()
    from mxnet_tpu.telemetry import fleet
    c = fleet.start_collector(fleet_dir=fleet_dir, interval=interval)
    c.sweep()  # first paint needs data before the first tick elapses

    def fetch(window=None):
        return c.fleetz_doc(window=window)
    return fetch


def _diff(path_a, path_b, out=sys.stdout):
    with open(path_a) as f:
        a = json.load(f)
    with open(path_b) as f:
        b = json.load(f)
    aa, ba = a.get("aggregates") or {}, b.get("aggregates") or {}
    out.write("aggregates:\n")
    for k in sorted(set(aa) | set(ba)):
        if k in ("per_rank", "hbm_owner_bytes", "models"):
            continue
        va, vb = aa.get(k), ba.get(k)
        if va != vb:
            out.write("  %-24s %s -> %s\n"
                      % (k, _fmt_val(va), _fmt_val(vb)))
    pa, pb = aa.get("per_rank") or {}, ba.get("per_rank") or {}
    for tid in sorted(set(pa) | set(pb)):
        ra, rb = pa.get(tid) or {}, pb.get(tid) or {}
        deltas = []
        for k in ("step_seconds", "mfu_pct", "hbm_bytes", "verdict"):
            if ra.get(k) != rb.get(k):
                deltas.append("%s: %s -> %s" % (k, ra.get(k), rb.get(k)))
        if not ra:
            deltas.insert(0, "appeared")
        if not rb:
            deltas.insert(0, "vanished")
        if deltas:
            out.write("%-10s %s\n" % (tid, "; ".join(deltas)))
    al_a = {(x["rule"], x["group"])
            for x in (a.get("alerts") or {}).get("active") or []}
    al_b = {(x["rule"], x["group"])
            for x in (b.get("alerts") or {}).get("active") or []}
    for rule, group in sorted(al_b - al_a):
        out.write("alert fired: %s on %s\n" % (rule, group))
    for rule, group in sorted(al_a - al_b):
        out.write("alert resolved: %s on %s\n" % (rule, group))
    return 0


def _smoke():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("MXNET_FLEET_DIR", None)
    _import_mx()
    import tempfile
    tmp = tempfile.mkdtemp(prefix="fleetwatch_smoke_")
    dump_path = os.path.join(tmp, "flight_self.json")
    os.environ["MXNET_FLIGHT_RECORDER_PATH"] = dump_path
    from mxnet_tpu import telemetry, tracing
    from mxnet_tpu.telemetry import fleet

    port = telemetry.start_http_server(port=0)
    # synthetic signals: a step gauge (drives fleet_step_rate) and a
    # serving histogram whose only sample is off-scale -> p99 overflow
    telemetry.gauge(
        "step_seconds_ewma",
        "exponentially weighted moving average of the step interval"
    ).set(0.05)
    telemetry.histogram(
        "serving_request_seconds",
        "Request wall time from submit to completion").observe(1e9)
    fleet.register_endpoint(port, fleet_dir=tmp)
    fleet.register_rule(fleet.AlertRule(
        "smoke_step_rate", kind="threshold", severity="page",
        metric="fleet_step_rate", threshold=0.0,
        offender="step_seconds",
        help="synthetic smoke rule: any positive fleet step rate"),
        replace=True)
    c = fleet.start_collector(fleet_dir=tmp, interval=0.2, debounce=60.0)
    deadline = time.time() + 10.0
    fired = 0
    while time.time() < deadline:
        fired = telemetry.value("fleet_alerts_total",
                                rule="smoke_step_rate", severity="page")
        if fired and os.path.exists(dump_path):
            break
        time.sleep(0.1)
    time.sleep(0.5)  # extra ticks: the firing alert must not re-fire
    doc = c.fleetz_doc()
    out = render(doc)
    scrapes = telemetry.value("fleet_scrape_total", target="worker0")
    p99 = c.store.latest("serving_request_seconds", "p99", "worker0")
    checks = {
        "self_scrape": scrapes >= 2,
        "rank_attributed": any(
            (s.get("labels") or {}).get("rank") == "worker0"
            for s in doc["series"].values()),
        "alert_fired_once": telemetry.value(
            "fleet_alerts_total", rule="smoke_step_rate",
            severity="page") == 1,
        "flight_dump_captured": os.path.exists(dump_path),
        "overflow_renders_gtmax": p99 is None and ">max" in out,
    }
    # the collector's own dump must carry a schema-valid fleet block
    collector_dump = tracing.flight.dump(reason="manual")
    block_ok = False
    if collector_dump:
        with open(collector_dump) as f:
            dumped = json.load(f)
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import merge_traces
        problems = merge_traces.validate_flight_dump(dumped)
        block_ok = "fleet" in dumped and not problems
        if problems:
            for p in problems:
                print("validate: %s" % p, file=sys.stderr)
    checks["collector_dump_fleet_block"] = block_ok
    telemetry.stop_http_server()
    fleet.reset()
    ok = all(checks.values())
    print(json.dumps({"probe": "fleetwatch", "ok": ok,
                      "scrapes": scrapes, **checks}))
    return 0 if ok else 1


def main(argv):
    ap = argparse.ArgumentParser(
        description="live fleet dashboard over the telemetry fleet "
                    "control plane (see docs/observability.md 'Fleet')")
    ap.add_argument("--url", default=None,
                    help="a running collector's base URL "
                         "(e.g. http://127.0.0.1:9102)")
    ap.add_argument("--fleet-dir", default=None,
                    help="run an embedded collector over this fleet "
                         "directory (default: $MXNET_FLEET_DIR)")
    ap.add_argument("--interval", type=float, default=None,
                    help="embedded collector scrape interval seconds")
    ap.add_argument("--watch", type=float, default=None, metavar="SECS",
                    help="refresh the dashboard every SECS")
    ap.add_argument("--window", type=float, default=None,
                    help="sparkline window seconds")
    ap.add_argument("--format", choices=("ascii", "json"),
                    default="ascii")
    ap.add_argument("--snapshot", nargs="?", const="-", default=None,
                    metavar="FILE",
                    help="write the raw fleet JSON to FILE ('-'=stdout)")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                    help="diff two saved snapshots")
    ap.add_argument("--smoke", action="store_true",
                    help="in-process acceptance smoke (no server needed)")
    args = ap.parse_args(argv)

    if args.smoke:
        return _smoke()
    if args.diff:
        return _diff(args.diff[0], args.diff[1])

    if args.url:
        def fetch(window=None):
            return _fetch(args.url, window=window)
    else:
        fleet_dir = args.fleet_dir or os.environ.get("MXNET_FLEET_DIR")
        if not fleet_dir:
            ap.error("need --url, --fleet-dir or MXNET_FLEET_DIR")
        fetch = _embedded(fleet_dir, args.interval)
    _import_mx()

    doc = fetch(window=args.window)
    if args.snapshot is not None:
        text = json.dumps(doc, indent=2, sort_keys=True, default=str)
        if args.snapshot == "-":
            print(text)
        else:
            with open(args.snapshot, "w") as f:
                f.write(text)
            print("wrote %s" % args.snapshot)
        return 0

    while True:
        if args.format == "json":
            out = json.dumps(doc, indent=2, sort_keys=True, default=str)
        else:
            out = render(doc)
        if args.watch is not None:
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(out)
        sys.stdout.flush()
        if args.watch is None:
            return 0
        time.sleep(args.watch)
        doc = fetch(window=args.window)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
