#!/usr/bin/env python
"""Probe: decompose the ResNet-50 train-step conv time by shape x pass.

Round-3 finding (probe_pallas_conv.py): isolated forward convs run at
150-195 TF, yet the full train step implies ~35 TF aggregate.  This probe
times, per conv class: the forward chain (t_f), forward+input-grad chain
(t_fd), and forward+both-grads chain (t_fdw).  dgrad ~= t_fd - t_f and
wgrad ~= t_fdw - t_fd.  A relu sits after every conv so gradients are
input-dependent and nothing constant-folds.

Run:  python tools/probe_resnet_step.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

REPS = 4


def time_chain(step, x0, chain):
    def build(n):
        @jax.jit
        def f(x):
            def body(c, _):
                return step(c) * jnp.bfloat16(0.25), None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(y.astype(jnp.float32))
        return f
    f1, f2 = build(chain), build(2 * chain)
    float(f1(x0)); float(f2(x0))
    best1 = best2 = 1e9
    for _ in range(REPS):
        t0 = time.perf_counter(); float(f1(x0))
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter(); float(f2(x0))
        best2 = min(best2, time.perf_counter() - t0)
    return max(best2 - best1, 1e-9) / chain


def main():
    N = 128
    rng = np.random.default_rng(0)

    def conv(x, w, s=1):
        return jax.lax.conv_general_dilated(
            x, w, (s, s), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

    # (name, H, W, C, K, kh, stride, count) — 1x1s probed as up+down pairs
    classes = [
        ("stem7x7s2 3>64", 224, 224, 3, 64, 7, 2, 1),
        ("3x3s1 56 c64", 56, 56, 64, 64, 3, 1, 3),
        ("3x3s1 28 c128", 28, 28, 128, 128, 3, 1, 4),
        ("3x3s1 14 c256", 14, 14, 256, 256, 3, 1, 6),
        ("3x3s1 7 c512", 7, 7, 512, 512, 3, 1, 3),
        ("1x1pair 56 64/256", 56, 56, 64, 256, 1, 1, 3),
        ("1x1pair 28 128/512", 28, 28, 128, 512, 1, 1, 4),
        ("1x1pair 14 256/1k", 14, 14, 256, 1024, 1, 1, 6),
        ("1x1pair 7 512/2k", 7, 7, 512, 2048, 1, 1, 3),
        ("3x3s2 56>28 c128", 56, 56, 128, 128, 3, 2, 1),
        ("3x3s2 28>14 c256", 28, 28, 256, 256, 3, 2, 1),
        ("3x3s2 14>7 c512", 14, 14, 512, 512, 3, 2, 1),
        ("proj1x1s2 56 256>512", 56, 56, 256, 512, 1, 2, 1),
    ]
    tot = {"fwd": 0.0, "dgrad": 0.0, "wgrad": 0.0}
    flops_tot = 0.0
    print(f"{'class':>22} {'fwd_ms':>8} {'dgrad':>8} {'wgrad':>8} "
          f"{'fwdTF':>7} {'dTF':>6} {'wTF':>6}")
    for (name, H, W, C, K, kh, s, count) in classes:
        Ho, Wo = H // s, W // s
        x = jnp.asarray(rng.standard_normal((N, H, W, C)) * 0.1, jnp.bfloat16)
        pair = kh == 1 and s == 1
        if pair:
            w1 = jnp.asarray(rng.standard_normal((1, 1, C, K)) * 0.1,
                             jnp.bfloat16)
            w2 = jnp.asarray(rng.standard_normal((1, 1, K, C)) * 0.1,
                             jnp.bfloat16)

            def net(xx, ws):
                return jnp.sum(jax.nn.relu(conv(jax.nn.relu(
                    conv(xx, ws[0])), ws[1])).astype(jnp.float32))

            def f_only(c):
                return jax.nn.relu(conv(jax.nn.relu(conv(c, w1)), w2))
            ws = (w1, w2)
            flops = 2 * N * H * W * C * K * 2
        else:
            w1 = jnp.asarray(rng.standard_normal((kh, kh, C, K)) * 0.1,
                             jnp.bfloat16)
            # mixer restores carry shape for strided / channel-changing
            wm = jnp.asarray(rng.standard_normal((1, 1, K, C)) * 0.1,
                             jnp.bfloat16)

            def net(xx, ws):
                return jnp.sum(jax.nn.relu(
                    conv(xx, ws[0], s)).astype(jnp.float32))

            def f_only(c):
                y = jax.nn.relu(conv(c, w1, s))
                y = conv(y, wm)
                if s != 1:
                    y = jax.image.resize(y, (N, H, W, C), "nearest")
                return y
            ws = (w1,)
            flops = 2 * N * Ho * Wo * C * K * kh * kh

        chain = max(32, min(320, int(0.25 / (flops * 3 / 60e12)) // 2 * 2))

        t_f = time_chain(f_only, x, chain)

        def fd(c):
            return jax.grad(lambda xx: net(xx, ws))(c)
        t_fd = time_chain(fd, x, chain)

        def fdw(c):
            dx, dws = jax.grad(lambda xx, ww: net(xx, ww),
                               argnums=(0, 1))(c, ws)
            keep = sum(jnp.sum(d.astype(jnp.float32)) for d in
                       jax.tree_util.tree_leaves(dws))
            return dx * (1 + 1e-9 * keep).astype(dx.dtype)
        t_fdw = time_chain(fdw, x, chain)

        d_ms = max(t_fd - t_f, 1e-9)
        wg_ms = max(t_fdw - t_fd, 1e-9)
        print(f"{name:>22} {t_f*1e3:8.3f} {d_ms*1e3:8.3f} {wg_ms*1e3:8.3f} "
              f"{flops/t_f/1e12:7.1f} {flops/d_ms/1e12:6.1f} "
              f"{flops/wg_ms/1e12:6.1f}   x{count}", flush=True)
        tot["fwd"] += t_f * 1e3 * count
        tot["dgrad"] += d_ms * 1e3 * count
        tot["wgrad"] += wg_ms * 1e3 * count
        flops_tot += 3 * flops * count

    print("\nper-step conv totals (ms):",
          {k: round(v, 2) for k, v in tot.items()},
          " sum=", round(sum(tot.values()), 1),
          " aggregate TF=", round(flops_tot / sum(tot.values()) / 1e9, 1))


if __name__ == "__main__":
    main()
