// Native engine unit test (the tests/cpp/engine/threaded_engine_test.cc
// analog): exercises the C ABI directly — write ordering, read
// concurrency, error poisoning, WaitForAll — with plain asserts so it
// needs no test framework.
#include <atomic>
#include <cassert>
#include <chrono>
#include <cstdio>
#include <thread>
#include <vector>

extern "C" {
typedef int64_t (*EngineFn)(void* payload, int64_t prior_err);
void* MXNativeEngineCreate(int num_workers);
void MXNativeEngineFree(void* h);
void* MXNativeEngineNewVar(void* h);
void MXNativeEngineDeleteVar(void* h, void* v);
void MXNativeEnginePush(void* h, EngineFn fn, void* payload, void** cvars,
                        int nc, void** mvars, int nm, int prio);
int64_t MXNativeEngineWaitForVar(void* h, void* v);
void MXNativeEngineWaitForAll(void* h);
}

namespace {

std::vector<int> g_order;
std::atomic<int> g_concurrent{0};
std::atomic<int> g_max_concurrent{0};

int64_t append_op(void* payload, int64_t prior) {
  if (prior) return prior;
  g_order.push_back(static_cast<int>(reinterpret_cast<intptr_t>(payload)));
  return 0;
}

int64_t slow_read(void* payload, int64_t prior) {
  if (prior) return prior;
  int cur = ++g_concurrent;
  int prev = g_max_concurrent.load();
  while (cur > prev && !g_max_concurrent.compare_exchange_weak(prev, cur)) {
  }
  std::this_thread::sleep_for(std::chrono::milliseconds(20));
  --g_concurrent;
  return 0;
}

int64_t failing_op(void*, int64_t prior) {
  if (prior) return prior;
  return 42;  // error code
}

int64_t never_runs(void* payload, int64_t prior) {
  if (prior) return prior;  // poisoned: must propagate, not execute
  g_order.push_back(-1);
  return 0;
}

}  // namespace

int main() {
  void* eng = MXNativeEngineCreate(4);

  // 1. writes to one var serialize in push order
  void* v = MXNativeEngineNewVar(eng);
  for (int i = 0; i < 100; ++i) {
    MXNativeEnginePush(eng, append_op, reinterpret_cast<void*>(
        static_cast<intptr_t>(i)), nullptr, 0, &v, 1, 0);
  }
  assert(MXNativeEngineWaitForVar(eng, v) == 0);
  assert(g_order.size() == 100);
  for (int i = 0; i < 100; ++i) assert(g_order[i] == i);
  std::printf("ordering OK\n");

  // 2. reads of one var run concurrently
  void* v2 = MXNativeEngineNewVar(eng);
  for (int i = 0; i < 4; ++i) {
    MXNativeEnginePush(eng, slow_read, nullptr, &v2, 1, nullptr, 0, 0);
  }
  MXNativeEngineWaitForAll(eng);
  assert(g_max_concurrent.load() >= 2);
  std::printf("read concurrency OK (max %d)\n", g_max_concurrent.load());

  // 3. failing op poisons its var; dependents skip; error surfaces once
  void* v3 = MXNativeEngineNewVar(eng);
  MXNativeEnginePush(eng, failing_op, nullptr, nullptr, 0, &v3, 1, 0);
  MXNativeEnginePush(eng, never_runs, nullptr, nullptr, 0, &v3, 1, 0);
  assert(MXNativeEngineWaitForVar(eng, v3) == 42);
  for (int x : g_order) assert(x != -1);
  assert(MXNativeEngineWaitForVar(eng, v3) == 0);  // cleared after surfacing
  std::printf("error propagation OK\n");

  // 4. delete-variable runs after pending ops
  void* v4 = MXNativeEngineNewVar(eng);
  MXNativeEnginePush(eng, append_op, reinterpret_cast<void*>(
      static_cast<intptr_t>(1000)), nullptr, 0, &v4, 1, 0);
  MXNativeEngineDeleteVar(eng, v4);
  MXNativeEngineWaitForAll(eng);
  assert(g_order.back() == 1000);
  std::printf("delete var OK\n");

  MXNativeEngineFree(eng);
  std::printf("ALL ENGINE TESTS PASSED\n");
  return 0;
}
