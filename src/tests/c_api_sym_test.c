/* Pure-C consumer of the symbolic half of the C API waist (reference
 * parity: include/mxnet/c_api.h Part 3 MXSymbol* + Part 4 MXExecutor*).
 * Builds a 2-layer MLP symbolically, round-trips it through JSON, infers
 * shapes, binds an executor, and trains linear-regression style until the
 * loss drops — proving create/compose/list/infer/bind/forward/backward
 * end-to-end from C. */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <mxnet_tpu/c_api.h>

static int failures = 0;
#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      ++failures;                                                          \
      fprintf(stderr, "FAILED %s:%d: %s (last error: %s)\n", __FILE__,     \
              __LINE__, #cond, MXGetLastError());                          \
    }                                                                      \
  } while (0)

static AtomicSymbolCreator find_creator(const char *name) {
  mx_uint n = 0;
  AtomicSymbolCreator *cs = NULL;
  if (MXSymbolListAtomicSymbolCreators(&n, &cs) != 0) return NULL;
  for (mx_uint i = 0; i < n; ++i) {
    const char *nm = NULL;
    MXSymbolGetAtomicSymbolName(cs[i], &nm);
    if (nm && strcmp(nm, name) == 0) return cs[i];
  }
  return NULL;
}

/* FullyConnected(data, num_hidden=h) with auto-created weight/bias */
static SymbolHandle fc_layer(SymbolHandle data, const char *name, int hid) {
  AtomicSymbolCreator c = find_creator("FullyConnected");
  CHECK(c != NULL);
  char hidbuf[16];
  snprintf(hidbuf, sizeof(hidbuf), "%d", hid);
  const char *pk[] = {"num_hidden"};
  const char *pv[] = {hidbuf};
  SymbolHandle fc = NULL;
  CHECK(MXSymbolCreateAtomicSymbol(c, 1, pk, pv, &fc) == 0);
  const char *ak[] = {"data"};
  SymbolHandle args[] = {data};
  CHECK(MXSymbolCompose(fc, name, 1, ak, args) == 0);
  return fc;
}

int main(void) {
  /* ---- build: data -> fc1(16) -> Activation(relu) -> fc2(1) ---- */
  SymbolHandle data = NULL;
  CHECK(MXSymbolCreateVariable("data", &data) == 0);
  SymbolHandle fc1 = fc_layer(data, "fc1", 16);

  AtomicSymbolCreator act_c = find_creator("Activation");
  CHECK(act_c != NULL);
  const char *apk[] = {"act_type"};
  const char *apv[] = {"relu"};
  SymbolHandle act = NULL;
  CHECK(MXSymbolCreateAtomicSymbol(act_c, 1, apk, apv, &act) == 0);
  SymbolHandle act_args[] = {fc1};
  CHECK(MXSymbolCompose(act, "relu1", 1, NULL, act_args) == 0);

  SymbolHandle net = fc_layer(act, "fc2", 1);

  /* ---- introspection ---- */
  mx_uint n_args = 0;
  const char **arg_names = NULL;
  CHECK(MXSymbolListArguments(net, &n_args, &arg_names) == 0);
  CHECK(n_args == 5);  /* data, fc1_weight, fc1_bias, fc2_weight, fc2_bias */
  CHECK(strcmp(arg_names[0], "data") == 0);
  CHECK(strcmp(arg_names[1], "fc1_weight") == 0);

  mx_uint n_outs = 0;
  const char **out_names = NULL;
  CHECK(MXSymbolListOutputs(net, &n_outs, &out_names) == 0);
  CHECK(n_outs == 1 && strstr(out_names[0], "fc2") != NULL);

  const char *sname = NULL;
  int ok = 0;
  CHECK(MXSymbolGetName(net, &sname, &ok) == 0);
  CHECK(ok == 1 && strcmp(sname, "fc2") == 0);

  /* op info for the wrapper-generator contract */
  AtomicSymbolCreator fc_c = find_creator("FullyConnected");
  const char *iname = NULL, *idesc = NULL, *kv = NULL;
  mx_uint in_args = 0;
  const char **inames = NULL, **itypes = NULL, **idescs = NULL;
  CHECK(MXSymbolGetAtomicSymbolInfo(fc_c, &iname, &idesc, &in_args, &inames,
                                    &itypes, &idescs, &kv) == 0);
  CHECK(strcmp(iname, "FullyConnected") == 0);
  CHECK(in_args >= 4);  /* data, weight, bias + num_hidden... */
  CHECK(strcmp(itypes[0], "NDArray-or-Symbol") == 0);
  CHECK(strstr(itypes[in_args - 1], "optional") != NULL ||
        strstr(itypes[in_args - 1], "required") != NULL);
  CHECK(strcmp(kv, "") == 0);

  /* ---- JSON round trip ---- */
  const char *json = NULL;
  CHECK(MXSymbolSaveToJSON(net, &json) == 0);
  CHECK(json != NULL && strstr(json, "fc1_weight") != NULL);
  SymbolHandle net2 = NULL;
  CHECK(MXSymbolCreateFromJSON(json, &net2) == 0);
  mx_uint n_args2 = 0;
  const char **arg_names2 = NULL;
  CHECK(MXSymbolListArguments(net2, &n_args2, &arg_names2) == 0);
  CHECK(n_args2 == n_args);

  /* ---- shape inference ---- */
  const char *ikeys[] = {"data"};
  mx_uint ind_ptr[] = {0, 2};
  mx_uint shape_data[] = {8, 4};   /* batch 8, 4 features */
  mx_uint in_sz = 0, out_sz = 0, aux_sz = 0;
  const mx_uint *in_nd = NULL, *out_nd = NULL, *aux_nd = NULL;
  const mx_uint **in_sh = NULL, **out_sh = NULL, **aux_sh = NULL;
  int complete = 0;
  CHECK(MXSymbolInferShape(net, 1, ikeys, ind_ptr, shape_data, &in_sz,
                           &in_nd, &in_sh, &out_sz, &out_nd, &out_sh,
                           &aux_sz, &aux_nd, &aux_sh, &complete) == 0);
  CHECK(complete == 1 && in_sz == 5 && out_sz == 1);
  CHECK(in_nd[1] == 2 && in_sh[1][0] == 16 && in_sh[1][1] == 4);
  CHECK(out_nd[0] == 2 && out_sh[0][0] == 8 && out_sh[0][1] == 1);

  /* ---- bind + train: y = x @ w_true, loss must drop ---- */
  NDArrayHandle args[5], grads[5];
  mx_uint req[5];
  for (mx_uint i = 0; i < in_sz; ++i) {
    CHECK(MXNDArrayCreate(in_sh[i], in_nd[i], 1, 0, 0, &args[i]) == 0);
    CHECK(MXNDArrayCreate(in_sh[i], in_nd[i], 1, 0, 0, &grads[i]) == 0);
    req[i] = (i == 0) ? 0 : 1;   /* no grad for data */
  }
  /* init weights small-deterministic, data + targets fixed */
  float buf[16 * 4];
  for (int i = 0; i < 16 * 4; ++i) buf[i] = 0.01f * (float)((i % 7) - 3);
  CHECK(MXNDArraySyncCopyFromCPU(args[1], buf, 16 * 4) == 0);
  for (int i = 0; i < 16; ++i) buf[i] = 0.02f * (float)((i % 5) - 2);
  CHECK(MXNDArraySyncCopyFromCPU(args[3], buf, 16) == 0);
  float x[8 * 4], y[8];
  for (int i = 0; i < 8 * 4; ++i) x[i] = 0.25f * (float)((i % 9) - 4);
  for (int i = 0; i < 8; ++i) {
    y[i] = 0.0f;
    for (int j = 0; j < 4; ++j) y[i] += x[i * 4 + j] * (0.5f + 0.25f * j);
  }
  CHECK(MXNDArraySyncCopyFromCPU(args[0], x, 8 * 4) == 0);

  ExecutorHandle ex = NULL;
  CHECK(MXExecutorBind(net, 1, 0, 5, args, grads, req, 0, NULL, &ex) == 0);

  float first_loss = -1.0f, last_loss = -1.0f;
  const char *lr_k[] = {"lr"};
  const char *lr_v[] = {"0.2"};
  for (int it = 0; it < 120; ++it) {
    CHECK(MXExecutorForward(ex, 1) == 0);
    mx_uint nout = 0;
    NDArrayHandle *outs = NULL;
    CHECK(MXExecutorOutputs(ex, &nout, &outs) == 0);
    CHECK(nout == 1);
    float pred[8];
    CHECK(MXNDArraySyncCopyToCPU(outs[0], pred, 8) == 0);
    float loss = 0.0f, head[8];
    for (int i = 0; i < 8; ++i) {
      float d = pred[i] - y[i];
      loss += d * d / 8.0f;
      head[i] = 2.0f * d / 8.0f;   /* dL/dpred for MSE */
    }
    if (it == 0) first_loss = loss;
    last_loss = loss;
    NDArrayHandle hg = NULL;
    mx_uint hshape[] = {8, 1};
    CHECK(MXNDArrayCreate(hshape, 2, 1, 0, 0, &hg) == 0);
    CHECK(MXNDArraySyncCopyFromCPU(hg, head, 8) == 0);
    CHECK(MXExecutorBackward(ex, 1, &hg) == 0);
    MXNDArrayFree(hg);
    /* SGD: w -= lr * grad via the imperative waist, out= in place */
    for (int i = 1; i < 5; ++i) {
      NDArrayHandle io[2] = {args[i], grads[i]};
      int no = 1;
      NDArrayHandle *op = &args[i];
      CHECK(MXImperativeInvokeByName("sgd_update", 2, io, &no, &op, 1,
                                     lr_k, lr_v) == 0);
    }
    for (mx_uint i = 0; i < nout; ++i) MXNDArrayFree(outs[i]);
  }
  CHECK(first_loss > 0.0f);
  CHECK(last_loss < 0.1f * first_loss);

  /* error contract: composing with a bogus arg name must fail cleanly */
  SymbolHandle bad = NULL;
  const char *bk[] = {"num_hidden"};
  const char *bv[] = {"3"};
  CHECK(MXSymbolCreateAtomicSymbol(fc_c, 1, bk, bv, &bad) == 0);
  SymbolHandle bargs[] = {data};
  const char *bkeys[] = {"not_an_arg"};
  CHECK(MXSymbolCompose(bad, "bad", 1, bkeys, bargs) != 0);
  CHECK(strlen(MXGetLastError()) > 0);

  MXExecutorFree(ex);
  for (int i = 0; i < 5; ++i) {
    MXNDArrayFree(args[i]);
    MXNDArrayFree(grads[i]);
  }
  MXSymbolFree(net);
  MXSymbolFree(net2);
  MXSymbolFree(fc1);
  MXSymbolFree(act);
  MXSymbolFree(data);
  MXSymbolFree(bad);

  if (failures == 0) {
    printf("c_api_sym_test: all checks passed (final loss %.5f from %.5f)\n",
           last_loss, first_loss);
    return 0;
  }
  fprintf(stderr, "c_api_sym_test: %d failures\n", failures);
  return 1;
}
