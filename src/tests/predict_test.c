/* C-ABI smoke test for the predict API (reference parity:
 * example/image-classification/predict-cpp/image-classification-predict.cc
 * usage of c_predict_api.h).
 *
 * Pure C consumer: loads a symbol JSON + parameter blob from argv, feeds a
 * deterministic float32 input, prints the flat output to stdout (one value
 * per line, "%.6g").  The pytest harness (tests/test_predict_capi.py)
 * compiles+runs this and compares against the Python Predictor on the same
 * input.
 *
 * Usage: predict_test symbol.json params.bin N C H W
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef uint32_t mx_uint;
typedef void *PredictorHandle;

extern const char *MXGetLastError(void);
extern int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                        int param_size, int dev_type, int dev_id,
                        mx_uint num_input_nodes, const char **input_keys,
                        const mx_uint *input_shape_indptr,
                        const mx_uint *input_shape_data,
                        PredictorHandle *out);
extern int MXPredSetInput(PredictorHandle handle, const char *key,
                          const float *data, mx_uint size);
extern int MXPredForward(PredictorHandle handle);
extern int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                                mx_uint **shape_data, mx_uint *shape_ndim);
extern int MXPredGetOutput(PredictorHandle handle, mx_uint index,
                           float *data, mx_uint size);
extern int MXPredFree(PredictorHandle handle);

#define CHECK(call)                                                     \
  do {                                                                  \
    if ((call) != 0) {                                                  \
      fprintf(stderr, "FAIL %s: %s\n", #call, MXGetLastError());        \
      return 1;                                                         \
    }                                                                   \
  } while (0)

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path);
    return NULL;
  }
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc((size_t)*size + 1);
  if (fread(buf, 1, (size_t)*size, f) != (size_t)*size) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[*size] = '\0';
  fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  if (argc != 7) {
    fprintf(stderr, "usage: %s symbol.json params.bin N C H W\n", argv[0]);
    return 2;
  }
  long json_size = 0, param_size = 0;
  char *json = read_file(argv[1], &json_size);
  char *params = read_file(argv[2], &param_size);
  if (!json || !params) return 2;

  mx_uint shape[4];
  for (int i = 0; i < 4; ++i) shape[i] = (mx_uint)atoi(argv[3 + i]);
  mx_uint indptr[2] = {0, 4};
  const char *keys[1] = {"data"};

  PredictorHandle pred = NULL;
  CHECK(MXPredCreate(json, params, (int)param_size, /*dev_type=*/1,
                     /*dev_id=*/0, 1, keys, indptr, shape, &pred));

  mx_uint n = shape[0] * shape[1] * shape[2] * shape[3];
  float *input = (float *)malloc(n * sizeof(float));
  for (mx_uint i = 0; i < n; ++i) {
    input[i] = (float)((double)(i % 17) / 8.0 - 1.0);
  }
  /* error path: wrong size must fail with a message, not crash */
  if (MXPredSetInput(pred, "data", input, n + 1) == 0) {
    fprintf(stderr, "FAIL: oversized set_input accepted\n");
    return 1;
  }
  if (MXPredSetInput(pred, "nosuch", input, n) == 0) {
    fprintf(stderr, "FAIL: unknown key accepted\n");
    return 1;
  }
  CHECK(MXPredSetInput(pred, "data", input, n));
  CHECK(MXPredForward(pred));

  mx_uint *oshape = NULL, ondim = 0;
  CHECK(MXPredGetOutputShape(pred, 0, &oshape, &ondim));
  mx_uint osize = 1;
  fprintf(stderr, "output shape:");
  for (mx_uint i = 0; i < ondim; ++i) {
    fprintf(stderr, " %u", oshape[i]);
    osize *= oshape[i];
  }
  fprintf(stderr, "\n");

  float *out = (float *)malloc(osize * sizeof(float));
  CHECK(MXPredGetOutput(pred, 0, out, osize));
  for (mx_uint i = 0; i < osize; ++i) printf("%.6g\n", (double)out[i]);

  CHECK(MXPredFree(pred));
  free(out);
  free(input);
  free(json);
  free(params);
  return 0;
}
