/* Pure-C consumer of the C API waist (reference parity:
 * include/mxnet/c_api.h Parts 0-2).  Exercises NDArray CRUD, sync copies,
 * imperative invoke through the creator table, save/load, op listing, and
 * the error contract — in a fresh process where the library bootstraps the
 * embedded interpreter itself. */
#include <assert.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

typedef uint32_t mx_uint;
typedef void *NDArrayHandle;
typedef void *AtomicSymbolCreator;

extern const char *MXGetLastError(void);
extern int MXGetVersion(int *out);
extern int MXRandomSeed(int seed);
extern int MXNDArrayWaitAll(void);
extern int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                           int dev_id, int delay_alloc, NDArrayHandle *out);
extern int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                             int dev_id, int delay_alloc, int dtype,
                             NDArrayHandle *out);
extern int MXNDArrayFree(NDArrayHandle h);
extern int MXNDArrayGetShape(NDArrayHandle h, mx_uint *out_dim,
                             const mx_uint **out_pdata);
extern int MXNDArrayGetDType(NDArrayHandle h, int *out);
extern int MXNDArrayGetContext(NDArrayHandle h, int *dev_type, int *dev_id);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                    size_t size);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data, size_t size);
extern int MXNDArrayWaitToRead(NDArrayHandle h);
extern int MXNDArraySlice(NDArrayHandle h, mx_uint b, mx_uint e,
                          NDArrayHandle *out);
extern int MXNDArrayReshape(NDArrayHandle h, int ndim, int *dims,
                            NDArrayHandle *out);
extern int MXNDArraySave(const char *fname, mx_uint n, NDArrayHandle *args,
                         const char **keys);
extern int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                         NDArrayHandle **out_arr, mx_uint *out_name_size,
                         const char ***out_names);
extern int MXListAllOpNames(mx_uint *out_size, const char ***out_array);
extern int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                            AtomicSymbolCreator **out_array);
extern int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator c,
                                       const char **name);
extern int MXImperativeInvoke(AtomicSymbolCreator c, int num_inputs,
                              NDArrayHandle *inputs, int *num_outputs,
                              NDArrayHandle **outputs, int num_params,
                              const char **keys, const char **vals);
extern int MXImperativeInvokeByName(const char *name, int num_inputs,
                                    NDArrayHandle *inputs, int *num_outputs,
                                    NDArrayHandle **outputs, int num_params,
                                    const char **keys, const char **vals);

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      fprintf(stderr, "FAILED %s:%d: %s (last error: %s)\n", __FILE__,   \
              __LINE__, #cond, MXGetLastError());                        \
      return 1;                                                          \
    }                                                                    \
  } while (0)

int main(void) {
  int version = 0;
  CHECK(MXGetVersion(&version) == 0 && version == 10200);

  /* create + shape + dtype + context */
  mx_uint shape[2] = {2, 3};
  NDArrayHandle a = NULL;
  CHECK(MXNDArrayCreate(shape, 2, 1 /*cpu*/, 0, 0, &a) == 0);
  mx_uint dim = 0;
  const mx_uint *pshape = NULL;
  CHECK(MXNDArrayGetShape(a, &dim, &pshape) == 0);
  CHECK(dim == 2 && pshape[0] == 2 && pshape[1] == 3);
  int dtype = -1;
  CHECK(MXNDArrayGetDType(a, &dtype) == 0 && dtype == 0);
  int dev_type = 0, dev_id = -1;
  CHECK(MXNDArrayGetContext(a, &dev_type, &dev_id) == 0);
  CHECK(dev_type == 1 && dev_id == 0);

  /* sync copies round trip */
  float values[6] = {0.f, 1.f, 2.f, 3.f, 4.f, 5.f};
  CHECK(MXNDArraySyncCopyFromCPU(a, values, 6) == 0);
  float back[6] = {0};
  CHECK(MXNDArrayWaitToRead(a) == 0);
  CHECK(MXNDArraySyncCopyToCPU(a, back, 6) == 0);
  for (int i = 0; i < 6; ++i) CHECK(back[i] == values[i]);

  /* int32 array via CreateEx (int64 degrades to int32 without JAX x64 —
   * the framework-wide dtype policy) */
  NDArrayHandle ai = NULL;
  CHECK(MXNDArrayCreateEx(shape, 2, 1, 0, 0, 4 /*int32*/, &ai) == 0);
  CHECK(MXNDArrayGetDType(ai, &dtype) == 0 && dtype == 4);
  MXNDArrayFree(ai);

  /* invoke by name: a + 1.5 */
  const char *keys1[] = {"scalar"};
  const char *vals1[] = {"1.5"};
  int nout = 0;
  NDArrayHandle *outs = NULL;
  CHECK(MXImperativeInvokeByName("_plus_scalar", 1, &a, &nout, &outs, 1,
                                 keys1, vals1) == 0);
  CHECK(nout == 1);
  CHECK(MXNDArraySyncCopyToCPU(outs[0], back, 6) == 0);
  for (int i = 0; i < 6; ++i) CHECK(back[i] == values[i] + 1.5f);
  NDArrayHandle plus = outs[0];

  /* creator table: find 'dot', multiply (2,3)x(3,2) */
  mx_uint n_creators = 0;
  AtomicSymbolCreator *creators = NULL;
  CHECK(MXSymbolListAtomicSymbolCreators(&n_creators, &creators) == 0);
  CHECK(n_creators > 100);
  AtomicSymbolCreator dot = NULL;
  for (mx_uint i = 0; i < n_creators; ++i) {
    const char *nm = NULL;
    CHECK(MXSymbolGetAtomicSymbolName(creators[i], &nm) == 0);
    if (strcmp(nm, "dot") == 0) dot = creators[i];
  }
  CHECK(dot != NULL);
  mx_uint shape_b[2] = {3, 2};
  NDArrayHandle b = NULL;
  CHECK(MXNDArrayCreate(shape_b, 2, 1, 0, 0, &b) == 0);
  float ones[6] = {1, 1, 1, 1, 1, 1};
  CHECK(MXNDArraySyncCopyFromCPU(b, ones, 6) == 0);
  NDArrayHandle dot_in[2];
  dot_in[0] = a;
  dot_in[1] = b;
  nout = 0;
  outs = NULL;  /* NULL *outputs = allocate (non-NULL would mean out=) */
  CHECK(MXImperativeInvoke(dot, 2, dot_in, &nout, &outs, 0, NULL, NULL) == 0);
  CHECK(nout == 1);
  CHECK(MXNDArrayGetShape(outs[0], &dim, &pshape) == 0);
  CHECK(dim == 2 && pshape[0] == 2 && pshape[1] == 2);
  float dots[4] = {0};
  CHECK(MXNDArraySyncCopyToCPU(outs[0], dots, 4) == 0);
  CHECK(dots[0] == 3.f && dots[3] == 12.f);   /* row sums of a */
  MXNDArrayFree(outs[0]);

  /* slice + reshape */
  NDArrayHandle sl = NULL;
  CHECK(MXNDArraySlice(a, 1, 2, &sl) == 0);
  CHECK(MXNDArrayGetShape(sl, &dim, &pshape) == 0);
  CHECK(dim == 2 && pshape[0] == 1 && pshape[1] == 3);
  MXNDArrayFree(sl);
  int dims[2] = {3, 2};
  NDArrayHandle rs = NULL;
  CHECK(MXNDArrayReshape(a, 2, dims, &rs) == 0);
  CHECK(MXNDArrayGetShape(rs, &dim, &pshape) == 0);
  CHECK(pshape[0] == 3 && pshape[1] == 2);
  MXNDArrayFree(rs);

  /* save / load named dict */
  const char *names[] = {"weight", "bias"};
  NDArrayHandle pair[2];
  pair[0] = a;
  pair[1] = plus;
  CHECK(MXNDArraySave("/tmp/c_api_test.params", 2, pair, names) == 0);
  mx_uint n_loaded = 0, n_names = 0;
  NDArrayHandle *loaded = NULL;
  const char **loaded_names = NULL;
  CHECK(MXNDArrayLoad("/tmp/c_api_test.params", &n_loaded, &loaded, &n_names,
                      &loaded_names) == 0);
  CHECK(n_loaded == 2 && n_names == 2);
  CHECK(strcmp(loaded_names[0], "weight") == 0); /* save order kept */
  CHECK(strcmp(loaded_names[1], "bias") == 0);
  CHECK(MXNDArraySyncCopyToCPU(loaded[0], back, 6) == 0);
  for (int i = 0; i < 6; ++i) CHECK(back[i] == values[i]);
  MXNDArrayFree(loaded[0]);
  MXNDArrayFree(loaded[1]);

  /* op listing */
  mx_uint n_ops = 0;
  const char **op_names = NULL;
  CHECK(MXListAllOpNames(&n_ops, &op_names) == 0);
  CHECK(n_ops == n_creators);

  /* out= contract: supply the output handle, result lands in place */
  {
    NDArrayHandle target = NULL;
    CHECK(MXNDArrayCreate(shape, 2, 1, 0, 0, &target) == 0);
    const char *sk[] = {"scalar"};
    const char *sv[] = {"2.0"};
    int n_sup = 1;
    NDArrayHandle sup[1];
    sup[0] = target;
    NDArrayHandle *psup = sup;
    CHECK(MXImperativeInvokeByName("_mul_scalar", 1, &a, &n_sup, &psup, 1,
                                   sk, sv) == 0);
    CHECK(MXNDArraySyncCopyToCPU(target, back, 6) == 0);
    for (int i = 0; i < 6; ++i) CHECK(back[i] == values[i] * 2.0f);
    MXNDArrayFree(target);
  }

  /* error contract: bad op param surfaces -1 + message, then recovery */
  const char *bad_keys[] = {"no_such_param"};
  const char *bad_vals[] = {"1"};
  nout = 0;
  outs = NULL;
  CHECK(MXImperativeInvokeByName("FullyConnected", 1, &a, &nout, &outs, 1,
                                 bad_keys, bad_vals) != 0);
  CHECK(strlen(MXGetLastError()) > 0);
  CHECK(MXRandomSeed(7) == 0);
  CHECK(MXNDArrayWaitAll() == 0);

  MXNDArrayFree(plus);
  MXNDArrayFree(b);
  MXNDArrayFree(a);
  printf("C API TEST OK (%u ops)\n", n_ops);
  return 0;
}
