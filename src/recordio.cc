// RecordIO reader/writer — native data-pipeline framing (SURVEY.md N14/N24).
//
// Reference analog: dmlc-core RecordIO (consumed by src/io/* and
// tools/im2rec.cc): each record is framed as
//   uint32 magic = 0xced7230a
//   uint32 lrec  = (cflag << 29) | length      (cflag 0 for whole records)
//   data bytes, zero-padded to a 4-byte boundary
// — byte-compatible with mxnet_tpu/recordio.py's Python fallback so files
// written by either are read by both.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Writer {
  FILE* f = nullptr;
};

struct Reader {
  FILE* f = nullptr;
  std::vector<char> buf;
};

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

}  // namespace

extern "C" {

const char* MXNativeRecordIOGetLastError() { return g_last_error.c_str(); }

void* MXNativeRecordIOWriterCreate(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) {
    set_error(std::string("cannot open for write: ") + path);
    return nullptr;
  }
  Writer* w = new Writer();
  w->f = f;
  return w;
}

int MXNativeRecordIOWriterWrite(void* h, const char* data, uint64_t size) {
  Writer* w = static_cast<Writer*>(h);
  if (size > kLenMask) {
    set_error("record too large (> 2^29-1 bytes) for single-part framing");
    return -1;
  }
  uint32_t hdr[2] = {kMagic, static_cast<uint32_t>(size & kLenMask)};
  if (std::fwrite(hdr, sizeof(hdr), 1, w->f) != 1) {
    set_error("short write (header)");
    return -1;
  }
  if (size && std::fwrite(data, 1, size, w->f) != size) {
    set_error("short write (payload)");
    return -1;
  }
  uint64_t pad = (4 - (size & 3)) & 3;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, w->f) != pad) {
    set_error("short write (pad)");
    return -1;
  }
  return 0;
}

int64_t MXNativeRecordIOWriterTell(void* h) {
  return std::ftell(static_cast<Writer*>(h)->f);
}

void MXNativeRecordIOWriterClose(void* h) {
  Writer* w = static_cast<Writer*>(h);
  if (w->f) std::fclose(w->f);
  delete w;
}

void* MXNativeRecordIOReaderCreate(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    set_error(std::string("cannot open for read: ") + path);
    return nullptr;
  }
  Reader* r = new Reader();
  r->f = f;
  return r;
}

// Returns: 0 ok (out/out_size set; buffer valid until next call),
//          1 clean EOF, -1 error.
int MXNativeRecordIOReaderRead(void* h, const char** out,
                               uint64_t* out_size) {
  Reader* r = static_cast<Reader*>(h);
  uint32_t hdr[2];
  size_t got = std::fread(hdr, sizeof(uint32_t), 2, r->f);
  if (got == 0) return 1;  // EOF at a record boundary
  if (got != 2) {
    set_error("truncated record header");
    return -1;
  }
  if (hdr[0] != kMagic) {
    set_error("bad magic (corrupt recordio file)");
    return -1;
  }
  uint64_t size = hdr[1] & kLenMask;
  uint64_t padded = (size + 3) & ~uint64_t(3);
  r->buf.resize(padded);
  if (padded && std::fread(r->buf.data(), 1, padded, r->f) != padded) {
    set_error("truncated record payload");
    return -1;
  }
  *out = r->buf.data();
  *out_size = size;
  return 0;
}

int MXNativeRecordIOReaderSeek(void* h, uint64_t pos) {
  return std::fseek(static_cast<Reader*>(h)->f, static_cast<long>(pos),
                    SEEK_SET);
}

int64_t MXNativeRecordIOReaderTell(void* h) {
  return std::ftell(static_cast<Reader*>(h)->f);
}

void MXNativeRecordIOReaderClose(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r->f) std::fclose(r->f);
  delete r;
}

}  // extern "C"
