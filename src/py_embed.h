// Shared CPython-embedding plumbing for the C ABI libraries
// (libmxnet_tpu_predict.so, libmxnet_tpu_c.so): thread-local error strings,
// interpreter bootstrap, GIL guard, import helper.  Each library gets its
// own copy of the thread-local error state (reference semantics:
// MXGetLastError is per-library, include/mxnet/c_api.h).
#ifndef MXNET_TPU_SRC_PY_EMBED_H_
#define MXNET_TPU_SRC_PY_EMBED_H_

#include <Python.h>

#include <mutex>
#include <string>

namespace py_embed {

inline thread_local std::string g_last_error;

inline void SetError(const std::string &msg) { g_last_error = msg; }

// Capture the pending Python exception into the error string.
inline void SetPyError(const char *fallback) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = fallback;
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *utf8 = PyUnicode_AsUTF8(s);
      if (utf8 != nullptr) msg = utf8;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  SetError(msg);
}

// One-time interpreter bring-up.  When the host process already runs
// Python (e.g. tests loading the .so via ctypes) we piggyback on it.
inline bool EnsurePython() {
  static std::once_flag once;
  static bool ok = false;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      PyConfig config;
      PyConfig_InitPythonConfig(&config);
      PyStatus status = Py_InitializeFromConfig(&config);
      PyConfig_Clear(&config);
      if (PyStatus_Exception(status)) {
        return;  // ok stays false; callers surface the error
      }
      // Release the GIL acquired by Py_Initialize so PyGILState_Ensure
      // works from any caller thread.
      PyEval_SaveThread();
    }
    ok = true;
  });
  return ok;
}

struct GILGuard {
  PyGILState_STATE state;
  GILGuard() : state(PyGILState_Ensure()) {}
  ~GILGuard() { PyGILState_Release(state); }
};

// Import module attr; new reference, nullptr with error set on failure.
inline PyObject *GetAttr(const char *module, const char *attr) {
  PyObject *mod = PyImport_ImportModule(module);
  if (mod == nullptr) return nullptr;
  PyObject *a = PyObject_GetAttrString(mod, attr);
  Py_DECREF(mod);
  return a;
}

}  // namespace py_embed

#endif  // MXNET_TPU_SRC_PY_EMBED_H_
