// C predict ABI (reference parity: include/mxnet/c_predict_api.h:78-200,
// src/c_api/c_predict_api.cc — SURVEY.md N18).
//
// The reference exposes a minimal inference-only C surface —
// MXPredCreate / MXPredSetInput / MXPredForward / MXPredGetOutput — which is
// the waist every non-Python binding and the mobile amalgamation ride.  The
// TPU-native runtime's executor is the Python-built XLA plan, so this ABI
// embeds CPython (the official stable embedding API, no numpy headers
// needed) and drives mxnet_tpu.predictor.Predictor.  From the caller's
// side the contract is identical to the reference: flat float32 buffers in,
// flat float32 buffers out, thread-local error strings via MXGetLastError.
//
// Build: make libmxnet_tpu_predict.so (links libpython).  Host processes
// must have mxnet_tpu importable (PYTHONPATH or installed).
#include <Python.h>

#include "py_embed.h"

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

typedef uint32_t mx_uint;
typedef void *PredictorHandle;
typedef void *NDListHandle;

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

namespace {

using py_embed::EnsurePython;
using py_embed::g_last_error;
using py_embed::GILGuard;
using py_embed::SetError;
using py_embed::SetPyError;

struct Predictor {
  PyObject *obj = nullptr;                       // mxnet_tpu Predictor
  std::map<std::string, std::vector<mx_uint>> input_shapes;
  std::vector<mx_uint> shape_scratch;            // MXPredGetOutputShape
  ~Predictor() {
    if (obj != nullptr) {
      GILGuard gil;
      Py_DECREF(obj);
    }
  }
};

struct NDList {
  // Converted eagerly at create time so the pointers handed out by
  // MXNDListGet stay valid until MXNDListFree (reference contract) — a
  // shared scratch buffer would alias consecutive Get calls.
  struct Entry {
    std::string key;                             // "" for list-format blobs
    std::vector<float> data;
    std::vector<mx_uint> shape;
  };
  std::vector<Entry> entries;
};

// Fill pred->input_shapes and return a new {key: shape tuple} dict.
PyObject *BuildShapesDict(
    std::map<std::string, std::vector<mx_uint>> *input_shapes,
    mx_uint num_input_nodes, const char **input_keys,
    const mx_uint *input_shape_indptr, const mx_uint *input_shape_data) {
  PyObject *shapes = PyDict_New();
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    std::vector<mx_uint> shape(input_shape_data + input_shape_indptr[i],
                               input_shape_data + input_shape_indptr[i + 1]);
    (*input_shapes)[input_keys[i]] = shape;
    PyObject *tup = PyTuple_New(shape.size());
    for (size_t d = 0; d < shape.size(); ++d) {
      PyTuple_SET_ITEM(tup, d, PyLong_FromUnsignedLong(shape[d]));
    }
    PyDict_SetItemString(shapes, input_keys[i], tup);
    Py_DECREF(tup);
  }
  return shapes;
}

// Read obj.shape (a tuple of ints) into *shape without touching the data.
bool ShapeOf(PyObject *obj, std::vector<mx_uint> *shape) {
  PyObject *shp = PyObject_GetAttrString(obj, "shape");
  if (shp == nullptr) return false;
  PyObject *seq = PySequence_Tuple(shp);
  Py_DECREF(shp);
  if (seq == nullptr) return false;
  shape->clear();
  Py_ssize_t n = PyTuple_Size(seq);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape->push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(seq, i))));
  }
  Py_DECREF(seq);
  return !PyErr_Occurred();
}

using py_embed::GetAttr;

// flat float32 buffer -> numpy array of `shape` (copy).
PyObject *BufferToNumpy(const float *data, size_t size,
                        const std::vector<mx_uint> &shape) {
  PyObject *np_frombuffer = GetAttr("numpy", "frombuffer");
  if (np_frombuffer == nullptr) return nullptr;
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)));
  PyObject *arr = PyObject_CallFunction(np_frombuffer, "Os", bytes,
                                        "float32");
  Py_DECREF(bytes);
  Py_DECREF(np_frombuffer);
  if (arr == nullptr) return nullptr;
  PyObject *shape_tuple = PyTuple_New(shape.size());
  for (size_t i = 0; i < shape.size(); ++i) {
    PyTuple_SET_ITEM(shape_tuple, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject *reshaped =
      PyObject_CallMethod(arr, "reshape", "O", shape_tuple);
  Py_DECREF(shape_tuple);
  Py_DECREF(arr);
  return reshaped;
}

// any array-like -> flat float32 std::vector (via .asnumpy() if present).
bool NumpyToBuffer(PyObject *arr, std::vector<float> *out,
                   std::vector<mx_uint> *shape) {
  PyObject *np = arr;
  if (PyObject_HasAttrString(arr, "asnumpy")) {
    np = PyObject_CallMethod(arr, "asnumpy", nullptr);
    if (np == nullptr) return false;
  } else {
    Py_INCREF(np);
  }
  PyObject *np32 = PyObject_CallMethod(np, "astype", "s", "float32");
  Py_DECREF(np);
  if (np32 == nullptr) return false;
  if (shape != nullptr) {
    shape->clear();
    PyObject *shp = PyObject_GetAttrString(np32, "shape");
    if (shp == nullptr) {
      Py_DECREF(np32);
      return false;
    }
    Py_ssize_t n = PyTuple_Size(shp);
    for (Py_ssize_t i = 0; i < n; ++i) {
      shape->push_back(static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i))));
    }
    Py_DECREF(shp);
  }
  PyObject *bytes = PyObject_CallMethod(np32, "tobytes", nullptr);
  Py_DECREF(np32);
  if (bytes == nullptr) return false;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(bytes, &buf, &len);
  out->resize(static_cast<size_t>(len) / sizeof(float));
  std::memcpy(out->data(), buf, static_cast<size_t>(len));
  Py_DECREF(bytes);
  return true;
}

}  // namespace

MXNET_DLL const char *MXGetLastError() { return g_last_error.c_str(); }

// Create a predictor from symbol JSON + parameter blob + input shapes.
// dev_type follows the reference enum (1 = cpu, 2 = gpu; this runtime also
// accepts 4 = tpu and maps 2 -> the default accelerator context).
MXNET_DLL int MXPredCreate(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes,
                           const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           PredictorHandle *out) {
  if (!EnsurePython()) {
    SetError("failed to initialize embedded Python");
    return -1;
  }
  GILGuard gil;
  auto *pred = new Predictor();
  PyObject *shapes =
      BuildShapesDict(&pred->input_shapes, num_input_nodes, input_keys,
                      input_shape_indptr, input_shape_data);
  PyObject *cls = GetAttr("mxnet_tpu.predictor", "Predictor");
  if (cls == nullptr) {
    SetPyError("cannot import mxnet_tpu.predictor (is mxnet_tpu on "
               "PYTHONPATH?)");
    Py_DECREF(shapes);
    delete pred;
    return -1;
  }
  PyObject *params = PyBytes_FromStringAndSize(
      static_cast<const char *>(param_bytes), param_size);
  const char *dev_str = dev_type == 1 ? "cpu" : dev_type == 4 ? "tpu"
                                                              : "gpu";
  PyObject *kwargs = Py_BuildValue("{s:s, s:i, s:O}", "dev_type", dev_str,
                                   "dev_id", dev_id, "input_shapes",
                                   shapes);
  PyObject *args = Py_BuildValue("(sO)", symbol_json_str, params);
  pred->obj = PyObject_Call(cls, args, kwargs);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(params);
  Py_DECREF(shapes);
  Py_DECREF(cls);
  if (pred->obj == nullptr) {
    SetPyError("MXPredCreate failed");
    delete pred;
    return -1;
  }
  *out = pred;
  return 0;
}

MXNET_DLL int MXPredSetInput(PredictorHandle handle, const char *key,
                             const float *data, mx_uint size) {
  auto *pred = static_cast<Predictor *>(handle);
  GILGuard gil;
  auto it = pred->input_shapes.find(key);
  if (it == pred->input_shapes.end()) {
    SetError(std::string("unknown input key: ") + key);
    return -1;
  }
  size_t expect = 1;
  for (mx_uint d : it->second) expect *= d;
  if (expect != size) {
    SetError("MXPredSetInput: size mismatch for '" + std::string(key) +
             "': got " + std::to_string(size) + ", expected " +
             std::to_string(expect));
    return -1;
  }
  PyObject *arr = BufferToNumpy(data, size, it->second);
  if (arr == nullptr) {
    SetPyError("MXPredSetInput: buffer conversion failed");
    return -1;
  }
  PyObject *r = PyObject_CallMethod(pred->obj, "set_input", "sO", key, arr);
  Py_DECREF(arr);
  if (r == nullptr) {
    SetPyError("MXPredSetInput failed");
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXPredForward(PredictorHandle handle) {
  auto *pred = static_cast<Predictor *>(handle);
  GILGuard gil;
  PyObject *r = PyObject_CallMethod(pred->obj, "forward", nullptr);
  if (r == nullptr) {
    SetPyError("MXPredForward failed");
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                                   mx_uint **shape_data,
                                   mx_uint *shape_ndim) {
  auto *pred = static_cast<Predictor *>(handle);
  GILGuard gil;
  PyObject *out =
      PyObject_CallMethod(pred->obj, "get_output", "I", index);
  if (out == nullptr) {
    SetPyError("MXPredGetOutputShape failed");
    return -1;
  }
  if (!ShapeOf(out, &pred->shape_scratch)) {
    Py_DECREF(out);
    SetPyError("MXPredGetOutputShape: cannot read output shape");
    return -1;
  }
  Py_DECREF(out);
  *shape_data = pred->shape_scratch.data();
  *shape_ndim = static_cast<mx_uint>(pred->shape_scratch.size());
  return 0;
}

MXNET_DLL int MXPredGetOutput(PredictorHandle handle, mx_uint index,
                              float *data, mx_uint size) {
  auto *pred = static_cast<Predictor *>(handle);
  GILGuard gil;
  PyObject *out =
      PyObject_CallMethod(pred->obj, "get_output", "I", index);
  if (out == nullptr) {
    SetPyError("MXPredGetOutput failed");
    return -1;
  }
  std::vector<float> buf;
  if (!NumpyToBuffer(out, &buf, nullptr)) {
    Py_DECREF(out);
    SetPyError("MXPredGetOutput: conversion failed");
    return -1;
  }
  Py_DECREF(out);
  if (buf.size() != size) {
    SetError("MXPredGetOutput: size mismatch: output has " +
             std::to_string(buf.size()) + " elements, caller asked for " +
             std::to_string(size));
    return -1;
  }
  std::memcpy(data, buf.data(), size * sizeof(float));
  return 0;
}

MXNET_DLL int MXPredReshape(PredictorHandle handle,
                            mx_uint num_input_nodes,
                            const char **input_keys,
                            const mx_uint *input_shape_indptr,
                            const mx_uint *input_shape_data,
                            PredictorHandle *out) {
  auto *pred = static_cast<Predictor *>(handle);
  GILGuard gil;
  auto *fresh = new Predictor();
  PyObject *shapes =
      BuildShapesDict(&fresh->input_shapes, num_input_nodes, input_keys,
                      input_shape_indptr, input_shape_data);
  fresh->obj = PyObject_CallMethod(pred->obj, "reshape", "O", shapes);
  Py_DECREF(shapes);
  if (fresh->obj == nullptr) {
    SetPyError("MXPredReshape failed");
    delete fresh;
    return -1;
  }
  *out = fresh;
  return 0;
}

MXNET_DLL int MXPredFree(PredictorHandle handle) {
  delete static_cast<Predictor *>(handle);
  return 0;
}

// ---- NDList: parameter-blob inspection (MXNDListCreate family) ----------

MXNET_DLL int MXNDListCreate(const char *nd_file_bytes, int size,
                             NDListHandle *out, mx_uint *out_length) {
  if (!EnsurePython()) {
    SetError("failed to initialize embedded Python");
    return -1;
  }
  GILGuard gil;
  PyObject *loader = GetAttr("mxnet_tpu.predictor", "load_ndarray_file");
  if (loader == nullptr) {
    SetPyError("cannot import mxnet_tpu.predictor");
    return -1;
  }
  PyObject *bytes = PyBytes_FromStringAndSize(nd_file_bytes, size);
  PyObject *loaded = PyObject_CallFunctionObjArgs(loader, bytes, nullptr);
  Py_DECREF(bytes);
  Py_DECREF(loader);
  if (loaded == nullptr) {
    SetPyError("MXNDListCreate failed");
    return -1;
  }
  auto *list = new NDList();
  bool failed = false;
  if (PyDict_Check(loaded)) {
    PyObject *key = nullptr, *value = nullptr;
    Py_ssize_t pos = 0;
    while (PyDict_Next(loaded, &pos, &key, &value)) {
      NDList::Entry e;
      const char *k = PyUnicode_AsUTF8(key);
      e.key = k != nullptr ? k : "";
      if (!NumpyToBuffer(value, &e.data, &e.shape)) {
        failed = true;
        break;
      }
      list->entries.push_back(std::move(e));
    }
  } else if (PyList_Check(loaded)) {
    // list-format blob (nd.save of a list): entries have empty keys,
    // matching the reference MXNDListCreate contract
    for (Py_ssize_t i = 0; i < PyList_Size(loaded); ++i) {
      NDList::Entry e;
      if (!NumpyToBuffer(PyList_GetItem(loaded, i), &e.data, &e.shape)) {
        failed = true;
        break;
      }
      list->entries.push_back(std::move(e));
    }
  } else {
    SetError("MXNDListCreate: blob did not load as a dict or list");
    failed = true;
  }
  Py_DECREF(loaded);
  if (failed) {
    if (PyErr_Occurred()) SetPyError("MXNDListCreate: conversion failed");
    delete list;
    return -1;
  }
  *out = list;
  *out_length = static_cast<mx_uint>(list->entries.size());
  return 0;
}

MXNET_DLL int MXNDListGet(NDListHandle handle, mx_uint index,
                          const char **out_key, const float **out_data,
                          const mx_uint **out_shape, mx_uint *out_ndim) {
  auto *list = static_cast<NDList *>(handle);
  if (index >= list->entries.size()) {
    SetError("MXNDListGet: index out of range");
    return -1;
  }
  const NDList::Entry &e = list->entries[index];
  *out_key = e.key.c_str();
  *out_data = e.data.data();
  *out_shape = e.shape.data();
  *out_ndim = static_cast<mx_uint>(e.shape.size());
  return 0;
}

MXNET_DLL int MXNDListFree(NDListHandle handle) {
  delete static_cast<NDList *>(handle);
  return 0;
}
