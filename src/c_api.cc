// C API waist — NDArray CRUD + imperative invoke + op listing
// (reference parity: include/mxnet/c_api.h Parts 0-2 — MXGetLastError,
// MXNDArrayCreate*/Free/GetShape/GetDType/SyncCopy*/WaitToRead/WaitAll/
// Slice/Reshape/GetContext/Save/Load, MXListAllOpNames,
// MXSymbolListAtomicSymbolCreators + MXImperativeInvoke; src/c_api/c_api.cc
// and c_api_ndarray.cc in the reference tree — SURVEY.md N17).
//
// Same architecture as the predict ABI (src/predict.cc): the TPU-native
// runtime's compute path is the Python-built XLA plan, so this library
// embeds CPython and marshals through mxnet_tpu._capi_bridge, which takes
// and returns only simple types.  From the caller's side the contract
// matches the reference: opaque NDArrayHandle, flat host buffers, string
// attrs, thread-local error strings, 0/-1 return codes.
//
// Build: make libmxnet_tpu_c.so (links libpython).  Host processes must
// have mxnet_tpu importable (PYTHONPATH or installed).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "py_embed.h"

typedef uint32_t mx_uint;
typedef void *NDArrayHandle;
typedef void *AtomicSymbolCreator;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

namespace {

using py_embed::EnsurePython;
using py_embed::g_last_error;
using py_embed::GILGuard;
using py_embed::SetError;
using py_embed::SetPyError;

// An NDArrayHandle: owns one bridge NDArray + scratch the shape pointer
// handed to callers stays valid in (reference MXAPIThreadLocalEntry role,
// but per-handle so concurrent handles don't stomp each other).
struct ND {
  PyObject *obj = nullptr;
  std::vector<mx_uint> shape_scratch;
  ~ND() {
    if (obj != nullptr) {
      GILGuard gil;
      Py_DECREF(obj);
    }
  }
};

// Call mxnet_tpu._capi_bridge.<fn>(*args).  Steals `args` (a tuple).
// Returns a new reference or nullptr with g_last_error set.
PyObject *CallBridge(const char *fn, PyObject *args) {
  PyObject *mod = PyImport_ImportModule("mxnet_tpu._capi_bridge");
  if (mod == nullptr) {
    Py_XDECREF(args);
    SetPyError("cannot import mxnet_tpu._capi_bridge");
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    Py_XDECREF(args);
    SetPyError(fn);
    return nullptr;
  }
  PyObject *out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) SetPyError(fn);
  return out;
}

// Wrap a bridge NDArray (new reference, stolen) into a fresh handle.
NDArrayHandle WrapND(PyObject *obj) {
  ND *h = new ND();
  h->obj = obj;
  return static_cast<NDArrayHandle>(h);
}

PyObject *ObjOf(NDArrayHandle handle) {
  return static_cast<ND *>(handle)->obj;
}

PyObject *UIntTuple(const mx_uint *data, mx_uint n) {
  PyObject *tup = PyTuple_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyTuple_SET_ITEM(tup, i, PyLong_FromUnsignedLong(data[i]));
  }
  return tup;
}

bool FillShapeScratch(ND *h) {
  PyObject *shp = CallBridge("shape_of",
                             Py_BuildValue("(O)", h->obj));
  if (shp == nullptr) return false;
  h->shape_scratch.clear();
  Py_ssize_t n = PyTuple_Size(shp);
  for (Py_ssize_t i = 0; i < n; ++i) {
    h->shape_scratch.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i))));
  }
  Py_DECREF(shp);
  if (PyErr_Occurred()) {
    // fetch+clear the pending exception into MXGetLastError — leaving it
    // set would poison the next CPython call (SystemError) and report a
    // stale message here (advisor r04)
    SetPyError("shape_of");
    return false;
  }
  return true;
}

// Interned op-name table backing AtomicSymbolCreator values.  A failed
// first load is retried on the next call (transient import errors must not
// wedge the process), and the failure message is set per failing call so
// every thread sees it in its MXGetLastError.
std::vector<std::string> *OpNameTable() {
  static std::mutex mu;
  static std::vector<std::string> table;
  static bool ok = false;
  // GIL strictly before mu: a caller already holding the GIL must not be
  // able to block on mu while another thread holds mu and waits for the
  // GIL (classic lock-order inversion)
  GILGuard gil;
  std::lock_guard<std::mutex> lock(mu);
  if (!ok) {
    PyObject *names = CallBridge("list_ops", PyTuple_New(0));
    if (names == nullptr) return nullptr;   // error set by CallBridge
    Py_ssize_t n = PyList_Size(names);
    table.clear();
    table.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      table.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
    }
    Py_DECREF(names);
    ok = true;
  }
  return &table;
}

}  // namespace

// ---- Part 0: global state -------------------------------------------------

MXNET_DLL const char *MXGetLastError() { return g_last_error.c_str(); }

MXNET_DLL int MXGetVersion(int *out) {
  *out = 10200;  // reference-era version code (1.2.0)
  return 0;
}

MXNET_DLL int MXRandomSeed(int seed) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("random_seed", Py_BuildValue("(i)", seed));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArrayWaitAll() {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("wait_all", PyTuple_New(0));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXEngineWaitAll() { return MXNDArrayWaitAll(); }

MXNET_DLL int MXNotifyShutdown() { return MXNDArrayWaitAll(); }

// ---- Part 1: NDArray ------------------------------------------------------

MXNET_DLL int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim,
                                int dev_type, int dev_id, int delay_alloc,
                                int dtype, NDArrayHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *shp = UIntTuple(shape, ndim);
  PyObject *obj = CallBridge("create", Py_BuildValue(
      "(Niiii)", shp, dev_type, dev_id, dtype, delay_alloc));
  if (obj == nullptr) return -1;
  *out = WrapND(obj);
  return 0;
}

MXNET_DLL int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              NDArrayHandle *out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc,
                           0 /*float32*/, out);
}

MXNET_DLL int MXNDArrayCreateNone(NDArrayHandle *out) {
  mx_uint shape[1] = {0};
  return MXNDArrayCreate(shape, 1, 1 /*cpu*/, 0, 0, out);
}

MXNET_DLL int MXNDArrayFree(NDArrayHandle handle) {
  delete static_cast<ND *>(handle);
  return 0;
}

MXNET_DLL int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                                const mx_uint **out_pdata) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  ND *h = static_cast<ND *>(handle);
  if (!FillShapeScratch(h)) return -1;
  *out_dim = static_cast<mx_uint>(h->shape_scratch.size());
  *out_pdata = h->shape_scratch.data();
  return 0;
}

MXNET_DLL int MXNDArrayGetDType(NDArrayHandle handle, int *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("dtype_code_of",
                           Py_BuildValue("(O)", ObjOf(handle)));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                                  int *out_dev_id) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("ctx_of", Py_BuildValue("(O)", ObjOf(handle)));
  if (r == nullptr) return -1;
  *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 0)));
  *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
  Py_DECREF(r);
  return 0;
}

// size is an element count (reference contract, CHECKed equal to the
// array's size on the bridge side); the bridge reads/writes the caller's
// buffer directly through the pointer, deriving bytes from the handle's
// dtype — no itemsize table to keep in sync here.
MXNET_DLL int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                                       size_t size) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("copy_from_ptr", Py_BuildValue(
      "(KKO)", static_cast<unsigned long long>(
                   reinterpret_cast<uintptr_t>(data)),
      static_cast<unsigned long long>(size), ObjOf(handle)));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     size_t size) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("copy_to_ptr", Py_BuildValue(
      "(KKO)", static_cast<unsigned long long>(
                   reinterpret_cast<uintptr_t>(data)),
      static_cast<unsigned long long>(size), ObjOf(handle)));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArrayWaitToRead(NDArrayHandle handle) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("wait_to_read",
                           Py_BuildValue("(O)", ObjOf(handle)));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                             NDArrayHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *obj = CallBridge("slice_", Py_BuildValue(
      "(OII)", ObjOf(handle), begin, end));
  if (obj == nullptr) return -1;
  *out = WrapND(obj);
  return 0;
}

MXNET_DLL int MXNDArrayReshape(NDArrayHandle handle, int ndim, int *dims,
                               NDArrayHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *tup = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(tup, i, PyLong_FromLong(dims[i]));
  }
  PyObject *obj = CallBridge("reshape", Py_BuildValue(
      "(ON)", ObjOf(handle), tup));
  if (obj == nullptr) return -1;
  *out = WrapND(obj);
  return 0;
}

MXNET_DLL int MXNDArraySave(const char *fname, mx_uint num_args,
                            NDArrayHandle *args, const char **keys) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *handles = PyList_New(num_args);
  PyObject *names = PyList_New(keys ? num_args : 0);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject *o = ObjOf(args[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(handles, i, o);
    if (keys) {
      PyList_SET_ITEM(names, i, PyUnicode_FromString(keys[i]));
    }
  }
  PyObject *r = CallBridge("save", Py_BuildValue("(sNN)", fname,
                                                 handles, names));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                            NDArrayHandle **out_arr, mx_uint *out_name_size,
                            const char ***out_names) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("load", Py_BuildValue("(s)", fname));
  if (r == nullptr) return -1;
  PyObject *arrs = PyTuple_GetItem(r, 0);
  PyObject *names = PyTuple_GetItem(r, 1);
  // thread-local return scratch (reference MXAPIThreadLocalEntry): the
  // handle array + name pointers stay valid until the next Load on this
  // thread; the handles themselves are caller-owned (caller frees each).
  static thread_local std::vector<NDArrayHandle> ret_handles;
  static thread_local std::vector<std::string> ret_names;
  static thread_local std::vector<const char *> ret_name_ptrs;
  ret_handles.clear();
  ret_names.clear();
  ret_name_ptrs.clear();
  Py_ssize_t n = PyList_Size(arrs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(arrs, i);
    Py_INCREF(o);
    ret_handles.push_back(WrapND(o));
  }
  Py_ssize_t nn = PyList_Size(names);
  for (Py_ssize_t i = 0; i < nn; ++i) {
    ret_names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
  }
  for (auto &s : ret_names) ret_name_ptrs.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(ret_handles.size());
  *out_arr = ret_handles.data();
  *out_name_size = static_cast<mx_uint>(ret_name_ptrs.size());
  *out_names = ret_name_ptrs.data();
  return 0;
}

// ---- Part 2: op listing + imperative invoke -------------------------------

MXNET_DLL int MXListAllOpNames(mx_uint *out_size, const char ***out_array) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  auto *table = OpNameTable();
  if (table == nullptr) { return -1; }
  static thread_local std::vector<const char *> ptrs;
  ptrs.clear();
  for (auto &s : *table) ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(ptrs.size());
  *out_array = ptrs.data();
  return 0;
}

MXNET_DLL int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                               AtomicSymbolCreator **out_array) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  auto *table = OpNameTable();
  if (table == nullptr) { return -1; }
  static thread_local std::vector<AtomicSymbolCreator> creators;
  creators.clear();
  for (auto &s : *table) {
    creators.push_back(const_cast<std::string *>(&s));
  }
  *out_size = static_cast<mx_uint>(creators.size());
  *out_array = creators.data();
  return 0;
}

MXNET_DLL int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                          const char **name) {
  *name = static_cast<std::string *>(creator)->c_str();
  return 0;
}

MXNET_DLL int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs, int num_params,
                                 const char **param_keys,
                                 const char **param_vals) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  const std::string *op = static_cast<std::string *>(creator);
  PyObject *ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject *o = ObjOf(inputs[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
  }
  PyObject *keys = PyList_New(num_params);
  PyObject *vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  // Reference contract: a non-NULL *outputs is a caller-supplied array of
  // existing handles the results are written into (out= semantics — how
  // sgd_update(w, g, out=w) updates in place over the ABI).
  bool has_outs = (*outputs != nullptr && *num_outputs > 0);
  PyObject *outs = PyList_New(has_outs ? *num_outputs : 0);
  if (has_outs) {
    for (int i = 0; i < *num_outputs; ++i) {
      PyObject *o = ObjOf((*outputs)[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(outs, i, o);
    }
  }
  PyObject *r = CallBridge("invoke", Py_BuildValue(
      "(sNNNN)", op->c_str(), ins, keys, vals, outs));
  if (r == nullptr) return -1;
  if (has_outs) {
    Py_DECREF(r);   // results already written into the supplied handles
    return 0;
  }
  static thread_local std::vector<NDArrayHandle> ret;
  ret.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(r, i);
    Py_INCREF(o);
    ret.push_back(WrapND(o));
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(ret.size());
  *outputs = ret.data();
  return 0;
}

// ---- Part 2b: autograd (MXAutograd* in the reference ABI) -----------------

MXNET_DLL int MXAutogradSetIsRecording(int is_recording, int *prev) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("autograd_set_recording",
                           Py_BuildValue("(i)", is_recording));
  if (r == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXAutogradSetIsTraining(int is_training, int *prev) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("autograd_set_training",
                           Py_BuildValue("(i)", is_training));
  if (r == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXAutogradMarkVariables(mx_uint num_var,
                                      NDArrayHandle *var_handles) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *vars = PyList_New(num_var);
  for (mx_uint i = 0; i < num_var; ++i) {
    PyObject *o = ObjOf(var_handles[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(vars, i, o);
  }
  PyObject *r = CallBridge("autograd_mark_variables",
                           Py_BuildValue("(N)", vars));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXAutogradBackward(mx_uint num_output,
                                 NDArrayHandle *output_handles,
                                 int retain_graph) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *heads = PyList_New(num_output);
  for (mx_uint i = 0; i < num_output; ++i) {
    PyObject *o = ObjOf(output_handles[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(heads, i, o);
  }
  PyObject *r = CallBridge("autograd_backward",
                           Py_BuildValue("(Ni)", heads, retain_graph));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *obj = CallBridge("get_grad", Py_BuildValue("(O)", ObjOf(handle)));
  if (obj == nullptr) return -1;
  *out = WrapND(obj);
  return 0;
}

// ---- Part 3: symbol (reference c_api.h:1028, src/c_api/c_api_symbolic.cc) --
//
// A SymbolHandle owns one bridge Symbol (or pending _AtomicSymbol) plus the
// per-handle return scratch for string lists / JSON / inferred shapes, so
// concurrent handles never stomp each other (MXAPIThreadLocalEntry role).

namespace {

struct Sym {
  PyObject *obj = nullptr;
  std::vector<std::string> strs;
  std::vector<const char *> ptrs;
  std::string json;
  std::string name;   // GetName scratch — must not clobber the JSON one
  // InferShape scratch: flat dims + ndim + per-shape pointers, 3 sections
  std::vector<mx_uint> shape_dims[3];
  std::vector<mx_uint> shape_ndim[3];
  std::vector<const mx_uint *> shape_ptr[3];
  ~Sym() {
    if (obj != nullptr) {
      GILGuard gil;
      Py_DECREF(obj);
    }
  }
};

PyObject *SymObj(SymbolHandle h) { return static_cast<Sym *>(h)->obj; }

// Fill a handle's (strs, ptrs) scratch from a PyList[str]; returns false
// with the error set on a non-list / non-str payload.
bool FillStrList(Sym *h, PyObject *list) {
  h->strs.clear();
  h->ptrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *s = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    if (s == nullptr) { SetPyError("string list"); return false; }
    h->strs.emplace_back(s);
  }
  for (auto &s : h->strs) h->ptrs.push_back(s.c_str());
  return true;
}

int SymbolListCommon(const char *bridge_fn, SymbolHandle sym,
                     mx_uint *out_size, const char ***out_array) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  Sym *h = static_cast<Sym *>(sym);
  PyObject *r = CallBridge(bridge_fn, Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return -1;
  bool ok = FillStrList(h, r);
  Py_DECREF(r);
  if (!ok) return -1;
  *out_size = static_cast<mx_uint>(h->ptrs.size());
  *out_array = h->ptrs.data();
  return 0;
}

}  // namespace

MXNET_DLL int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                                         mx_uint num_param, const char **keys,
                                         const char **vals,
                                         SymbolHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  const std::string *op = static_cast<std::string *>(creator);
  PyObject *pk = PyList_New(num_param);
  PyObject *pv = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SET_ITEM(pk, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(pv, i, PyUnicode_FromString(vals[i]));
  }
  PyObject *obj = CallBridge("symbol_create_atomic", Py_BuildValue(
      "(sNN)", op->c_str(), pk, pv));
  if (obj == nullptr) return -1;
  Sym *h = new Sym();
  h->obj = obj;
  *out = h;
  return 0;
}

MXNET_DLL int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *obj = CallBridge("symbol_create_variable",
                             Py_BuildValue("(s)", name));
  if (obj == nullptr) return -1;
  Sym *h = new Sym();
  h->obj = obj;
  *out = h;
  return 0;
}

MXNET_DLL int MXSymbolCompose(SymbolHandle sym, const char *name,
                              mx_uint num_args, const char **keys,
                              SymbolHandle *args) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  Sym *h = static_cast<Sym *>(sym);
  PyObject *pk = PyList_New(keys ? num_args : 0);
  PyObject *pa = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    if (keys) PyList_SET_ITEM(pk, i, PyUnicode_FromString(keys[i]));
    PyObject *o = SymObj(args[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(pa, i, o);
  }
  PyObject *composed = CallBridge("symbol_compose", Py_BuildValue(
      "(OsNN)", h->obj, name ? name : "", pk, pa));
  if (composed == nullptr) return -1;
  // reference semantics: the same handle becomes the composed symbol
  Py_DECREF(h->obj);
  h->obj = composed;
  return 0;
}

MXNET_DLL int MXSymbolCopy(SymbolHandle sym, SymbolHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *obj = CallBridge("symbol_copy", Py_BuildValue("(O)", SymObj(sym)));
  if (obj == nullptr) return -1;
  Sym *h = new Sym();
  h->obj = obj;
  *out = h;
  return 0;
}

MXNET_DLL int MXSymbolFree(SymbolHandle sym) {
  delete static_cast<Sym *>(sym);
  return 0;
}

MXNET_DLL int MXSymbolGetName(SymbolHandle sym, const char **out,
                              int *success) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  Sym *h = static_cast<Sym *>(sym);
  PyObject *r = CallBridge("symbol_get_name", Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return -1;
  const char *s = PyUnicode_AsUTF8(r);
  h->name.assign(s ? s : "");
  Py_DECREF(r);
  *out = h->name.c_str();
  if (success) *success = h->name.empty() ? 0 : 1;
  return 0;
}

MXNET_DLL int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                                    const char ***out_array) {
  return SymbolListCommon("symbol_list_arguments", sym, out_size, out_array);
}

MXNET_DLL int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                                  const char ***out_array) {
  return SymbolListCommon("symbol_list_outputs", sym, out_size, out_array);
}

MXNET_DLL int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint *out_size,
                                          const char ***out_array) {
  return SymbolListCommon("symbol_list_aux", sym, out_size, out_array);
}

MXNET_DLL int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  Sym *h = static_cast<Sym *>(sym);
  PyObject *r = CallBridge("symbol_tojson", Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return -1;
  const char *s = PyUnicode_AsUTF8(r);
  if (s == nullptr) { Py_DECREF(r); SetPyError("tojson"); return -1; }
  h->json.assign(s);
  Py_DECREF(r);
  *out_json = h->json.c_str();
  return 0;
}

MXNET_DLL int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *obj = CallBridge("symbol_from_json", Py_BuildValue("(s)", json));
  if (obj == nullptr) return -1;
  Sym *h = new Sym();
  h->obj = obj;
  *out = h;
  return 0;
}

MXNET_DLL int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                          const char **name,
                                          const char **description,
                                          mx_uint *num_args,
                                          const char ***arg_names,
                                          const char ***arg_type_infos,
                                          const char ***arg_descriptions,
                                          const char **key_var_num_args) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  const std::string *op = static_cast<std::string *>(creator);
  PyObject *r = CallBridge("op_info", Py_BuildValue("(s)", op->c_str()));
  if (r == nullptr) return -1;
  // scratch lives until the next GetAtomicSymbolInfo on this thread
  struct InfoScratch {
    std::string doc, kv;
    std::vector<std::string> names, types;
    std::vector<const char *> name_ptrs, type_ptrs, desc_ptrs;
  };
  static thread_local InfoScratch sc;
  sc.names.clear(); sc.types.clear();
  sc.name_ptrs.clear(); sc.type_ptrs.clear(); sc.desc_ptrs.clear();
  const char *doc = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
  if (doc == nullptr) PyErr_Clear();  // tolerate a missing doc, but don't
                                      // leave its exception pending
  sc.doc.assign(doc ? doc : "");
  PyObject *tensor_args = PyTuple_GetItem(r, 1);
  PyObject *pnames = PyTuple_GetItem(r, 2);
  PyObject *ptypes = PyTuple_GetItem(r, 3);
  PyObject *preq = PyTuple_GetItem(r, 4);
  long variadic = PyLong_AsLong(PyTuple_GetItem(r, 5));
  for (Py_ssize_t i = 0; i < PyList_Size(tensor_args); ++i) {
    const char *an = PyUnicode_AsUTF8(PyList_GetItem(tensor_args, i));
    if (an == nullptr) { Py_DECREF(r); SetPyError("op_info"); return -1; }
    sc.names.emplace_back(an);
    sc.types.emplace_back("NDArray-or-Symbol");
  }
  for (Py_ssize_t i = 0; i < PyList_Size(pnames); ++i) {
    const char *pn = PyUnicode_AsUTF8(PyList_GetItem(pnames, i));
    if (pn == nullptr) { Py_DECREF(r); SetPyError("op_info"); return -1; }
    sc.names.emplace_back(pn);
    const char *pt = PyUnicode_AsUTF8(PyList_GetItem(ptypes, i));
    if (pt == nullptr) { Py_DECREF(r); SetPyError("op_info"); return -1; }
    std::string t = pt;
    t += PyLong_AsLong(PyList_GetItem(preq, i)) ? ", required"
                                                : ", optional";
    sc.types.emplace_back(t);
  }
  Py_DECREF(r);
  for (size_t i = 0; i < sc.names.size(); ++i) {
    sc.name_ptrs.push_back(sc.names[i].c_str());
    sc.type_ptrs.push_back(sc.types[i].c_str());
    sc.desc_ptrs.push_back("");
  }
  sc.kv = variadic ? "num_args" : "";
  *name = op->c_str();
  *description = sc.doc.c_str();
  *num_args = static_cast<mx_uint>(sc.names.size());
  *arg_names = sc.name_ptrs.data();
  *arg_type_infos = sc.type_ptrs.data();
  *arg_descriptions = sc.desc_ptrs.data();
  *key_var_num_args = sc.kv.c_str();
  return 0;
}

MXNET_DLL int MXSymbolInferShape(
    SymbolHandle sym, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
    const mx_uint ***in_shape_data,
    mx_uint *out_shape_size, const mx_uint **out_shape_ndim,
    const mx_uint ***out_shape_data,
    mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
    const mx_uint ***aux_shape_data, int *complete) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  Sym *h = static_cast<Sym *>(sym);
  PyObject *pk = PyList_New(num_args);
  PyObject *ps = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(pk, i, PyUnicode_FromString(keys[i]));
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject *shp = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j) {
      PyTuple_SET_ITEM(shp, j - lo,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
    }
    PyList_SET_ITEM(ps, i, shp);
  }
  PyObject *r = CallBridge("symbol_infer_shape", Py_BuildValue(
      "(ONNi)", h->obj, pk, ps, 0));
  if (r == nullptr) return -1;
  bool all_known = true;
  for (int sec = 0; sec < 3; ++sec) {
    PyObject *shapes = PyTuple_GetItem(r, sec);
    auto &dims = h->shape_dims[sec];
    auto &ndim = h->shape_ndim[sec];
    auto &ptr = h->shape_ptr[sec];
    dims.clear(); ndim.clear(); ptr.clear();
    Py_ssize_t n = PyList_Size(shapes);
    std::vector<size_t> offs;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *shp = PyList_GetItem(shapes, i);
      Py_ssize_t nd = PyTuple_Size(shp);
      if (nd == 0) all_known = false;
      ndim.push_back(static_cast<mx_uint>(nd));
      offs.push_back(dims.size());
      for (Py_ssize_t j = 0; j < nd; ++j) {
        dims.push_back(static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyTuple_GetItem(shp, j))));
      }
    }
    for (size_t i = 0; i < offs.size(); ++i) {
      ptr.push_back(dims.data() + offs[i]);   // stable: dims is final
    }
  }
  Py_DECREF(r);
  *in_shape_size = static_cast<mx_uint>(h->shape_ndim[0].size());
  *in_shape_ndim = h->shape_ndim[0].data();
  *in_shape_data = h->shape_ptr[0].data();
  *out_shape_size = static_cast<mx_uint>(h->shape_ndim[1].size());
  *out_shape_ndim = h->shape_ndim[1].data();
  *out_shape_data = h->shape_ptr[1].data();
  *aux_shape_size = static_cast<mx_uint>(h->shape_ndim[2].size());
  *aux_shape_ndim = h->shape_ndim[2].data();
  *aux_shape_data = h->shape_ptr[2].data();
  if (complete) *complete = all_known ? 1 : 0;
  return 0;
}

// ---- Part 4: executor (reference c_api.h:1483, c_api_executor.cc) ---------

namespace {

struct Exec {
  PyObject *obj = nullptr;
  std::vector<NDArrayHandle> out_handles;
  ~Exec() {
    if (obj != nullptr) {
      GILGuard gil;
      Py_DECREF(obj);
    }
  }
};

}  // namespace

MXNET_DLL int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                             mx_uint len, NDArrayHandle *in_args,
                             NDArrayHandle *arg_grad_store,
                             mx_uint *grad_req_type, mx_uint aux_states_len,
                             NDArrayHandle *aux_states, ExecutorHandle *out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *pargs = PyList_New(len);
  PyObject *pgrads = PyList_New(len);
  PyObject *preq = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i) {
    PyObject *a = ObjOf(in_args[i]);
    Py_INCREF(a);
    PyList_SET_ITEM(pargs, i, a);
    PyObject *g = Py_None;
    if (arg_grad_store != nullptr && arg_grad_store[i] != nullptr) {
      g = ObjOf(arg_grad_store[i]);
    }
    Py_INCREF(g);
    PyList_SET_ITEM(pgrads, i, g);
    PyList_SET_ITEM(preq, i, PyLong_FromUnsignedLong(
        grad_req_type ? grad_req_type[i] : 0));
  }
  PyObject *paux = PyList_New(aux_states_len);
  for (mx_uint i = 0; i < aux_states_len; ++i) {
    PyObject *a = ObjOf(aux_states[i]);
    Py_INCREF(a);
    PyList_SET_ITEM(paux, i, a);
  }
  PyObject *obj = CallBridge("executor_bind", Py_BuildValue(
      "(OiiNNNN)", SymObj(sym), dev_type, dev_id, pargs, pgrads, preq,
      paux));
  if (obj == nullptr) return -1;
  Exec *h = new Exec();
  h->obj = obj;
  *out = h;
  return 0;
}

MXNET_DLL int MXExecutorForward(ExecutorHandle handle, int is_train) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *r = CallBridge("executor_forward", Py_BuildValue(
      "(Oi)", static_cast<Exec *>(handle)->obj, is_train));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                                 NDArrayHandle *head_grads) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  PyObject *heads = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i) {
    PyObject *o = ObjOf(head_grads[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(heads, i, o);
  }
  PyObject *r = CallBridge("executor_backward", Py_BuildValue(
      "(ON)", static_cast<Exec *>(handle)->obj, heads));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

MXNET_DLL int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                                NDArrayHandle **out) {
  if (!EnsurePython()) { SetError("python init failed"); return -1; }
  GILGuard gil;
  Exec *h = static_cast<Exec *>(handle);
  PyObject *r = CallBridge("executor_outputs",
                           Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return -1;
  h->out_handles.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(r, i);
    Py_INCREF(o);
    h->out_handles.push_back(WrapND(o));
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(h->out_handles.size());
  *out = h->out_handles.data();
  return 0;
}

MXNET_DLL int MXExecutorFree(ExecutorHandle handle) {
  delete static_cast<Exec *>(handle);
  return 0;
}

// Convenience: invoke by op name directly (TPU-native addition so C callers
// can skip the creator-table round trip; the reference reaches the same
// code through NNVM's Op::Get).
MXNET_DLL int MXImperativeInvokeByName(const char *op_name, int num_inputs,
                                       NDArrayHandle *inputs,
                                       int *num_outputs,
                                       NDArrayHandle **outputs,
                                       int num_params, const char **param_keys,
                                       const char **param_vals) {
  std::string name(op_name);
  return MXImperativeInvoke(&name, num_inputs, inputs, num_outputs, outputs,
                            num_params, param_keys, param_vals);
}
