// Threaded var-dependency engine — the native core of mxnet_tpu's host
// scheduler (SURVEY.md N1).
//
// Reference analog: src/engine/threaded_engine.{h,cc} +
// threaded_engine_perdevice.cc.  Semantics preserved:
//  - ops declare const (read) and mutable (write) vars; an op runs when every
//    var has granted its access (ThreadedVar queue protocol,
//    threaded_engine.cc:51-143: FIFO queue per var; head write granted alone,
//    head reads granted together).
//  - worker thread pool executes ready ops; priority ops jump the queue
//    (threaded_engine_perdevice.cc priority CPU queue).
//  - errors: a failing op poisons its mutable vars; WaitForVar surfaces the
//    error code at the next sync point (std::exception_ptr protocol,
//    threaded_engine.cc:466-468 — here an int code the Python layer maps back
//    to the stored exception).
//  - WaitForAll drains everything.
//
// TPU-native division of labor: device async belongs to XLA/PjRt; this engine
// schedules HOST work (IO decode, kvstore reductions, checkpoint writes,
// custom-op callbacks) so it overlaps device compute with exact read/write
// ordering — the part of the reference engine TPU still needs.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_set>
#include <vector>

extern "C" {
typedef int64_t (*EngineFn)(void* payload, int64_t prior_err);  // 0 = ok
typedef void* EngineHandle;
typedef void* VarHandle;
}

namespace mxnet_tpu {

struct Opr;

struct Var {
  // FIFO of pending requests (opr, is_write) — VersionedVarBlock analog
  std::deque<std::pair<Opr*, bool>> queue;
  int granted_reads = 0;
  bool granted_write = false;
  int64_t err_code = 0;   // poisoned-var error (0 = none)
  bool to_delete = false;
};

struct Opr {
  EngineFn fn = nullptr;
  void* payload = nullptr;
  std::vector<Var*> const_vars;
  std::vector<Var*> mutable_vars;
  int pending = 0;        // grants still outstanding
  bool prio = false;
  Var* delete_var = nullptr;  // set for DeleteVariable sentinel ops
  // WaitForVar sentinel: invoked with a snapshot of the var's error taken
  // under mu_ BEFORE this op's read grant is released — a write queued
  // behind the wait must not be able to poison the var first
  std::function<void(int64_t)> wait_state;
};

class Engine {
 public:
  explicit Engine(int num_workers) {
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    WaitForAll();
    {
      std::unique_lock<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    cv_ready_.notify_all();
    for (auto& t : workers_) t.join();
    for (Var* v : all_vars_) delete v;
  }

  Var* NewVar() {
    std::unique_lock<std::mutex> lk(mu_);
    Var* v = new Var();
    all_vars_.insert(v);
    return v;
  }

  void Push(EngineFn fn, void* payload, Var** cvars, int nc, Var** mvars,
            int nm, int prio) {
    Opr* op = new Opr();
    op->fn = fn;
    op->payload = payload;
    op->const_vars.assign(cvars, cvars + nc);
    op->mutable_vars.assign(mvars, mvars + nm);
    op->prio = prio != 0;
    Schedule(op);
  }

  // DeleteVariable: reference semantics — the var dies after all previously
  // pushed ops touching it complete (engine.h DeleteVariable).
  void DeleteVar(Var* v) {
    Opr* op = new Opr();
    op->fn = nullptr;
    op->delete_var = v;
    op->mutable_vars.push_back(v);
    Schedule(op);
  }

  // Returns the var's error code (0 = clean) after all its pending writes
  // (and reads) complete.
  int64_t WaitForVar(Var* v) {
    struct WaitState {
      std::mutex m;
      std::condition_variable cv;
      bool done = false;
      int64_t err = 0;
    } st;
    Opr* op = new Opr();
    op->fn = nullptr;
    op->const_vars.push_back(v);
    op->wait_state = [&st](int64_t e) {
      std::unique_lock<std::mutex> lk(st.m);
      st.err = e;
      st.done = true;
      st.cv.notify_all();
    };
    Schedule(op);
    std::unique_lock<std::mutex> lk(st.m);
    st.cv.wait(lk, [&st] { return st.done; });
    return st.err;
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_drained_.wait(lk, [this] { return inflight_ == 0; });
  }

 private:
  void Schedule(Opr* op) {
    std::vector<Opr*> ready;
    {
      std::unique_lock<std::mutex> lk(mu_);
      ++inflight_;
      op->pending = static_cast<int>(op->const_vars.size() +
                                     op->mutable_vars.size());
      if (op->pending == 0) {
        ready.push_back(op);
      } else {
        for (Var* v : op->const_vars) v->queue.emplace_back(op, false);
        for (Var* v : op->mutable_vars) v->queue.emplace_back(op, true);
        for (Var* v : op->const_vars) TryGrant(v, &ready);
        for (Var* v : op->mutable_vars) TryGrant(v, &ready);
      }
    }
    Enqueue(ready);
  }

  // grant accesses at the head of v's queue (scheduler lock held)
  void TryGrant(Var* v, std::vector<Opr*>* ready) {
    while (!v->queue.empty()) {
      auto [op, is_write] = v->queue.front();
      if (is_write) {
        if (v->granted_reads > 0 || v->granted_write) return;
        v->granted_write = true;
      } else {
        if (v->granted_write) return;
        ++v->granted_reads;
      }
      v->queue.pop_front();
      if (--op->pending == 0) ready->push_back(op);
      if (is_write) return;  // a write blocks everything behind it
    }
  }

  void Enqueue(const std::vector<Opr*>& ready) {
    if (ready.empty()) return;
    {
      std::unique_lock<std::mutex> lk(mu_);
      for (Opr* op : ready) {
        if (op->prio)
          prio_queue_.push_back(op);
        else
          queue_.push_back(op);
      }
    }
    cv_ready_.notify_all();
  }

  void Execute(Opr* op) {
    int64_t err = 0;
    // dependent-op propagation: an op touching a poisoned var forwards the
    // error (threaded_engine.h:255-256 exception chaining).  The callback is
    // STILL invoked with the prior error so the language binding can release
    // its closure state (it skips the user fn itself on prior_err != 0).
    if (op->fn) {
      std::unique_lock<std::mutex> lk(mu_);
      for (Var* v : op->const_vars)
        if (v->err_code) err = v->err_code;
      for (Var* v : op->mutable_vars)
        if (v->err_code) err = v->err_code;
    }
    if (op->wait_state) {
      int64_t werr;
      {
        // snapshot + clear the error while this wait op still holds its read
        // grant: no write queued behind the wait can have run yet, so the
        // snapshot can only contain errors from ops pushed before the wait
        std::unique_lock<std::mutex> lk(mu_);
        Var* v = op->const_vars.front();
        werr = v->err_code;
        v->err_code = 0;  // reference clears the exception once surfaced
      }
      op->wait_state(werr);
    } else if (op->fn) {
      err = op->fn(op->payload, err);
    }
    std::vector<Opr*> ready;
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (err != 0) {
        // poison mutable vars (exception_ptr-on-var analog)
        for (Var* v : op->mutable_vars) v->err_code = err;
      }
      for (Var* v : op->const_vars) {
        --v->granted_reads;
        TryGrant(v, &ready);
      }
      for (Var* v : op->mutable_vars) {
        v->granted_write = false;
        if (op->delete_var == v) {
          all_vars_.erase(v);
          delete v;
          continue;
        }
        TryGrant(v, &ready);
      }
      --inflight_;
      if (inflight_ == 0) cv_drained_.notify_all();
    }
    delete op;
    Enqueue(ready);
  }

  void WorkerLoop() {
    for (;;) {
      Opr* op = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_ready_.wait(lk, [this] {
          return shutdown_ || !prio_queue_.empty() || !queue_.empty();
        });
        if (shutdown_ && prio_queue_.empty() && queue_.empty()) return;
        if (!prio_queue_.empty()) {
          op = prio_queue_.front();
          prio_queue_.pop_front();
        } else {
          op = queue_.front();
          queue_.pop_front();
        }
      }
      Execute(op);
    }
  }

  std::mutex mu_;
  std::condition_variable cv_ready_;
  std::condition_variable cv_drained_;
  std::deque<Opr*> queue_;
  std::deque<Opr*> prio_queue_;
  std::vector<std::thread> workers_;
  std::unordered_set<Var*> all_vars_;
  int inflight_ = 0;
  bool shutdown_ = false;
};

}  // namespace mxnet_tpu

// ---------------------------------------------------------------------------
// C ABI (the c_api.h waist, SURVEY.md N17 — engine section)
// ---------------------------------------------------------------------------
using mxnet_tpu::Engine;
using mxnet_tpu::Var;

extern "C" {

EngineHandle MXNativeEngineCreate(int num_workers) {
  return new Engine(num_workers);
}

void MXNativeEngineFree(EngineHandle h) { delete static_cast<Engine*>(h); }

VarHandle MXNativeEngineNewVar(EngineHandle h) {
  return static_cast<Engine*>(h)->NewVar();
}

void MXNativeEngineDeleteVar(EngineHandle h, VarHandle v) {
  static_cast<Engine*>(h)->DeleteVar(static_cast<Var*>(v));
}

void MXNativeEnginePush(EngineHandle h, EngineFn fn, void* payload,
                        VarHandle* cvars, int nc, VarHandle* mvars, int nm,
                        int prio) {
  static_cast<Engine*>(h)->Push(fn, payload,
                                reinterpret_cast<Var**>(cvars), nc,
                                reinterpret_cast<Var**>(mvars), nm, prio);
}

int64_t MXNativeEngineWaitForVar(EngineHandle h, VarHandle v) {
  return static_cast<Engine*>(h)->WaitForVar(static_cast<Var*>(v));
}

void MXNativeEngineWaitForAll(EngineHandle h) {
  static_cast<Engine*>(h)->WaitForAll();
}

}  // extern "C"
