#!/usr/bin/env python
"""Dense autoencoder with layer-wise then end-to-end training.

Reference analog: ``example/autoencoder/`` (stacked autoencoder on MNIST).
The TPU-relevant pattern demonstrated: an encoder/decoder pair trained
under one Trainer with an L2 reconstruction loss, each step a single fused
XLA program; the bottleneck forces a low-dimensional code.

Runs on synthetic data (random low-rank images + noise) so the
reconstruction task is genuinely compressible and needs no download.

Run:  python example/autoencoder/autoencoder.py --num-epochs 20
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="dense autoencoder on synthetic low-rank data",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=20)
parser.add_argument("--samples", type=int, default=1024)
parser.add_argument("--dim", type=int, default=64)
parser.add_argument("--rank", type=int, default=4, help="true data rank")
parser.add_argument("--code", type=int, default=8, help="bottleneck width")
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.05)


def make_data(n, dim, rank, seed=0):
    rng = np.random.RandomState(seed)
    basis = rng.randn(rank, dim).astype(np.float32)
    codes = rng.randn(n, rank).astype(np.float32)
    return codes @ basis + rng.normal(0, 0.05, (n, dim)).astype(np.float32)


def build(dim, code):
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"),
            nn.Dense(code, activation=None),            # bottleneck
            nn.Dense(32, activation="relu"),
            nn.Dense(dim, activation=None))
    return net


def main(args):
    x = make_data(args.samples, args.dim, args.rank)
    net = build(args.dim, args.code)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    l2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter(x, None, batch_size=args.batch_size,
                           shuffle=True)
    first = last = None
    for epoch in range(args.num_epochs):
        it.reset()
        total, nb = 0.0, 0
        for batch in it:
            with autograd.record():
                rec = net(batch.data[0])
                L = l2(rec, batch.data[0])
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.mean().asnumpy())
            nb += 1
        avg = total / nb
        if first is None:
            first = avg
        last = avg
        if epoch % 5 == 0:
            print("epoch %d recon loss %.4f" % (epoch, avg))
    print("recon loss %.4f -> %.4f" % (first, last))
    return first, last


if __name__ == "__main__":
    main(parser.parse_args())
