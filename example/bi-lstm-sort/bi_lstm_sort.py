#!/usr/bin/env python
"""Bidirectional LSTM that learns to sort short digit sequences.

Reference analog: ``example/bi-lstm-sort/`` — the classic demo that a
BiLSTM can emit the sorted version of its input sequence, position by
position.  The TPU-relevant pattern demonstrated: the bidirectional fused
LSTM layer (two direction passes fused into one scan program) with a
per-timestep classification head.

Run:  python example/bi-lstm-sort/bi_lstm_sort.py --seq-len 6
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn

parser = argparse.ArgumentParser(
    description="BiLSTM sequence sorting",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=15)
parser.add_argument("--samples", type=int, default=2000)
parser.add_argument("--seq-len", type=int, default=6)
parser.add_argument("--vocab", type=int, default=10, help="digit range")
parser.add_argument("--hidden", type=int, default=64)
parser.add_argument("--embed", type=int, default=16)
parser.add_argument("--batch-size", type=int, default=50)
parser.add_argument("--lr", type=float, default=0.01)


class SortNet(gluon.HybridBlock):
    def __init__(self, vocab, embed, hidden, **kw):
        super().__init__(**kw)
        self.emb = nn.Embedding(vocab, embed)
        # input_size resolves the symbolic (hybridized) shape up front
        self.lstm = rnn.LSTM(hidden, bidirectional=True, layout="NTC",
                             input_size=embed)
        self.head = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x):
        h = self.lstm(self.emb(x))       # (N, T, 2*hidden)
        return self.head(h)              # (N, T, vocab)


def make_data(n, seq_len, vocab, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab, (n, seq_len)).astype(np.float32)
    y = np.sort(x, axis=1)
    return x, y


def main(args):
    x, y = make_data(args.samples, args.seq_len, args.vocab)
    net = SortNet(args.vocab, args.embed, args.hidden)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)
    for epoch in range(args.num_epochs):
        it.reset()
        total, nb = 0.0, 0
        for batch in it:
            with autograd.record():
                out = net(batch.data[0])                # (N, T, V)
                L = ce(out.reshape((-1, args.vocab)),
                       batch.label[0].reshape((-1,)))
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.mean().asnumpy())
            nb += 1
        if epoch % 5 == 0:
            print("epoch %d loss %.4f" % (epoch, total / nb))
    pred = net(mx.nd.array(x)).asnumpy().argmax(-1)
    acc = float((pred == y).mean())
    print("per-position sort accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main(parser.parse_args())
