#!/usr/bin/env python
"""Train with a softmax loss implemented as a numpy Custom op.

Reference analog: ``example/numpy-ops/custom_softmax.py`` — the canonical
custom-op-bridge demo: forward and backward written in numpy, registered
with ``mx.operator.register``, dropped into a Module symbol as the loss
layer.  The TPU-relevant machinery exercised: host callbacks crossing the
XLA boundary on the framework's dedicated custom-op worker (the reference
runs them on a worker thread so the engine never blocks —
src/operator/custom/custom-inl.h).

Run:  python example/numpy-ops/custom_softmax.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx

parser = argparse.ArgumentParser(
    description="custom numpy softmax loss",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=10)
parser.add_argument("--samples", type=int, default=640)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.1)


class Softmax(mx.operator.CustomOp):
    """Numpy forward/backward (reference custom_softmax.py:31-52)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(np.int32)
        y = out_data[0].asnumpy().copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))


@mx.operator.register("demo_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def make_data(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    y = (x[:, :4].sum(1) > 0).astype(np.float32) + \
        2 * (x[:, 4:8].sum(1) > 0).astype(np.float32)
    return x, y


def main(args):
    x, y = make_data(args.samples)
    S = mx.symbol
    data = S.var("data")
    label = S.var("softmax_label")
    fc1 = S.FullyConnected(data, num_hidden=64, name="fc1")
    act = S.Activation(fc1, act_type="relu")
    fc2 = S.FullyConnected(act, num_hidden=4, name="fc2")
    net = S.Custom(fc2, label, op_type="demo_softmax", name="softmax")

    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    mod.fit(it, num_epoch=args.num_epochs,
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(), eval_metric="acc")
    score = mod.score(it, "acc")
    acc = dict(score)["accuracy"]
    print("custom-softmax Module accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main(parser.parse_args())
