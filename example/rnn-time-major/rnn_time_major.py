#!/usr/bin/env python
"""Time-major RNN: TNC layout for the sequence hot loop.

Reference analog: ``example/rnn-time-major/rnn_cell_demo.py`` — the
layout lesson: recurrent loops iterate the TIME axis, so keeping time
outermost (TNC) makes every timestep slice contiguous; batch-major (NTC)
pays a transpose per step.  On TPU the same logic holds inside the
compiled program: the fused LSTM's ``lax.scan`` carries (N, C) slices,
and a TNC input feeds them without a data movement.

Demo: the same char-level LM trained twice — NTC vs TNC — must produce
IDENTICAL losses (layout is semantics-free) while TNC skips the
transposes.  Synthetic 90%-deterministic Markov text.

Run:  python example/rnn-time-major/rnn_time_major.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

parser = argparse.ArgumentParser(
    description="Time-major vs batch-major LSTM LM",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=120)
parser.add_argument("--batch-size", type=int, default=16)
parser.add_argument("--seq-len", type=int, default=16)
parser.add_argument("--vocab", type=int, default=16)
parser.add_argument("--hidden", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.01)


def markov_batch(rng, bs, T, vocab):
    """90%-deterministic successor rule: next = (cur * 3 + 1) % vocab."""
    x = np.zeros((bs, T + 1), np.int64)
    x[:, 0] = rng.randint(0, vocab, bs)
    for t in range(T):
        nxt = (x[:, t] * 3 + 1) % vocab
        rand = rng.randint(0, vocab, bs)
        pick = rng.uniform(size=bs) < 0.9
        x[:, t + 1] = np.where(pick, nxt, rand)
    return x[:, :-1], x[:, 1:]


class CharLM(gluon.Block):
    def __init__(self, vocab, hidden, layout, **kw):
        super().__init__(**kw)
        self.layout = layout
        with self.name_scope():
            self.embed = nn.Embedding(vocab, hidden)
            self.lstm = rnn.LSTM(hidden, layout=layout)
            self.proj = nn.Dense(vocab, flatten=False)

    def forward(self, x):              # x arrives (B, T) always
        e = self.embed(x)              # (B, T, H)
        if self.layout == "TNC":
            e = e.transpose((1, 0, 2))
            h = self.lstm(e)           # (T, B, H) — time-major hot loop
            h = h.transpose((1, 0, 2))
        else:
            h = self.lstm(e)           # (B, T, H)
        return self.proj(h)


def train(layout, args):
    rng = np.random.RandomState(7)     # same DATA stream both layouts
    mx.random.seed(0)                  # ...and the same parameter init,
    net = CharLM(args.vocab, args.hidden, layout)   # so ppls compare
    net.initialize(mx.init.Xavier())
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    last = None
    for it in range(args.iters):
        xb, yb = markov_batch(rng, args.batch_size, args.seq_len,
                              args.vocab)
        x, y = nd.array(xb.astype(np.float32)), nd.array(
            yb.astype(np.float32))
        with autograd.record():
            logits = net(x)
            loss = ce(logits.reshape((-1, args.vocab)), y.reshape((-1,)))
        loss.backward()
        tr.step(args.batch_size)
        last = float(loss.asnumpy().mean())
    return last


def main(args):
    ntc = train("NTC", args)
    tnc = train("TNC", args)
    ppl_ntc, ppl_tnc = float(np.exp(ntc)), float(np.exp(tnc))
    print("final ppl  NTC %.3f   TNC %.3f  (uniform would be %d)"
          % (ppl_ntc, ppl_tnc, args.vocab))
    return ppl_ntc, ppl_tnc


if __name__ == "__main__":
    a = parser.parse_args()
    p_ntc, p_tnc = main(a)
    # both layouts learn the 90% rule (ppl well under uniform=16) and —
    # with seeded init + identical data — match near-exactly (layout is
    # semantics-free; only transpose-order float rounding differs)
    ok = p_ntc < 6 and p_tnc < 6 and abs(p_ntc - p_tnc) / p_ntc < 0.02
    raise SystemExit(0 if ok else 1)
