#!/usr/bin/env python
"""Train an MLP or LeNet on MNIST (parity: example/image-classification/
train_mnist.py — BASELINE.json config #1).

Uses MNISTIter when the idx files exist under --data-dir; otherwise a
synthetic stand-in iterator so the example runs anywhere (the reference's
``--benchmark`` synthetic-data pattern, common/data.py).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def get_mlp():
    data = mx.sym.var("data")
    flat = mx.sym.Flatten(data)
    fc1 = mx.sym.FullyConnected(flat, num_hidden=128, name="fc1")
    act1 = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, num_hidden=64, name="fc2")
    act2 = mx.sym.Activation(fc2, act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(fc3, mx.sym.var("softmax_label"),
                                name="softmax")


def get_lenet():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50)
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flat = mx.sym.Flatten(p2)
    f1 = mx.sym.FullyConnected(flat, num_hidden=500)
    a3 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a3, num_hidden=10)
    return mx.sym.SoftmaxOutput(f2, mx.sym.var("softmax_label"),
                                name="softmax")


def get_iters(args, flat):
    shape = (784,) if flat else (1, 28, 28)
    train_img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    if os.path.exists(train_img):
        train = mx.io.MNISTIter(
            image=train_img,
            label=os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
            data_shape=shape, batch_size=args.batch_size, shuffle=True)
        val = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
            data_shape=shape, batch_size=args.batch_size)
        return train, val
    # synthetic learnable stand-in: 10 class prototypes + noise
    rng = np.random.RandomState(0)
    n = 2000
    protos = rng.rand(10, int(np.prod(shape))).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.float32)
    x = (protos[y.astype(int)] +
         0.3 * rng.randn(n, protos.shape[1]).astype(np.float32))
    x = x.reshape((n,) + shape)
    split = int(n * 0.9)
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)
    print("note: MNIST files not found under %s — training on a synthetic "
          "stand-in" % args.data_dir)
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    ap.add_argument("--data-dir", default="data/mnist")
    ap.add_argument("--ctx", default=None, choices=[None, "cpu", "tpu"])
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--model-prefix", default=None)
    args = ap.parse_args()

    ctx = {None: None, "cpu": mx.cpu(), "tpu": mx.tpu()}[args.ctx]
    net = get_mlp() if args.network == "mlp" else get_lenet()
    train, val = get_iters(args, flat=args.network == "mlp")

    mod = mx.mod.Module(net, context=ctx or mx.context.current_context())
    cbs = [mx.callback.Speedometer(args.batch_size, 50)]
    epoch_cbs = []
    if args.model_prefix:
        epoch_cbs.append(mx.callback.do_checkpoint(args.model_prefix))
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            eval_metric="acc", num_epoch=args.num_epochs,
            kvstore=args.kv_store,
            batch_end_callback=cbs, epoch_end_callback=epoch_cbs)
    score = mod.score(val, mx.metric.Accuracy())
    print("final validation accuracy:", dict(score))


if __name__ == "__main__":
    main()
