#!/usr/bin/env python
"""Pretrained-model accuracy harness (parity:
example/image-classification/test_score.py:30 — the reference downloads
pretrained ImageNet models and asserts their known accuracies).

Zero-egress variant: scores the in-repo pretrained checkpoint
``models/digits-lenet`` (a small conv net trained to >0.97 validation
accuracy on sklearn's 8x8 digits — the repo's stand-in for the MNIST/
ImageNet artifacts) and asserts the stored accuracy still reproduces.
Any regression in conv/pool/FC/softmax inference, checkpoint loading, or
Module.bind shows up here as a score drop.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")

# model name -> (epoch, expected accuracy on the digits val split)
PRETRAINED = {
    "digits-lenet": (20, 0.973),
    "digits-resnet": (25, 0.979),   # residual net, train_digits_resnet.py
}


def val_data(batch_size=99):
    from sklearn.datasets import load_digits
    X, y = load_digits(return_X_y=True)
    X = (X / 16.0).astype(np.float32).reshape(-1, 1, 8, 8)
    y = y.astype(np.float32)
    rng = np.random.RandomState(7)          # same split as training
    idx = rng.permutation(len(X))
    X, y = X[idx], y[idx]
    return mx.io.NDArrayIter(X[1500:], y[1500:], batch_size=batch_size)


def score(model, epoch, ctx=None, tol=0.01):
    prefix = os.path.join(REPO, "models", model)
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, epoch)
    mod = mx.mod.Module(sym, context=ctx)
    val = val_data()
    mod.bind(for_training=False, data_shapes=val.provide_data,
             label_shapes=val.provide_label)
    mod.set_params(arg_params, aux_params)
    acc = dict(mod.score(val, "acc"))["accuracy"]
    expected = PRETRAINED[model][1]
    ok = acc >= expected - tol
    print("%s-%04d  accuracy %.4f  expected %.4f  %s"
          % (model, epoch, acc, expected, "OK" if ok else "FAIL"))
    return acc, ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None,
                    help="score one model (default: all)")
    args = ap.parse_args()
    models = [args.model] if args.model else list(PRETRAINED)
    failed = False
    for m in models:
        _, ok = score(m, PRETRAINED[m][0])
        failed |= not ok
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
