#!/usr/bin/env python
"""ImageNet training (parity: example/image-classification/train_imagenet.py
— the north-star benchmark driver, BASELINE.json config #2).

``--benchmark 1`` runs on synthetic data (the reference's common/data.py
synthetic iterator) and reports img/s; real data comes from an
ImageRecordIter .rec produced by tools/im2rec.py.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from symbols import resnet  # noqa: E402


class SyntheticIter(mx.io.DataIter):
    """Random device-resident batches (common/data.py --benchmark 1)."""

    def __init__(self, data_shape, batch_size, num_classes, num_batches=50):
        super().__init__(batch_size)
        rng = np.random.RandomState(0)
        self._data = mx.nd.array(
            rng.rand(batch_size, *data_shape).astype(np.float32))
        self._label = mx.nd.array(
            rng.randint(0, num_classes, (batch_size,)).astype(np.float32))
        self._num = num_batches
        self._i = 0
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size,) + data_shape)]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (batch_size,))]

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self._num:
            raise StopIteration
        self._i += 1
        return mx.io.DataBatch(data=[self._data], label=[self._label])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="resnet")
    ap.add_argument("--num-layers", type=int, default=50)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--benchmark", type=int, default=0)
    ap.add_argument("--num-batches", type=int, default=50)
    ap.add_argument("--data-train", default=None,
                    help=".rec file from tools/im2rec.py")
    args = ap.parse_args()

    shape = tuple(int(x) for x in args.image_shape.split(","))
    sym = resnet.get_symbol(args.num_classes, args.num_layers,
                            args.image_shape)

    if args.benchmark or not args.data_train:
        train = SyntheticIter(shape, args.batch_size, args.num_classes,
                              args.num_batches)
    else:
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True, rand_mirror=True)

    mod = mx.mod.Module(sym)
    tic = [time.time()]

    def speed_cb(param):
        if param.nbatch and param.nbatch % 10 == 0:
            dt = time.time() - tic[0]
            print("epoch %d batch %d: %.1f img/s"
                  % (param.epoch, param.nbatch,
                     10 * args.batch_size / max(dt, 1e-9)))
            tic[0] = time.time()

    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            eval_metric="acc", num_epoch=args.num_epochs,
            kvstore=args.kv_store, batch_end_callback=[speed_cb])


if __name__ == "__main__":
    main()
