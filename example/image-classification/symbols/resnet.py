"""Symbolic ResNet v1 builder (parity: example/image-classification/
symbols/resnet.py in the reference; the Module-path twin of
gluon.model_zoo.vision.resnet)."""
import mxnet_tpu as mx


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True):
    if bottle_neck:
        bn1 = mx.sym.BatchNorm(data, fix_gamma=False, name=name + "_bn1")
        act1 = mx.sym.Activation(bn1, act_type="relu")
        conv1 = mx.sym.Convolution(act1, num_filter=num_filter // 4,
                                   kernel=(1, 1), no_bias=True,
                                   name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(conv1, fix_gamma=False, name=name + "_bn2")
        act2 = mx.sym.Activation(bn2, act_type="relu")
        conv2 = mx.sym.Convolution(act2, num_filter=num_filter // 4,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, name=name + "_conv2")
        bn3 = mx.sym.BatchNorm(conv2, fix_gamma=False, name=name + "_bn3")
        act3 = mx.sym.Activation(bn3, act_type="relu")
        conv3 = mx.sym.Convolution(act3, num_filter=num_filter,
                                   kernel=(1, 1), no_bias=True,
                                   name=name + "_conv3")
        out = conv3
        shortcut_from = act1
    else:
        bn1 = mx.sym.BatchNorm(data, fix_gamma=False, name=name + "_bn1")
        act1 = mx.sym.Activation(bn1, act_type="relu")
        conv1 = mx.sym.Convolution(act1, num_filter=num_filter,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(conv1, fix_gamma=False, name=name + "_bn2")
        act2 = mx.sym.Activation(bn2, act_type="relu")
        out = mx.sym.Convolution(act2, num_filter=num_filter, kernel=(3, 3),
                                 pad=(1, 1), no_bias=True,
                                 name=name + "_conv2")
        shortcut_from = act1
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(shortcut_from, num_filter=num_filter,
                                      kernel=(1, 1), stride=stride,
                                      no_bias=True, name=name + "_sc")
    return out + shortcut


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224"):
    """ResNet v1 (pre-act) for ImageNet-scale inputs."""
    configs = {18: ([2, 2, 2, 2], [64, 64, 128, 256, 512], False),
               34: ([3, 4, 6, 3], [64, 64, 128, 256, 512], False),
               50: ([3, 4, 6, 3], [64, 256, 512, 1024, 2048], True),
               101: ([3, 4, 23, 3], [64, 256, 512, 1024, 2048], True),
               152: ([3, 8, 36, 3], [64, 256, 512, 1024, 2048], True)}
    if num_layers not in configs:
        raise ValueError("unsupported num_layers %d" % num_layers)
    units, filters, bottle_neck = configs[num_layers]
    data = mx.sym.var("data")
    body = mx.sym.Convolution(data, num_filter=filters[0], kernel=(7, 7),
                              stride=(2, 2), pad=(3, 3), no_bias=True,
                              name="conv0")
    body = mx.sym.BatchNorm(body, fix_gamma=False, name="bn0")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                          pool_type="max")
    for i, n in enumerate(units):
        stride = (1, 1) if i == 0 else (2, 2)
        body = residual_unit(body, filters[i + 1], stride, False,
                             "stage%d_unit1" % (i + 1), bottle_neck)
        for j in range(n - 1):
            body = residual_unit(body, filters[i + 1], (1, 1), True,
                                 "stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck)
    bn = mx.sym.BatchNorm(body, fix_gamma=False, name="bn1")
    act = mx.sym.Activation(bn, act_type="relu")
    pool = mx.sym.Pooling(act, global_pool=True, kernel=(7, 7),
                          pool_type="avg")
    flat = mx.sym.Flatten(pool)
    fc = mx.sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                                name="softmax")
