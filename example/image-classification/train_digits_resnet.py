#!/usr/bin/env python
"""Train the second in-repo pretrained artifact: a small residual conv
net on sklearn digits (parity: example/image-classification README's
pretrained-model recipes; zero-egress stand-in for the ImageNet zoo).

Architecture: 8x8 -> conv16/BN/relu -> 2 residual blocks (16, then 32
with a strided projection) -> global pool -> dense 10.  Trained with the
Module.fit path (symbolic, BatchNorm aux states, momentum SGD) so the
artifact exercises the same machinery as the reference's resnet recipes.

Saves models/digits-resnet-00NN.params.npz + -symbol.json and prints the
validation accuracy; tests/train/test_score.py asserts it keeps
reproducing.

Run:  python example/image-classification/train_digits_resnet.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as S  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")


def residual_unit(data, num_filter, stride, dim_match, name):
    bn1 = S.BatchNorm(data, fix_gamma=False, name=name + "_bn1")
    act1 = S.Activation(bn1, act_type="relu", name=name + "_relu1")
    conv1 = S.Convolution(act1, num_filter=num_filter, kernel=(3, 3),
                          stride=stride, pad=(1, 1), no_bias=True,
                          name=name + "_conv1")
    bn2 = S.BatchNorm(conv1, fix_gamma=False, name=name + "_bn2")
    act2 = S.Activation(bn2, act_type="relu", name=name + "_relu2")
    conv2 = S.Convolution(act2, num_filter=num_filter, kernel=(3, 3),
                          stride=(1, 1), pad=(1, 1), no_bias=True,
                          name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = S.Convolution(act1, num_filter=num_filter,
                                 kernel=(1, 1), stride=stride,
                                 no_bias=True, name=name + "_sc")
    return conv2 + shortcut


def build_symbol(num_classes=10):
    data = S.var("data")
    body = S.Convolution(data, num_filter=16, kernel=(3, 3), pad=(1, 1),
                         no_bias=True, name="conv0")
    body = residual_unit(body, 16, (1, 1), False, "stage1_unit1")
    body = residual_unit(body, 16, (1, 1), True, "stage1_unit2")
    body = residual_unit(body, 32, (2, 2), False, "stage2_unit1")
    body = residual_unit(body, 32, (1, 1), True, "stage2_unit2")
    bn = S.BatchNorm(body, fix_gamma=False, name="bn_final")
    act = S.Activation(bn, act_type="relu", name="relu_final")
    pool = S.Pooling(act, global_pool=True, pool_type="avg",
                     kernel=(2, 2), name="pool_final")
    flat = S.Flatten(pool)
    fc = S.FullyConnected(flat, num_hidden=num_classes, name="fc")
    return S.SoftmaxOutput(fc, name="softmax")


def digits_iters(batch_size=64):
    from sklearn.datasets import load_digits
    X, y = load_digits(return_X_y=True)
    X = (X / 16.0).astype(np.float32).reshape(-1, 1, 8, 8)
    y = y.astype(np.float32)
    rng = np.random.RandomState(7)          # split shared with test_score
    idx = rng.permutation(len(X))
    X, y = X[idx], y[idx]
    train = mx.io.NDArrayIter(X[:1500], y[:1500], batch_size=batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(X[1500:], y[1500:], batch_size=99)
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=25)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--prefix", default=os.path.join(REPO, "models",
                                                     "digits-resnet"))
    args = ap.parse_args()

    mx.random.seed(42)
    np.random.seed(42)
    train, val = digits_iters()
    net = build_symbol()
    mod = mx.mod.Module(net)
    mod.fit(train,
            eval_data=val,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            num_epoch=args.epochs,
            epoch_end_callback=mx.callback.do_checkpoint(
                args.prefix, period=args.epochs),
            batch_end_callback=None)
    score = mod.score(val, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    print("final val accuracy: %.4f (artifact %s-%04d)"
          % (acc, args.prefix, args.epochs))
    return 0 if acc > 0.95 else 1


if __name__ == "__main__":
    sys.exit(main())
