#!/usr/bin/env python
"""Inference throughput benchmark over the model zoo (parity:
example/image-classification/benchmark_score.py — synthetic inputs,
img/s per network/batch-size)."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def score(net_name, batch_size, image_size=224, warmup=3, iters=10):
    net = getattr(vision, net_name)()
    net.initialize()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(batch_size, 3, image_size, image_size))
    for _ in range(warmup):
        net(x).wait_to_read()
    tic = time.time()
    for _ in range(iters):
        net(x).wait_to_read()
    return iters * batch_size / (time.time() - tic)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", default="resnet18_v1,resnet50_v1,"
                    "mobilenet1_0,squeezenet1_0")
    ap.add_argument("--batch-sizes", default="1,32")
    ap.add_argument("--image-size", type=int, default=224)
    args = ap.parse_args()
    for name in args.networks.split(","):
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            print("network: %-16s batch %3d: %8.1f img/s"
                  % (name, bs, score(name, bs, args.image_size)))


if __name__ == "__main__":
    main()
