#!/usr/bin/env python
"""Variable-length LSTM language model with BucketingModule.

The canonical bucketing demo (reference:
example/rnn/bucketing/lstm_bucketing.py): sentences are grouped into
length buckets, one executor is bound per bucket, and all buckets SHARE
parameters — the Module-era answer to ragged sequences.

TPU-native notes (this rewrite, not a translation):
- the recurrence is the fused ``sym.RNN`` op (ops/rnn.py): one op for the
  whole stack, lowering to the Pallas fused-LSTM kernel on TPU instead of
  per-timestep unrolled cells;
- each bucket length is one static XLA program — bucketing doubles as the
  static-shape strategy jit wants;
- with no corpus on disk the demo synthesizes a Markov "language" so it
  runs out of the box; pass ``--data <file>`` for real text.

Run:  python example/rnn/bucketing/lstm_bucketing.py --num-epochs 5
"""
import argparse
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.rnn import BucketSentenceIter

parser = argparse.ArgumentParser(
    description="Train an LSTM LM on variable-length sentences",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--data", type=str, default=None,
                    help="text file (one sentence per line); synthetic "
                         "corpus when omitted")
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-hidden", type=int, default=200)
parser.add_argument("--num-embed", type=int, default=200)
parser.add_argument("--num-epochs", type=int, default=5)
parser.add_argument("--optimizer", type=str, default="adam",
                    help="adam converges much faster than sgd on the "
                         "marginal-vs-conditional plateau of LM tasks")
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--wd", type=float, default=0.0)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--buckets", type=str, default="10,20,30,40")
parser.add_argument("--sentences", type=int, default=2000,
                    help="synthetic corpus size")
parser.add_argument("--vocab", type=int, default=64,
                    help="synthetic vocab size")


def tokenize_text(fname, vocab=None, invalid_label=0, start_label=1):
    """Encode a one-sentence-per-line text file to int sequences
    (the mx.rnn.encode_sentences role)."""
    vocab = dict(vocab or {})
    sentences = []
    with open(fname) as f:
        for line in f:
            words = line.split()
            if not words:
                continue
            s = []
            for w in words:
                if w not in vocab:
                    vocab[w] = len(vocab) + start_label
                s.append(vocab[w])
            sentences.append(s)
    return sentences, vocab


def synthetic_corpus(n, vocab_size, seed=0):
    """Markov 'language': next = (3*prev + 1) % V with 10% noise, ragged
    lengths — learnable structure without a dataset download."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ln = int(rng.choice([8, 15, 25, 35]))
        s = [int(rng.randint(1, vocab_size))]
        for _ in range(ln - 1):
            s.append((3 * s[-1] + 1) % vocab_size if rng.rand() < 0.9
                     else int(rng.randint(1, vocab_size)))
        out.append(s)
    return out


def make_sym_gen(vocab_size, args):
    def sym_gen(seq_len):
        data = sym.var("data")                       # (B, seq_len)
        label = sym.var("softmax_label")
        embed = sym.Embedding(data, input_dim=vocab_size,
                              output_dim=args.num_embed, name="embed")
        # fused whole-stack recurrence, TNC layout
        tnc = sym.transpose(embed, axes=(1, 0, 2))
        rnn_params = sym.var("lstm_parameters")
        init = sym.zeros(shape=(args.num_layers, args.batch_size,
                                args.num_hidden))
        out = sym.RNN(tnc, rnn_params, init, init, state_size=args.num_hidden,
                      num_layers=args.num_layers, mode="lstm", name="lstm")
        out = sym.transpose(out, axes=(1, 0, 2))     # back to (B, T, H)
        pred = sym.Reshape(out, shape=(-1, args.num_hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
        label_flat = sym.Reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(pred, label_flat, name="softmax",
                                normalization="batch")
        return out, ("data",), ("softmax_label",)
    return sym_gen


def main(args):
    buckets = [int(b) for b in args.buckets.split(",")]
    invalid_label = 0
    if args.data:
        train_sent, vocab = tokenize_text(args.data,
                                          invalid_label=invalid_label)
        vocab_size = len(vocab) + 1
    else:
        train_sent = synthetic_corpus(args.sentences, args.vocab)
        vocab_size = args.vocab

    data_train = BucketSentenceIter(train_sent, args.batch_size,
                                    buckets=buckets,
                                    invalid_label=invalid_label)

    model = mx.mod.BucketingModule(
        sym_gen=make_sym_gen(vocab_size, args),
        default_bucket_key=data_train.default_bucket_key)

    metric = mx.metric.Perplexity(ignore_label=invalid_label)
    model.fit(
        train_data=data_train,
        eval_metric=metric,
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr, "wd": args.wd},
        # the packed RNN parameter vector needs the FusedRNN initializer
        # (per-block Xavier + forget-gate bias), everything else Xavier
        initializer=mx.init.Mixed(
            [".*lstm_parameters", ".*"],
            [mx.init.FusedRNN(mx.init.Xavier(factor_type="in",
                                             magnitude=2.34),
                              args.num_hidden, args.num_layers, "lstm"),
             mx.init.Xavier(factor_type="in", magnitude=2.34)]),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))

    data_train.reset()
    metric.reset()
    model.score(data_train, metric)
    ppl = dict(metric.get_name_value())["perplexity"]
    print("final train perplexity: %.3f" % ppl)
    return ppl


if __name__ == "__main__":
    main(parser.parse_args())
