#!/usr/bin/env python
"""Named-entity recognition: BiLSTM sequence labeling.

Reference analog: ``example/named_entity_recognition/src/ner.py`` — the
sequence-LABELING recipe (one tag per token, not one class per
sentence): embedding -> bidirectional LSTM -> per-token projection ->
per-token softmax CE, evaluated with entity-class accuracy (the
reference uses a custom composite metric over non-O tags).

Synthetic corpus with a context-sensitive rule an order-0 model cannot
learn: "trigger" tokens (ids 1-4) tag the NEXT token as an entity of the
trigger's type; every other token is O.  A per-token classifier without
sequence context tops out near the O-rate; the BiLSTM must carry the
trigger across a timestep.

Run:  python example/named_entity_recognition/ner.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

parser = argparse.ArgumentParser(
    description="BiLSTM NER on a synthetic trigger-tagged corpus",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=120)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--seq-len", type=int, default=20)
parser.add_argument("--vocab", type=int, default=50)
parser.add_argument("--n-types", type=int, default=4)
parser.add_argument("--hidden", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.01)


def make_batch(rng, bs, T, vocab, n_types):
    """Tokens uniform; ids 1..n_types are triggers tagging the NEXT
    token as entity type 1..n_types; tag 0 is O."""
    x = rng.randint(n_types + 1, vocab, size=(bs, T))
    trig_pos = rng.randint(0, T - 1, size=(bs, 3))
    tags = np.zeros((bs, T), np.int64)
    for i in range(bs):
        for p in trig_pos[i]:
            t = rng.randint(1, n_types + 1)
            x[i, p] = t
            tags[i, p + 1] = t
    return (nd.array(x.astype(np.float32)),
            nd.array(tags.astype(np.float32)))


class BiLSTMTagger(gluon.Block):
    def __init__(self, vocab, n_tags, hidden, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(vocab, hidden)
            self.lstm = rnn.LSTM(hidden, num_layers=1, bidirectional=True,
                                 layout="NTC")
            self.proj = nn.Dense(n_tags, flatten=False)

    def forward(self, x):
        e = self.embed(x)                  # (B, T, H)
        h = self.lstm(e)                   # (B, T, 2H)
        return self.proj(h)                # (B, T, n_tags)


def main(args):
    rng = np.random.RandomState(0)
    n_tags = args.n_types + 1
    net = BiLSTMTagger(args.vocab, n_tags, args.hidden)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    ent_accs = []
    for it in range(args.iters):
        x, y = make_batch(rng, args.batch_size, args.seq_len, args.vocab,
                          args.n_types)
        with autograd.record():
            logits = net(x)
            loss = loss_fn(logits.reshape((-1, n_tags)), y.reshape((-1,)))
        loss.backward()
        trainer.step(args.batch_size)
        if it >= args.iters - 15:
            pred = logits.asnumpy().argmax(-1)
            lab = y.asnumpy()
            ent = lab > 0                   # score ENTITY tokens only
            ent_accs.append(float((pred[ent] == lab[ent]).mean()))
    acc = float(np.mean(ent_accs))
    print("NER entity-token accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    a = parser.parse_args()
    acc = main(a)
    raise SystemExit(0 if acc > 0.9 else 1)
