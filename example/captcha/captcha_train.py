#!/usr/bin/env python
"""Captcha: multi-digit recognition with one softmax head per position.

Reference analog: ``example/captcha/mxnet_captcha.R`` (and the OCR FAQ's
python variant) — the classic multi-label trick: a conv trunk feeds N
parallel classifier heads, one per character slot; the loss is the SUM
of the per-slot cross-entropies and accuracy counts a sample only when
EVERY slot is right.

Synthetic captcha: a 16x48 strip with 3 digit glyphs (5x3 pixel fonts)
at jittered positions + noise.

Run:  python example/captcha/captcha_train.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="Multi-head captcha recognition",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=200)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--n-digits", type=int, default=3)
parser.add_argument("--lr", type=float, default=0.002)

# 5x3 pixel fonts for digits 0-9
_FONT = {
    0: "111101101101111", 1: "010110010010111", 2: "111001111100111",
    3: "111001111001111", 4: "101101111001001", 5: "111100111001111",
    6: "111100111101111", 7: "111001010010010", 8: "111101111101111",
    9: "111101111001111",
}


def _glyph(d):
    g = np.array([float(c) for c in _FONT[d]], np.float32).reshape(5, 3)
    return np.kron(g, np.ones((2, 2), np.float32))   # 10x6 glyph


def make_batch(rng, bs, n_digits):
    H, W = 16, 16 * n_digits
    xs = np.zeros((bs, 1, H, W), np.float32)
    ys = np.zeros((bs, n_digits), np.float32)
    for i in range(bs):
        for j in range(n_digits):
            d = int(rng.randint(10))
            ys[i, j] = d
            r = 3 + int(rng.randint(-2, 3))
            c = 16 * j + 4 + int(rng.randint(-3, 4))
            xs[i, 0, r:r + 10, c:c + 6] = _glyph(d)
    xs += rng.randn(bs, 1, H, W).astype(np.float32) * 0.15
    return nd.array(xs), nd.array(ys)


class CaptchaNet(gluon.Block):
    def __init__(self, n_digits, **kw):
        super().__init__(**kw)
        self.n_digits = n_digits
        with self.name_scope():
            self.trunk = nn.Sequential()
            self.trunk.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
                           nn.MaxPool2D(2),
                           nn.Conv2D(32, 3, padding=1, activation="relu"),
                           nn.Dense(128, activation="relu"))
            self.heads = []
            for j in range(n_digits):
                head = nn.Dense(10)
                self.register_child(head)
                self.heads.append(head)

    def forward(self, x):
        h = self.trunk(x)
        return [head(h) for head in self.heads]


def main(args):
    rng = np.random.RandomState(0)
    net = CaptchaNet(args.n_digits)
    net.initialize(mx.init.Xavier())
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    accs = []
    for it in range(args.iters):
        x, y = make_batch(rng, args.batch_size, args.n_digits)
        with autograd.record():
            outs = net(x)
            # summed per-slot CE (the multi-head captcha loss)
            loss = sum(ce(o, y[:, j]).mean()
                       for j, o in enumerate(outs))
        loss.backward()
        trainer.step(args.batch_size)
        if it >= args.iters - 15:
            pred = np.stack([o.asnumpy().argmax(1) for o in outs], 1)
            # whole-captcha accuracy: every slot must match
            accs.append(float((pred == y.asnumpy()).all(1).mean()))
    acc = float(np.mean(accs))
    print("captcha whole-sequence accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    a = parser.parse_args()
    acc = main(a)
    raise SystemExit(0 if acc > 0.8 else 1)
