#!/usr/bin/env python
"""Multi-task training: one trunk, two classification heads.

Reference analog: ``example/multi-task/`` (MNIST digit + odd/even heads
trained jointly).  The TPU-relevant pattern demonstrated: two losses
summed into one backward pass — XLA fuses the joint step into a single
program, and a composite metric tracks both tasks.

Run:  python example/multi-task/multitask.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="two-head multi-task training",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=15)
parser.add_argument("--samples", type=int, default=1024)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.05)
parser.add_argument("--classes", type=int, default=4)


class MultiTaskNet(gluon.HybridBlock):
    def __init__(self, classes, **kw):
        super().__init__(**kw)
        self.trunk = nn.HybridSequential()
        self.trunk.add(nn.Dense(64, activation="relu"),
                       nn.Dense(32, activation="relu"))
        self.head_cls = nn.Dense(classes)     # which class
        self.head_par = nn.Dense(2)           # class parity

    def hybrid_forward(self, F, x):
        h = self.trunk(x)
        return self.head_cls(h), self.head_par(h)


def make_data(n, classes, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, 16) * 2.5
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, 16) * 0.7
    return x.astype(np.float32), y.astype(np.float32), \
        (y % 2).astype(np.float32)


def main(args):
    x, y_cls, y_par = make_data(args.samples, args.classes)
    net = MultiTaskNet(args.classes)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    n = x.shape[0]
    idx = np.arange(n)
    for epoch in range(args.num_epochs):
        np.random.RandomState(epoch).shuffle(idx)
        total, nb = 0.0, 0
        for i in range(0, n - args.batch_size + 1, args.batch_size):
            j = idx[i:i + args.batch_size]
            data = mx.nd.array(x[j])
            with autograd.record():
                out_cls, out_par = net(data)
                L = ce(out_cls, mx.nd.array(y_cls[j])) + \
                    0.5 * ce(out_par, mx.nd.array(y_par[j]))
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.mean().asnumpy())
            nb += 1
        if epoch % 5 == 0:
            print("epoch %d joint loss %.4f" % (epoch, total / nb))
    out_cls, out_par = net(mx.nd.array(x))
    acc_cls = float((out_cls.asnumpy().argmax(1) == y_cls).mean())
    acc_par = float((out_par.asnumpy().argmax(1) == y_par).mean())
    print("class acc %.3f / parity acc %.3f" % (acc_cls, acc_par))
    return acc_cls, acc_par


if __name__ == "__main__":
    main(parser.parse_args())
