#!/usr/bin/env python
"""ONNX interchange walkthrough: export a trained model, re-import it,
and verify prediction parity.

Reference analog: ``example/onnx/`` (super_resolution import demo) over
``mx.contrib.onnx`` — the interchange story for serving stacks that
speak ONNX.  This framework ships its own protobuf codec
(``contrib/onnx_proto.py``) and 85 importer conversions, so the
round-trip needs no external onnx installation.

Run:  python example/onnx/onnx_roundtrip.py
"""
import argparse
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu import symbol as S
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="Train a small CNN, export to ONNX, re-import, compare",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--steps", type=int, default=20)
parser.add_argument("--out", type=str, default=None,
                    help="where to write the .onnx file (tempdir default)")


def main(args):
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
                nn.MaxPool2D(2),
                nn.Flatten(),
                nn.Dense(10))
    net.initialize(ctx=mx.cpu())
    x = mx.nd.random.uniform(shape=(4, 1, 8, 8))
    y = mx.nd.array(np.random.randint(0, 10, (4,)))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    net(x).wait_to_read()
    net.hybridize()
    for _ in range(args.steps):
        with autograd.record():
            L = ce(net(x), y).mean()
        L.backward()
        tr.step(1)
    ref = net(x).asnumpy()

    # export: symbol + params -> .onnx
    sym = net(S.var("data"))
    params = {}
    for name, p in net.collect_params().items():
        params[name] = p.data()
    with tempfile.TemporaryDirectory() as tmp:
        path = args.out or os.path.join(tmp, "model.onnx")
        mx.contrib.onnx.export_model(sym, params, (4, 1, 8, 8),
                                     onnx_file=path)
        print("exported:", path, "(%d bytes)" % os.path.getsize(path))

        # re-import and compare
        sym2, arg2, aux2 = mx.contrib.onnx.import_model(path)
    ex = sym2.bind(mx.cpu(), {**arg2, "data": x}, aux_states=aux2)
    got = ex.forward(is_train=False)[0].asnumpy()
    err = float(np.abs(got - ref).max())
    print("round-trip max abs err: %.2e" % err)
    assert err < 1e-4, err
    print("ONNX round-trip OK")
    return err


if __name__ == "__main__":
    main(parser.parse_args())
