#!/usr/bin/env python
"""Module-API GAN: two Modules trained adversarially with hand-routed
gradients.

Reference analog: ``example/gan/gan_mnist.py`` — the pre-Gluon GAN
recipe whose whole point is Module plumbing: generator and discriminator
are SEPARATE bound Modules; the generator never sees a loss directly —
its gradient arrives via the discriminator's INPUT gradients
(``get_input_grads``), pushed backward through G with ``backward(grad)``.
(The Gluon-style DCGAN lives in example/gluon/dcgan.py; this one
exercises the Module mechanics.)

Synthetic task: the real distribution is a unit circle in 2-D (radius 1,
uniform angle).  G maps 8-D noise -> 2-D points; D classifies real/fake.
Success = generated points land near the circle: mean |radius-1| small.

Run:  python example/gan/gan_mnist.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import DataBatch

parser = argparse.ArgumentParser(
    description="Module-API GAN on a 2-D circle distribution",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=600)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--z-dim", type=int, default=8)
parser.add_argument("--lr", type=float, default=0.002)


def generator_symbol():
    z = sym.var("z")
    h = sym.FullyConnected(z, num_hidden=32, name="g_fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=32, name="g_fc2")
    h = sym.Activation(h, act_type="relu")
    return sym.FullyConnected(h, num_hidden=2, name="g_out")


def discriminator_symbol():
    x = sym.var("data")
    h = sym.FullyConnected(x, num_hidden=32, name="d_fc1")
    h = sym.LeakyReLU(h, act_type="leaky", slope=0.2)
    h = sym.FullyConnected(h, num_hidden=32, name="d_fc2")
    h = sym.LeakyReLU(h, act_type="leaky", slope=0.2)
    d = sym.FullyConnected(h, num_hidden=1, name="d_out")
    # logistic loss head: label 1 = real.  LogisticRegressionOutput's
    # backward is (sigmoid(x) - label), the GAN update both nets need.
    return sym.LogisticRegressionOutput(d, sym.var("label"), name="dloss")


def sample_real(rng, n):
    t = rng.uniform(0, 2 * np.pi, n).astype(np.float32)
    return np.stack([np.cos(t), np.sin(t)], 1)


def main(args):
    rng = np.random.RandomState(0)
    bs, zd = args.batch_size, args.z_dim

    gen = mx.mod.Module(generator_symbol(), data_names=("z",),
                        label_names=())
    gen.bind(data_shapes=[("z", (bs, zd))], inputs_need_grad=False)
    gen.init_params(mx.init.Xavier())
    gen.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    dis = mx.mod.Module(discriminator_symbol(), data_names=("data",),
                        label_names=("label",))
    # inputs_need_grad=True: the generator's training signal IS d(data)
    dis.bind(data_shapes=[("data", (bs, 2))],
             label_shapes=[("label", (bs, 1))], inputs_need_grad=True)
    dis.init_params(mx.init.Xavier())
    dis.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    ones = mx.nd.ones((bs, 1))
    zeros = mx.nd.zeros((bs, 1))

    def eval_radius():
        pts = []
        for _ in range(4):
            z = mx.nd.array(rng.randn(bs, zd).astype(np.float32))
            gen.forward(DataBatch(data=[z], label=[]), is_train=False)
            pts.append(gen.get_outputs()[0].asnumpy())
        pts = np.concatenate(pts)
        return float(np.abs(np.linalg.norm(pts, axis=1) - 1.0).mean())

    # GAN training is oscillatory: checkpoint-style selection (best
    # trailing eval) is the standard way to report it
    evals = []
    for it in range(args.iters):
        z = mx.nd.array(rng.randn(bs, zd).astype(np.float32))
        fake = None

        # --- D step: real up, fake down -----------------------------
        gen.forward(DataBatch(data=[z], label=[]), is_train=True)
        fake = gen.get_outputs()[0]
        real = mx.nd.array(sample_real(rng, bs))
        dis.forward(DataBatch(data=[real], label=[ones]), is_train=True)
        dis.backward()
        grads_real = [[g.copy() for g in gl]
                      for gl in dis._exec_group.grad_arrays]
        dis.forward(DataBatch(data=[fake], label=[zeros]), is_train=True)
        dis.backward()
        # accumulate the two phases' gradients, then one update
        for gl, rl in zip(dis._exec_group.grad_arrays, grads_real):
            for g, r in zip(gl, rl):
                g += r
        dis.update()

        # --- G step: push D's input grads back through G ------------
        dis.forward(DataBatch(data=[fake], label=[ones]), is_train=True)
        dis.backward()
        dz = dis.get_input_grads()[0]
        gen.backward([dz])
        gen.update()
        if it >= args.iters // 3 and (it + 1) % 50 == 0:
            evals.append(eval_radius())

    radius_err = min(evals) if evals else float("inf")
    print("best mean |radius - 1| of generated points: %.4f" % radius_err)
    return radius_err


if __name__ == "__main__":
    a = parser.parse_args()
    err = main(a)
    raise SystemExit(0 if err < 0.25 else 1)
