#!/usr/bin/env python
"""Neural style transfer by optimizing the input image.

Reference analog: ``example/neural-style/neuralstyle.py`` — hold a conv
feature extractor fixed, define content loss (feature match) + style loss
(Gram-matrix match), and run gradient descent on the *image*.  The
TPU-relevant pattern demonstrated: parameter-free optimization of an
input tensor (``attach_grad`` on the image, Adam on its gradient), every
step one fused XLA program.

The extractor here is a small fixed random-weight convnet: random conv
features are known to support style transfer (the demo's point is the
input-optimization machinery, not VGG fidelity — swap in
``model_zoo.vision.vgg19`` features for real use).

Run:  python example/neural-style/neural_style.py --steps 150
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="neural style by input optimization",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--size", type=int, default=32)
parser.add_argument("--steps", type=int, default=150)
parser.add_argument("--lr", type=float, default=0.05)
parser.add_argument("--style-weight", type=float, default=50.0)


def build_extractor(seed=0):
    """Fixed random conv stack; returns features at two depths."""
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.Conv2D(32, 3, padding=1, strides=2, activation="relu"),
            nn.Conv2D(32, 3, padding=1, activation="relu"))
    net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    shallow = net[:1]
    return net, shallow


def make_images(size, seed=0):
    """Content: centered blob.  Style: diagonal stripes."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    content = np.exp(-(((yy - size / 2) ** 2 + (xx - size / 2) ** 2)
                       / (2 * (size / 5.0) ** 2)))
    style = 0.5 + 0.5 * np.sin((xx + yy) * (2 * np.pi / 8))
    c = np.stack([content] * 3)[None]
    s = np.stack([style] * 3)[None]
    return c.astype(np.float32), s.astype(np.float32)


def gram(feat):
    b, c, h, w = feat.shape
    f = feat.reshape((c, h * w))
    return mx.nd.dot(f, f.T) / (c * h * w)


def main(args):
    deep, shallow = build_extractor()
    content_img, style_img = make_images(args.size)

    content_feat = deep(mx.nd.array(content_img))
    style_gram = gram(shallow(mx.nd.array(style_img)))

    img = mx.nd.array(content_img.copy())
    img.attach_grad()
    trainer = None  # manual adam on a bare tensor
    m = mx.nd.zeros(img.shape)
    v = mx.nd.zeros(img.shape)
    first = last = None
    for step in range(1, args.steps + 1):
        with autograd.record():
            cf = deep(img)
            sf = gram(shallow(img))
            content_loss = ((cf - content_feat) ** 2).mean()
            style_loss = ((sf - style_gram) ** 2).mean()
            L = content_loss + args.style_weight * style_loss
        L.backward()
        # adam update on the image
        g = img.grad
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * (g ** 2)
        mhat = m / (1 - 0.9 ** step)
        vhat = v / (1 - 0.999 ** step)
        img -= args.lr * mhat / (vhat.sqrt() + 1e-8)
        l = float(L.asnumpy())
        if first is None:
            first = l
        last = l
        if step % 50 == 0:
            print("step %d loss %.5f (content %.5f style %.5f)"
                  % (step, l, float(content_loss.asnumpy()),
                     float(style_loss.asnumpy())))
    print("total loss %.5f -> %.5f" % (first, last))
    return first, last, img.asnumpy()


if __name__ == "__main__":
    main(parser.parse_args())
