#!/usr/bin/env python
"""DCGAN on Gluon: adversarial training with two Trainers.

Reference analog: ``example/gluon/dcgan.py`` — generator/discriminator
convnets trained adversarially.  The TPU-relevant pattern demonstrated:
two hybridized networks with separate Trainers stepping against each
other inside one process, each forward/backward a fused XLA program.

Runs on synthetic data (axis-aligned gaussian blobs) so it needs no
dataset download; swap ``real_batches`` for a real image iterator
(e.g. ``ImageRecordIter``) for actual use.

Run:  python example/gluon/dcgan.py --num-epochs 3
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="DCGAN on synthetic blobs",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=3)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--nz", type=int, default=16, help="latent dim")
parser.add_argument("--lr", type=float, default=0.02)
parser.add_argument("--samples", type=int, default=512)
parser.add_argument("--size", type=int, default=16)


def real_batches(n, size, batch, seed=0):
    """Synthetic 'dataset': blurry gaussian blobs at random positions."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    imgs = []
    for _ in range(n):
        cy, cx = rng.uniform(4, size - 4, 2)
        s = rng.uniform(1.5, 3.0)
        img = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * s * s)))
        imgs.append(img * 2 - 1)                     # [-1, 1]
    imgs = np.stack(imgs)[:, None, :, :].astype(np.float32)
    for i in range(0, n - batch + 1, batch):
        yield imgs[i:i + batch]


def build_nets():
    netG = nn.HybridSequential()
    with netG.name_scope():
        netG.add(nn.Dense(4 * 4 * 32), nn.Activation("relu"))
        netG.add(nn.HybridLambda(lambda F, x: F.reshape(
            x, shape=(-1, 32, 4, 4))))
        netG.add(nn.Conv2DTranspose(16, 4, strides=2, padding=1),
                 nn.Activation("relu"))
        netG.add(nn.Conv2DTranspose(1, 4, strides=2, padding=1),
                 nn.Activation("tanh"))
    netD = nn.HybridSequential()
    with netD.name_scope():
        netD.add(nn.Conv2D(16, 4, strides=2, padding=1),
                 nn.LeakyReLU(0.2))
        netD.add(nn.Conv2D(32, 4, strides=2, padding=1),
                 nn.LeakyReLU(0.2))
        netD.add(nn.Flatten(), nn.Dense(1))
    return netG, netD


def main(args):
    mx.random.seed(0)        # param init + latents ride the mx RNG
    np.random.seed(0)
    if args.samples < args.batch_size or args.num_epochs < 1:
        parser.error("need --samples >= --batch-size and >= 1 epoch")
    if args.size != 16:
        parser.error("the demo generator topology is fixed at 16x16 "
                     "output; adapt build_nets for other --size values")
    netG, netD = build_nets()
    netG.initialize(init=mx.init.Normal(0.02))
    netD.initialize(init=mx.init.Normal(0.02))
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    z0 = mx.nd.random.normal(shape=(args.batch_size, args.nz))
    netG(z0).wait_to_read()
    netD(netG(z0)).wait_to_read()
    netG.hybridize()
    netD.hybridize()
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})

    ones = mx.nd.ones((args.batch_size,))
    zeros = mx.nd.zeros((args.batch_size,))
    for epoch in range(args.num_epochs):
        dl = gl = d_acc = n = 0
        for real in real_batches(args.samples, args.size,
                                 args.batch_size, seed=epoch):
            realn = mx.nd.array(real)
            z = mx.nd.random.normal(shape=(args.batch_size, args.nz))
            # D step: real -> 1, fake -> 0 (G forward recorded once and
            # reused — detached for D, live for G)
            with autograd.record():
                fake = netG(z)
                out_r = netD(realn).reshape((-1,))
                out_f = netD(fake.detach()).reshape((-1,))
                errD = (loss_fn(out_r, ones)
                        + loss_fn(out_f, zeros)).mean()
            errD.backward()
            trainerD.step(1)
            # G step: fool D
            with autograd.record():
                errG = loss_fn(netD(fake).reshape((-1,)), ones).mean()
            errG.backward()
            trainerG.step(1)
            dl += float(errD.asnumpy())
            gl += float(errG.asnumpy())
            d_acc += float(((out_r.sigmoid() > 0.5).asnumpy().mean()
                            + (out_f.sigmoid() < 0.5).asnumpy().mean())
                           / 2)
            n += 1
        print("epoch %d  lossD %.3f  lossG %.3f  D-acc %.2f"
              % (epoch, dl / n, gl / n, d_acc / n))
    fake = netG(z0).asnumpy()
    assert np.isfinite(fake).all()
    return dl / n, gl / n, d_acc / n


if __name__ == "__main__":
    main(parser.parse_args())
