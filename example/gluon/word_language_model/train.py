#!/usr/bin/env python
"""Gluon LSTM word language model (parity: example/gluon/
word_language_model/train.py — BASELINE.json config #3).

Trains an embedding + LSTM + decoder on a text corpus with truncated BPTT,
reporting perplexity.  Without --data it trains on a built-in toy corpus so
the example runs with zero downloads.
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402

TOY_CORPUS = ("the quick brown fox jumps over the lazy dog . "
              "a stitch in time saves nine . "
              "all that glitters is not gold . ") * 200


class Corpus:
    def __init__(self, text):
        tokens = text.split()
        self.vocab = sorted(set(tokens))
        self.tok2id = {t: i for i, t in enumerate(self.vocab)}
        self.data = np.asarray([self.tok2id[t] for t in tokens], np.float32)


def batchify(data, batch_size):
    n = len(data) // batch_size
    return mx.nd.array(
        data[: n * batch_size].reshape(batch_size, n).T)  # (T, N)


class RNNModel(gluon.Block):
    def __init__(self, vocab_size, embed=128, hidden=256, layers=2,
                 dropout=0.2, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, embed)
            self.rnn = rnn.LSTM(hidden, num_layers=layers, dropout=dropout,
                                input_size=embed)
            self.decoder = nn.Dense(vocab_size, in_units=hidden)
            self.hidden = hidden

    def forward(self, inputs, state):
        emb = self.drop(self.encoder(inputs))
        output, state = self.rnn(emb, state)
        output = self.drop(output)
        decoded = self.decoder(output.reshape((-1, self.hidden)))
        return decoded, state

    def begin_state(self, *a, **kw):
        return self.rnn.begin_state(*a, **kw)


def detach(state):
    if isinstance(state, (list, tuple)):
        return [detach(s) for s in state]
    return state.detach()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="path to a text corpus")
    ap.add_argument("--batch-size", type=int, default=20)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    args = ap.parse_args()

    text = open(args.data).read() if args.data else TOY_CORPUS
    corpus = Corpus(text)
    data = batchify(corpus.data, args.batch_size)
    ntokens = len(corpus.vocab)
    model = RNNModel(ntokens)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total_loss, total_tokens = 0.0, 0
        state = model.begin_state(batch_size=args.batch_size)
        tic = time.time()
        for i in range(0, data.shape[0] - 1, args.bptt):
            seq = min(args.bptt, data.shape[0] - 1 - i)
            x = data[i:i + seq]
            y = data[i + 1:i + 1 + seq].reshape((-1,))
            state = detach(state)
            with mx.autograd.record():
                out, state = model(x, state)
                loss = loss_fn(out, y)
            loss.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads,
                                         args.clip * seq * args.batch_size)
            trainer.step(seq * args.batch_size)
            total_loss += float(loss.sum().asnumpy())
            total_tokens += seq * args.batch_size
        ppl = math.exp(total_loss / total_tokens)
        print("epoch %d: perplexity %.2f (%.0f tokens/s)"
              % (epoch, ppl, total_tokens / (time.time() - tic)))


if __name__ == "__main__":
    main()
