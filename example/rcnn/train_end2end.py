#!/usr/bin/env python
"""Faster R-CNN end-to-end training (parity: example/rcnn/train_end2end.py).

The reference's RCNN example is a full package (rcnn/symbol, AnchorLoader,
ProposalTarget custom op, MutableModule); this is the same topology in one
file, exercising every RCNN-specific piece of the framework:

  backbone convs -> RPN head (cls + bbox) -> SoftmaxOutput with ignore
  labels + smooth-L1 RPN bbox loss -> ``_contrib_Proposal`` (anchor decode
  + NMS, fixed-capacity TPU formulation) -> **ProposalTarget as a Python
  custom op** (the reference's rcnn/symbol/proposal_target.py pattern over
  the custom-op bridge) -> ROIPooling -> classifier/regressor heads.

Data is synthetic (colored rectangles, zero egress) with the exact label
conventions of the reference pipeline: padded gt_boxes (x1,y1,x2,y2,cls),
RPN anchor targets with -1 = ignore, class-specific bbox regression with
per-class weights.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402

# ---- config (reference rcnn/config.py, shrunk to demo scale) -------------
IMG = 64
STRIDE = 8
FEAT = IMG // STRIDE
SCALES = (2.0, 4.0)        # anchor box sizes in stride units
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
NUM_CLASSES = 3            # background + 2 shapes
ROI_BATCH = 32             # sampled rois per image (TRAIN.BATCH_ROIS)
POST_NMS = 64


def make_anchors():
    """(A*F*F, 4) anchors, x1y1x2y2 (rcnn/processing/generate_anchor.py)."""
    anchors = []
    for y in range(FEAT):
        for x in range(FEAT):
            cx, cy = (x + 0.5) * STRIDE, (y + 0.5) * STRIDE
            for s in SCALES:
                for r in RATIOS:
                    w = STRIDE * s * np.sqrt(r)
                    h = STRIDE * s / np.sqrt(r)
                    anchors.append([cx - w / 2, cy - h / 2,
                                    cx + w / 2, cy + h / 2])
    return np.asarray(anchors, np.float32)


ANCHORS = make_anchors()


def iou(boxes, gt):
    """(N,4) x (M,4) -> (N,M)."""
    ix1 = np.maximum(boxes[:, None, 0], gt[None, :, 0])
    iy1 = np.maximum(boxes[:, None, 1], gt[None, :, 1])
    ix2 = np.minimum(boxes[:, None, 2], gt[None, :, 2])
    iy2 = np.minimum(boxes[:, None, 3], gt[None, :, 3])
    iw = np.maximum(ix2 - ix1, 0)
    ih = np.maximum(iy2 - iy1, 0)
    inter = iw * ih
    area_b = ((boxes[:, 2] - boxes[:, 0]) *
              (boxes[:, 3] - boxes[:, 1]))[:, None]
    area_g = ((gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1]))[None, :]
    return inter / np.maximum(area_b + area_g - inter, 1e-9)


def bbox_transform(rois, gt):
    """Box -> regression deltas (rcnn/processing/bbox_transform.py)."""
    rw = np.maximum(rois[:, 2] - rois[:, 0], 1.0)
    rh = np.maximum(rois[:, 3] - rois[:, 1], 1.0)
    rcx = rois[:, 0] + rw / 2
    rcy = rois[:, 1] + rh / 2
    gw = np.maximum(gt[:, 2] - gt[:, 0], 1.0)
    gh = np.maximum(gt[:, 3] - gt[:, 1], 1.0)
    gcx = gt[:, 0] + gw / 2
    gcy = gt[:, 1] + gh / 2
    return np.stack([(gcx - rcx) / rw, (gcy - rcy) / rh,
                     np.log(gw / rw), np.log(gh / rh)], axis=1)


def anchor_target(gt):
    """RPN training targets for one image (rcnn/io/rpn.py assign_anchor):
    labels (A*F*F,) in {-1 ignore, 0 neg, 1 pos}; bbox targets/weights
    (4A, F, F)."""
    labels = np.full(len(ANCHORS), -1, np.float32)
    targets = np.zeros((len(ANCHORS), 4), np.float32)
    weights = np.zeros((len(ANCHORS), 4), np.float32)
    if len(gt):
        overlaps = iou(ANCHORS, gt[:, :4])
        max_ov = overlaps.max(axis=1)
        argmax = overlaps.argmax(axis=1)
        labels[max_ov < 0.3] = 0
        labels[max_ov >= 0.5] = 1
        labels[overlaps.argmax(axis=0)] = 1  # best anchor per gt
        pos = labels == 1
        targets[pos] = bbox_transform(ANCHORS[pos], gt[argmax[pos], :4])
        weights[pos] = 1.0
    else:
        labels[:] = 0
    # (A*F*F,) per-position ordering -> (4A, F, F): anchors vary fastest
    t = targets.reshape(FEAT, FEAT, A * 4).transpose(2, 0, 1)
    w = weights.reshape(FEAT, FEAT, A * 4).transpose(2, 0, 1)
    return labels.reshape(FEAT, FEAT, A).transpose(2, 0, 1).reshape(-1), t, w


@mx.operator.register("proposal_target_demo")
class ProposalTargetProp(mx.operator.CustomOpProp):
    """Sample proposals vs gt into fixed-size RCNN training batches
    (reference rcnn/symbol/proposal_target.py custom op)."""

    def __init__(self, num_classes=str(NUM_CLASSES),
                 batch_rois=str(ROI_BATCH)):
        super().__init__(need_top_grad=False)
        self.nc = int(num_classes)
        self.br = int(batch_rois)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        return (in_shape,
                [[self.br, 5], [self.br], [self.br, 4 * self.nc],
                 [self.br, 4 * self.nc]], [])

    def create_operator(self, ctx, shapes, dtypes):
        prop = self

        class Op(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                rois = in_data[0].asnumpy()        # (P, 5)
                gt = in_data[1].asnumpy()          # (M, 5) padded with -1
                gt = gt[gt[:, 4] >= 0]
                # include gt boxes as proposals (reference behavior)
                if len(gt):
                    gt_rois = np.concatenate(
                        [np.zeros((len(gt), 1), np.float32), gt[:, :4]], 1)
                    rois = np.concatenate([rois, gt_rois], 0)
                n = prop.br
                labels = np.zeros(len(rois), np.float32)
                targets = np.zeros((len(rois), 4), np.float32)
                if len(gt):
                    ov = iou(rois[:, 1:], gt[:, :4])
                    mx_ov = ov.max(1)
                    am = ov.argmax(1)
                    fg = mx_ov >= 0.5
                    labels[fg] = gt[am[fg], 4] + 1  # class ids 1..C-1
                    targets[fg] = bbox_transform(rois[fg, 1:],
                                                 gt[am[fg], :4])
                # sample: up to n/4 fg, rest bg
                fg_idx = np.where(labels > 0)[0]
                bg_idx = np.where(labels == 0)[0]
                rng = np.random
                fg_take = fg_idx[rng.permutation(len(fg_idx))[:n // 4]]
                need = n - len(fg_take)
                bg_take = bg_idx[rng.permutation(len(bg_idx))[:need]]
                take = np.concatenate([fg_take, bg_take])
                if not len(take):
                    take = np.zeros(n, np.int64)
                while len(take) < n:   # wrap-pad until the batch is full
                    take = np.concatenate([take, take[:n - len(take)]])
                sr = rois[take].astype(np.float32)
                sl = labels[take]
                st = np.zeros((n, 4 * prop.nc), np.float32)
                sw = np.zeros((n, 4 * prop.nc), np.float32)
                for i, lab in enumerate(sl):
                    c = int(lab)
                    if c > 0:
                        st[i, 4 * c:4 * c + 4] = targets[take[i]]
                        sw[i, 4 * c:4 * c + 4] = 1.0
                self.assign(out_data[0], req[0], mx.nd.array(sr))
                self.assign(out_data[1], req[1], mx.nd.array(sl))
                self.assign(out_data[2], req[2], mx.nd.array(st))
                self.assign(out_data[3], req[3], mx.nd.array(sw))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                for i, g in enumerate(in_grad):
                    self.assign(g, req[i], mx.nd.zeros(g.shape))

        return Op()


def build_symbol():
    data = sym.var("data")
    im_info = sym.var("im_info")
    gt_boxes = sym.var("gt_boxes")
    rpn_label = sym.var("rpn_label")
    rpn_bbox_target = sym.var("rpn_bbox_target")
    rpn_bbox_weight = sym.var("rpn_bbox_weight")

    # backbone: 3 stride-2 convs -> stride 8 feature map
    x = data
    for i, nf in enumerate((16, 32, 64)):
        x = sym.Convolution(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                            num_filter=nf, name="conv%d" % i)
        x = sym.Activation(x, act_type="relu")
    feat = x

    # RPN head
    rpn = sym.Activation(
        sym.Convolution(feat, kernel=(3, 3), pad=(1, 1), num_filter=32,
                        name="rpn_conv"), act_type="relu")
    rpn_cls_score = sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * A,
                                    name="rpn_cls_score")
    rpn_bbox_pred = sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * A,
                                    name="rpn_bbox_pred")
    score_reshape = sym.Reshape(rpn_cls_score, shape=(0, 2, -1, 0),
                                name="rpn_cls_score_reshape")
    rpn_cls_prob = sym.SoftmaxOutput(score_reshape, rpn_label,
                                     multi_output=True, use_ignore=True,
                                     ignore_label=-1, name="rpn_cls_prob")
    rpn_bbox_loss = sym.MakeLoss(
        sym.smooth_l1(rpn_bbox_weight *
                      (rpn_bbox_pred - rpn_bbox_target), scalar=3.0) *
        (1.0 / ROI_BATCH), name="rpn_bbox_loss")

    # proposals (fixed post-NMS capacity) + target sampling custom op
    prob_back = sym.Reshape(rpn_cls_prob, shape=(0, 2 * A, -1, FEAT),
                            name="rpn_cls_prob_reshape")
    rois = sym.Proposal(prob_back, rpn_bbox_pred, im_info,
                        feature_stride=STRIDE, scales=SCALES,
                        ratios=RATIOS, rpn_pre_nms_top_n=128,
                        rpn_post_nms_top_n=POST_NMS, threshold=0.7,
                        rpn_min_size=4, name="rois")
    group = sym.Custom(rois, gt_boxes, op_type="proposal_target_demo",
                       num_classes=str(NUM_CLASSES),
                       batch_rois=str(ROI_BATCH), name="ptarget")
    sampled_rois, label, bbox_target, bbox_weight = \
        group[0], group[1], group[2], group[3]

    # RCNN head
    pooled = sym.ROIPooling(feat, sampled_rois, pooled_size=(4, 4),
                            spatial_scale=1.0 / STRIDE, name="roi_pool")
    flat = sym.Flatten(pooled)
    fc = sym.Activation(sym.FullyConnected(flat, num_hidden=64,
                                           name="fc6"), act_type="relu")
    cls_score = sym.FullyConnected(fc, num_hidden=NUM_CLASSES,
                                   name="cls_score")
    bbox_pred = sym.FullyConnected(fc, num_hidden=4 * NUM_CLASSES,
                                   name="bbox_pred")
    cls_prob = sym.SoftmaxOutput(cls_score, label, name="cls_prob")
    bbox_loss = sym.MakeLoss(
        sym.smooth_l1(bbox_weight * (bbox_pred - bbox_target),
                      scalar=1.0) * (1.0 / ROI_BATCH), name="bbox_loss")
    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss,
                      sym.BlockGrad(label)])


def synth_image(rng):
    """Image with 1-2 rectangles of class 0 (dark) / 1 (bright)."""
    img = rng.uniform(0, 0.2, (3, IMG, IMG)).astype(np.float32)
    boxes = []
    for _ in range(rng.randint(1, 3)):
        w, h = rng.randint(12, 32, 2)
        x1 = rng.randint(0, IMG - w)
        y1 = rng.randint(0, IMG - h)
        cls = rng.randint(0, 2)
        val = 0.5 if cls == 0 else 1.0
        img[:, y1:y1 + h, x1:x1 + w] = val + \
            rng.uniform(-0.05, 0.05, (3, h, w))
        boxes.append([x1, y1, x1 + w, y1 + h, cls])
    gt = np.full((4, 5), -1, np.float32)
    gt[:len(boxes)] = np.asarray(boxes, np.float32)
    return img, gt


def train(args):
    net = build_symbol()
    ex = net.simple_bind(
        ctx=mx.current_context(), grad_req="write",
        data=(1, 3, IMG, IMG), im_info=(1, 3), gt_boxes=(4, 5),
        rpn_label=(1, A * FEAT, FEAT),
        rpn_bbox_target=(1, 4 * A, FEAT, FEAT),
        rpn_bbox_weight=(1, 4 * A, FEAT, FEAT))
    init = mx.init.Xavier()
    data_names = {"data", "im_info", "gt_boxes", "rpn_label",
                  "rpn_bbox_target", "rpn_bbox_weight"}
    for name, arr in ex.arg_dict.items():
        if name not in data_names:
            init(mx.init.InitDesc(name), arr)

    rng = np.random.RandomState(0)
    im_info = np.asarray([[IMG, IMG, 1.0]], np.float32)
    history = []
    for it in range(args.num_iter):
        img, gt = synth_image(rng)
        labels, bt, bw = anchor_target(gt[gt[:, 4] >= 0])
        outs = ex.forward(
            is_train=True, data=mx.nd.array(img[None]),
            im_info=mx.nd.array(im_info), gt_boxes=mx.nd.array(gt),
            rpn_label=mx.nd.array(labels.reshape(1, A * FEAT, FEAT)),
            rpn_bbox_target=mx.nd.array(bt[None]),
            rpn_bbox_weight=mx.nd.array(bw[None]))
        ex.backward()
        for name, grad in ex.grad_dict.items():
            if name in data_names:
                continue
            ex.arg_dict[name][:] = ex.arg_dict[name] - args.lr * grad
        rpn_prob = outs[0].asnumpy()        # (1, 2, A*F*F) probs
        rpn_lab = labels
        probs = rpn_prob.reshape(2, -1)
        valid = rpn_lab >= 0
        rpn_nll = -np.log(np.maximum(
            probs[rpn_lab[valid].astype(int), np.where(valid)[0]], 1e-9))
        cls_lab = outs[4].asnumpy().astype(int)
        cls_nll = -np.log(np.maximum(
            outs[2].asnumpy()[np.arange(len(cls_lab)), cls_lab], 1e-9))
        total = (rpn_nll.mean() + float(outs[1].asnumpy().sum()) +
                 cls_nll.mean() + float(outs[3].asnumpy().sum()))
        history.append(total)
        if it % max(1, args.num_iter // 10) == 0:
            print("iter %3d  rpn_cls %.3f  rpn_bbox %.4f  cls %.3f  "
                  "bbox %.4f  total %.3f"
                  % (it, rpn_nll.mean(), outs[1].asnumpy().sum(),
                     cls_nll.mean(), outs[3].asnumpy().sum(), total))
    first = np.mean(history[:5])
    last = np.mean(history[-5:])
    print("loss %.3f -> %.3f" % (first, last))
    return first, last


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-iter", type=int, default=100)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()
    train(args)


if __name__ == "__main__":
    main()
