#!/usr/bin/env python
"""Speech recognition: BiLSTM acoustic model + CTC on spectrogram frames.

Reference analog: ``example/speech_recognition/main.py`` (the
DeepSpeech-style recipe: spectrogram -> recurrent acoustic model -> CTC
over unaligned transcripts; ``arch_deepspeech.py``).

Synthetic speech: each "utterance" is a sequence of phones; a phone p is
rendered as 3-6 frames of a characteristic spectral envelope (two
"formant" bumps over 20 mel-ish bands) with speaker-level gain and
additive noise, separated by silence gaps.  The acoustic model must
learn BOTH the spectral identity of each phone and the alignment — the
CTC marginalization handles the latter.  Greedy decode, phone error
measured as exact-match rate of collapsed sequences.

Run:  python example/speech_recognition/speech_lstm_ctc.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

parser = argparse.ArgumentParser(
    description="BiLSTM+CTC acoustic model on synthetic speech",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=200)
parser.add_argument("--batch-size", type=int, default=16)
parser.add_argument("--n-phones", type=int, default=6)
parser.add_argument("--n-bands", type=int, default=20)
parser.add_argument("--max-frames", type=int, default=40)
parser.add_argument("--hidden", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.01)


def _phone_envelope(p, n_bands):
    """Two formant bumps whose centers encode the phone identity."""
    f1 = 2 + (p * 3) % (n_bands // 2)
    f2 = n_bands // 2 + (p * 5) % (n_bands // 2 - 2)
    band = np.arange(n_bands)
    env = (np.exp(-0.5 * ((band - f1) / 1.5) ** 2)
           + 0.8 * np.exp(-0.5 * ((band - f2) / 2.0) ** 2))
    return env.astype(np.float32)


def make_batch(rng, bs, n_phones, n_bands, T):
    """(frames, labels, label_lens): 2-4 phones per utterance, each
    3-6 frames, 1-3 silence frames between."""
    xs = np.zeros((bs, T, n_bands), np.float32)
    max_l = 4
    # gluon CTCLoss convention (blank_label="last"): labels 0-based,
    # padding -1, blank = n_phones (the last class)
    ys = np.full((bs, max_l), -1.0, np.float32)
    lens = np.zeros((bs,), np.int32)
    for i in range(bs):
        n = int(rng.randint(2, max_l + 1))
        t = int(rng.randint(0, 3))
        gain = 0.8 + 0.4 * rng.uniform()
        lab = 0
        for j in range(n):
            if t >= T:
                break          # no room: the transcript must not carry
            p = int(rng.randint(n_phones))       # phones with no audio
            ys[i, lab] = p
            lab += 1
            dur = int(rng.randint(4, 8))
            env = _phone_envelope(p, n_bands) * gain
            for _ in range(dur):
                if t >= T:
                    break
                xs[i, t] = env
                t += 1
            t += int(rng.randint(1, 4))          # silence gap
        lens[i] = lab
    xs += rng.randn(bs, T, n_bands).astype(np.float32) * 0.08
    return nd.array(xs), nd.array(ys), lens


class AcousticModel(gluon.Block):
    def __init__(self, n_out, hidden, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.proj_in = nn.Dense(hidden, flatten=False,
                                    activation="relu")
            self.lstm = rnn.LSTM(hidden, num_layers=1, bidirectional=True,
                                 layout="NTC")
            self.head = nn.Dense(n_out, flatten=False)

    def forward(self, x):                        # (B, T, bands)
        h = self.proj_in(x)
        h = self.lstm(h)
        return self.head(h)                      # (B, T, n_phones+1)


def greedy_decode(logits):
    """Best path: argmax per frame, collapse repeats, strip blanks
    (blank = last class, the gluon CTCLoss convention)."""
    blank = logits.shape[-1] - 1
    path = logits.argmax(-1)
    out = []
    for row in path:
        seq, prev = [], -1
        for s in row:
            if s != prev and s != blank:
                seq.append(int(s))
            prev = s
        out.append(seq)
    return out


def main(args):
    rng = np.random.RandomState(0)
    net = AcousticModel(args.n_phones + 1, args.hidden)
    net.initialize(mx.init.Xavier())
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    matches = []
    for it in range(args.iters):
        x, y, lens = make_batch(rng, args.batch_size, args.n_phones,
                                args.n_bands, args.max_frames)
        with autograd.record():
            logits = net(x)
            loss = ctc(logits, y)
        loss.backward()
        trainer.step(args.batch_size)
        if it >= args.iters - 15:
            decoded = greedy_decode(logits.asnumpy())
            for i in range(args.batch_size):
                truth = [int(v) for v in y.asnumpy()[i][:lens[i]]]
                matches.append(float(decoded[i] == truth))
    acc = float(np.mean(matches))
    print("utterance exact-match rate: %.4f" % acc)
    return acc


if __name__ == "__main__":
    a = parser.parse_args()
    acc = main(a)
    raise SystemExit(0 if acc > 0.7 else 1)
