#!/usr/bin/env python
"""Model-parallel multi-layer LSTM language model (parity:
example/model-parallel/lstm — the reference's coarse model-parallelism
showcase: each LSTM layer lives in its own ``ctx_group``, bound to a
different device via ``group2ctx``; activations cross device boundaries
between layers while each layer's weights stay resident on its device).

TPU-native notes: placement uses the group2ctx executor path
(``AssignContext`` parity); on a real pod you would instead shard layers
with pipeline parallelism (``mxnet_tpu.parallel.pipeline``) — this example
exists for reference-workflow parity and runs on any multi-device setup
(including the CPU interpreter with multiple virtual devices).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
         python lstm.py --num-layers 4 --num-epochs 4
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402


def lstm_cell(num_hidden, indata, prev_c, prev_h, layer, t):
    """One explicit LSTM cell from FC ops (reference lstm.py pattern —
    weights shared across time via name reuse)."""
    i2h = sym.FullyConnected(indata, num_hidden=num_hidden * 4,
                             name="l%d_i2h" % layer)
    h2h = sym.FullyConnected(prev_h, num_hidden=num_hidden * 4,
                             name="l%d_h2h" % layer)
    gates = i2h + h2h
    sliced = sym.SliceChannel(gates, num_outputs=4,
                              name="l%d_t%d_slice" % (layer, t))
    in_gate = sym.Activation(sliced[0], act_type="sigmoid")
    in_trans = sym.Activation(sliced[1], act_type="tanh")
    forget = sym.Activation(sliced[2], act_type="sigmoid")
    out_gate = sym.Activation(sliced[3], act_type="sigmoid")
    next_c = forget * prev_c + in_gate * in_trans
    next_h = out_gate * sym.Activation(next_c, act_type="tanh")
    return next_c, next_h


def build(seq_len, vocab, num_embed, num_hidden, num_layers):
    """Unrolled LM: embedding on group 'embed', LSTM layer i on group
    'layer_i', decoder on the last layer's group."""
    data = sym.var("data")            # (batch, seq_len)
    label = sym.var("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        embed = sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                              name="embed")
        steps = sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                 squeeze_axis=True, name="embed_slice")
    states = []
    for layer in range(num_layers):
        with mx.AttrScope(ctx_group="layer_%d" % layer):
            c = sym.var("l%d_init_c" % layer)
            h = sym.var("l%d_init_h" % layer)
        states.append((c, h))
    outputs = []
    for t in range(seq_len):
        x = steps[t]
        for layer in range(num_layers):
            with mx.AttrScope(ctx_group="layer_%d" % layer):
                c, h = lstm_cell(num_hidden, x, states[layer][0],
                                 states[layer][1], layer, t)
            states[layer] = (c, h)
            x = h
        outputs.append(x)
    with mx.AttrScope(ctx_group="layer_%d" % (num_layers - 1)):
        concat = sym.concat(*outputs, dim=0)      # (seq*batch, hidden)
        pred = sym.FullyConnected(concat, num_hidden=vocab, name="decoder")
        flat_label = sym.Reshape(sym.transpose(label, axes=(1, 0)),
                                 shape=(-1,))
        out = sym.SoftmaxOutput(pred, flat_label, name="softmax")
    return out


def synthetic_corpus(n_tokens, vocab, rng):
    """Markov-ish synthetic ids so the LM has learnable structure."""
    ids = np.zeros(n_tokens, np.int64)
    for i in range(1, n_tokens):
        ids[i] = (ids[i - 1] * 31 + 7) % vocab if rng.rand() < 0.8 \
            else rng.randint(vocab)
    return ids


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-layers", type=int, default=4)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()
    train(args)


def train(args):
    import jax
    n_dev = len(jax.devices())
    # layer i -> device i (mod available); embedding with layer 0
    group2ctx = {"embed": mx.Context(mx.current_context().device_type, 0)}
    for layer in range(args.num_layers):
        group2ctx["layer_%d" % layer] = mx.Context(
            mx.current_context().device_type, layer % n_dev)
    print("placement:", {g: str(c) for g, c in group2ctx.items()})

    net = build(args.seq_len, args.vocab, args.num_embed, args.num_hidden,
                args.num_layers)

    rng = np.random.RandomState(0)
    corpus = synthetic_corpus(20_000, args.vocab, rng)
    n_seq = (len(corpus) - 1) // args.seq_len
    X = corpus[:n_seq * args.seq_len].reshape(n_seq, args.seq_len)
    Y = corpus[1:n_seq * args.seq_len + 1].reshape(n_seq, args.seq_len)

    init_states = {}
    for layer in range(args.num_layers):
        for s in ("c", "h"):
            init_states["l%d_init_%s" % (layer, s)] = \
                (args.batch_size, args.num_hidden)
    ex = net.simple_bind(ctx=list(group2ctx.values())[0],
                         group2ctx=group2ctx, grad_req="write",
                         data=(args.batch_size, args.seq_len),
                         softmax_label=(args.batch_size, args.seq_len),
                         **init_states)
    init = mx.init.Xavier()
    for name, arr in ex.arg_dict.items():
        if name in ("data", "softmax_label") or "_init_" in name:
            continue
        init(mx.init.InitDesc(name), arr)
    zeros = {k: mx.nd.zeros(v) for k, v in init_states.items()}

    last_ppl = None
    for epoch in range(args.num_epochs):
        order = rng.permutation(n_seq // args.batch_size)
        total_nll, total_tok = 0.0, 0
        for b in order:
            s = b * args.batch_size
            xb = X[s:s + args.batch_size].astype(np.float32)
            yb = Y[s:s + args.batch_size].astype(np.float32)
            outs = ex.forward(is_train=True, data=mx.nd.array(xb),
                              softmax_label=mx.nd.array(yb), **zeros)
            ex.backward()
            for name, grad in ex.grad_dict.items():
                if name in ("data", "softmax_label") or "_init_" in name:
                    continue
                ex.arg_dict[name][:] = ex.arg_dict[name] - \
                    (args.lr / args.batch_size) * grad
            probs = outs[0].asnumpy()
            flat_y = yb.T.reshape(-1).astype(np.int64)
            nll = -np.log(np.maximum(
                probs[np.arange(len(flat_y)), flat_y], 1e-12))
            total_nll += nll.sum()
            total_tok += len(flat_y)
        last_ppl = float(np.exp(total_nll / total_tok))
        print("epoch %d  perplexity %.2f" % (epoch, last_ppl))
    return last_ppl


if __name__ == "__main__":
    main()
