#!/usr/bin/env python
"""CTC sequence recognition: LSTM + CTCLoss on unaligned labels.

Reference analog: ``example/ctc/lstm_ocr.py`` — recognize a character
sequence from frames WITHOUT per-frame alignment, the CTC training
pattern (warp-ctc / ``src/operator/contrib/ctc_loss.cc``).

Synthetic task: each sample is T noisy frames; a random digit string
(length 3-5) is embedded as runs of one-hot frames separated by blank
gaps.  The LSTM must learn the alignment itself — exactly what CTC's
forward-backward marginalization provides.  Greedy (best-path) decoding
collapses repeats and strips blanks.

Run:  python example/ctc/lstm_ocr.py --num-epochs 10
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn, rnn

parser = argparse.ArgumentParser(
    description="LSTM + CTC on synthetic digit sequences",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=30)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--hidden", type=int, default=48)
parser.add_argument("--lr", type=float, default=0.02)
parser.add_argument("--samples", type=int, default=512)
parser.add_argument("--seq-len", type=int, default=20)
parser.add_argument("--max-label", type=int, default=5)

VOCAB = 10          # digits; CTC blank is class index VOCAB (="last")


def make_data(n, T, max_label, seed=0):
    rng = np.random.RandomState(seed)
    X = np.zeros((n, T, VOCAB), np.float32)
    Y = np.full((n, max_label), -1.0, np.float32)   # -1 padding
    for i in range(n):
        L = rng.randint(3, max_label + 1)
        digits = rng.randint(0, VOCAB, L)
        Y[i, :L] = digits
        t = rng.randint(0, 2)
        for d in digits:
            runlen = rng.randint(2, 4)
            X[i, t:t + runlen, d] = 1.0
            t += runlen + rng.randint(1, 3)          # blank gap
            if t >= T:
                break
    X += rng.randn(n, T, VOCAB).astype(np.float32) * 0.1
    return X, Y


def greedy_decode(logits):
    """Best path: per-frame argmax, collapse repeats, drop blanks."""
    path = logits.argmax(-1)                        # (N, T)
    out = []
    for row in path:
        seq, prev = [], -1
        for c in row:
            if c != prev and c != VOCAB:
                seq.append(int(c))
            prev = c
        out.append(seq)
    return out


def main(args):
    mx.random.seed(0)      # deterministic init for the smoke tests
    if args.samples < args.batch_size or args.num_epochs < 1:
        parser.error("need --samples >= --batch-size and >= 1 epoch")
    X, Y = make_data(args.samples, args.seq_len, args.max_label)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(rnn.LSTM(args.hidden, layout="NTC"))
        net.add(nn.Dense(VOCAB + 1, flatten=False))  # + blank (last)
    net.initialize()
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    xb0 = mx.nd.array(X[:args.batch_size])
    net(xb0).wait_to_read()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})

    for epoch in range(args.num_epochs):
        tot, nb = 0.0, 0
        for i in range(0, args.samples - args.batch_size + 1,
                       args.batch_size):
            xb = mx.nd.array(X[i:i + args.batch_size])
            yb = mx.nd.array(Y[i:i + args.batch_size])
            with autograd.record():
                L = ctc(net(xb), yb).mean()
            L.backward()
            tr.step(1)
            tot += float(L.asnumpy())
            nb += 1
        if epoch % 2 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d  ctc loss %.3f" % (epoch, tot / nb))

    # exact-sequence accuracy via greedy decode
    logits = net(mx.nd.array(X)).asnumpy()
    decoded = greedy_decode(logits)
    correct = 0
    for i, seq in enumerate(decoded):
        label = [int(d) for d in Y[i] if d >= 0]
        correct += int(seq == label)
    acc = correct / len(decoded)
    print("exact-sequence accuracy: %.3f" % acc)
    return tot / nb, acc


if __name__ == "__main__":
    main(parser.parse_args())
