#!/usr/bin/env python
"""Sparse linear classification (parity: example/sparse/
linear_classification/ — BASELINE.json config #5).

Logistic regression over sparse (CSR) features with a row_sparse weight:
sparse dot forward, row_sparse gradients, kvstore row_sparse_pull of just
the touched rows — the embedding-style sparse training loop of the
reference, on synthetic criteo-like data.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def synthetic_csr(num_samples, num_features, nnz_per_row, rng):
    true_w = rng.randn(num_features).astype(np.float32)
    dense = np.zeros((num_samples, num_features), np.float32)
    for i in range(num_samples):
        cols = rng.choice(num_features, nnz_per_row, replace=False)
        dense[i, cols] = rng.rand(nnz_per_row).astype(np.float32)
    logits = dense @ true_w
    y = (logits > np.median(logits)).astype(np.float32)
    return nd.array(dense).tostype("csr"), nd.array(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--num-samples", type=int, default=512)
    ap.add_argument("--nnz", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--kv-store", default="local")
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    X, y = synthetic_csr(args.num_samples, args.num_features, args.nnz, rng)
    kv = mx.kv.create(args.kv_store)
    # server-side optimizer: pushes apply SGD on the stored weight
    # (update_on_kvstore, kvstore_dist_server.h pattern); the server
    # holds the full dense weight, workers pull row_sparse slices
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=args.lr))
    kv.init("w", nd.zeros((args.num_features, 1)))
    weight = nd.zeros((args.num_features, 1)).tostype("row_sparse")
    bias = nd.zeros((1,))

    for epoch in range(args.epochs):
        total, correct, loss_sum = 0, 0, 0.0
        for start in range(0, args.num_samples, args.batch_size):
            xb = X[start:start + args.batch_size]
            yb = y[start:start + args.batch_size]
            # pull only the rows this batch touches (kvstore_dist.h
            # row-sparse pull pattern)
            row_ids = nd.array(np.unique(xb.indices.asnumpy()))
            kv.row_sparse_pull("w", out=weight, row_ids=row_ids)
            dense_w = weight.tostype("default")
            xb_d = xb.tostype("default")
            logits = (nd.dot(xb_d, dense_w) + bias).reshape((-1,))
            p = nd.sigmoid(logits)
            # logistic gradient, row-sparse on the touched rows
            err = (p - yb).reshape((-1, 1))
            grad_dense = nd.dot(xb_d.T, err) / xb.shape[0]
            grad = grad_dense.tostype("row_sparse")
            kv.push("w", grad)
            # local SGD on the pulled copy for bias
            bias -= args.lr * err.mean()
            eps = 1e-7
            loss_sum += float((-(yb * nd.log(p + eps) +
                                 (1 - yb) * nd.log(1 - p + eps))).sum()
                              .asnumpy())
            correct += int(((p > 0.5) == yb).sum().asnumpy())
            total += xb.shape[0]
        print("epoch %d: loss %.4f acc %.3f"
              % (epoch, loss_sum / total, correct / total))


if __name__ == "__main__":
    # use an sgd updater on the kvstore (server-side update pattern)
    main()
