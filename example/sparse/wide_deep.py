#!/usr/bin/env python
"""Wide & Deep learning with sparse features (parity: example/sparse/
wide_deep/train.py — BASELINE.json config #5, the reference's flagship
sparse workload).

Architecture (Cheng et al. 2016, as in the reference):
  * **wide** — linear model over high-dimensional sparse (CSR) features,
    weight stored/updated row-sparse via kvstore ``row_sparse_pull`` of
    only the rows each batch touches (``kvstore_dist.h`` embedding-style
    pull path);
  * **deep** — SparseEmbedding lookups (``_contrib_SparseEmbedding``) on
    categorical columns feeding an MLP; embedding gradients are pushed
    row-sparse.

TPU-native notes: compute (gather, matmuls, sigmoid-CE) is dense XLA —
sparsity lives in the *communication/update* path (which rows are pulled
and pushed), matching the reference's design where SparseEmbedding's
FComputeEx only sparsifies the gradient.  Data is synthetic criteo-like
(zero egress); swap ``synthetic_batches`` with a ``LibSVMIter`` over a real
dataset for production use.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, nd  # noqa: E402


def synthetic_batches(num_samples, wide_dim, nnz, num_cats, vocab, rng):
    """Criteo-like synthetic data: sparse wide features + categorical ids,
    label from a hidden bilinear rule so the model is learnable."""
    true_w = rng.randn(wide_dim).astype(np.float32) * 2.0
    true_e = rng.randn(num_cats, vocab).astype(np.float32)
    wide_rows = []
    cats = rng.randint(0, vocab, size=(num_samples, num_cats))
    logits = np.zeros(num_samples, np.float32)
    for i in range(num_samples):
        cols = rng.choice(wide_dim, nnz, replace=False)
        vals = rng.rand(nnz).astype(np.float32)
        row = np.zeros(wide_dim, np.float32)
        row[cols] = vals
        wide_rows.append(row)
        logits[i] = row @ true_w + true_e[np.arange(num_cats),
                                          cats[i]].sum()
    X = np.stack(wide_rows)
    y = (logits > np.median(logits)).astype(np.float32)
    return X, cats.astype(np.float32), y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-samples", type=int, default=512)
    ap.add_argument("--wide-dim", type=int, default=2000)
    ap.add_argument("--nnz", type=int, default=15)
    ap.add_argument("--num-cats", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--embed-dim", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    args = ap.parse_args()
    train(args)


def train(args):
    rng = np.random.RandomState(0)
    X, cats, y = synthetic_batches(args.num_samples, args.wide_dim,
                                   args.nnz, args.num_cats, args.vocab,
                                   rng)
    kv = mx.kv.create(args.kv_store)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=args.lr))

    # sparse params live on the kvstore; workers pull touched rows only
    kv.init("wide_w", nd.zeros((args.wide_dim, 1)))
    kv.init("embed", nd.array(
        rng.uniform(-0.05, 0.05,
                    (args.num_cats * args.vocab, args.embed_dim))
        .astype(np.float32)))

    # dense MLP params update locally
    def dense_param(shape):
        p = nd.array(rng.uniform(-0.1, 0.1, shape).astype(np.float32))
        p.attach_grad()
        return p

    in_dim = args.num_cats * args.embed_dim
    w1, b1 = dense_param((args.hidden, in_dim)), dense_param((args.hidden,))
    w2, b2 = dense_param((1, args.hidden)), dense_param((1,))
    bias = dense_param((1,))

    # flatten categorical ids into one embedding table:
    # id of (col c, value v) = c * vocab + v
    offsets = (np.arange(args.num_cats) * args.vocab)[None, :]
    flat_cats = cats + offsets

    n = args.num_samples
    final_acc = 0.0
    for epoch in range(args.epochs):
        order = rng.permutation(n)
        loss_sum, correct = 0.0, 0
        for start in range(0, n, args.batch_size):
            sel = order[start:start + args.batch_size]
            xb = nd.array(X[sel])
            cb = nd.array(flat_cats[sel])
            yb = nd.array(y[sel])

            # ---- sparse pulls: only the rows this batch touches -------
            wide_touch = nd.array(
                np.unique(np.nonzero(X[sel])[1]).astype(np.float32))
            embed_touch = nd.array(
                np.unique(flat_cats[sel]).astype(np.float32))
            wide_w = nd.zeros((args.wide_dim, 1)).tostype("row_sparse")
            kv.row_sparse_pull("wide_w", out=wide_w, row_ids=wide_touch)
            embed_w = nd.zeros((args.num_cats * args.vocab,
                                args.embed_dim)).tostype("row_sparse")
            kv.row_sparse_pull("embed", out=embed_w, row_ids=embed_touch)

            wide_dense = wide_w.tostype("default")
            embed_dense = embed_w.tostype("default")
            wide_dense.attach_grad()
            embed_dense.attach_grad()

            with autograd.record():
                emb = nd._contrib_SparseEmbedding(
                    cb, embed_dense,
                    input_dim=args.num_cats * args.vocab,
                    output_dim=args.embed_dim)
                deep_in = emb.reshape((emb.shape[0], -1))
                h = nd.relu(nd.dot(deep_in, w1.T) +
                            b1.reshape((1, -1)))
                deep_out = nd.dot(h, w2.T) + b2.reshape((1, -1))
                wide_out = nd.dot(xb, wide_dense)
                logits = (wide_out + deep_out).reshape((-1,)) + bias
                # numerically stable sigmoid cross-entropy
                loss = (nd.relu(logits) - logits * yb +
                        nd.log(1.0 + nd.exp(-nd.abs(logits)))).sum()
            loss.backward()

            # ---- row-sparse pushes; server applies the optimizer ------
            kv.push("wide_w", nd.sparse_retain(
                wide_dense.grad, wide_touch).tostype("row_sparse"))
            kv.push("embed", nd.sparse_retain(
                embed_dense.grad, embed_touch).tostype("row_sparse"))
            for p in (w1, b1, w2, b2, bias):
                p -= args.lr * p.grad / xb.shape[0]
                p.grad[:] = 0

            loss_sum += float(loss.asnumpy())
            pred = (logits.asnumpy() > 0)
            correct += int((pred == (y[sel] > 0.5)).sum())
        final_acc = correct / n
        print("epoch %d  loss %.4f  acc %.3f"
              % (epoch, loss_sum / n, final_acc))
    return final_acc


if __name__ == "__main__":
    main()
