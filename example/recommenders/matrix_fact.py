#!/usr/bin/env python
"""Matrix-factorization recommender: embeddings + dot-product ratings.

Reference analog: ``example/recommenders/demo1-MF.ipynb`` /
``matrix_fact.py`` — learn user and item embeddings whose dot product
predicts ratings (the classic MovieLens recipe).  TPU shape: the whole
batch of embedding lookups and dot products is one fused XLA program;
sparse gradients flow through the Embedding op's gather transpose.

Synthetic data: a random low-rank ratings matrix plus noise, so the
demo is self-contained; point ``--data`` style loaders at MovieLens
for real use.

Run:  python example/recommenders/matrix_fact.py --num-epochs 10
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="Matrix factorization on a synthetic low-rank matrix",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=10)
parser.add_argument("--batch-size", type=int, default=256)
parser.add_argument("--factors", type=int, default=8)
parser.add_argument("--users", type=int, default=200)
parser.add_argument("--items", type=int, default=120)
parser.add_argument("--rank", type=int, default=4,
                    help="true rank of the synthetic ratings matrix")
parser.add_argument("--lr", type=float, default=0.05)
parser.add_argument("--ratings", type=int, default=8000)


class MFBlock(gluon.block.HybridBlock):
    def __init__(self, n_users, n_items, k, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.user = nn.Embedding(n_users, k)
            self.item = nn.Embedding(n_items, k)

    def hybrid_forward(self, F, users, items):
        return F.sum(self.user(users) * self.item(items), axis=-1)


def make_ratings(n_users, n_items, rank, n, seed=0):
    rng = np.random.RandomState(seed)
    U = rng.randn(n_users, rank).astype(np.float32) / np.sqrt(rank)
    V = rng.randn(n_items, rank).astype(np.float32) / np.sqrt(rank)
    R = U @ V.T
    u = rng.randint(0, n_users, n)
    i = rng.randint(0, n_items, n)
    r = R[u, i] + rng.randn(n).astype(np.float32) * 0.05
    return (u.astype(np.float32), i.astype(np.float32),
            r.astype(np.float32))


def main(args):
    mx.random.seed(0)      # deterministic init for the smoke tests
    if args.ratings < args.batch_size or args.num_epochs < 1:
        parser.error("need --ratings >= --batch-size and >= 1 epoch")
    u, i, r = make_ratings(args.users, args.items, args.rank,
                           args.ratings)
    net = MFBlock(args.users, args.items, args.factors)
    net.initialize(init=mx.init.Normal(0.1))
    l2 = gluon.loss.L2Loss()
    net(mx.nd.array(u[:4]), mx.nd.array(i[:4])).wait_to_read()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})

    rmse = None
    for epoch in range(args.num_epochs):
        tot, nb = 0.0, 0
        for s in range(0, args.ratings - args.batch_size + 1,
                       args.batch_size):
            ub = mx.nd.array(u[s:s + args.batch_size])
            ib = mx.nd.array(i[s:s + args.batch_size])
            rb = mx.nd.array(r[s:s + args.batch_size])
            with autograd.record():
                L = l2(net(ub, ib), rb).mean()
            L.backward()
            tr.step(1)
            tot += float(L.asnumpy())
            nb += 1
        rmse = float(np.sqrt(2 * tot / nb))      # L2Loss = 1/2 (p-r)^2
        if epoch % 2 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d  train RMSE %.4f" % (epoch, rmse))
    return rmse


if __name__ == "__main__":
    main(parser.parse_args())
