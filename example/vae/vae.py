#!/usr/bin/env python
"""Variational autoencoder with the reparameterization trick.

Reference analog: ``example/vae/VAE.py`` / ``mxnet_adversarial_vae`` —
encoder emits (mu, logvar), a sampled latent feeds the decoder, and the
loss is reconstruction + KL.  The TPU-relevant pattern demonstrated:
random sampling *inside* the recorded graph (``mx.nd.random.normal``
under ``autograd.record`` — the functional threefry key threading makes
this reproducible), with gradients flowing through the reparameterized
sample.

Run:  python example/vae/vae.py --num-epochs 25
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="dense VAE on synthetic low-rank data",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=25)
parser.add_argument("--samples", type=int, default=1024)
parser.add_argument("--dim", type=int, default=32)
parser.add_argument("--latent", type=int, default=4)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--kl-weight", type=float, default=0.1)


class VAE(gluon.HybridBlock):
    def __init__(self, dim, latent, **kw):
        super().__init__(**kw)
        self.latent = latent
        self.enc = nn.HybridSequential()
        self.enc.add(nn.Dense(48, activation="relu"),
                     nn.Dense(2 * latent))      # mu ++ logvar
        self.dec = nn.HybridSequential()
        self.dec.add(nn.Dense(48, activation="relu"),
                     nn.Dense(dim))

    def encode(self, x):
        h = self.enc(x)
        return h[:, :self.latent], h[:, self.latent:]

    def hybrid_forward(self, F, x):
        mu, logvar = self.encode(x)
        eps = mx.nd.random.normal(shape=mu.shape)
        z = mu + eps * (0.5 * logvar).exp()     # reparameterization
        return self.dec(z), mu, logvar


def elbo_loss(rec, x, mu, logvar, kl_weight):
    rec_loss = ((rec - x) ** 2).sum(axis=1)
    kl = -0.5 * (1 + logvar - mu ** 2 - logvar.exp()).sum(axis=1)
    return (rec_loss + kl_weight * kl).mean()


def make_data(n, dim, seed=0):
    rng = np.random.RandomState(seed)
    basis = rng.randn(3, dim).astype(np.float32)
    return np.tanh(rng.randn(n, 3).astype(np.float32) @ basis)


def main(args):
    x = make_data(args.samples, args.dim)
    net = VAE(args.dim, args.latent)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    n = x.shape[0]
    # untrained -ELBO on the full set: the baseline the training beats
    data_all = mx.nd.array(x)
    rec, mu, logvar = net(data_all)
    init_elbo = float(elbo_loss(rec, data_all, mu, logvar,
                                args.kl_weight).asnumpy())
    first = last = None
    for epoch in range(args.num_epochs):
        idx = np.random.RandomState(epoch).permutation(n)
        total, nb = 0.0, 0
        for i in range(0, n - args.batch_size + 1, args.batch_size):
            data = mx.nd.array(x[idx[i:i + args.batch_size]])
            with autograd.record():
                rec, mu, logvar = net(data)
                L = elbo_loss(rec, data, mu, logvar, args.kl_weight)
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.asnumpy())
            nb += 1
        avg = total / nb
        if first is None:
            first = avg
        last = avg
        if epoch % 5 == 0:
            print("epoch %d -ELBO %.4f" % (epoch, avg))
    # draw fresh samples from the prior through the decoder
    z = mx.nd.random.normal(shape=(8, args.latent))
    samples = net.dec(z).asnumpy()
    print("-ELBO untrained %.4f -> %.4f; sample std %.3f"
          % (init_elbo, last, samples.std()))
    return init_elbo, last


if __name__ == "__main__":
    main(parser.parse_args())
