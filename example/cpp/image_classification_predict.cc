// C++ inference demo over the C predict ABI (reference parity:
// example/image-classification/predict-cpp/image-classification-predict.cc
// and the header-only cpp-package frontend, both of which consume
// include/mxnet/c_predict_api.h).
//
// Usage:
//   make            # builds ../../src predict library + this binary
//   ./image_classification_predict model-symbol.json model.params.npz
//       1 3 224 224 < image.f32   (raw float32 NCHW pixels on stdin)
//
// Prints the top-5 (class index, probability) pairs.  Any checkpoint saved
// by mxnet_tpu.model.save_checkpoint / Symbol.save + nd.save works.
#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <numeric>
#include <sstream>
#include <string>
#include <vector>

typedef uint32_t mx_uint;
typedef void *PredictorHandle;

extern "C" {
const char *MXGetLastError();
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, mx_uint size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);
int MXPredGetOutput(PredictorHandle handle, mx_uint index, float *data,
                    mx_uint size);
int MXPredFree(PredictorHandle handle);
}

namespace {

std::string ReadFile(const std::string &path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    std::fprintf(stderr, "cannot open %s\n", path.c_str());
    std::exit(2);
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

void Check(int rc, const char *what) {
  if (rc != 0) {
    std::fprintf(stderr, "%s failed: %s\n", what, MXGetLastError());
    std::exit(1);
  }
}

}  // namespace

int main(int argc, char **argv) {
  if (argc != 7) {
    std::fprintf(stderr,
                 "usage: %s symbol.json params N C H W < input.f32\n",
                 argv[0]);
    return 2;
  }
  const std::string symbol_json = ReadFile(argv[1]);
  const std::string params = ReadFile(argv[2]);
  mx_uint shape[4];
  for (int i = 0; i < 4; ++i) {
    shape[i] = static_cast<mx_uint>(std::atoi(argv[3 + i]));
  }
  const mx_uint indptr[2] = {0, 4};
  const char *keys[1] = {"data"};

  PredictorHandle pred = nullptr;
  Check(MXPredCreate(symbol_json.c_str(), params.data(),
                     static_cast<int>(params.size()), /*dev_type=*/1,
                     /*dev_id=*/0, 1, keys, indptr, shape, &pred),
        "MXPredCreate");

  const mx_uint n = shape[0] * shape[1] * shape[2] * shape[3];
  std::vector<float> input(n);
  if (std::fread(input.data(), sizeof(float), n, stdin) != n) {
    std::fprintf(stderr, "expected %u float32 values on stdin\n", n);
    return 2;
  }
  Check(MXPredSetInput(pred, "data", input.data(), n), "MXPredSetInput");
  Check(MXPredForward(pred), "MXPredForward");

  mx_uint *oshape = nullptr, ondim = 0;
  Check(MXPredGetOutputShape(pred, 0, &oshape, &ondim),
        "MXPredGetOutputShape");
  mx_uint osize = 1;
  for (mx_uint i = 0; i < ondim; ++i) osize *= oshape[i];
  std::vector<float> probs(osize);
  Check(MXPredGetOutput(pred, 0, probs.data(), osize), "MXPredGetOutput");

  const mx_uint classes = ondim >= 2 ? oshape[ondim - 1] : osize;
  std::vector<int> order(classes);
  std::iota(order.begin(), order.end(), 0);
  std::partial_sort(order.begin(),
                    order.begin() + std::min<mx_uint>(5, classes),
                    order.end(), [&](int a, int b) {
                      return probs[a] > probs[b];
                    });
  for (mx_uint i = 0; i < std::min<mx_uint>(5, classes); ++i) {
    std::printf("class %d  p=%.4f\n", order[i], probs[order[i]]);
  }
  Check(MXPredFree(pred), "MXPredFree");
  return 0;
}
