"""Mean-average-precision metrics for SSD eval (ref
example/ssd/evaluate/eval_metric.py: MApMetric / VOC07MApMetric).

update() consumes (labels, preds) where preds[0] is the MultiBoxDetection
output (batch, num_det, 6) rows ``[cls_id, score, x1, y1, x2, y2]`` (cls_id
-1 = suppressed) and labels[0] is the padded ground truth (batch, num_obj,
5+) rows ``[cls_id, x1, y1, x2, y2, (difficult)]`` padded with -1.
"""
import numpy as np

from mxnet_tpu import metric as metric_mod


def _iou(box, boxes):
    ix1 = np.maximum(box[0], boxes[:, 0])
    iy1 = np.maximum(box[1], boxes[:, 1])
    ix2 = np.minimum(box[2], boxes[:, 2])
    iy2 = np.minimum(box[3], boxes[:, 3])
    iw = np.maximum(0, ix2 - ix1)
    ih = np.maximum(0, iy2 - iy1)
    inter = iw * ih
    area = (box[2] - box[0]) * (box[3] - box[1])
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    union = area + areas - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


class MApMetric(metric_mod.EvalMetric):
    """VOC mean average precision (all-points interpolation)."""

    def __init__(self, ovp_thresh=0.5, use_difficult=False, class_names=None,
                 pred_idx=0):
        self.ovp_thresh = ovp_thresh
        self.use_difficult = use_difficult
        self.class_names = class_names
        self.pred_idx = pred_idx
        if class_names is None:
            name = "mAP"
        else:
            name = [c + "_AP" for c in class_names] + ["mAP"]
        super().__init__(name)
        self.reset()

    def reset(self):
        # per-class: list of (score, tp) records + gt count
        self._records = {}
        self._gt_counts = {}
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        labels = labels[0].asnumpy() if hasattr(labels[0], "asnumpy") \
            else np.asarray(labels[0])
        dets = preds[self.pred_idx]
        dets = dets.asnumpy() if hasattr(dets, "asnumpy") \
            else np.asarray(dets)
        for b in range(labels.shape[0]):
            gt = labels[b]
            gt = gt[gt[:, 0] >= 0]
            difficult = gt[:, 5].astype(bool) if (
                gt.shape[1] > 5 and not self.use_difficult) \
                else np.zeros(len(gt), bool)
            det = dets[b]
            det = det[det[:, 0] >= 0]
            for cid in np.unique(np.concatenate(
                    [gt[:, 0], det[:, 0]])).astype(int):
                cls_gt = gt[gt[:, 0] == cid]
                cls_dif = difficult[gt[:, 0] == cid]
                self._gt_counts[cid] = self._gt_counts.get(cid, 0) + \
                    int((~cls_dif).sum())
                cls_det = det[det[:, 0] == cid]
                order = np.argsort(-cls_det[:, 1])
                matched = np.zeros(len(cls_gt), bool)
                recs = self._records.setdefault(cid, [])
                for d in cls_det[order]:
                    if len(cls_gt) == 0:
                        recs.append((d[1], 0))
                        continue
                    ious = _iou(d[2:6], cls_gt[:, 1:5])
                    j = int(np.argmax(ious))
                    if ious[j] >= self.ovp_thresh and not matched[j]:
                        matched[j] = True
                        if not cls_dif[j]:
                            recs.append((d[1], 1))
                        # difficult matches are ignored entirely
                    else:
                        recs.append((d[1], 0))

    def _average_precision(self, rec, prec):
        """All-points AP (ref eval_metric.py:66)."""
        mrec = np.concatenate(([0.0], rec, [1.0]))
        mpre = np.concatenate(([0.0], prec, [0.0]))
        for i in range(mpre.size - 1, 0, -1):
            mpre[i - 1] = max(mpre[i - 1], mpre[i])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1])

    def _class_ap(self, cid):
        recs = self._records.get(cid, [])
        n_gt = self._gt_counts.get(cid, 0)
        if n_gt == 0:
            return None
        if not recs:
            return 0.0
        arr = np.array(sorted(recs, key=lambda r: -r[0]))
        tp = np.cumsum(arr[:, 1])
        fp = np.cumsum(1 - arr[:, 1])
        rec = tp / n_gt
        prec = tp / np.maximum(tp + fp, 1e-12)
        return self._average_precision(rec, prec)

    def get(self):
        cids = sorted(self._gt_counts)
        aps = {c: self._class_ap(c) for c in cids}
        valid = [v for v in aps.values() if v is not None]
        mean_ap = float(np.mean(valid)) if valid else 0.0
        if self.class_names is None:
            return ("mAP", mean_ap)
        names, values = [], []
        for i, cname in enumerate(self.class_names):
            names.append(cname + "_AP")
            values.append(aps.get(i) if aps.get(i) is not None else 0.0)
        names.append("mAP")
        values.append(mean_ap)
        return (names, values)


class VOC07MApMetric(MApMetric):
    """11-point interpolated AP (ref eval_metric.py:VOC07MApMetric)."""

    def _average_precision(self, rec, prec):
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            mask = rec >= t
            p = np.max(prec[mask]) if mask.any() else 0.0
            ap += p / 11.0
        return ap
