"""Synthetic detection dataset -> packed RecordIO (zero-egress stand-in
for VOC: colored shapes on textured backgrounds, 3 classes).

Produces the same artifact a user would build with tools/im2rec.py from a
.lst of real images + det labels (wire format of
src/io/iter_image_det_recordio.cc): each record is a JPEG plus the label
``[header_width, obj_width, objs...]`` with normalized corners.
"""
import os

import numpy as np

from mxnet_tpu import recordio

CLASS_NAMES = ["circle", "square", "triangle"]


def _draw_sample(rng, size):
    import cv2

    img = rng.randint(0, 80, (size, size, 3), np.uint8) + \
        rng.randint(0, 40, (size, size, 1), np.uint8)
    n_obj = rng.randint(1, 4)
    boxes = []
    for _ in range(n_obj):
        cls = rng.randint(0, 3)
        s = rng.randint(size // 6, size // 3)          # half-extent
        cx = rng.randint(s + 1, size - s - 1)
        cy = rng.randint(s + 1, size - s - 1)
        color = tuple(int(c) for c in rng.randint(140, 255, 3))
        if cls == 0:
            cv2.circle(img, (cx, cy), s, color, -1)
        elif cls == 1:
            cv2.rectangle(img, (cx - s, cy - s), (cx + s, cy + s), color, -1)
        else:
            pts = np.array([[cx, cy - s], [cx - s, cy + s], [cx + s, cy + s]])
            cv2.fillPoly(img, [pts], color)
        boxes.append([cls, (cx - s) / size, (cy - s) / size,
                      (cx + s) / size, (cy + s) / size])
    return img, boxes


def build_rec(path_prefix, num_images=200, size=128, seed=0):
    """Write {prefix}.rec/.idx; returns (rec_path, idx_path)."""
    rec_path, idx_path = path_prefix + ".rec", path_prefix + ".idx"
    if os.path.exists(rec_path) and os.path.exists(idx_path):
        return rec_path, idx_path
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(num_images):
        img, boxes = _draw_sample(rng, size)
        label = [2.0, 5.0]
        for b in boxes:
            label.extend(b)
        header = recordio.IRHeader(0, np.array(label, np.float32), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return rec_path, idx_path
