#!/usr/bin/env python
"""SSD object-detection training (parity: example/ssd/train.py —
BASELINE.json config #4, compact form).

A small VGG-style backbone with two multibox heads, trained on synthetic
boxes: MultiBoxPrior anchors -> MultiBoxTarget assignment -> joint
cls (SoftmaxOutput-style) + loc (smooth-L1) loss; inference decodes with
MultiBoxDetection + box_nms.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


class ToySSD(gluon.Block):
    """Backbone + per-scale class/box predictors."""

    def __init__(self, num_classes=2, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.sizes = [(0.2, 0.35), (0.4, 0.6)]
        self.ratios = [(1.0, 2.0, 0.5)] * 2
        self.anchors_per = len(self.sizes[0]) - 1 + len(self.ratios[0])
        with self.name_scope():
            self.body = nn.Sequential()
            for f in (16, 32):
                self.body.add(nn.Conv2D(f, 3, padding=1, activation="relu"))
                self.body.add(nn.MaxPool2D(2))
            self.down = nn.Sequential()
            self.down.add(nn.Conv2D(32, 3, padding=1, activation="relu"))
            self.down.add(nn.MaxPool2D(2))
            self.cls_preds = nn.Sequential()
            self.box_preds = nn.Sequential()
            for _ in range(2):
                self.cls_preds.add(nn.Conv2D(
                    self.anchors_per * (num_classes + 1), 3, padding=1))
                self.box_preds.add(nn.Conv2D(self.anchors_per * 4, 3,
                                             padding=1))

    def forward(self, x):
        feats = [self.body(x)]
        feats.append(self.down(feats[0]))
        anchors, cls_preds, box_preds = [], [], []
        for i, f in enumerate(feats):
            anchors.append(nd.contrib.MultiBoxPrior(
                f, sizes=self.sizes[i], ratios=self.ratios[i]))
            c = self.cls_preds[i](f)
            cls_preds.append(
                c.transpose((0, 2, 3, 1)).reshape((c.shape[0], -1)))
            b = self.box_preds[i](f)
            box_preds.append(
                b.transpose((0, 2, 3, 1)).reshape((b.shape[0], -1)))
        anchors = nd.concat(*anchors, dim=1)
        cls_preds = nd.concat(*cls_preds, dim=1).reshape(
            (x.shape[0], -1, self.num_classes + 1))
        box_preds = nd.concat(*box_preds, dim=1)
        return anchors, cls_preds, box_preds


def synthetic_batch(batch_size, rng):
    """Images with one bright square; label = its box, class 0."""
    imgs = rng.rand(batch_size, 3, 64, 64).astype(np.float32) * 0.2
    labels = np.full((batch_size, 1, 5), -1.0, np.float32)
    for i in range(batch_size):
        s = rng.randint(12, 28)
        x0 = rng.randint(0, 64 - s)
        y0 = rng.randint(0, 64 - s)
        imgs[i, :, y0:y0 + s, x0:x0 + s] = 1.0
        labels[i, 0] = [0, x0 / 64, y0 / 64, (x0 + s) / 64, (y0 + s) / 64]
    return nd.array(imgs), nd.array(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-batches", type=int, default=60)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    net = ToySSD()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss()

    tic = time.time()
    for it in range(args.num_batches):
        x, y = synthetic_batch(args.batch_size, rng)
        with autograd.record():
            anchors, cls_preds, box_preds = net(x)
            box_t, box_m, cls_t = nd.contrib.MultiBoxTarget(
                anchors, y, cls_preds.transpose((0, 2, 1)),
                negative_mining_ratio=3.0)
            l_cls = cls_loss(cls_preds, cls_t)
            l_box = box_loss(box_preds * box_m, box_t * box_m)
            loss = l_cls + l_box
        loss.backward()
        trainer.step(args.batch_size)
        if it % 10 == 0:
            print("batch %3d: cls %.4f box %.4f (%.1f img/s)"
                  % (it, float(l_cls.mean().asnumpy()),
                     float(l_box.mean().asnumpy()),
                     args.batch_size * 10 / max(time.time() - tic, 1e-9)))
            tic = time.time()

    # inference: decode + NMS
    x, y = synthetic_batch(2, rng)
    anchors, cls_preds, box_preds = net(x)
    probs = nd.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
    det = nd.contrib.MultiBoxDetection(probs, box_preds, anchors,
                                       nms_threshold=0.45)
    kept = det.asnumpy()[0]
    kept = kept[kept[:, 0] >= 0][:3]
    print("top detections (id, score, box):")
    for row in kept:
        print("  ", np.round(row, 3))


if __name__ == "__main__":
    main()
