#!/usr/bin/env python
"""VGG16-SSD training end to end from packed RecordIO detection data
(ref example/ssd/train.py + train/train_net.py).

Pipeline: .rec (det wire format) -> mx.io.ImageDetRecordIter (IoU-crop /
pad / flip augmentation, padded labels) -> SSD train symbol (MultiBoxTarget
assignment, softmax + smooth-L1 losses) -> Module.fit -> VOC07 mAP eval.

With no arguments it trains on a generated synthetic shapes dataset
(dataset.py; zero-egress stand-in for VOC — point --train-rec/--val-rec at
real im2rec output to train on actual data).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx

from dataset import build_rec, CLASS_NAMES
from eval_metric import VOC07MApMetric
from symbol.symbol_factory import get_symbol_train


class MultiBoxMetric(mx.metric.EvalMetric):
    """Training-loss monitor: CE over matched anchors + smooth-L1
    (ref example/ssd/train/metric.py:22)."""

    def __init__(self, eps=1e-8):
        self.eps = eps
        super().__init__(["CrossEntropy", "SmoothL1"])
        self.reset()

    def reset(self):
        self.num_inst = [0, 0]
        self.sum_metric = [0.0, 0.0]

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()
        loc_loss = preds[1].asnumpy()
        cls_label = preds[2].asnumpy()
        valid = np.sum(cls_label >= 0)
        flat = cls_label.flatten()
        mask = np.where(flat >= 0)[0]
        idx = np.int64(flat[mask])
        prob = cls_prob.transpose(0, 2, 1).reshape(-1, cls_prob.shape[1])
        self.sum_metric[0] += (-np.log(prob[mask, idx] + self.eps)).sum()
        self.num_inst[0] += valid
        self.sum_metric[1] += np.sum(loc_loss)
        self.num_inst[1] += valid

    def get(self):
        return (self.name, [s / n if n else float("nan")
                            for s, n in zip(self.sum_metric, self.num_inst)])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="vgg16_reduced")
    ap.add_argument("--data-shape", type=int, default=64,
                    help="input size (64 = small preset; 300 = full SSD300)")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.004)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--wd", type=float, default=5e-4)
    ap.add_argument("--train-rec", default="")
    ap.add_argument("--val-rec", default="")
    ap.add_argument("--num-images", type=int, default=160,
                    help="synthetic dataset size when no --train-rec given")
    ap.add_argument("--prefix", default="/tmp/ssd_model")
    args = ap.parse_args()

    if args.train_rec:
        train_rec, val_rec = args.train_rec, args.val_rec or args.train_rec
        train_idx = val_idx = None
        num_classes = 20                       # VOC default
        class_names = None
    else:
        root = os.path.join("/tmp", "ssd_shapes")
        os.makedirs(root, exist_ok=True)
        train_rec, train_idx = build_rec(os.path.join(root, "train"),
                                         num_images=args.num_images, seed=0)
        val_rec, val_idx = build_rec(os.path.join(root, "val"),
                                     num_images=max(32, args.num_images // 4),
                                     seed=1)
        num_classes = len(CLASS_NAMES)
        class_names = CLASS_NAMES

    shape = (3, args.data_shape, args.data_shape)
    train_iter = mx.io.ImageDetRecordIter(
        train_rec, shape, args.batch_size, path_imgidx=train_idx,
        shuffle=True, label_pad_width=24, mean_r=123.68, mean_g=116.78,
        mean_b=103.94, rand_crop=0.5, rand_pad=0.5, rand_mirror=True)
    val_iter = mx.io.ImageDetRecordIter(
        val_rec, shape, args.batch_size, path_imgidx=val_idx,
        label_pad_width=24, mean_r=123.68, mean_g=116.78, mean_b=103.94)

    net = get_symbol_train(args.network, args.data_shape, num_classes)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",))

    mod.fit(train_iter,
            eval_data=val_iter,
            eval_metric=MultiBoxMetric(),
            validation_metric=VOC07MApMetric(ovp_thresh=0.5,
                                             class_names=class_names,
                                             pred_idx=3),
            num_epoch=args.epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": args.momentum, "wd": args.wd},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    metric = VOC07MApMetric(ovp_thresh=0.5, class_names=class_names,
                            pred_idx=3)
    for name, value in mod.score(val_iter, metric):
        print("%s=%f" % (name, value))
    mod.save_checkpoint(args.prefix, args.epochs)
    print("saved %s-%04d.params" % (args.prefix, args.epochs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
