"""SSD network factory (ref example/ssd/symbol/symbol_factory.py +
symbol_builder.py): presets per backbone/input-size, train and deploy
symbol builders wired to the MultiBox op trio.
"""
import importlib

from mxnet_tpu import symbol as sym

from .common import multi_layer_feature, multibox_layer

_CONFIGS = {
    ("vgg16_reduced", 300): dict(
        from_layers=["relu4_3", "relu7", "", "", "", ""],
        num_filters=[512, -1, 512, 256, 256, 256],
        strides=[-1, -1, 2, 2, 1, 1],
        pads=[-1, -1, 1, 1, 0, 0],
        sizes=[[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
               [0.71, 0.79], [0.88, 0.961]],
        ratios=[[1, 2, 0.5]] * 2 + [[1, 2, 0.5, 3, 1.0 / 3]] * 2 +
               [[1, 2, 0.5]] * 2,
        normalizations=[20, -1, -1, -1, -1, -1],
        num_channels=[512],
        steps=[x / 300.0 for x in (8, 16, 32, 64, 100, 300)],
    ),
    # small config for tests/smoke runs (64px, 3 scales)
    ("vgg16_reduced", 64): dict(
        from_layers=["relu4_3", "relu7", ""],
        num_filters=[512, -1, 256],
        strides=[-1, -1, 2],
        pads=[-1, -1, 1],
        sizes=[[0.2, 0.272], [0.45, 0.55], [0.8, 0.9]],
        ratios=[[1, 2, 0.5]] * 3,
        normalizations=[20, -1, -1],
        num_channels=[512],
        steps=[],
    ),
}


def get_config(network, data_shape, **kwargs):
    key = (network, int(data_shape))
    if key not in _CONFIGS:
        raise NotImplementedError(
            "no SSD preset for %s-%d (have: %s)" %
            (network, data_shape, sorted(_CONFIGS)))
    cfg = dict(_CONFIGS[key])
    cfg.update(network=network, data_shape=data_shape)
    cfg.update(kwargs)
    return cfg


def _features(network, num_classes, cfg):
    mod = importlib.import_module("symbol." + network) \
        if __package__ in (None, "") else \
        importlib.import_module("." + network, package=__package__)
    body = mod.get_symbol(num_classes)
    return multi_layer_feature(body, cfg["from_layers"], cfg["num_filters"],
                               cfg["strides"], cfg["pads"])


def get_symbol_train(network, data_shape, num_classes, nms_thresh=0.5,
                     force_suppress=False, nms_topk=400, **kwargs):
    """Training symbol: multibox target assignment + losses + monitoring
    detection branch (ref symbol_builder.py:29)."""
    cfg = get_config(network, data_shape, **kwargs)
    label = sym.var("label")
    layers = _features(network, num_classes, cfg)
    loc_preds, cls_preds, anchors = multibox_layer(
        layers, num_classes, sizes=cfg["sizes"], ratios=cfg["ratios"],
        normalization=cfg["normalizations"],
        num_channels=cfg["num_channels"], clip=False, steps=cfg["steps"])

    tmp = sym.contrib.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5, ignore_label=-1,
        negative_mining_ratio=3, minimum_negative_samples=0,
        negative_mining_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2),
        name="multibox_target")
    loc_target, loc_target_mask, cls_target = tmp[0], tmp[1], tmp[2]

    cls_prob = sym.SoftmaxOutput(cls_preds, cls_target, ignore_label=-1,
                                 use_ignore=True, grad_scale=1.0,
                                 multi_output=True, normalization="valid",
                                 name="cls_prob")
    loc_loss_ = sym.smooth_l1(loc_target_mask * (loc_preds - loc_target),
                              scalar=1.0, name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, grad_scale=1.0,
                            normalization="valid", name="loc_loss")
    cls_label = sym.MakeLoss(cls_target, grad_scale=0, name="cls_label")
    det = sym.contrib.MultiBoxDetection(
        cls_prob, loc_preds, anchors, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=nms_topk)
    det = sym.MakeLoss(det, grad_scale=0, name="det_out")
    return sym.Group([cls_prob, loc_loss, cls_label, det])


def get_symbol(network, data_shape, num_classes, nms_thresh=0.5,
               force_suppress=False, nms_topk=400, **kwargs):
    """Deploy symbol: detections only (ref symbol_builder.py:118)."""
    cfg = get_config(network, data_shape, **kwargs)
    layers = _features(network, num_classes, cfg)
    loc_preds, cls_preds, anchors = multibox_layer(
        layers, num_classes, sizes=cfg["sizes"], ratios=cfg["ratios"],
        normalization=cfg["normalizations"],
        num_channels=cfg["num_channels"], clip=False, steps=cfg["steps"])
    cls_prob = sym.softmax(cls_preds, axis=1, name="cls_prob")
    return sym.contrib.MultiBoxDetection(
        cls_prob, loc_preds, anchors, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=nms_topk)
