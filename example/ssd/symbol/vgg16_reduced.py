"""VGG16-reduced backbone for SSD (ref example/ssd/symbol/vgg16_reduced.py:
fc6/fc7 replaced by dilated conv6 / 1x1 conv7; pool5 is 3x3 stride-1).

Written config-driven rather than unrolled: the topology is the published
VGG16-SSD architecture; the code is original.
"""
from mxnet_tpu import symbol as sym

# (layers_in_group, channels); pool after each group
_GROUPS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]


def get_symbol(num_classes=1000, **kwargs):
    net = sym.var("data")
    for g, (n_layers, ch) in enumerate(_GROUPS, start=1):
        for i in range(1, n_layers + 1):
            net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                                  num_filter=ch, name="conv%d_%d" % (g, i))
            net = sym.Activation(net, act_type="relu",
                                 name="relu%d_%d" % (g, i))
        if g == 5:
            # pool5: 3x3 stride 1 keeps fc6's receptive field growable
            net = sym.Pooling(net, pool_type="max", kernel=(3, 3),
                              stride=(1, 1), pad=(1, 1), name="pool5")
        else:
            conv = {"pooling_convention": "full"} if g == 3 else {}
            net = sym.Pooling(net, pool_type="max", kernel=(2, 2),
                              stride=(2, 2), name="pool%d" % g, **conv)
    # fc6 as dilated 3x3 conv, fc7 as 1x1 conv
    net = sym.Convolution(net, kernel=(3, 3), pad=(6, 6), dilate=(6, 6),
                          num_filter=1024, name="fc6")
    net = sym.Activation(net, act_type="relu", name="relu6")
    net = sym.Convolution(net, kernel=(1, 1), num_filter=1024, name="fc7")
    net = sym.Activation(net, act_type="relu", name="relu7")
    return net
