"""SSD head builders (ref example/ssd/symbol/common.py:96-301):
multi-scale feature extraction + per-scale loc/cls/anchor heads.
"""
import numpy as np

from mxnet_tpu import init
from mxnet_tpu import symbol as sym


def conv_act_layer(from_layer, name, num_filter, kernel=(1, 1), pad=(0, 0),
                   stride=(1, 1), act_type="relu"):
    net = sym.Convolution(from_layer, kernel=kernel, pad=pad, stride=stride,
                          num_filter=num_filter, name="%s_conv" % name)
    return sym.Activation(net, act_type=act_type, name="%s_%s" % (name,
                                                                  act_type))


def multi_layer_feature(body, from_layers, num_filters, strides, pads,
                        min_filter=128):
    """Pick named feature maps from the backbone; append 1x1->3x3 extra
    stages for '' entries (ref common.py:96)."""
    assert from_layers and from_layers[0].strip()
    assert len(from_layers) == len(num_filters) == len(strides) == len(pads)
    internals = body.get_internals()
    layers = []
    for k, (name, nf, s, p) in enumerate(
            zip(from_layers, num_filters, strides, pads)):
        if name.strip():
            layers.append(internals[name.strip() + "_output"])
        else:
            assert layers and nf > 0
            num_1x1 = max(min_filter, nf // 2)
            c1 = conv_act_layer(layers[-1], "multi_feat_%d_conv_1x1" % k,
                                num_1x1)
            c3 = conv_act_layer(c1, "multi_feat_%d_conv_3x3" % k, nf,
                                kernel=(3, 3), pad=(p, p), stride=(s, s))
            layers.append(c3)
    return layers


def multibox_layer(from_layers, num_classes, sizes=(0.2, 0.95), ratios=(1,),
                   normalization=-1, num_channels=(), clip=False, steps=()):
    """Attach loc/cls prediction convs + anchor generators to each feature
    scale; concat into [loc_preds, cls_preds, anchor_boxes]
    (ref common.py:153)."""
    n = len(from_layers)
    assert n > 0 and num_classes > 0
    if not isinstance(ratios[0], (list, tuple)):
        ratios = [ratios] * n
    if len(sizes) == 2 and not isinstance(sizes[0], (list, tuple)):
        assert 0 < sizes[0] < 1 and sizes[0] < sizes[1] < 1
        start = sizes[0] / 2.0
        tmp = np.linspace(sizes[0], sizes[1], num=n - 1)
        sizes = list(zip([start] + tmp.tolist(),
                         tmp.tolist() + [tmp[-1] + start]))
    assert len(sizes) == n and len(ratios) == n
    if not isinstance(normalization, (list, tuple)):
        normalization = [normalization] * n
    num_channels = list(num_channels)
    num_cls = num_classes + 1            # background = class 0

    loc_layers, cls_layers, anchor_layers = [], [], []
    for k, layer in enumerate(from_layers):
        name = layer.name
        if normalization[k] > 0:
            layer = sym.L2Normalization(layer, mode="channel",
                                        name="%s_norm" % name)
            scale = sym.var("%s_scale" % name,
                            shape=(1, num_channels.pop(0), 1, 1),
                            init=init.Constant(normalization[k]),
                            attr={"__wd_mult__": "0.1"})
            layer = sym.broadcast_mul(scale, layer)
        size, ratio = sizes[k], ratios[k]
        num_anchors = len(size) - 1 + len(ratio)

        loc = sym.Convolution(layer, kernel=(3, 3), pad=(1, 1),
                              num_filter=num_anchors * 4,
                              name="%s_loc_pred_conv" % name)
        loc = sym.Flatten(sym.transpose(loc, axes=(0, 2, 3, 1)))
        loc_layers.append(loc)

        cls = sym.Convolution(layer, kernel=(3, 3), pad=(1, 1),
                              num_filter=num_anchors * num_cls,
                              name="%s_cls_pred_conv" % name)
        cls = sym.Flatten(sym.transpose(cls, axes=(0, 2, 3, 1)))
        cls_layers.append(cls)

        step = (steps[k], steps[k]) if steps else (-1.0, -1.0)
        anchors = sym.contrib.MultiBoxPrior(
            layer, sizes=tuple(size), ratios=tuple(ratio), clip=clip,
            steps=step, name="%s_anchors" % name)
        anchor_layers.append(sym.Flatten(anchors))

    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1)
    cls_preds = sym.Reshape(cls_preds, shape=(0, -1, num_cls))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1),
                              name="multibox_cls_pred")
    anchors = sym.Concat(*anchor_layers, dim=1)
    anchors = sym.Reshape(anchors, shape=(0, -1, 4), name="multibox_anchors")
    return [loc_preds, cls_preds, anchors]
