#!/usr/bin/env python
"""CapsNet: capsule layers with dynamic routing-by-agreement.

Reference analog: ``example/capsnet/capsulenet.py`` (Sabour et al. 2017)
— a genuinely different training loop: class scores are CAPSULE VECTOR
LENGTHS, routing coefficients are computed by an inner agreement
iteration (softmax over coupling logits, updated from u_hat . v), and
the loss is the margin loss, not cross-entropy.

TPU-native: the routing iterations are a fixed-trip-count Python loop
inside one hybridized forward — XLA unrolls and fuses them; everything
stays on the MXU as batched einsum-style matmuls (no data-dependent
control flow, exactly what jit wants).

Synthetic task: the 10-class lit-patch digits (same family as the other
toy vision demos) at 16x16; primary caps 8-D, digit caps 16-D, 3 routing
iterations.

Run:  python example/capsnet/capsnet.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="CapsNet with dynamic routing on synthetic digits",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=150)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.002)
parser.add_argument("--routing-iters", type=int, default=3)
parser.add_argument("--px", type=int, default=16)


def squash(s, axis=-1, eps=1e-7):
    """v = |s|^2/(1+|s|^2) * s/|s| — the capsule nonlinearity."""
    sq = nd.sum(s * s, axis=axis, keepdims=True)
    norm = nd.sqrt(sq + eps)
    return (sq / (1.0 + sq)) * (s / norm)


class CapsNet(gluon.Block):
    """conv -> primary caps (8-D) -> routed digit caps (16-D)."""

    def __init__(self, n_class=10, prim_dim=8, digit_dim=16, n_prim=32,
                 routing_iters=3, **kw):
        super().__init__(**kw)
        self.n_class = n_class
        self.prim_dim = prim_dim
        self.digit_dim = digit_dim
        self.routing_iters = routing_iters
        with self.name_scope():
            self.conv1 = nn.Conv2D(32, kernel_size=5, padding=2,
                                   activation="relu")
            # primary caps: one conv whose channels split into capsules
            self.prim = nn.Conv2D(n_prim * prim_dim // 4, kernel_size=5,
                                  strides=2, padding=2)
            # routing weight W: (1, n_in, n_class, digit_dim, prim_dim),
            # n_in fixed after first forward via deferred init
            # unit-scale init: Xavier over the 5-D fan collapses u_hat
            # (and the squash's quadratic small-signal response then kills
            # the gradient entirely — lengths pin at 0)
            self.W = self.params.get(
                "routing_weight", shape=(1, 0, n_class, digit_dim,
                                         prim_dim),
                init=mx.init.Normal(sigma=1.0),
                allow_deferred_init=True)

    def forward(self, x):
        b = x.shape[0]
        h = self.conv1(x)
        p = self.prim(h)                                  # (B, C, H, W)
        u = p.reshape((b, self.prim_dim, -1)).transpose((0, 2, 1))
        u = squash(u)                                     # (B, n_in, 8)
        n_in = u.shape[1]
        if self.W.shape[1] == 0:
            self.W.shape = (1, n_in, self.n_class, self.digit_dim,
                            self.prim_dim)
            self.W._finish_deferred_init()
        W = self.W.data()
        # u_hat[b,i,j,:] = W[i,j] @ u[b,i]: predictions from each
        # primary capsule for every digit capsule
        u_ = u.reshape((b, n_in, 1, self.prim_dim, 1))
        u_hat = nd.sum(W * u_.transpose((0, 1, 2, 4, 3)),
                       axis=4)                            # (B,n_in,10,16)

        # routing by agreement: logits b_ij start at 0; fixed iterations
        logits = nd.zeros((b, n_in, self.n_class, 1), ctx=x.context)
        u_hat_ng = u_hat.detach()   # agreement uses no-grad predictions
        v = None
        for it in range(self.routing_iters):
            c = nd.softmax(logits, axis=2)                # coupling
            uh = u_hat if it == self.routing_iters - 1 else u_hat_ng
            s = nd.sum(c * uh, axis=1)                    # (B,10,16)
            v = squash(s)
            if it < self.routing_iters - 1:
                agree = nd.sum(u_hat_ng * v.reshape(
                    (b, 1, self.n_class, self.digit_dim)),
                    axis=3, keepdims=True)
                logits = logits + agree
        return v                                          # (B,10,16)


def margin_loss(v, label, n_class, m_pos=0.9, m_neg=0.1, lam=0.5):
    """L = T max(0, m+ - |v|)^2 + lam (1-T) max(0, |v| - m-)^2."""
    lengths = nd.sqrt(nd.sum(v * v, axis=2) + 1e-7)       # (B,10)
    t = nd.one_hot(label, n_class)
    pos = nd.maximum(0.0, m_pos - lengths) ** 2
    neg = nd.maximum(0.0, lengths - m_neg) ** 2
    return nd.mean(nd.sum(t * pos + lam * (1 - t) * neg, axis=1))


def make_batch(rng, bs, px, n_class=10):
    xs = np.zeros((bs, 1, px, px), np.float32)
    ys = np.zeros((bs,), np.float32)
    for i in range(bs):
        c = int(rng.randint(n_class))
        ys[i] = c
        r0, c0 = (c // 5) * (px // 2), (c % 5) * 3
        xs[i, 0, r0:r0 + 4, c0:c0 + 4] = 1.0
    xs += rng.randn(bs, 1, px, px).astype(np.float32) * 0.15
    return nd.array(xs), nd.array(ys)


def main(args):
    rng = np.random.RandomState(0)
    net = CapsNet(routing_iters=args.routing_iters)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    accs = []
    for it in range(args.iters):
        x, y = make_batch(rng, args.batch_size, args.px)
        with autograd.record():
            v = net(x)
            loss = margin_loss(v, y, net.n_class)
        loss.backward()
        trainer.step(args.batch_size)
        if it >= args.iters - 20:
            lengths = nd.sqrt(nd.sum(v * v, axis=2))
            pred = lengths.asnumpy().argmax(1)
            accs.append(float((pred == y.asnumpy()).mean()))
    acc = float(np.mean(accs))
    print("capsnet routing accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    a = parser.parse_args()
    acc = main(a)
    raise SystemExit(0 if acc > 0.8 else 1)
