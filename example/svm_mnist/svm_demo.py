#!/usr/bin/env python
"""SVM output layer: max-margin training through the Module API.

Reference analog: ``example/svm_mnist/svm_mnist.py`` — swap SoftmaxOutput
for ``SVMOutput`` (L1/L2 hinge loss, src/operator/svm_output.cc) on an
MLP and train with the same fit loop.

Run:  python example/svm_mnist/svm_demo.py --l2
"""
import argparse

import numpy as np

import mxnet_tpu as mx

parser = argparse.ArgumentParser(
    description="SVMOutput max-margin training",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=10)
parser.add_argument("--samples", type=int, default=1024)
parser.add_argument("--classes", type=int, default=4)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.1)
parser.add_argument("--margin", type=float, default=1.0)
parser.add_argument("--l2", action="store_true",
                    help="squared hinge instead of L1 hinge")


def make_data(n, classes, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, 24) * 2.0
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, 24) * 0.6
    return x.astype(np.float32), y.astype(np.float32)


def main(args):
    x, y = make_data(args.samples, args.classes)
    S = mx.symbol
    data = S.var("data")
    label = S.var("svm_label")
    fc1 = S.FullyConnected(data, num_hidden=48, name="fc1")
    act = S.Activation(fc1, act_type="relu")
    fc2 = S.FullyConnected(act, num_hidden=args.classes, name="fc2")
    net = S.SVMOutput(fc2, label, margin=args.margin,
                      use_linear=not args.l2, name="svm")

    mod = mx.mod.Module(net, data_names=["data"], label_names=["svm_label"])
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="svm_label")
    mod.fit(it, num_epoch=args.num_epochs,
            optimizer_params={"learning_rate": args.lr},
            eval_metric="acc")
    acc = dict(mod.score(it, "acc"))["accuracy"]
    print("SVM (%s hinge) accuracy: %.3f"
          % ("L2" if args.l2 else "L1", acc))
    return acc


if __name__ == "__main__":
    main(parser.parse_args())
