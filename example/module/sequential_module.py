#!/usr/bin/env python
"""Module API tour: Module, checkpointing, and SequentialModule.

Reference analog: ``example/module/`` (mod_demo / sequential_module): the
pre-Gluon intermediate API — symbol in, bind/init/fit/predict/score out.
TPU-native: every bound executor compiles its whole symbol into one XLA
program (mxnet_tpu/executor.py), so the Module-era batching discipline
(fixed shapes per bind) is exactly what jit wants.

Demonstrates, on a synthetic two-moons-style classification task:
1. plain ``Module``: bind → init_params → fit → predict → score;
2. epoch checkpointing with ``save_checkpoint`` / ``Module.load``;
3. ``SequentialModule``: two Modules chained, trained end-to-end.

Run:  python example/module/sequential_module.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import NDArrayIter

parser = argparse.ArgumentParser(
    description="Module API demo on synthetic classification",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=10)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.1)
parser.add_argument("--samples", type=int, default=1024)
parser.add_argument("--checkpoint-prefix", type=str, default=None,
                    help="save per-epoch checkpoints under this prefix")


def make_data(n, seed=0):
    """Two interleaved half-circles ('moons') + noise, 2 classes."""
    rng = np.random.RandomState(seed)
    half = n // 2
    t = rng.uniform(0, np.pi, half)
    x0 = np.stack([np.cos(t), np.sin(t)], 1)
    x1 = np.stack([1 - np.cos(t), 0.5 - np.sin(t)], 1)
    x = np.concatenate([x0, x1]).astype(np.float32)
    x += rng.randn(*x.shape).astype(np.float32) * 0.1
    y = np.concatenate([np.zeros(half), np.ones(half)]).astype(np.float32)
    idx = rng.permutation(n)
    return x[idx], y[idx]


def mlp_symbol():
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=32, name="fc2")
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, num_hidden=2, name="fc3")
    return sym.SoftmaxOutput(out, sym.var("softmax_label"), name="softmax")


def run_module(args, train_iter, val_iter):
    """Part 1+2: plain Module with fit/score/predict and checkpoints."""
    mod = mx.mod.Module(mlp_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    cb = (mx.callback.do_checkpoint(args.checkpoint_prefix)
          if args.checkpoint_prefix else None)
    mod.fit(train_iter, eval_data=val_iter,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            eval_metric="acc",
            epoch_end_callback=cb,
            num_epoch=args.num_epochs)
    metric = mx.metric.Accuracy()
    val_iter.reset()
    mod.score(val_iter, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    print("Module val accuracy: %.3f" % acc)

    if args.checkpoint_prefix:
        # resume the final epoch from disk and verify it scores the same
        loaded = mx.mod.Module.load(args.checkpoint_prefix,
                                    args.num_epochs,
                                    data_names=("data",),
                                    label_names=("softmax_label",))
        loaded.bind(data_shapes=val_iter.provide_data,
                    label_shapes=val_iter.provide_label)
        metric.reset()
        val_iter.reset()
        loaded.score(val_iter, metric)
        print("reloaded checkpoint accuracy: %.3f"
              % dict(metric.get_name_value())["accuracy"])
    return acc


def run_sequential(args, train_iter, val_iter):
    """Part 3: SequentialModule — a feature extractor Module feeding a
    classifier Module, trained as one pipeline."""
    feat = sym.FullyConnected(sym.var("data"), num_hidden=32, name="feat")
    feat = sym.Activation(feat, act_type="relu")

    head = sym.FullyConnected(sym.var("data"), num_hidden=2, name="head")
    head = sym.SoftmaxOutput(head, sym.var("softmax_label"),
                             name="softmax")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(feat, data_names=("data",), label_names=()))
    seq.add(mx.mod.Module(head, data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)

    seq.fit(train_iter,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            eval_metric="acc",
            num_epoch=args.num_epochs)
    metric = mx.metric.Accuracy()
    val_iter.reset()
    seq.score(val_iter, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    print("SequentialModule val accuracy: %.3f" % acc)
    return acc


def main(args):
    x, y = make_data(args.samples)
    n_val = args.samples // 4
    train_iter = NDArrayIter(data=x[n_val:], label=y[n_val:],
                             batch_size=args.batch_size, shuffle=True,
                             label_name="softmax_label")
    val_iter = NDArrayIter(data=x[:n_val], label=y[:n_val],
                           batch_size=args.batch_size,
                           label_name="softmax_label")
    acc1 = run_module(args, train_iter, val_iter)
    train_iter.reset()
    acc2 = run_sequential(args, train_iter, val_iter)
    return acc1, acc2


if __name__ == "__main__":
    main(parser.parse_args())
