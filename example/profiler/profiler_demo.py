#!/usr/bin/env python
"""Profiler usage: trace a training loop to a Chrome-trace JSON.

Reference analog: ``example/profiler/profiler_ndarray.py`` /
``profiler_matmul.py`` — configure, run ops, dump, inspect.  The
TPU-relevant pattern demonstrated: the same ``mx.profiler`` API records
host-side op-dispatch spans plus user Task/Frame markers; the dump is a
``chrome://tracing`` JSON (reference Profiler::DumpProfile semantics).

Run:  python example/profiler/profiler_demo.py --out /tmp/trace.json
"""
import argparse
import json

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="profiler demo",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--out", default="/tmp/mxnet_tpu_trace.json")
parser.add_argument("--steps", type=int, default=8)
parser.add_argument("--batch-size", type=int, default=32)


def main(args):
    profiler.set_config(filename=args.out, profile_all=True)

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    rs = np.random.RandomState(0)
    x = rs.randn(args.batch_size, 32).astype(np.float32)
    y = rs.randint(0, 10, args.batch_size).astype(np.float32)

    profiler.set_state("run")
    domain = profiler.Domain("example")
    train_task = profiler.Task(domain, "train_steps")
    train_task.start()
    for step in range(args.steps):
        with autograd.record():
            L = ce(net(mx.nd.array(x)), mx.nd.array(y))
        L.backward()
        trainer.step(args.batch_size)
    mx.nd.waitall()
    train_task.stop()
    profiler.set_state("stop")
    profiler.dump()

    with open(args.out) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    op_names = {e.get("name") for e in events if e.get("ph") == "X"}
    print("trace: %d events, ops seen include %s"
          % (len(events), sorted(n for n in op_names
                                 if n and "FullyConnected" in n)[:2]))
    return args.out, len(events), op_names


if __name__ == "__main__":
    main(parser.parse_args())
