#!/usr/bin/env python
"""Multivariate time-series forecasting (LSTNet-style conv+GRU).

Reference analog: ``example/multivariate_time_series/src/lstnet.py`` —
forecasting D correlated channels: a 1-D conv over the time window
extracts short-term motifs, a GRU summarizes them, a dense head predicts
the next value of every channel, trained with L2 loss and evaluated by
relative error vs the naive last-value forecast.

Synthetic data: D=8 channels of phase-shifted sinusoids where channel d
is a lagged mixture of channels (d-1, d-2) plus noise — the
cross-channel correlations LSTNet's conv stage exists to exploit; the
naive forecast cannot use them.

Run:  python example/multivariate_time_series/lstnet.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

parser = argparse.ArgumentParser(
    description="LSTNet-style multivariate forecasting",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=200)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--window", type=int, default=24)
parser.add_argument("--horizon", type=int, default=6)
parser.add_argument("--channels", type=int, default=8)
parser.add_argument("--lr", type=float, default=0.005)


def make_series(T, D, seed=0):
    rng = np.random.RandomState(seed)
    t = np.arange(T + 2)
    base = np.stack([np.sin(2 * np.pi * t / (12 + 3 * d) + d)
                     for d in range(D)], 1)
    x = base.copy()
    for d in range(2, D):
        x[:, d] = 0.4 * x[:, d] + 0.4 * np.roll(base[:, d - 1], 1) \
            + 0.2 * np.roll(base[:, d - 2], 2)
    return (x + rng.randn(*x.shape) * 0.05).astype(np.float32)


class LSTNet(gluon.Block):
    def __init__(self, channels, **kw):
        super().__init__(**kw)
        with self.name_scope():
            # conv over (window, channels) viewed as a 1xWxD image
            self.conv = nn.Conv2D(32, kernel_size=(3, channels),
                                  activation="relu")
            self.gru = rnn.GRU(32, layout="NTC")
            self.head = nn.Dense(channels)

    def forward(self, x):                      # x: (B, W, D)
        c = self.conv(x.expand_dims(1))        # (B, 32, W-2, 1)
        seq = c.squeeze(axis=3).transpose((0, 2, 1))   # (B, W-2, 32)
        h = self.gru(seq)                      # (B, W-2, 32)
        return self.head(h[:, -1, :])          # (B, D)


def main(args):
    W, D = args.window, args.channels
    series = make_series(4096, D)
    rng = np.random.RandomState(1)

    # horizon-h forecasting (the reference benchmarks horizons 3-24):
    # at h steps out the last-value naive forecast decorrelates, so the
    # model must use the temporal + cross-channel structure to win
    h = args.horizon

    def batch(bs):
        idx = rng.randint(0, len(series) - W - h - 1, bs)
        xb = np.stack([series[i:i + W] for i in idx])
        yb = np.stack([series[i + W + h - 1] for i in idx])
        return nd.array(xb), nd.array(yb)

    net = LSTNet(D)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    l2 = gluon.loss.L2Loss()

    for it in range(args.iters):
        xb, yb = batch(args.batch_size)
        with autograd.record():
            loss = l2(net(xb), yb)
        loss.backward()
        trainer.step(args.batch_size)

    # eval: model MSE vs naive last-value forecast MSE
    xb, yb = batch(256)
    pred = net(xb).asnumpy()
    naive = xb.asnumpy()[:, -1, :]
    y = yb.asnumpy()
    mse = float(((pred - y) ** 2).mean())
    mse_naive = float(((naive - y) ** 2).mean())
    rel = mse / mse_naive
    print("model MSE %.5f, naive MSE %.5f, ratio %.3f"
          % (mse, mse_naive, rel))
    return rel


if __name__ == "__main__":
    a = parser.parse_args()
    rel = main(a)
    raise SystemExit(0 if rel < 0.5 else 1)
