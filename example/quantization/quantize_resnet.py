#!/usr/bin/env python
"""INT8 quantization walkthrough: calibrate, fuse, compare.

Reference analog: ``example/quantization/imagenet_gen_qsym.py`` — take a
trained fp32 model, calibrate activation ranges on sample data, emit the
int8 symbol + params, and validate accuracy against fp32.

TPU-native pipeline demonstrated here (``quantize_model(fuse=True)``):
BatchNorms are folded into conv weights, calibration covers conv/FC and
residual-add outputs plus the data input, and the graph is rewritten
with fused ``_sg_int8_*`` ops — every scale a static attribute, the
requantize+ReLU epilogue fused into each conv, residual adds computed
int8-to-int8.  Measured on a v5e chip this is 1.29x bf16 inference at
top-1 agreement 1.000 (docs/perf_analysis.md round 4); the reference's
dynamic-range layout (``fuse=False``) is also available for parity.

With no ImageNet on disk the demo uses a model-zoo ResNet-18 on
synthetic data; swap in real weights via ``net.load_parameters`` and a
real ``calib_data`` iterator for production use.

Run:  python example/quantization/quantize_resnet.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as S
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.io import NDArrayIter

parser = argparse.ArgumentParser(
    description="Quantize a model zoo ResNet to fused int8",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--model", type=str, default="resnet18_v1")
parser.add_argument("--batch-size", type=int, default=8)
parser.add_argument("--image-size", type=int, default=32)
parser.add_argument("--calib-mode", type=str, default="naive",
                    choices=["naive", "entropy"],
                    help="entropy (KL) needs representative calib data")
parser.add_argument("--num-calib", type=int, default=8)
parser.add_argument("--no-fuse", action="store_true",
                    help="use the reference-layout dynamic-range pass")


def main(args):
    net = getattr(vision, args.model)()
    net.initialize()            # default context: tpu(0) if present
    shape = (args.batch_size, 3, args.image_size, args.image_size)
    x = mx.nd.random.uniform(0, 1, shape=shape)
    net(x).wait_to_read()
    net.hybridize()

    # 1. export the symbol + params (the deploy form)
    sym = net(S.var("data"))
    params = net.collect_params()
    arg_params = {n: params[n].data()
                  for n in sym.list_arguments() if n != "data"}
    aux_params = {n: params[n].data()
                  for n in sym.list_auxiliary_states()}

    # 2. calibrate + quantize
    calib = NDArrayIter(data=x.asnumpy(), batch_size=args.batch_size)
    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        sym, arg_params, aux_params,
        calib_mode=args.calib_mode, calib_data=calib,
        num_calib_examples=args.num_calib, fuse=not args.no_fuse)

    # int8 weights carry the _quantize suffix (public naming convention
    # of the pass) — one per quantized conv/FC layer
    n_int8 = sum(1 for n in qsym.list_arguments()
                 if n.endswith("_quantize"))
    print("quantized layers: %d (%s pass)"
          % (n_int8, "fused" if not args.no_fuse else "legacy"))

    # 3. validate against fp32
    ref = net(x).asnumpy()
    ex = qsym.bind(x.context, {**qargs, "data": x}, aux_states=qauxs)
    got = ex.forward(is_train=False)[0].asnumpy()
    agree = float((got.argmax(1) == ref.argmax(1)).mean())
    corr = float(np.corrcoef(got.ravel(), ref.ravel())[0, 1])
    print("top-1 agreement vs fp32: %.3f   output corr: %.4f"
          % (agree, corr))
    return agree, corr, n_int8


if __name__ == "__main__":
    main(parser.parse_args())
