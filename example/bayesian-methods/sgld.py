#!/usr/bin/env python
"""Bayesian methods: SGLD posterior sampling for logistic regression.

Reference analog: ``example/bayesian-methods/sgld.ipynb`` /
``bdk_demo.py`` (Welling & Teh 2011) — stochastic gradient Langevin
dynamics: each step adds N(0, eps) noise to the eps/2-scaled gradient
step (eps = lr/N here) so the iterates
SAMPLE the posterior instead of collapsing to the MAP; predictions
average over the collected samples (Bayesian model averaging), and the
posterior spread is meaningful uncertainty, not noise.

Synthetic task: 2-class logistic regression on separable-with-overlap
Gaussians.  Success criteria: (1) posterior-averaged accuracy beats a
coin flip comfortably; (2) the sampled weights actually spread (nonzero
posterior std) instead of collapsing — the thing SGLD exists to do.

Run:  python example/bayesian-methods/sgld.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

parser = argparse.ArgumentParser(
    description="SGLD Bayesian logistic regression",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=600)
parser.add_argument("--burnin", type=int, default=300)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.05)
parser.add_argument("--prior-prec", type=float, default=1.0)


def make_data(n, seed=0):
    rng = np.random.RandomState(seed)
    half = n // 2
    x0 = rng.randn(half, 2) + np.array([1.2, 1.2])
    x1 = rng.randn(half, 2) - np.array([1.2, 1.2])
    x = np.concatenate([x0, x1]).astype(np.float32)
    y = np.concatenate([np.ones(half), np.zeros(half)]).astype(np.float32)
    idx = rng.permutation(n)
    return x[idx], y[idx]


def main(args):
    rng = np.random.RandomState(0)
    X, Y = make_data(1024)
    n = len(X)
    w = nd.zeros((2, 1))
    b = nd.zeros((1,))
    w.attach_grad()
    b.attach_grad()

    samples = []
    for it in range(args.iters):
        i = rng.randint(0, n - args.batch_size)
        xb = nd.array(X[i:i + args.batch_size])
        yb = nd.array(Y[i:i + args.batch_size].reshape(-1, 1))
        with autograd.record():
            logit = nd.dot(xb, w) + b
            # negative log posterior on the minibatch, rescaled to the
            # full dataset (the SGLD estimator), + Gaussian prior
            nll = nd.mean(nd.relu(logit) - logit * yb +
                          nd.log(1 + nd.exp(-nd.abs(logit)))) * n
            prior = 0.5 * args.prior_prec * (nd.sum(w * w) + nd.sum(b * b))
            loss = nll + prior
        loss.backward()
        # Langevin update: gradient step + N(0, lr) noise
        eps = args.lr / n
        for p in (w, b):
            noise = nd.array(rng.randn(*p.shape).astype(np.float32))
            p -= 0.5 * eps * p.grad
            p += noise * float(np.sqrt(eps))
        if it >= args.burnin and it % 10 == 0:
            samples.append((w.asnumpy().copy(), b.asnumpy().copy()))

    # Bayesian model averaging over the posterior samples
    probs = np.zeros((n, 1))
    for ws, bs_ in samples:
        z = X @ ws + bs_
        probs += 1.0 / (1.0 + np.exp(-z))
    probs /= len(samples)
    acc = float(((probs[:, 0] > 0.5) == (Y > 0.5)).mean())
    w_std = float(np.std([s[0] for s in samples], axis=0).mean())
    # w-std printed at %.6f: consumers (the smoke test) compare near
    # 1e-4, so the print must resolve past that boundary
    print("SGLD: %d samples, posterior-avg accuracy %.4f, "
          "posterior w-std %.6f" % (len(samples), acc, w_std))
    return acc, w_std


if __name__ == "__main__":
    a = parser.parse_args()
    acc, w_std = main(a)
    raise SystemExit(0 if acc > 0.9 and w_std > 1e-4 else 1)
