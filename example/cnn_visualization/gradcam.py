#!/usr/bin/env python
"""CNN visualization: vanilla saliency + Grad-CAM.

Reference analog: ``example/cnn_visualization/gradcam.py`` — explain a
CNN's prediction by (a) the input-gradient saliency map and (b) Grad-CAM:
weight the last conv layer's activation maps by their pooled gradients
and relu the sum, localizing WHERE the evidence is.

Verifiable synthetic setup: train a small conv net on the lit-patch
digits, then check that BOTH maps concentrate their mass inside the
patch that determines the class — ground truth for "the explanation
points at the evidence" that real photos can't give.

Run:  python example/cnn_visualization/gradcam.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="Saliency + Grad-CAM on a synthetic-digit CNN",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=120)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--px", type=int, default=16)
parser.add_argument("--lr", type=float, default=0.05)


class Net(gluon.Block):
    """Trunk conv stack with an exposed last-conv feature map."""

    def __init__(self, n_class=10, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = nn.Conv2D(16, 3, padding=1, activation="relu")
            self.c2 = nn.Conv2D(32, 3, padding=1, activation="relu")
            self.head = nn.Dense(n_class)

    def features(self, x):
        return self.c2(self.c1(x))               # (B, 32, H, W)

    def forward(self, x):
        f = self.features(x)
        return self.head(nd.mean(f, axis=(2, 3)))


def make_batch(rng, bs, px, n_class=10):
    xs = np.zeros((bs, 1, px, px), np.float32)
    ys = np.zeros((bs,), np.float32)
    boxes = []
    for i in range(bs):
        c = int(rng.randint(n_class))
        ys[i] = c
        r0, c0 = (c // 5) * (px // 2), (c % 5) * 3
        xs[i, 0, r0:r0 + 4, c0:c0 + 4] = 1.0
        boxes.append((r0, c0))
    xs += rng.randn(bs, 1, px, px).astype(np.float32) * 0.1
    return nd.array(xs), nd.array(ys), boxes


def mass_inside(maps, boxes, pad=1):
    """Fraction of (relu'd) map mass inside the evidence box, averaged."""
    fr = []
    for m, (r0, c0) in zip(maps, boxes):
        m = np.maximum(m, 0)
        total = m.sum() + 1e-9
        r1, c1 = max(0, r0 - pad), max(0, c0 - pad)
        inside = m[r1:r0 + 4 + pad, c1:c0 + 4 + pad].sum()
        fr.append(inside / total)
    return float(np.mean(fr))


def main(args):
    if args.px < 16:
        raise SystemExit("--px must be >= 16: the 10-class patch layout "
                         "places evidence up to column 15")
    rng = np.random.RandomState(0)
    net = Net()
    net.initialize(mx.init.Xavier())
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": args.lr, "momentum": 0.9})
    for it in range(args.iters):
        x, y, _ = make_batch(rng, args.batch_size, args.px)
        with autograd.record():
            loss = ce(net(x), y)
        loss.backward()
        tr.step(args.batch_size)

    # --- explanations on a fresh batch -------------------------------
    x, y, boxes = make_batch(rng, 16, args.px)
    x.attach_grad()
    with autograd.record():
        f = net.features(x)
        score = nd.pick(net.head(nd.mean(f, axis=(2, 3))), y)
        s = nd.sum(score)
    s.backward()
    saliency = np.abs(x.grad.asnumpy())[:, 0]          # (B, H, W)

    # Grad-CAM: pooled d score / d feature-map weights the channels.
    # With a global-mean + linear head the pooled gradient IS the head
    # row (dscore/df[c] = W[y,c]/HW), so the weights come straight from
    # the trained head — same math, one backward saved
    W = net.head.weight.data().asnumpy()               # (10, 32)
    fmap = f.asnumpy()                                 # (B, 32, H, W)
    cams = []
    for i in range(len(fmap)):
        wvec = W[int(y.asnumpy()[i])]                  # (32,)
        cams.append(np.einsum("c,chw->hw", wvec, fmap[i]))
    sal_frac = mass_inside(saliency, boxes)
    cam_frac = mass_inside(np.stack(cams), boxes)
    # baseline: the box covers 16/256 = 6% of the image
    print("saliency mass in box: %.3f   grad-cam mass in box: %.3f "
          "(box area fraction %.3f)"
          % (sal_frac, cam_frac, 16.0 / (args.px * args.px)))
    return sal_frac, cam_frac


if __name__ == "__main__":
    a = parser.parse_args()
    sal, cam = main(a)
    # both explanations concentrate well above the 6% area baseline
    # (input-grad saliency is noisier than CAM by nature)
    raise SystemExit(0 if sal > 0.15 and cam > 0.3 else 1)
