#!/usr/bin/env python
"""Fast Gradient Sign Method adversarial examples.

Reference analog: ``example/adversary/adversary_generation.ipynb`` — train
a classifier, then perturb inputs along the sign of the input gradient and
watch accuracy collapse.  The TPU-relevant pattern demonstrated: taking
gradients *with respect to inputs* (``attach_grad`` on data, not just
parameters) through a hybridized network.

Runs on a synthetic two-moons-style problem so it needs no dataset
download.

Run:  python example/adversary/fgsm.py --epsilon 0.3
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="FGSM adversarial attack demo",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=12)
parser.add_argument("--samples", type=int, default=1024)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.1)
parser.add_argument("--epsilon", type=float, default=0.3,
                    help="L-inf perturbation budget")


def make_data(n, seed=0):
    """Two interleaved half-circles ('moons'), 8-dim lifted."""
    rng = np.random.RandomState(seed)
    t = rng.uniform(0, np.pi, n)
    cls = rng.randint(0, 2, n)
    x = np.stack([np.cos(t) + cls * 1.0 - 0.5,
                  np.sin(t) * (1 - 2 * cls) + cls * 0.25], 1)
    x += rng.normal(0, 0.08, x.shape)
    # lift to 8 dims with a fixed random projection (keeps the demo's
    # gradient non-trivial in every input coordinate)
    proj = np.random.RandomState(42).randn(2, 8) * 0.7
    return (x @ proj).astype(np.float32), cls.astype(np.float32)


def accuracy(net, x, y):
    pred = net(mx.nd.array(x)).asnumpy().argmax(1)
    return float((pred == y).mean())


def main(args):
    x, y = make_data(args.samples)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"),
            nn.Dense(32, activation="relu"),
            nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)
    for epoch in range(args.num_epochs):
        it.reset()
        total = 0.0
        for batch in it:
            with autograd.record():
                out = net(batch.data[0])
                L = loss_fn(out, batch.label[0])
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.mean().asnumpy())
    clean_acc = accuracy(net, x, y)

    # FGSM: one gradient step on the *input*, sign-quantized
    data = mx.nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = net(data)
        L = loss_fn(out, mx.nd.array(y))
    L.backward()
    x_adv = (data + args.epsilon * mx.nd.sign(data.grad)).asnumpy()
    adv_acc = accuracy(net, x_adv, y)
    print("clean accuracy %.3f -> adversarial accuracy %.3f (eps=%.2f)"
          % (clean_acc, adv_acc, args.epsilon))
    return clean_acc, adv_acc


if __name__ == "__main__":
    main(parser.parse_args())
