#!/usr/bin/env python
"""Noise-contrastive estimation for a toy skip-gram embedding.

Reference analog: ``example/nce-loss/`` (word2vec/LSTM with NCE instead of
full softmax).  The TPU-relevant pattern demonstrated: avoiding the full
(vocab-wide) softmax by scoring one true class against k sampled noise
classes — embedding gathers + a binary logistic loss per candidate, all
static-shaped for XLA.

Synthetic corpus: tokens co-occur in fixed blocks of 4, so words in the
same block should land close in embedding space.

Run:  python example/nce-loss/nce.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="NCE skip-gram demo",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=12)
parser.add_argument("--vocab", type=int, default=64)
parser.add_argument("--block", type=int, default=4,
                    help="words per co-occurrence block")
parser.add_argument("--embed", type=int, default=16)
parser.add_argument("--negatives", type=int, default=8)
parser.add_argument("--pairs", type=int, default=4096)
parser.add_argument("--batch-size", type=int, default=256)
parser.add_argument("--lr", type=float, default=0.1)


def make_pairs(n, vocab, block, seed=0):
    """(center, context) pairs drawn within blocks of `block` words."""
    rng = np.random.RandomState(seed)
    centers = rng.randint(0, vocab, n)
    offsets = rng.randint(0, block, n)
    contexts = (centers // block) * block + offsets
    return centers.astype(np.int32), contexts.astype(np.int32)


class NCEModel(gluon.Block):
    def __init__(self, vocab, embed, **kw):
        super().__init__(**kw)
        self.in_emb = nn.Embedding(vocab, embed)
        self.out_emb = nn.Embedding(vocab, embed)

    def forward(self, center, candidates):
        # center: (B,), candidates: (B, 1+k) — true context first
        e_c = self.in_emb(center)                    # (B, D)
        e_o = self.out_emb(candidates)               # (B, 1+k, D)
        return (e_o * e_c.expand_dims(1)).sum(axis=-1)   # logits (B, 1+k)


def main(args):
    centers, contexts = make_pairs(args.pairs, args.vocab, args.block)
    net = NCEModel(args.vocab, args.embed)
    net.initialize(mx.init.Uniform(0.1))
    sig = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    n = args.pairs
    rng = np.random.RandomState(1)
    first = last = None
    for epoch in range(args.num_epochs):
        idx = np.random.RandomState(epoch).permutation(n)
        total, nb = 0.0, 0
        for i in range(0, n - args.batch_size + 1, args.batch_size):
            j = idx[i:i + args.batch_size]
            # candidates: true context + k uniform negatives (NCE noise)
            negs = rng.randint(0, args.vocab,
                               (len(j), args.negatives))
            cands = np.concatenate([contexts[j][:, None], negs], 1)
            labels = np.zeros_like(cands, np.float32)
            labels[:, 0] = 1.0
            with autograd.record():
                logits = net(mx.nd.array(centers[j]),
                             mx.nd.array(cands))
                L = sig(logits, mx.nd.array(labels)).mean()
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.asnumpy())
            nb += 1
        avg = total / nb
        if first is None:
            first = avg
        last = avg
        if epoch % 4 == 0:
            print("epoch %d nce loss %.4f" % (epoch, avg))

    # same-block words should be nearer than cross-block words
    emb = net.in_emb.weight.data().asnumpy().copy()
    emb /= np.linalg.norm(emb, axis=1, keepdims=True) + 1e-8
    sims = emb @ emb.T
    blocks = np.arange(args.vocab) // args.block
    same = sims[blocks[:, None] == blocks[None, :]]
    diff = sims[blocks[:, None] != blocks[None, :]]
    # exclude the diagonal self-similarities from 'same'
    margin = (same.sum() - args.vocab) / (same.size - args.vocab) \
        - diff.mean()
    print("loss %.4f -> %.4f; same-block minus cross-block cosine %.3f"
          % (first, last, margin))
    return first, last, margin


if __name__ == "__main__":
    main(parser.parse_args())
