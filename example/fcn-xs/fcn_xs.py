#!/usr/bin/env python
"""FCN-xs: fully-convolutional dense prediction (semantic segmentation).

Reference analog: ``example/fcn-xs/fcn_xs.py`` + ``symbol_fcnxs.py`` — the
only dense-prediction trainer in the reference tree: a conv encoder whose
score map is upsampled back to input resolution with ``Deconvolution``,
fused with a finer skip score via ``Crop`` + elementwise sum (the FCN-16s
pattern), trained with per-pixel ``SoftmaxOutput(multi_output=True)``.

TPU-native: the whole symbol (encoder, deconv upsampling, crop-align,
pixel softmax) binds into ONE XLA program through the Module API; the
deconv lowers to ``conv_general_dilated`` transpose form on the MXU.

Synthetic task: each image contains an axis-aligned bright rectangle on a
noisy background; the per-pixel label is {0: background, 1: rectangle}.
A stride-4 encoder must recover pixel-accurate masks through the
deconv+skip decoder — exactly what FCN's architecture exists to do.

Run:  python example/fcn-xs/fcn_xs.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import NDArrayIter

parser = argparse.ArgumentParser(
    description="FCN-16s-style segmentation on synthetic rectangles",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=12)
parser.add_argument("--batch-size", type=int, default=8)
parser.add_argument("--samples", type=int, default=256)
parser.add_argument("--image-size", type=int, default=32)
parser.add_argument("--lr", type=float, default=0.2)
parser.add_argument("--num-classes", type=int, default=2)


def make_data(n, px, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 1, px, px).astype(np.float32) * 0.3
    y = np.zeros((n, px, px), np.float32)
    for i in range(n):
        h, w = rng.randint(px // 4, px // 2, size=2)
        r, c = rng.randint(0, px - h), rng.randint(0, px - w)
        x[i, 0, r:r + h, c:c + w] += 2.0
        y[i, r:r + h, c:c + w] = 1.0
    return x, y


def fcn_symbol(num_classes):
    """Encoder (stride 4) -> score; skip (stride 2) -> score; deconv both
    back to full resolution, crop-align, sum — the FCN-16s topology at
    toy scale (reference symbol_fcnxs.py:offset-and-crop pattern)."""
    data = sym.var("data")
    # stride-2 block
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=16, pad=(1, 1),
                         name="conv1")
    a1 = sym.Activation(c1, act_type="relu")
    p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool1")                      # px/2
    # stride-4 block
    c2 = sym.Convolution(p1, kernel=(3, 3), num_filter=32, pad=(1, 1),
                         name="conv2")
    a2 = sym.Activation(c2, act_type="relu")
    p2 = sym.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool2")                      # px/4
    # class scores at both depths
    score4 = sym.Convolution(p2, kernel=(1, 1), num_filter=num_classes,
                             name="score4")
    score2 = sym.Convolution(p1, kernel=(1, 1), num_filter=num_classes,
                             name="score2")
    # upsample the deep score 2x, fuse with the skip, then 2x again
    up2 = sym.Deconvolution(score4, kernel=(4, 4), stride=(2, 2),
                            pad=(1, 1), num_filter=num_classes,
                            no_bias=True, name="up2")   # px/2
    up2c = sym.Crop(up2, score2, name="crop2")
    fused = up2c + score2
    up1 = sym.Deconvolution(fused, kernel=(4, 4), stride=(2, 2),
                            pad=(1, 1), num_filter=num_classes,
                            no_bias=True, name="up1")   # px
    up1c = sym.Crop(up1, data, name="crop1")
    return sym.SoftmaxOutput(up1c, sym.var("softmax_label"),
                             multi_output=True, normalization="valid",
                             name="softmax")


def main(args):
    px = args.image_size
    x, y = make_data(args.samples, px)
    n_val = args.samples // 4
    train = NDArrayIter(x[n_val:], y[n_val:], args.batch_size,
                        shuffle=True, label_name="softmax_label")
    val = NDArrayIter(x[:n_val], y[:n_val], args.batch_size,
                      label_name="softmax_label")

    mod = mx.mod.Module(fcn_symbol(args.num_classes),
                        data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(magnitude=2.0),
            num_epoch=args.num_epochs)

    # pixel accuracy on the validation split
    val.reset()
    hits = total = 0
    for batch in val:
        mod.forward(batch, is_train=False)
        prob = mod.get_outputs()[0].asnumpy()       # (B, C, H, W)
        pred = prob.argmax(axis=1)
        lab = batch.label[0].asnumpy()
        hits += (pred == lab).sum()
        total += lab.size
    acc = hits / max(total, 1)
    print("FCN pixel accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    a = parser.parse_args()
    acc = main(a)
    raise SystemExit(0 if acc > 0.9 else 1)
