#!/usr/bin/env python
"""Long-context transformer LM trained with ring attention (SP).

Beyond-parity demo (SURVEY.md §5.7): the reference (2018) handles long
sequences with bucketing/truncated BPTT; this framework shards the
SEQUENCE axis across the device mesh and trains a causal transformer LM
whose attention is exact ring attention — K/V shards rotate over the ICI
ring while each device keeps its Q shard, so per-device attention memory
is O(T/n · T/n) and the full training step (fwd + the round-5 ring
BACKWARD, where dk/dv accumulators ride the ring) compiles into one SPMD
XLA program.  On TPU the per-shard inner loop dispatches the Pallas
flash kernels in both directions (measured 2.2–2.3x over the scan
formulation at T_loc ≥ 2048, docs/perf_analysis.md round 5).

Model: embed -> N x [preLN, ring-causal-attention, preLN, MLP] -> tied
head.  Data: the synthetic 90%-Markov token stream (learnable rule;
uniform ppl = vocab).  Everything - params, optimizer state, the step -
lives in one jitted function over the mesh.

Run (8 virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python example/long-context-lm/train_ring_lm.py
"""
import argparse
import functools

import numpy as np

parser = argparse.ArgumentParser(
    description="Transformer LM over a sequence-parallel mesh",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=150)
parser.add_argument("--batch-size", type=int, default=4)
parser.add_argument("--seq-len", type=int, default=256,
                    help="global sequence length (sharded over sp)")
parser.add_argument("--vocab", type=int, default=32)
parser.add_argument("--d-model", type=int, default=64)
parser.add_argument("--n-heads", type=int, default=4)
parser.add_argument("--n-layers", type=int, default=2)
parser.add_argument("--sp", type=int, default=0,
                    help="sp mesh size (0 = all local devices)")
parser.add_argument("--lr", type=float, default=0.02)


def markov_tokens(rng, bs, T, vocab):
    x = np.zeros((bs, T + 1), np.int32)
    x[:, 0] = rng.randint(0, vocab, bs)
    for t in range(T):
        nxt = (x[:, t] * 5 + 3) % vocab
        rand = rng.randint(0, vocab, bs)
        x[:, t + 1] = np.where(rng.uniform(size=bs) < 0.9, nxt, rand)
    return x[:, :-1], x[:, 1:]


def init_params(rng, vocab, d, n_heads, n_layers):
    def glorot(*shape):
        fan = sum(shape[-2:])
        return (rng.randn(*shape) * np.sqrt(2.0 / fan)).astype(np.float32)

    p = {"embed": (rng.randn(vocab, d) * 0.02).astype(np.float32)}
    for l in range(n_layers):
        p["l%d" % l] = {
            "ln1": np.ones(d, np.float32), "ln1b": np.zeros(d, np.float32),
            "wq": glorot(d, d), "wk": glorot(d, d), "wv": glorot(d, d),
            "wo": glorot(d, d),
            "ln2": np.ones(d, np.float32), "ln2b": np.zeros(d, np.float32),
            "w1": glorot(d, 4 * d), "w2": glorot(4 * d, d),
        }
    return p


def main(args):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu.ops.nn import streaming_ce
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.ring_attention import ring_attention

    n_sp = args.sp or len(jax.devices())
    mesh = make_mesh({"sp": n_sp}, devices=jax.devices()[:n_sp])
    assert args.seq_len % n_sp == 0, "seq_len must divide the sp mesh"
    d, H = args.d_model, args.n_heads
    dh = d // H

    def ln(x, g, b):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) / jnp.sqrt(v + 1e-5) * g + b

    def block(h, lp):
        # h: (B, T, D) with T sharded over sp
        B, T, _ = h.shape
        a = ln(h, lp["ln1"], lp["ln1b"])
        q = (a @ lp["wq"]).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
        k = (a @ lp["wk"]).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
        v = (a @ lp["wv"]).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
        o = ring_attention(q, k, v, mesh, axis="sp", causal=True,
                           block_size=max(8, T // n_sp))
        o = o.transpose(0, 2, 1, 3).reshape(B, T, d)
        h = h + o @ lp["wo"]
        a = ln(h, lp["ln2"], lp["ln2b"])
        return h + jax.nn.gelu(a @ lp["w1"]) @ lp["w2"]

    def loss_fn(params, toks, targets):
        h = params["embed"][toks]                        # (B, T, D)
        for l in range(args.n_layers):
            h = block(h, params["l%d" % l])
        logits = h @ params["embed"].T                   # tied head
        return jnp.mean(streaming_ce(
            logits.reshape(-1, args.vocab), targets.reshape(-1)))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(params, toks, targets, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, toks, targets)
        new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                     grads)
        return new, loss

    rng = np.random.RandomState(0)
    params = jax.tree_util.tree_map(
        jnp.asarray, init_params(rng, args.vocab, d, H, args.n_layers))
    tok_sh = NamedSharding(mesh, P(None, "sp"))

    first = last = None
    for it in range(args.iters):
        xb, yb = markov_tokens(rng, args.batch_size, args.seq_len,
                               args.vocab)
        toks = jax.device_put(jnp.asarray(xb), tok_sh)
        tgts = jax.device_put(jnp.asarray(yb), tok_sh)
        params, loss = train_step(params, toks, tgts, args.lr)
        v = float(loss)
        if first is None:
            first = v
        last = v
    ppl0, ppl1 = float(np.exp(first)), float(np.exp(last))
    print("ring-attention LM over sp=%d: ppl %.2f -> %.2f (uniform %d)"
          % (n_sp, ppl0, ppl1, args.vocab))
    return ppl0, ppl1


if __name__ == "__main__":
    a = parser.parse_args()
    p0, p1 = main(a)
    raise SystemExit(0 if p1 < 8.0 and p1 < 0.5 * p0 else 1)
