#!/usr/bin/env python
"""REINFORCE policy gradient on a toy gridworld.

Reference analog: ``example/reinforcement-learning/`` (A3C/DQN on gym).
The TPU-relevant pattern demonstrated: the RL loop structure — a numpy
environment on the host, a Gluon policy network on the device, episode
rollouts, and a policy-gradient loss (-log pi * advantage) built from
recorded log-probs.  No gym dependency: a 5x5 gridworld with a goal.

Run:  python example/reinforcement-learning/reinforce.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="REINFORCE on a 5x5 gridworld",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--episodes", type=int, default=400)
parser.add_argument("--grid", type=int, default=5)
parser.add_argument("--max-steps", type=int, default=20)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--gamma", type=float, default=0.95)
parser.add_argument("--seed", type=int, default=0)

MOVES = np.array([[0, 1], [0, -1], [1, 0], [-1, 0]])   # E W S N


class GridWorld:
    """Agent starts at (0,0); +1 at the goal corner, -0.01 per step, plus
    potential-based shaping (0.1 x distance-to-goal decrease) so the
    sparse goal reward has a learnable gradient — standard practice (Ng et
    al. 1999), and it leaves the optimal policy unchanged."""

    def __init__(self, n):
        self.n = n
        self.goal = np.array([n - 1, n - 1])

    def _dist(self):
        return float(np.abs(self.goal - self.pos).sum())

    def reset(self):
        self.pos = np.array([0, 0])
        return self.obs()

    def obs(self):
        o = np.zeros((self.n, self.n), np.float32)
        o[tuple(self.pos)] = 1.0
        return o.ravel()

    def step(self, action):
        d0 = self._dist()
        self.pos = np.clip(self.pos + MOVES[action], 0, self.n - 1)
        done = bool((self.pos == self.goal).all())
        shaped = 0.1 * (d0 - self._dist()) - 0.01
        return self.obs(), (1.0 if done else 0.0) + shaped, done


def main(args):
    rng = np.random.RandomState(args.seed)
    env = GridWorld(args.grid)
    policy = nn.Sequential()
    policy.add(nn.Dense(64, activation="relu"), nn.Dense(4))
    policy.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(policy.collect_params(), "adam",
                            {"learning_rate": args.lr})

    returns_log = []
    for ep in range(args.episodes):
        obs_buf, act_buf, rew_buf = [], [], []
        obs = env.reset()
        for _ in range(args.max_steps):
            logits = policy(mx.nd.array(obs[None])).asnumpy()[0]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            a = rng.choice(4, p=p)
            obs_buf.append(obs)
            act_buf.append(a)
            obs, r, done = env.step(a)
            rew_buf.append(r)
            if done:
                break
        # discounted returns, normalized as the advantage
        G, g = [], 0.0
        for r in reversed(rew_buf):
            g = r + args.gamma * g
            G.append(g)
        G = np.array(G[::-1], np.float32)
        returns_log.append(G[0])
        adv = (G - G.mean()) / (G.std() + 1e-6) if len(G) > 1 else G

        data = mx.nd.array(np.stack(obs_buf))
        acts = mx.nd.array(np.array(act_buf, np.float32))
        advs = mx.nd.array(adv)
        with autograd.record():
            logp = mx.nd.log_softmax(policy(data), axis=-1)
            chosen = mx.nd.pick(logp, acts, axis=1)
            loss = -(chosen * advs).sum()
        loss.backward()
        trainer.step(len(act_buf))
        if (ep + 1) % 100 == 0:
            print("episode %d avg return (last 50): %.3f"
                  % (ep + 1, np.mean(returns_log[-50:])))

    early = float(np.mean(returns_log[:50]))
    late = float(np.mean(returns_log[-50:]))
    print("avg return first-50 %.3f -> last-50 %.3f" % (early, late))
    return early, late


if __name__ == "__main__":
    main(parser.parse_args())
