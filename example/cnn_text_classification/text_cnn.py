#!/usr/bin/env python
"""TextCNN sentence classification (Kim 2014 architecture).

Reference analog: ``example/cnn_text_classification/text_cnn.py`` —
parallel 1-D convolutions of several kernel widths over embedded token
sequences, max-over-time pooled, concatenated into a classifier.  The
TPU-relevant pattern demonstrated: multi-branch convolution graphs fuse
into one XLA program; all branches static-shaped.

Synthetic task: sequences contain a class-specific trigram motif at a
random position — exactly what width-3 filters should detect.

Run:  python example/cnn_text_classification/text_cnn.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="TextCNN on synthetic motif sequences",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--num-epochs", type=int, default=12)
parser.add_argument("--samples", type=int, default=1536)
parser.add_argument("--seq-len", type=int, default=24)
parser.add_argument("--vocab", type=int, default=50)
parser.add_argument("--classes", type=int, default=3)
parser.add_argument("--embed", type=int, default=16)
parser.add_argument("--filters", type=int, default=32)
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--lr", type=float, default=0.01)


class TextCNN(gluon.HybridBlock):
    def __init__(self, vocab, embed, filters, classes, widths=(2, 3, 4),
                 **kw):
        super().__init__(**kw)
        self.emb = nn.Embedding(vocab, embed)
        self.convs = nn.HybridSequential()
        for w in widths:
            self.convs.add(nn.Conv1D(filters, w, activation="relu"))
        self.pool = nn.GlobalMaxPool1D()
        self.drop = nn.Dropout(0.3)
        self.out = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        e = self.emb(x).transpose((0, 2, 1))    # (N, C=embed, T)
        feats = [self.pool(c(e)).flatten() for c in self.convs]
        h = F.concat(*feats, dim=1)
        return self.out(self.drop(h))


def make_data(n, seq_len, vocab, classes, seed=0):
    """Each class plants its own trigram motif at a random position."""
    rng = np.random.RandomState(seed)
    motifs = rng.randint(vocab // 2, vocab, (classes, 3))
    x = rng.randint(0, vocab // 2, (n, seq_len))
    y = rng.randint(0, classes, n)
    pos = rng.randint(0, seq_len - 3, n)
    for i in range(n):
        x[i, pos[i]:pos[i] + 3] = motifs[y[i]]
    return x.astype(np.float32), y.astype(np.float32)


def main(args):
    x, y = make_data(args.samples, args.seq_len, args.vocab, args.classes)
    net = TextCNN(args.vocab, args.embed, args.filters, args.classes)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)
    for epoch in range(args.num_epochs):
        it.reset()
        total, nb = 0.0, 0
        for batch in it:
            with autograd.record():
                L = ce(net(batch.data[0]), batch.label[0])
            L.backward()
            trainer.step(args.batch_size)
            total += float(L.mean().asnumpy())
            nb += 1
        if epoch % 4 == 0:
            print("epoch %d loss %.4f" % (epoch, total / nb))
    pred = net(mx.nd.array(x)).asnumpy().argmax(1)
    acc = float((pred == y).mean())
    print("motif classification accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main(parser.parse_args())
