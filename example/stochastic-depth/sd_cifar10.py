#!/usr/bin/env python
"""Stochastic depth: residual blocks dropped with a per-layer schedule.

Reference analog: ``example/stochastic-depth/sd_cifar10.py`` (Huang et
al. 2016) — during training, residual block l is SKIPPED entirely with
probability 1 - p_l, where p_l decays linearly with depth
(p_l = 1 - l/L * (1 - p_L)); at test time every block runs, its residual
branch scaled by p_l.  A per-LAYER drop schedule, not per-activation
dropout — a genuinely different regularizer and train/test asymmetry.

TPU-native: the Bernoulli gate is one scalar per block per batch drawn
OUTSIDE the compute, multiplied into the residual branch — no
data-dependent control flow enters the XLA program (gate*branch lets the
compiler keep one static graph; a dropped block is a multiply by zero).

Run:  python example/stochastic-depth/sd_cifar10.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

parser = argparse.ArgumentParser(
    description="Stochastic-depth ResNet on synthetic CIFAR-like data",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--iters", type=int, default=150)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--n-blocks", type=int, default=6)
parser.add_argument("--death-rate", type=float, default=0.5,
                    help="1 - p_L: drop prob of the DEEPEST block")
parser.add_argument("--px", type=int, default=16)
parser.add_argument("--lr", type=float, default=0.05)


class SDResBlock(gluon.Block):
    """Residual block with a survival gate: out = x + gate * branch(x).

    gate is p_l-scaled at test time and Bernoulli(p_l)/1 at train time
    (the inverted-dropout-style formulation keeps E[out] equal)."""

    def __init__(self, channels, survive_p, **kw):
        super().__init__(**kw)
        self.survive_p = survive_p
        with self.name_scope():
            self.conv1 = nn.Conv2D(channels, 3, padding=1)
            self.bn1 = nn.BatchNorm()
            self.conv2 = nn.Conv2D(channels, 3, padding=1)
            self.bn2 = nn.BatchNorm()

    def forward(self, x):
        branch = nd.relu(self.bn1(self.conv1(x)))
        branch = self.bn2(self.conv2(branch))
        if autograd.is_training():
            # one coin per block per batch (the reference's schedule);
            # dropped -> the whole branch multiplies to zero and the
            # block is an identity this step
            gate = 1.0 if self.rng.uniform() < self.survive_p else 0.0
            branch = branch * gate
        else:
            branch = branch * self.survive_p
        return nd.relu(x + branch)


class SDResNet(gluon.Block):
    def __init__(self, n_blocks, death_rate, n_class=10, **kw):
        super().__init__(**kw)
        self.blocks = []
        with self.name_scope():
            self.stem = nn.Conv2D(32, 3, padding=1)
            for l in range(n_blocks):
                # linear decay: p_l = 1 - (l+1)/L * death_rate
                p = 1.0 - (l + 1) / n_blocks * death_rate
                blk = SDResBlock(32, p)
                self.register_child(blk)
                self.blocks.append(blk)
            self.head = nn.Dense(n_class)

    def set_rng(self, rng):
        for blk in self.blocks:
            blk.rng = rng

    def forward(self, x):
        h = nd.relu(self.stem(x))
        for blk in self.blocks:
            h = blk(h)
        h = nd.mean(h, axis=(2, 3))        # global average pool
        return self.head(h)


def make_batch(rng, bs, px, n_class=10):
    xs = np.zeros((bs, 1, px, px), np.float32)
    ys = np.zeros((bs,), np.float32)
    for i in range(bs):
        c = int(rng.randint(n_class))
        ys[i] = c
        r0, c0 = (c // 5) * (px // 2), (c % 5) * 3
        xs[i, 0, r0:r0 + 4, c0:c0 + 4] = 1.0
    xs += rng.randn(bs, 1, px, px).astype(np.float32) * 0.2
    return nd.array(xs), nd.array(ys)


def main(args):
    rng = np.random.RandomState(0)
    net = SDResNet(args.n_blocks, args.death_rate)
    net.set_rng(rng)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    for it in range(args.iters):
        x, y = make_batch(rng, args.batch_size, args.px)
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(args.batch_size)

    # eval: full depth, branches scaled by p_l
    hits = total = 0
    for _ in range(8):
        x, y = make_batch(rng, args.batch_size, args.px)
        pred = net(x).asnumpy().argmax(1)
        hits += (pred == y.asnumpy()).sum()
        total += len(pred)
    acc = hits / total
    print("stochastic-depth eval accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    a = parser.parse_args()
    acc = main(a)
    raise SystemExit(0 if acc > 0.85 else 1)
