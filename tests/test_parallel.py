"""Tests for the parallelism stack: ring/Ulysses attention, tensor
parallelism, data-parallel fused train step.

Parity model: SURVEY.md §2.2 — these are the TPU-native replacements for
the reference's DP kvstore / model-parallel paths plus the beyond-parity
sequence-parallel design; validated on the virtual 8-device CPU mesh like
the reference's process-level fake cluster.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.ring_attention import (blockwise_attention,
                                               ring_attention,
                                               ulysses_attention)
from mxnet_tpu.parallel import tensor_parallel as tp


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        t, tk = s.shape[-2], s.shape[-1]
        mask = np.arange(t)[:, None] >= np.arange(tk)[None, :]
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    shape = (2, 4, 32, 8)                     # B, H, T, D
    return tuple(jnp.asarray(rng.randn(*shape).astype(np.float32))
                 for _ in range(3))


class TestBlockwiseAttention:
    def test_matches_reference(self, qkv):
        q, k, v = qkv
        out = blockwise_attention(q, k, v, block_size=8)
        ref = _ref_attention(*[np.asarray(x) for x in qkv])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_causal(self, qkv):
        q, k, v = qkv
        out = blockwise_attention(q, k, v, block_size=8, causal=True)
        ref = _ref_attention(*[np.asarray(x) for x in qkv], causal=True)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_ragged_block(self, qkv):
        q, k, v = qkv
        out = blockwise_attention(q, k, v, block_size=5)  # 32 % 5 != 0
        ref = _ref_attention(*[np.asarray(x) for x in qkv])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)


class TestRingAttention:
    def test_matches_reference(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
        out = ring_attention(q, k, v, mesh, axis="sp")
        ref = _ref_attention(*[np.asarray(x) for x in qkv])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_causal(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
        out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
        ref = _ref_attention(*[np.asarray(x) for x in qkv], causal=True)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_8_way(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 8})
        out = ring_attention(q, k, v, mesh, axis="sp")
        ref = _ref_attention(*[np.asarray(x) for x in qkv])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)


class TestUlyssesAttention:
    def test_matches_reference(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
        out = ulysses_attention(q, k, v, mesh, axis="sp")
        ref = _ref_attention(*[np.asarray(x) for x in qkv])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)


class TestTensorParallel:
    def test_column_row_dense(self):
        rng = np.random.RandomState(0)
        mesh = make_mesh({"tp": 4}, devices=jax.devices()[:4])
        x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        w1 = jnp.asarray(rng.randn(16, 32).astype(np.float32))
        w2 = jnp.asarray(rng.randn(32, 16).astype(np.float32))
        col = tp.column_parallel_dense(x, w1, mesh)
        np.testing.assert_allclose(np.asarray(col), np.asarray(x @ w1),
                                   rtol=1e-4, atol=1e-4)
        row = tp.row_parallel_dense(col, w2, mesh)
        np.testing.assert_allclose(np.asarray(row),
                                   np.asarray(x @ w1 @ w2),
                                   rtol=1e-3, atol=1e-3)

    def test_mlp_block(self):
        rng = np.random.RandomState(1)
        mesh = make_mesh({"tp": 4}, devices=jax.devices()[:4])
        x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        w1 = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        w2 = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        out = tp.mlp_block(x, w1, w2, mesh)
        ref = np.maximum(np.asarray(x @ w1), 0) @ np.asarray(w2)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3,
                                   atol=1e-3)


class TestDataParallelTrainer:
    def test_dp_step_matches_single_device(self):
        from mxnet_tpu.parallel.data_parallel import dp_train_step
        rng = np.random.RandomState(0)
        mesh = make_mesh({"dp": 8})
        w = jnp.asarray(rng.randn(4, 2).astype(np.float32))
        x = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        y = jnp.asarray(rng.randn(16, 2).astype(np.float32))

        def loss_fn(params, batch):
            xb, yb = batch
            pred = xb @ params["w"]
            return jnp.mean((pred - yb) ** 2)

        step = dp_train_step(loss_fn, mesh, lr=0.1, momentum=0.0)
        params = {"w": w}
        moms = {"w": jnp.zeros_like(w)}
        # single-device reference BEFORE the step: params are donated
        # (buffers invalidated) by the fused SPMD step
        g = jax.grad(lambda p: loss_fn(p, (x, y)))(params)
        expect = np.asarray(w) - 0.1 * np.asarray(g["w"])
        ref_loss = float(loss_fn(params, (x, y)))
        new_params, new_moms, loss = step(params, moms, (x, y))
        np.testing.assert_allclose(np.asarray(new_params["w"]), expect,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
