"""Tests for the native C++ runtime: threaded dependency engine + RecordIO.

Parity model: reference tests/cpp/engine/threaded_engine_test.cc (ordering,
concurrency, wait semantics), tests/python/unittest/test_engine.py,
test_exc_handling.py (async exception propagation), test_recordio.py.
Skipped when no C++ toolchain built the library.
"""
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import _native

pytestmark = pytest.mark.skipif(_native.lib() is None,
                                reason="native library unavailable")


@pytest.fixture
def engine():
    from mxnet_tpu.engine import NativeThreadedEngine
    e = NativeThreadedEngine(4)
    yield e
    e.stop()


class TestNativeEngine:
    def test_write_ordering(self, engine):
        results = []
        v = engine.new_variable("v")
        for i in range(50):
            engine.push(lambda i=i: results.append(i), mutable_vars=(v,))
        engine.wait_for_var(v)
        assert results == list(range(50))

    def test_concurrent_reads(self, engine):
        state = {"cur": 0, "max": 0}
        lock = threading.Lock()

        def read():
            with lock:
                state["cur"] += 1
                state["max"] = max(state["max"], state["cur"])
            time.sleep(0.02)
            with lock:
                state["cur"] -= 1

        v = engine.new_variable()
        engine.push(lambda: None, mutable_vars=(v,))
        for _ in range(4):
            engine.push(read, const_vars=(v,))
        engine.wait_for_all()
        assert state["max"] >= 2

    def test_write_blocks_reads(self, engine):
        order = []
        v = engine.new_variable()

        def slow_write():
            time.sleep(0.05)
            order.append("w")

        engine.push(slow_write, mutable_vars=(v,))
        engine.push(lambda: order.append("r"), const_vars=(v,))
        engine.wait_for_all()
        assert order == ["w", "r"]

    def test_independent_vars_run_parallel(self, engine):
        barrier = threading.Barrier(2, timeout=5)
        v1, v2 = engine.new_variable(), engine.new_variable()
        engine.push(lambda: barrier.wait(), mutable_vars=(v1,))
        engine.push(lambda: barrier.wait(), mutable_vars=(v2,))
        engine.wait_for_all()   # would deadlock if serialized

    def test_exception_propagation(self, engine):
        results = []
        v = engine.new_variable()

        def boom():
            raise ValueError("async kaboom")

        engine.push(boom, mutable_vars=(v,))
        # dependent op must NOT run; it forwards the poison
        engine.push(lambda: results.append(1), mutable_vars=(v,))
        with pytest.raises(ValueError, match="async kaboom"):
            engine.wait_for_var(v)
        assert results == []
        # var usable again after the error surfaced
        engine.push(lambda: results.append(2), mutable_vars=(v,))
        engine.wait_for_var(v)
        assert results == [2]

    def test_push_sync(self, engine):
        out = []
        v = engine.new_variable()
        engine.push_sync(lambda: out.append(1), mutable_vars=(v,))
        assert out == [1]
        with pytest.raises(RuntimeError, match="sync boom"):
            engine.push_sync(self._raise_runtime, mutable_vars=(v,))
        with pytest.raises(RuntimeError):
            engine.wait_for_var(v)

    @staticmethod
    def _raise_runtime():
        raise RuntimeError("sync boom")

    def test_delete_variable(self, engine):
        out = []
        v = engine.new_variable()
        engine.push(lambda: out.append(1), mutable_vars=(v,))
        engine.delete_variable(v)
        engine.wait_for_all()
        assert out == [1]

    def test_read_write_interleave_order(self, engine):
        order = []
        lock = threading.Lock()

        def w(tag):
            def f():
                with lock:
                    order.append(tag)
            return f

        v = engine.new_variable()
        engine.push(w("w0"), mutable_vars=(v,))
        for i in range(3):
            engine.push(w("r%d" % i), const_vars=(v,))
        engine.push(w("w1"), mutable_vars=(v,))
        engine.push(w("r3"), const_vars=(v,))
        engine.wait_for_all()
        assert order[0] == "w0"
        assert set(order[1:4]) == {"r0", "r1", "r2"}
        assert order[4] == "w1"
        assert order[5] == "r3"

    def test_default_engine_is_native(self):
        from mxnet_tpu import engine as em
        if os.environ.get("MXNET_ENGINE_TYPE",
                          "ThreadedEnginePerDevice") != \
                "ThreadedEnginePerDevice":
            pytest.skip("non-default engine requested via env")
        e = em.get()
        assert isinstance(e, em.NativeThreadedEngine)


class TestNativeRecordIO:
    def test_roundtrip(self, tmp_path):
        from mxnet_tpu import recordio
        path = str(tmp_path / "data.rec")
        payloads = [b"hello", b"x" * 7, b"\x00\x01binary\x00", b"",
                    os.urandom(1000)]
        w = recordio.MXRecordIO(path, "w")
        assert w._nhandle  # the native backend is in use
        for p in payloads:
            w.write(p)
        w.close()
        r = recordio.MXRecordIO(path, "r")
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(rec)
        r.close()
        assert got == payloads

    def test_python_fallback_compat(self, tmp_path, monkeypatch):
        """Files written natively read back identically via the Python
        fallback (and vice versa) — same on-disk format."""
        from mxnet_tpu import recordio
        path = str(tmp_path / "x.rec")
        w = recordio.MXRecordIO(path, "w")
        w.write(b"abc")
        w.write(b"defgh")
        w.close()
        monkeypatch.setenv("MXNET_NO_NATIVE", "1")
        monkeypatch.setattr(_native, "_LIB", None)
        monkeypatch.setattr(_native, "_TRIED", False)
        r = recordio.MXRecordIO(path, "r")
        assert r._nhandle is None      # python fallback active
        assert r.read() == b"abc"
        assert r.read() == b"defgh"
        assert r.read() is None
        r.close()
        monkeypatch.setattr(_native, "_TRIED", False)

    def test_indexed(self, tmp_path):
        from mxnet_tpu import recordio
        rec = str(tmp_path / "i.rec")
        idx = str(tmp_path / "i.idx")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(10):
            w.write_idx(i, b"rec%d" % i)
        w.close()
        r = recordio.MXIndexedRecordIO(idx, rec, "r")
        assert r.read_idx(7) == b"rec7"
        assert r.read_idx(2) == b"rec2"
        assert r.keys == list(range(10))
        r.close()

    def test_pack_unpack_through_native(self, tmp_path):
        from mxnet_tpu import recordio
        path = str(tmp_path / "p.rec")
        header = recordio.IRHeader(0, 3.0, 7, 0)
        w = recordio.MXRecordIO(path, "w")
        w.write(recordio.pack(header, b"payload"))
        w.close()
        r = recordio.MXRecordIO(path, "r")
        h, s = recordio.unpack(r.read())
        assert h.label == 3.0 and h.id == 7 and s == b"payload"


def test_cpp_unit_tests_pass():
    """Build + run the native C++ test binary (the tests/cpp analog,
    SURVEY.md §4 item 3)."""
    import subprocess
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src")
    r = subprocess.run(["make", "-C", src, "test"], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL ENGINE TESTS PASSED" in r.stdout
