"""Tests for the INT8 quantization subsystem.

Parity model: reference tests/python/quantization/test_quantization.py
(op-level int8 vs fp32 comparisons + quantize_model flows).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.symbol as sym
from mxnet_tpu.io import NDArrayIter


def test_quantize_dequantize_roundtrip_int8():
    x = nd.array(np.random.RandomState(0).randn(3, 7).astype(np.float32))
    q, mn, mx_ = nd.contrib.quantize(x, x.min(), x.max(), out_type="int8")
    assert q.dtype == np.int8
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    t = max(abs(float(x.min().asnumpy())), abs(float(x.max().asnumpy())))
    assert np.abs(back - x.asnumpy()).max() <= t / 127 + 1e-6


def test_quantize_uint8():
    x = nd.array(np.array([[0.0, 0.5, 1.0]], np.float32))
    q, mn, mx_ = nd.contrib.quantize(x, nd.array([0.0]), nd.array([1.0]),
                                     out_type="uint8")
    assert q.dtype == np.uint8
    np.testing.assert_allclose(q.asnumpy(), [[0, 128, 255]], atol=1)
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    np.testing.assert_allclose(back, x.asnumpy(), atol=1 / 255 + 1e-6)


def test_requantize_with_calib():
    # int32 values representing reals in range +-10
    s32 = nd.array(np.array([[1 << 20, -(1 << 20)]]), dtype=np.int32)
    q, mn, mx_ = nd.contrib.requantize(
        s32, nd.array([-10.0]), nd.array([10.0]),
        min_calib_range=-0.01, max_calib_range=0.01)
    assert q.dtype == np.int8
    # real value ~ 1<<20 * 10/2^31 ~ 0.0049 -> quantized at ~62 of 127
    assert 55 <= int(q.asnumpy()[0, 0]) <= 70


def _quantize_np(x):
    t = float(np.abs(x).max())
    return np.clip(np.round(x * 127 / t), -127, 127).astype(np.int8), t


def test_quantized_conv_matches_fp32():
    rng = np.random.RandomState(0)
    data = rng.randn(2, 3, 8, 8).astype(np.float32)
    wt = (rng.randn(4, 3, 3, 3) * 0.2).astype(np.float32)
    qd, td = _quantize_np(data)
    qw, tw = _quantize_np(wt)
    out_q, omin, omax = nd.contrib.quantized_conv(
        nd.array(qd), nd.array(qw), nd.array([-td]), nd.array([td]),
        nd.array([-tw]), nd.array([tw]), kernel=(3, 3), num_filter=4,
        no_bias=True)
    assert out_q.dtype == np.int32
    real = nd.contrib.dequantize(out_q, omin, omax).asnumpy()
    ref = nd.Convolution(nd.array(data), nd.array(wt), kernel=(3, 3),
                         num_filter=4, no_bias=True).asnumpy()
    rel = np.abs(real - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantized_fc_with_bias():
    rng = np.random.RandomState(1)
    data = rng.randn(4, 6).astype(np.float32)
    wt = (rng.randn(3, 6) * 0.3).astype(np.float32)
    bias = (rng.randn(3) * 0.5).astype(np.float32)
    qd, td = _quantize_np(data)
    qw, tw = _quantize_np(wt)
    qb, tb = _quantize_np(bias)
    out_q, omin, omax = nd.contrib.quantized_fully_connected(
        nd.array(qd), nd.array(qw), nd.array(qb),
        nd.array([-td]), nd.array([td]), nd.array([-tw]), nd.array([tw]),
        nd.array([-tb]), nd.array([tb]), num_hidden=3)
    real = nd.contrib.dequantize(out_q, omin, omax).asnumpy()
    ref = data @ wt.T + bias
    rel = np.abs(real - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantized_pooling():
    data = np.arange(-8, 8, dtype=np.float32).reshape(1, 1, 4, 4)
    q, t = _quantize_np(data)
    out, mn, mx_ = nd.contrib.quantized_pooling(
        nd.array(q), nd.array([-t]), nd.array([t]),
        kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert out.dtype == np.int8
    real = nd.contrib.dequantize(out, mn, mx_).asnumpy()
    ref = nd.Pooling(nd.array(data), kernel=(2, 2), stride=(2, 2),
                     pool_type="max").asnumpy()
    np.testing.assert_allclose(real, ref, atol=t / 127 + 1e-6)


def _lenet_ish():
    data_s = sym.var("data")
    c1 = sym.Convolution(data_s, kernel=(3, 3), num_filter=8, name="conv1")
    r1 = sym.Activation(c1, act_type="relu")
    p1 = sym.Pooling(r1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool1")
    f1 = sym.Flatten(p1, name="flat1")
    fc = sym.FullyConnected(f1, num_hidden=10, name="fc1")
    return sym.SoftmaxOutput(fc, sym.var("softmax_label"), name="softmax")


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_model(calib_mode):
    rng = np.random.RandomState(0)
    out = _lenet_ish()
    xs = nd.array(rng.rand(4, 3, 8, 8).astype(np.float32))
    arg_shapes, _, _ = out.infer_shape(data=(4, 3, 8, 8), softmax_label=(4,))
    args = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        args[n] = nd.array((rng.randn(*s) * 0.1).astype(np.float32))
    calib = NDArrayIter(data=xs.asnumpy(), label=np.zeros(4), batch_size=4)
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        out, args, {}, calib_mode=calib_mode,
        calib_data=None if calib_mode == "none" else calib)
    # int8 weights stored offline
    assert any(n.endswith("_quantize") for n in qargs)
    assert qargs["conv1_weight_quantize"].dtype == np.int8
    ex_q = qsym.bind(mx.cpu(), {**qargs, "data": xs,
                                "softmax_label": nd.zeros((4,))})
    q_out = ex_q.forward(is_train=False)[0].asnumpy()
    ex_fp = out.bind(mx.cpu(), {**args, "data": xs,
                                "softmax_label": nd.zeros((4,))})
    f_out = ex_fp.forward(is_train=False)[0].asnumpy()
    assert np.abs(q_out - f_out).max() < 0.15
    if calib_mode != "entropy":
        # KL calibration may clip near-tie logits on random weights;
        # exact argmax agreement is only guaranteed for naive/none ranges
        assert (q_out.argmax(1) == f_out.argmax(1)).all()


def test_quantize_model_excluded_layer():
    out = _lenet_ish()
    rng = np.random.RandomState(0)
    arg_shapes, _, _ = out.infer_shape(data=(4, 3, 8, 8), softmax_label=(4,))
    args = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        args[n] = nd.array((rng.randn(*s) * 0.1).astype(np.float32))
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        out, args, {}, calib_mode="none", excluded_sym_names=["fc1"])
    assert "fc1_weight" in qsym.list_arguments()
    assert "fc1_weight_quantize" not in qsym.list_arguments()
    assert "conv1_weight_quantize" in qsym.list_arguments()


def test_quantize_model_with_batchnorm():
    """BN networks quantize end-to-end (regression: quantize_graph used to
    index hidden outputs of multi-output nodes like BatchNorm and crash
    with IndexError on every BN model, e.g. the ResNet zoo)."""
    rng = np.random.RandomState(1)
    data = sym.var("data")
    h = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        no_bias=True, name="convq")
    h = sym.BatchNorm(h, fix_gamma=False, name="bnq")
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    out = sym.FullyConnected(sym.Flatten(h), num_hidden=4, name="fcq")

    xs = nd.array(rng.rand(4, 3, 8, 8).astype(np.float32))
    arg_shapes, _, aux_shapes = out.infer_shape(data=(4, 3, 8, 8))
    args, auxs = {}, {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n != "data":
            args[n] = nd.array((rng.randn(*s) * 0.1).astype(np.float32))
    for n, s in zip(out.list_auxiliary_states(), aux_shapes):
        auxs[n] = nd.array(
            np.ones(s, np.float32) if "var" in n else np.zeros(s, np.float32))

    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        out, args, auxs, calib_mode="none", calib_data=None)
    assert any(n.endswith("_quantize") for n in qargs)
    ex_q = qsym.bind(mx.cpu(), {**qargs, "data": xs}, aux_states=qauxs)
    q_out = ex_q.forward(is_train=False)[0].asnumpy()
    ex_f = out.bind(mx.cpu(), {**args, "data": xs}, aux_states=auxs)
    f_out = ex_f.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(q_out).all()
    assert np.abs(q_out - f_out).max() < 0.25


# ---------------------------------------------------------------------------
# fused static-scale pipeline (round-4: BN fold + _sg_int8_* graph)
# ---------------------------------------------------------------------------
def _residual_net():
    """conv-bn-relu -> conv-bn -> (+ projected skip) -> relu -> pool -> fc,
    the minimal ResNet-shaped graph exercising every fused pattern."""
    data = sym.var("data")
    y = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        no_bias=True, name="convA")
    y = sym.BatchNorm(y, fix_gamma=False, eps=1e-5, name="bnA")
    y = sym.Activation(y, act_type="relu", name="reluA")
    y = sym.Convolution(y, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        no_bias=False, name="convB")
    y = sym.BatchNorm(y, fix_gamma=False, eps=1e-5, name="bnB")
    s = sym.Convolution(data, kernel=(1, 1), num_filter=8, no_bias=True,
                        name="convS")
    s = sym.BatchNorm(s, fix_gamma=False, eps=1e-5, name="bnS")
    z = sym.broadcast_add(y, s, name="addZ")
    z = sym.Activation(z, act_type="relu", name="reluZ")
    z = sym.Pooling(z, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="poolZ")
    return sym.FullyConnected(sym.Flatten(z), num_hidden=5, name="fcZ")


def _init_residual(out, shape=(8, 3, 10, 10), seed=0):
    rng = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = out.infer_shape(data=shape)
    args, auxs = {}, {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n != "data":
            args[n] = nd.array((rng.randn(*s) * 0.2).astype(np.float32))
    for n, s in zip(out.list_auxiliary_states(), aux_shapes):
        auxs[n] = nd.array(
            (np.abs(rng.rand(*s)) + 0.5).astype(np.float32) if "var" in n
            else (rng.randn(*s) * 0.1).astype(np.float32))
    x = nd.array(rng.rand(*shape).astype(np.float32))
    return args, auxs, x


def test_fold_batchnorm_exact():
    out = _residual_net()
    args, auxs, x = _init_residual(out)
    ref = out.bind(mx.cpu(), {**args, "data": x}, aux_states=auxs) \
        .forward(is_train=False)[0].asnumpy()

    from mxnet_tpu.contrib.quantization import fold_batchnorm
    fsym, fargs, fauxs = fold_batchnorm(out, args, auxs)
    ops = set(n.op.name for n in fsym._topo() if not n.is_var)
    assert "BatchNorm" not in ops, ops
    got = fsym.bind(mx.cpu(), {**{k: nd.array(v) for k, v in fargs.items()},
                               "data": x}) \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_fused_int8_graph_structure_and_accuracy():
    out = _residual_net()
    args, auxs, x = _init_residual(out)
    ref = out.bind(mx.cpu(), {**args, "data": x}, aux_states=auxs) \
        .forward(is_train=False)[0].asnumpy()

    calib = NDArrayIter(data=x.asnumpy(), batch_size=8)
    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        out, args, auxs, ctx=mx.cpu(), calib_mode="naive",
        calib_data=calib, num_calib_examples=8, fuse=True)

    ops = [n.op.name for n in qsym._topo() if not n.is_var]
    # all three convs fused, the residual add stays int8, exactly one
    # activation quantize (the data input) and one dequantize (head)
    assert ops.count("_sg_int8_conv") == 3, ops
    assert ops.count("_sg_int8_elemwise_add") == 1, ops
    assert ops.count("_contrib_quantize_v2") == 1, ops
    # head FC emits f32 straight from the accumulator (dequant_out), so
    # no standalone dequantize survives
    assert ops.count("_sg_int8_fully_connected") == 1, ops
    assert ops.count("_contrib_dequantize_v2") == 0, ops
    assert "Convolution" not in ops and "BatchNorm" not in ops, ops
    # relu epilogues are folded: no standalone Activation survives
    assert "Activation" not in ops, ops

    ex = qsym.bind(mx.cpu(), {**qargs, "data": x}, aux_states=qauxs)
    got = ex.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(got).all()
    # int8 path tracks fp32: same ranking on every sample
    assert (got.argmax(1) == ref.argmax(1)).all()
    corr = np.corrcoef(got.ravel(), ref.ravel())[0, 1]
    assert corr > 0.999, corr


def test_fused_int8_weight_dtypes():
    out = _residual_net()
    args, auxs, x = _init_residual(out, seed=3)
    calib = NDArrayIter(data=x.asnumpy(), batch_size=8)
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        out, args, auxs, ctx=mx.cpu(), calib_mode="naive",
        calib_data=calib, num_calib_examples=8, fuse=True)
    w = qargs["convA_weight_quantize"]
    assert w.dtype == np.int8
    # folded biases ride the s32 accumulator scale
    b32 = [n for n in qargs if n.endswith("_q32")]
    assert b32 and all(qargs[n].dtype == np.int32 for n in b32)


def test_fold_batchnorm_default_attrs():
    """Regression (round-4 review): BatchNorm created WITHOUT explicit
    attrs runs with its registered defaults (fix_gamma=True, eps=1e-3);
    the fold must read those same defaults via parsed_attrs, not guess."""
    rng = np.random.RandomState(5)
    data = sym.var("data")
    y = sym.Convolution(data, kernel=(3, 3), num_filter=4, no_bias=True,
                        name="convD")
    y = sym.BatchNorm(y, name="bnD")          # all-default attrs
    x = nd.array(rng.rand(2, 3, 8, 8).astype(np.float32))
    arg_shapes, _, aux_shapes = y.infer_shape(data=(2, 3, 8, 8))
    args, auxs = {}, {}
    for n, s in zip(y.list_arguments(), arg_shapes):
        if n != "data":
            args[n] = nd.array((rng.randn(*s) * 0.5).astype(np.float32))
    for n, s in zip(y.list_auxiliary_states(), aux_shapes):
        auxs[n] = nd.array(
            (np.abs(rng.rand(*s)) + 0.5).astype(np.float32) if "var" in n
            else (rng.randn(*s) * 0.2).astype(np.float32))
    ref = y.bind(mx.cpu(), {**args, "data": x}, aux_states=auxs) \
        .forward(is_train=False)[0].asnumpy()

    from mxnet_tpu.contrib.quantization import fold_batchnorm
    fsym, fargs, _ = fold_batchnorm(y, args, auxs)
    got = fsym.bind(mx.cpu(), {**{k: nd.array(v) for k, v in fargs.items()},
                               "data": x}) \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_fused_int8_skips_1d_conv():
    """1-D convs can't lower through the 2-D _sg_int8_conv; they must fall
    back to fp32 instead of crashing (round-4 review finding)."""
    data = sym.var("data")
    y = sym.Convolution(data, kernel=(3,), num_filter=4, pad=(1,),
                        no_bias=True, name="conv1d")
    out = sym.FullyConnected(sym.Flatten(y), num_hidden=3, name="fc1d")
    rng = np.random.RandomState(7)
    x = nd.array(rng.rand(4, 2, 16).astype(np.float32))
    arg_shapes, _, _ = out.infer_shape(data=(4, 2, 16))
    args = {n: nd.array((rng.randn(*s) * 0.3).astype(np.float32))
            for n, s in zip(out.list_arguments(), arg_shapes)
            if n != "data"}
    ref = out.bind(mx.cpu(), {**args, "data": x}) \
        .forward(is_train=False)[0].asnumpy()
    calib = NDArrayIter(data=x.asnumpy(), batch_size=4)
    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        out, args, {}, ctx=mx.cpu(), calib_mode="naive", calib_data=calib,
        num_calib_examples=4, fuse=True)
    ops = [n.op.name for n in qsym._topo() if not n.is_var]
    assert "_sg_int8_conv" not in ops, ops     # 1-D conv stayed fp32
    got = qsym.bind(mx.cpu(), {**qargs, "data": x}, aux_states=qauxs) \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.05)


def test_sg_int8_global_avg_pool_exact():
    """s8 global mean: s32 accumulate, rint back to s8, threshold
    unchanged (the round-5 head op); matches the f32 mean of the
    dequantized input within one s8 lattice step."""
    import numpy as np
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray.ndarray import invoke
    rng = np.random.RandomState(0)
    q = rng.randint(-127, 128, size=(2, 4, 5, 5)).astype(np.int8)
    out = invoke("_sg_int8_global_avg_pool", [nd.array(q, dtype="int8")],
                 {}).asnumpy()
    want = np.rint(q.astype(np.float64).mean((2, 3), keepdims=True))
    np.testing.assert_allclose(out.astype(np.float64), want, atol=0.51)
    assert out.dtype == np.int8
