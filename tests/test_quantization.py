"""Tests for the INT8 quantization subsystem.

Parity model: reference tests/python/quantization/test_quantization.py
(op-level int8 vs fp32 comparisons + quantize_model flows).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.symbol as sym
from mxnet_tpu.io import NDArrayIter


def test_quantize_dequantize_roundtrip_int8():
    x = nd.array(np.random.RandomState(0).randn(3, 7).astype(np.float32))
    q, mn, mx_ = nd.contrib.quantize(x, x.min(), x.max(), out_type="int8")
    assert q.dtype == np.int8
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    t = max(abs(float(x.min().asnumpy())), abs(float(x.max().asnumpy())))
    assert np.abs(back - x.asnumpy()).max() <= t / 127 + 1e-6


def test_quantize_uint8():
    x = nd.array(np.array([[0.0, 0.5, 1.0]], np.float32))
    q, mn, mx_ = nd.contrib.quantize(x, nd.array([0.0]), nd.array([1.0]),
                                     out_type="uint8")
    assert q.dtype == np.uint8
    np.testing.assert_allclose(q.asnumpy(), [[0, 128, 255]], atol=1)
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    np.testing.assert_allclose(back, x.asnumpy(), atol=1 / 255 + 1e-6)


def test_requantize_with_calib():
    # int32 values representing reals in range +-10
    s32 = nd.array(np.array([[1 << 20, -(1 << 20)]]), dtype=np.int32)
    q, mn, mx_ = nd.contrib.requantize(
        s32, nd.array([-10.0]), nd.array([10.0]),
        min_calib_range=-0.01, max_calib_range=0.01)
    assert q.dtype == np.int8
    # real value ~ 1<<20 * 10/2^31 ~ 0.0049 -> quantized at ~62 of 127
    assert 55 <= int(q.asnumpy()[0, 0]) <= 70


def _quantize_np(x):
    t = float(np.abs(x).max())
    return np.clip(np.round(x * 127 / t), -127, 127).astype(np.int8), t


def test_quantized_conv_matches_fp32():
    rng = np.random.RandomState(0)
    data = rng.randn(2, 3, 8, 8).astype(np.float32)
    wt = (rng.randn(4, 3, 3, 3) * 0.2).astype(np.float32)
    qd, td = _quantize_np(data)
    qw, tw = _quantize_np(wt)
    out_q, omin, omax = nd.contrib.quantized_conv(
        nd.array(qd), nd.array(qw), nd.array([-td]), nd.array([td]),
        nd.array([-tw]), nd.array([tw]), kernel=(3, 3), num_filter=4,
        no_bias=True)
    assert out_q.dtype == np.int32
    real = nd.contrib.dequantize(out_q, omin, omax).asnumpy()
    ref = nd.Convolution(nd.array(data), nd.array(wt), kernel=(3, 3),
                         num_filter=4, no_bias=True).asnumpy()
    rel = np.abs(real - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantized_fc_with_bias():
    rng = np.random.RandomState(1)
    data = rng.randn(4, 6).astype(np.float32)
    wt = (rng.randn(3, 6) * 0.3).astype(np.float32)
    bias = (rng.randn(3) * 0.5).astype(np.float32)
    qd, td = _quantize_np(data)
    qw, tw = _quantize_np(wt)
    qb, tb = _quantize_np(bias)
    out_q, omin, omax = nd.contrib.quantized_fully_connected(
        nd.array(qd), nd.array(qw), nd.array(qb),
        nd.array([-td]), nd.array([td]), nd.array([-tw]), nd.array([tw]),
        nd.array([-tb]), nd.array([tb]), num_hidden=3)
    real = nd.contrib.dequantize(out_q, omin, omax).asnumpy()
    ref = data @ wt.T + bias
    rel = np.abs(real - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantized_pooling():
    data = np.arange(-8, 8, dtype=np.float32).reshape(1, 1, 4, 4)
    q, t = _quantize_np(data)
    out, mn, mx_ = nd.contrib.quantized_pooling(
        nd.array(q), nd.array([-t]), nd.array([t]),
        kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert out.dtype == np.int8
    real = nd.contrib.dequantize(out, mn, mx_).asnumpy()
    ref = nd.Pooling(nd.array(data), kernel=(2, 2), stride=(2, 2),
                     pool_type="max").asnumpy()
    np.testing.assert_allclose(real, ref, atol=t / 127 + 1e-6)


def _lenet_ish():
    data_s = sym.var("data")
    c1 = sym.Convolution(data_s, kernel=(3, 3), num_filter=8, name="conv1")
    r1 = sym.Activation(c1, act_type="relu")
    p1 = sym.Pooling(r1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool1")
    f1 = sym.Flatten(p1, name="flat1")
    fc = sym.FullyConnected(f1, num_hidden=10, name="fc1")
    return sym.SoftmaxOutput(fc, sym.var("softmax_label"), name="softmax")


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_model(calib_mode):
    rng = np.random.RandomState(0)
    out = _lenet_ish()
    xs = nd.array(rng.rand(4, 3, 8, 8).astype(np.float32))
    arg_shapes, _, _ = out.infer_shape(data=(4, 3, 8, 8), softmax_label=(4,))
    args = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        args[n] = nd.array((rng.randn(*s) * 0.1).astype(np.float32))
    calib = NDArrayIter(data=xs.asnumpy(), label=np.zeros(4), batch_size=4)
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        out, args, {}, calib_mode=calib_mode,
        calib_data=None if calib_mode == "none" else calib)
    # int8 weights stored offline
    assert any(n.endswith("_quantize") for n in qargs)
    assert qargs["conv1_weight_quantize"].dtype == np.int8
    ex_q = qsym.bind(mx.cpu(), {**qargs, "data": xs,
                                "softmax_label": nd.zeros((4,))})
    q_out = ex_q.forward(is_train=False)[0].asnumpy()
    ex_fp = out.bind(mx.cpu(), {**args, "data": xs,
                                "softmax_label": nd.zeros((4,))})
    f_out = ex_fp.forward(is_train=False)[0].asnumpy()
    assert np.abs(q_out - f_out).max() < 0.15
    if calib_mode != "entropy":
        # KL calibration may clip near-tie logits on random weights;
        # exact argmax agreement is only guaranteed for naive/none ranges
        assert (q_out.argmax(1) == f_out.argmax(1)).all()


def test_quantize_model_excluded_layer():
    out = _lenet_ish()
    rng = np.random.RandomState(0)
    arg_shapes, _, _ = out.infer_shape(data=(4, 3, 8, 8), softmax_label=(4,))
    args = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        args[n] = nd.array((rng.randn(*s) * 0.1).astype(np.float32))
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        out, args, {}, calib_mode="none", excluded_sym_names=["fc1"])
    assert "fc1_weight" in qsym.list_arguments()
    assert "fc1_weight_quantize" not in qsym.list_arguments()
    assert "conv1_weight_quantize" in qsym.list_arguments()


def test_quantize_model_with_batchnorm():
    """BN networks quantize end-to-end (regression: quantize_graph used to
    index hidden outputs of multi-output nodes like BatchNorm and crash
    with IndexError on every BN model, e.g. the ResNet zoo)."""
    rng = np.random.RandomState(1)
    data = sym.var("data")
    h = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        no_bias=True, name="convq")
    h = sym.BatchNorm(h, fix_gamma=False, name="bnq")
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    out = sym.FullyConnected(sym.Flatten(h), num_hidden=4, name="fcq")

    xs = nd.array(rng.rand(4, 3, 8, 8).astype(np.float32))
    arg_shapes, _, aux_shapes = out.infer_shape(data=(4, 3, 8, 8))
    args, auxs = {}, {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n != "data":
            args[n] = nd.array((rng.randn(*s) * 0.1).astype(np.float32))
    for n, s in zip(out.list_auxiliary_states(), aux_shapes):
        auxs[n] = nd.array(
            np.ones(s, np.float32) if "var" in n else np.zeros(s, np.float32))

    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        out, args, auxs, calib_mode="none", calib_data=None)
    assert any(n.endswith("_quantize") for n in qargs)
    ex_q = qsym.bind(mx.cpu(), {**qargs, "data": xs}, aux_states=qauxs)
    q_out = ex_q.forward(is_train=False)[0].asnumpy()
    ex_f = out.bind(mx.cpu(), {**args, "data": xs}, aux_states=auxs)
    f_out = ex_f.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(q_out).all()
    assert np.abs(q_out - f_out).max() < 0.25
