"""Distributed kvstore tests: process-level fake cluster on one machine.

Parity model: tests/nightly/test_all.sh:55 + dist_sync_kvstore.py — fork N
worker processes with the launcher env and check exact cross-rank sums.
Also unit tests of the 2-bit gradient compressor (reference
tests/nightly/test_kvstore.py compression correctness).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore_compression import GradientCompression

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestGradientCompression:
    def test_quantize_with_error_feedback(self):
        gc = GradientCompression(threshold=0.5)
        import jax.numpy as jnp
        g = jnp.asarray(np.array([0.9, -0.9, 0.1, 0.0], np.float32))
        q1 = np.asarray(gc.compress("k", g))
        np.testing.assert_allclose(q1, [0.5, -0.5, 0.0, 0.0])
        # residual [0.4, -0.4, 0.1, 0] feeds back
        q2 = np.asarray(gc.compress("k", jnp.asarray(
            np.array([0.2, -0.2, 0.5, 0.0], np.float32))))
        np.testing.assert_allclose(q2, [0.5, -0.5, 0.5, 0.0])
        # cumulative quantized sum tracks the true sum within threshold
        total_true = np.array([1.1, -1.1, 0.6, 0.0])
        np.testing.assert_allclose(np.abs((q1 + q2) - total_true).max(),
                                   0.1, atol=1e-6)

    def test_pack_unpack_wire_format(self):
        vals = np.array([0.5, -0.5, 0.0] * 11, np.float32)  # 33 elems
        words = GradientCompression.pack(vals)
        assert words.dtype == np.uint32
        assert len(words) == 3                      # ceil(33/16)
        back = GradientCompression.unpack(words, len(vals), 0.5)
        np.testing.assert_allclose(back, vals)
        # 16x compression for fp32 payloads
        assert words.nbytes * 16 >= vals.nbytes

    def test_bad_params_rejected(self):
        with pytest.raises(mx.MXNetError):
            GradientCompression(type="1bit")
        with pytest.raises(mx.MXNetError):
            GradientCompression(threshold=0.0)
        kv = mx.kv.create("local")
        with pytest.raises(mx.MXNetError):
            kv.set_gradient_compression({"type": "2bit", "bogus": 1})

    def test_kvstore_api(self):
        kv = mx.kv.create("local")
        assert kv.gradient_compression is None
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        assert kv.gradient_compression.threshold == 0.5


@pytest.mark.skipif(os.environ.get("MXNET_SKIP_DIST_TESTS") == "1",
                    reason="dist tests disabled")
def test_dist_sync_kvstore_two_workers():
    """Fork a 2-worker local cluster through tools/launch.py machinery."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    worker = os.path.join(REPO, "tests", "dist_sync_kvstore_worker.py")
    # PYTHONPATH = repo only: an accelerator sitecustomize (e.g. axon's)
    # would initialize JAX backends before jax.distributed.initialize runs
    env = {"JAX_PLATFORMS": "cpu", "MXNET_NO_NATIVE": "0",
           "PYTHONPATH": REPO}
    rc = launch.launch_local(2, [sys.executable, worker], env_extra=env)
    assert rc == 0


def test_launch_cli_single_worker(tmp_path):
    """launch.py CLI end to end with a trivial command."""
    marker = tmp_path / "ran.txt"
    script = tmp_path / "job.py"
    script.write_text(
        "import os\n"
        "with open(%r, 'a') as f:\n"
        "    f.write(os.environ['DMLC_WORKER_ID'] + '\\n')\n" % str(marker))
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(script)],
        capture_output=True, text=True).returncode
    assert rc == 0
    ids = sorted(marker.read_text().split())
    assert ids == ["0", "1"]


@pytest.mark.skipif(os.environ.get("MXNET_SKIP_DIST_TESTS") == "1",
                    reason="dist tests disabled")
def test_dist_lenet_training_two_workers():
    """dist_lenet-style e2e (ref tests/nightly/dist_lenet.py): 2 forked
    workers train with dist_sync, assert convergence + cross-rank param
    equality + row_sparse pull."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    worker = os.path.join(REPO, "tests", "dist_lenet_worker.py")
    env = {"JAX_PLATFORMS": "cpu", "MXNET_NO_NATIVE": "0",
           "PYTHONPATH": REPO}
    rc = launch.launch_local(2, [sys.executable, worker], env_extra=env)
    assert rc == 0
