"""Symbol + Executor tests (parity: tests/python/unittest/test_symbol.py,
test_executor.py, test_infer_shape.py)."""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _mlp():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_compose_and_listing():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]
    assert net.list_auxiliary_states() == []


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (16, 100)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (10, 16)
    assert d["softmax_label"] == (32,)
    assert out_shapes == [(32, 10)]


def test_infer_shape_conv():
    data = sym.Variable("data")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv1")
    p = sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, _ = p.infer_shape(data=(4, 3, 32, 32))
    d = dict(zip(p.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["conv1_bias"] == (8,)
    assert out_shapes == [(4, 8, 16, 16)]


def test_batchnorm_aux():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn")
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    assert "bn_gamma" in bn.list_arguments()
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(2, 4, 8, 8))
    assert aux_shapes == [(4,), (4,)]
    assert out_shapes == [(2, 4, 8, 8)]


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    parsed = json.loads(js)
    assert "nodes" in parsed and "heads" in parsed
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # inference still works after roundtrip
    _, out_shapes, _ = net2.infer_shape(data=(8, 50))
    assert out_shapes == [(8, 10)]


def test_simple_bind_forward():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 20))
    for name in ("fc1_weight", "fc2_weight"):
        ex.arg_dict[name][:] = nd.random.normal(0, 0.1,
                                                shape=ex.arg_dict[name].shape)
    out = ex.forward(is_train=False, data=nd.ones((4, 20)))[0]
    assert out.shape == (4, 10)
    s = out.asnumpy().sum(axis=1)
    assert np.allclose(s, 1.0, atol=1e-5)  # softmax rows sum to 1


def test_executor_backward():
    x = sym.Variable("x")
    y = sym.Variable("y")
    z = (x * y).sum()
    xv = nd.array([1.0, 2.0, 3.0])
    yv = nd.array([4.0, 5.0, 6.0])
    gx = nd.zeros((3,))
    gy = nd.zeros((3,))
    ex = z.bind(ctx=mx.cpu(), args={"x": xv, "y": yv},
                args_grad={"x": gx, "y": gy})
    out = ex.forward(is_train=True)[0]
    assert np.isclose(out.asscalar(), 32.0)
    ex.backward()
    assert np.allclose(gx.asnumpy(), [4, 5, 6])
    assert np.allclose(gy.asnumpy(), [1, 2, 3])


def test_softmax_output_backward():
    data = sym.Variable("data")
    out = sym.SoftmaxOutput(data, name="sm")
    ex = out.simple_bind(ctx=mx.cpu(), grad_req="write", data=(2, 3))
    dat = nd.array([[1.0, 2.0, 3.0], [1.0, 1.0, 1.0]])
    lab = nd.array([2.0, 0.0])
    ex.forward(is_train=True, data=dat, sm_label=lab)
    ex.backward()
    p = ex.outputs[0].asnumpy()
    g = ex.grad_dict["data"].asnumpy()
    oh = np.eye(3)[[2, 0]]
    assert np.allclose(g, p - oh, atol=1e-5)


def test_group_and_getitem():
    a = sym.Variable("a")
    b = a * 2.0
    c = a + 1.0
    g = sym.Group([b, c])
    assert len(g.list_outputs()) == 2
    first = g[0]
    assert len(first.list_outputs()) == 1


def test_internals():
    net = _mlp()
    internals = net.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names
    feat = internals["fc1_output"]
    _, out_shapes, _ = feat.infer_shape(data=(2, 8))
    assert out_shapes == [(2, 16)]


def test_eval():
    a = sym.Variable("a")
    b = a * 3.0
    out = b.eval(ctx=mx.cpu(), a=nd.array([1.0, 2.0]))[0]
    assert np.allclose(out.asnumpy(), [3, 6])


def test_grad_req_add_executor():
    x = sym.Variable("x")
    y = (x * x).sum()
    xv = nd.array([2.0])
    gx = nd.zeros((1,))
    ex = y.bind(ctx=mx.cpu(), args={"x": xv}, args_grad={"x": gx},
                grad_req="add")
    for _ in range(2):
        ex.forward(is_train=True)
        ex.backward()
    assert np.allclose(gx.asnumpy(), [8.0])


def test_attr_scope():
    with mx.AttrScope(ctx_group="dev1"):
        a = sym.Variable("a")
        b = sym.FullyConnected(a, num_hidden=4, name="fc")
    assert b.attr("ctx_group") == "dev1"


def test_variable_shape_attr():
    v = sym.Variable("x", shape=(3, 4))
    assert v.attr("__shape__") == (3, 4)
