"""Gang member for the chaos acceptance tests (ISSUE 13 tentpole).

Launched by tests/test_chaos.py through ElasticRunner with a 3-member
gang: elastic rank 0 becomes the parameter server (DMLC_ROLE=server with
MXNET_KVSTORE_DURABLE_DIR), ranks 1..N become dist_async workers running
a least-squares regression with a server-side optimizer.  Faults are
injected by mxnet_tpu.chaos from MXNET_CHAOS_* env set by the test —
worker death (MXNET_CHAOS_DIE_AT_STEP), server death
(MXNET_CHAOS_DIE_AT_PUSH), wire faults (drop/delay/corrupt) — always
gated to generation 0 via MXNET_CHAOS_ONLY_GEN, so the relaunched gang
runs clean and the test can assert recovery.

Each worker appends "gen step loss" lines to <logdir>/loss_rank<k>.log so
the test can check the resumed loss trajectory continues where the killed
generation left off instead of restarting from scratch.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

LOGDIR = sys.argv[1]
TOTAL = int(sys.argv[2])

ERANK = int(os.environ["MXNET_ELASTIC_RANK"])
GEN = int(os.environ["MXNET_ELASTIC_RESTART"])
NWORKERS = int(os.environ["MXNET_ELASTIC_NWORKERS"]) - 1  # minus server


def run_server():
    os.environ["DMLC_ROLE"] = "server"
    os.environ["DMLC_NUM_WORKER"] = str(NWORKERS)
    import mxnet_tpu as mx
    mx.kv.create("dist_async")  # enters run_server(); returns on stop
    sys.exit(0)


def run_worker():
    rank = ERANK - 1
    os.environ["DMLC_ROLE"] = "worker"
    os.environ["DMLC_NUM_WORKER"] = str(NWORKERS)
    os.environ["DMLC_WORKER_ID"] = str(rank)
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import chaos, nd

    kv = mx.kv.create("dist_async")
    rng = np.random.RandomState(100 + rank)
    w_true = np.array([[1.0], [-2.0], [3.0]], np.float32)
    X = rng.randn(128, 3).astype(np.float32)
    y = X @ w_true

    kv.init("w", nd.zeros((3, 1)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))
    kv.barrier()

    w = nd.zeros((3, 1))
    log = open(os.path.join(LOGDIR, "loss_rank%d.log" % rank), "a")
    for step in range(TOTAL):
        kv.pull("w", out=w)
        i = (step * 32) % 96
        xb, yb = nd.array(X[i:i + 32]), nd.array(y[i:i + 32])
        resid = nd.dot(xb, w) - yb
        loss = float((resid.asnumpy() ** 2).mean())
        log.write("%d %d %.6f\n" % (GEN, step, loss))
        log.flush()
        grad = nd.dot(xb.T, resid) / 32
        kv.push("w", grad)
        chaos.step(step + 1)
    kv.barrier()

    kv.pull("w", out=w)
    err = float(np.abs(w.asnumpy() - w_true).max())
    print("rank %d gen %d final err %.4f" % (rank, GEN, err))
    assert err < 0.05, "chaos run did not converge: err=%.4f" % err
    kv.barrier()
    if rank == 0:
        with open(os.path.join(LOGDIR, "final.txt"), "w") as f:
            f.write("%g\n" % err)
        kv.send_command_to_servers(0, "")  # kStopServer
    kv.close()


if __name__ == "__main__":
    if ERANK == 0:
        run_server()
    else:
        run_worker()
