"""Fleet control plane (mxnet_tpu/telemetry/fleet.py + tools/fleetwatch.py).

Covers the endpoint-file discovery protocol (register / heartbeat /
stale-reap / torn writes), the client-side histogram-quantile mirror and
its off-scale-is-null overflow round trip, the consolidated ``/allz`` +
``/healthz`` + ``/fleetz`` + ``POST /flightz`` HTTP surface, the
scrape/merge/derive/alert collector tick (fire-once debounce, resolve,
absence, burn-rate coverage gate, page-severity flight-dump capture),
the fleetwatch renderer, and the 2-process dist acceptance run: two
workers + one kvstore server register in one fleet dir, the collector
in *this* process scrapes and merges them, and the injected straggler
fires the burn-rate page end-to-end (runlog event + flight dump on the
offending rank only).
"""
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from mxnet_tpu import runlog, telemetry, tracing
from mxnet_tpu.telemetry import fleet, timeseries
from mxnet_tpu.telemetry.fleet import AlertRule, FleetStore

import fleetwatch
import merge_traces


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    fleet.reset()
    yield
    fleet.reset()
    runlog.disable()
    telemetry.stop_http_server()
    telemetry.disable()
    telemetry.reset()


def _get_json(port, path):
    url = "http://127.0.0.1:%d%s" % (port, path)
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode("utf-8"))


# ---------------------------------------------------------------------------
# histogram-quantile overflow: null must survive the scrape round trip
# ---------------------------------------------------------------------------
class TestQuantileOverflow:
    def test_overflow_round_trip_renders_gtmax(self):
        h = telemetry.histogram("fleet_test_latency_seconds", "t")
        h.observe(0.005)
        h.observe(1e9)  # beyond the largest finite bucket
        assert h.quantile(0.99) == float("inf")
        # scrape -> JSON -> parse, exactly what the collector sees
        snap = json.loads(telemetry.snapshot_json())
        sample = snap["fleet_test_latency_seconds"]["samples"][0]
        # off-scale is null, never 0 (0 would read as "instant")
        assert fleet.quantile_from_buckets(sample, 0.99) is None
        p50 = fleet.quantile_from_buckets(sample, 0.5)
        assert p50 == pytest.approx(h.quantile(0.5))
        assert p50 > 0.0
        # the dashboard renders the null as >max, not a number
        assert fleetwatch._fmt_val(None, "p99") == ">max"
        assert fleetwatch._fmt_val(None, "p50") == ">max"
        assert fleetwatch._fmt_val(None, "value") == "-"

    def test_overflow_null_survives_store_snapshot(self):
        store = FleetStore(interval=0.5)
        now = time.time()
        store.push_rows([("serving_request_seconds", "p99",
                          {"rank": "worker0"}, "histogram", None)], now)
        key = timeseries.series_key("serving_request_seconds", "p99",
                                    {"rank": "worker0"})
        snap = json.loads(json.dumps(store.snapshot(window_seconds=30.0,
                                                    now=now)))
        pts = snap[key]["tiers"][0]["points"]
        assert pts and pts[-1][1] is None  # JSON null, not 0

    def test_edge_cases(self):
        assert fleet.quantile_from_buckets(
            {"buckets": {"+Inf": 0}, "count": 0}, 0.99) == 0.0
        # every observation in the overflow bucket
        assert fleet.quantile_from_buckets(
            {"buckets": {"0.5": 0, "+Inf": 3}, "count": 3}, 0.5) is None


# ---------------------------------------------------------------------------
# endpoint files: register / discover / heartbeat / reap
# ---------------------------------------------------------------------------
class TestEndpointDiscovery:
    def test_register_discover_unregister(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DMLC_ROLE", raising=False)
        monkeypatch.delenv("DMLC_WORKER_ID", raising=False)
        path = fleet.register_endpoint(12345, fleet_dir=str(tmp_path))
        assert path and os.path.exists(path)
        found = fleet.discover(str(tmp_path))
        assert set(found) == {"worker0"}
        assert found["worker0"]["port"] == 12345
        assert found["worker0"]["pid"] == os.getpid()
        # idempotent: re-registering replaces the announcement
        path2 = fleet.register_endpoint(23456, fleet_dir=str(tmp_path))
        assert fleet.discover(str(tmp_path))["worker0"]["port"] == 23456
        fleet.unregister_endpoint()
        assert not os.path.exists(path2)
        assert fleet.discover(str(tmp_path)) == {}

    def test_stale_endpoint_reaped(self, tmp_path):
        p = str(tmp_path / "endpoint_worker7_1.json")
        with open(p, "w") as f:
            json.dump({"rank": 7, "role": "worker", "pid": 1,
                       "host": "127.0.0.1", "port": 1, "run_id": "",
                       "unix_time": 0.0}, f)
        old = time.time() - 120.0
        os.utime(p, (old, old))
        before = telemetry.value("fleet_reaped_endpoints_total")
        assert fleet.discover(str(tmp_path), stale_after=30.0) == {}
        assert not os.path.exists(p)
        assert telemetry.value("fleet_reaped_endpoints_total") == before + 1

    def test_torn_write_tolerated(self, tmp_path):
        with open(str(tmp_path / "endpoint_worker0_1.json"), "w") as f:
            f.write("{not json")
        assert fleet.discover(str(tmp_path), stale_after=30.0) == {}

    def test_heartbeat_keeps_mtime_fresh(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_FLEET_HEARTBEAT", "0.05")
        path = fleet.register_endpoint(1, fleet_dir=str(tmp_path))
        old = time.time() - 120.0
        os.utime(path, (old, old))
        deadline = time.time() + 5.0
        while (os.stat(path).st_mtime < time.time() - 60.0
               and time.time() < deadline):
            time.sleep(0.05)
        assert os.stat(path).st_mtime > time.time() - 60.0


# ---------------------------------------------------------------------------
# HTTP surface: /allz, /healthz, /fleetz, POST /flightz
# ---------------------------------------------------------------------------
class TestHttpEndpoints:
    def test_allz_and_healthz(self):
        telemetry.gauge("step_seconds_ewma", "t").set(0.05)
        port = telemetry.start_http_server(0)
        doc = _get_json(port, "/allz?window=5")
        assert "unix_time" in doc and "healthz" in doc
        ewma = doc["metrics"]["step_seconds_ewma"]["samples"][0]
        assert ewma["value"] == pytest.approx(0.05)
        hz = _get_json(port, "/healthz")
        assert hz["status"] in ("ok", "degraded")

    def test_fleetz_404_without_collector(self, tmp_path):
        port = telemetry.start_http_server(0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(port, "/fleetz")
        assert ei.value.code == 404
        fleet.start_collector(fleet_dir=str(tmp_path), interval=5.0)
        doc = _get_json(port, "/fleetz?window=30")
        assert doc["fleet_dir"] == str(tmp_path)
        assert "aggregates" in doc and "alerts" in doc

    def test_flightz_post_triggers_dump(self, tmp_path, monkeypatch):
        dump = str(tmp_path / "flight.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", dump)
        port = telemetry.start_http_server(0)
        req = urllib.request.Request(
            "http://127.0.0.1:%d/flightz?reason=unit%%20page!" % port,
            data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=5) as resp:
            body = json.loads(resp.read().decode("utf-8"))
        assert body["path"] == dump
        doc = json.load(open(dump))
        assert doc["reason"] == "unit_page_"  # shell-unsafe chars scrubbed
        assert merge_traces.is_flight_dump(doc)
        assert merge_traces.validate_flight_dump(doc) == []

    def test_collector_dump_embeds_fleet_block(self, tmp_path, monkeypatch):
        dump = str(tmp_path / "flight_collector.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", dump)
        port = telemetry.start_http_server(0)
        fleet.register_endpoint(port, fleet_dir=str(tmp_path))
        c = fleet.start_collector(fleet_dir=str(tmp_path), interval=5.0)
        c.sweep()
        path = tracing.flight.dump(reason="manual")
        doc = json.load(open(path))
        assert "fleet" in doc
        assert set(doc["fleet"]["targets"])  # our own endpoint, merged
        assert merge_traces.validate_flight_dump(doc) == []


# ---------------------------------------------------------------------------
# collector tick: merge, derive, alert state machine
# ---------------------------------------------------------------------------
class TestCollectorAlerting:
    def test_fire_once_debounce_resolve(self, tmp_path, monkeypatch):
        dump = str(tmp_path / "flight_self.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", dump)
        rl = str(tmp_path / "runlog.jsonl")
        runlog.enable(rl)
        g = telemetry.gauge("step_seconds_ewma", "t")
        g.set(0.05)  # fleet step rate 20/s
        port = telemetry.start_http_server(0)
        fleet.register_endpoint(port, fleet_dir=str(tmp_path))
        fleet.register_rule(AlertRule(
            "t_slow_fleet", kind="threshold", severity="page",
            metric="fleet_step_rate", op="<", threshold=100.0,
            offender="step_seconds", help="unit-test rule"), replace=True)
        c = fleet.FleetCollector(fleet_dir=str(tmp_path), interval=0.2,
                                 debounce=60.0)
        now = time.time()

        def fired():
            return telemetry.value("fleet_alerts_total",
                                   rule="t_slow_fleet", severity="page")

        c.sweep(now)
        assert fired() == 1
        # the scrape merged rank-attributed and counted itself
        assert c.store.latest("step_seconds_ewma", "value",
                              "worker0") == pytest.approx(0.05)
        assert telemetry.value("fleet_scrape_total", target="worker0") == 1
        assert telemetry.value("fleet_alerts_active", severity="page") == 1
        # page severity POSTed the offender's flight-dump trigger
        assert os.path.exists(dump)
        # still firing on the next tick: edge-triggered, no refire
        c.sweep(now + 0.2)
        assert fired() == 1
        # condition clears -> resolve
        g.set(0.001)
        c.sweep(now + 0.4)
        assert not any(a["rule"] == "t_slow_fleet"
                       for a in c.active_alerts())
        assert telemetry.value("fleet_alerts_active", severity="page") == 0
        # condition back inside the debounce window -> still no refire
        g.set(0.05)
        c.sweep(now + 0.6)
        assert fired() == 1
        # ... and past the window it pages again
        c.sweep(now + 61.0)
        assert fired() == 2
        events = [json.loads(line) for line in open(rl) if line.strip()]
        alerts = [e for e in events if e["event"] == "fleet_alert"
                  and e["rule"] == "t_slow_fleet"]
        resolved = [e for e in events if e["event"] == "fleet_alert_resolved"
                    and e["rule"] == "t_slow_fleet"]
        assert len(alerts) == 2 and len(resolved) == 1
        assert alerts[0]["offender"] == "worker0"
        assert alerts[0]["flight_dump"] == dump

    def test_absence_fires_for_dead_target(self, tmp_path):
        fleet.register_rule(AlertRule("t_absent", kind="absence",
                                      severity="warn", threshold=0.5),
                            replace=True)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        with open(str(tmp_path / "endpoint_worker3_99.json"), "w") as f:
            json.dump({"rank": 3, "role": "worker", "pid": 99,
                       "host": "127.0.0.1", "port": dead_port,
                       "run_id": "", "unix_time": time.time()}, f)
        c = fleet.FleetCollector(fleet_dir=str(tmp_path), interval=0.2,
                                 timeout=0.5, debounce=60.0)
        now = time.time()
        c.sweep(now)
        assert telemetry.value("fleet_scrape_errors_total",
                               target="worker3") >= 1
        assert telemetry.value("fleet_alerts_total", rule="t_absent",
                               severity="warn") == 0
        c.sweep(now + 1.0)  # never scraped for 1.0s > 0.5s threshold
        assert telemetry.value("fleet_alerts_total", rule="t_absent",
                               severity="warn") == 1
        assert any(a["rule"] == "t_absent" and a["group"] == "worker3"
                   for a in c.active_alerts())

    def test_burn_rate_needs_long_window_coverage(self):
        rule = AlertRule("t_burn", kind="burn_rate", severity="page",
                         metric="fleet_straggler_skew", threshold=1.75,
                         windows=(2.0, 4.0))
        row = ("fleet_straggler_skew", "value", {"rank": "fleet"},
               "gauge", 1.9)
        t0 = time.time()
        # one hot sample: above threshold but no long-window coverage
        store = FleetStore(interval=0.5)
        store.push_rows([row], t0 - 0.1)
        (_, _, firing), = rule.conditions(store, t0)
        assert not firing
        # 4s of sustained skew: both windows above the band -> fires
        store = FleetStore(interval=0.5)
        for i in range(9):
            store.push_rows([row], t0 - 4.0 + i * 0.5)
        (_, value, firing), = rule.conditions(store, t0)
        assert firing and value == pytest.approx(1.9)
        # skew recovers: the short window drops below -> stops firing
        calm = ("fleet_straggler_skew", "value", {"rank": "fleet"},
                "gauge", 1.0)
        for i in range(3):
            store.push_rows([calm], t0 + 0.5 + i * 1.0)
        (_, _, firing), = rule.conditions(store, t0 + 2.5)
        assert not firing

    def test_rule_registry_guards(self):
        with pytest.raises(ValueError):
            AlertRule("bad", kind="nope")
        with pytest.raises(ValueError):
            AlertRule("bad", kind="threshold", metric="m", severity="loud")
        with pytest.raises(ValueError):
            AlertRule("bad", kind="burn_rate", metric="m")  # no windows
        with pytest.raises(ValueError):  # duplicate without replace=
            fleet.register_rule(AlertRule(
                "straggler_skew_burn", kind="threshold", metric="m",
                threshold=1.0))
        assert {r.name for r in fleet.rules()} >= {
            "straggler_skew_burn", "scrape_absence", "fleet_mfu_drop",
            "hbm_pressure"}


# ---------------------------------------------------------------------------
# fleetwatch rendering
# ---------------------------------------------------------------------------
class TestFleetwatch:
    def test_render_live_doc(self, tmp_path):
        telemetry.gauge("step_seconds_ewma", "t").set(0.05)
        port = telemetry.start_http_server(0)
        fleet.register_endpoint(port, fleet_dir=str(tmp_path))
        c = fleet.start_collector(fleet_dir=str(tmp_path), interval=5.0)
        c.sweep()
        out = fleetwatch.render(fleet.fleetz(window=30.0))
        assert "worker0" in out and "targets=1" in out
        # the same doc survives a JSON round trip (what --format json and
        # --snapshot/--diff consume)
        out2 = fleetwatch.render(json.loads(json.dumps(
            fleet.fleetz(window=30.0))))
        assert "worker0" in out2


# ---------------------------------------------------------------------------
# 2-process fleet acceptance: workers + kvstore server, end-to-end page
# ---------------------------------------------------------------------------
class TestDistFleet:
    def test_two_worker_fleet_straggler_page(self, tmp_path, monkeypatch):
        import launch

        fleet_dir = str(tmp_path / "fleet")
        os.makedirs(fleet_dir)
        rl = str(tmp_path / "runlog.jsonl")
        runlog.enable(rl)
        # shrink the burn windows so sustained == a few seconds
        monkeypatch.setenv("MXNET_FLEET_BURN_SHORT", "1.5")
        monkeypatch.setenv("MXNET_FLEET_BURN_LONG", "3.0")
        fleet.reset_rules()

        worker = os.path.join(REPO, "tests", "fleet_worker.py")
        rc_box = {}

        def _run():
            rc_box["rc"] = launch.launch_local(
                2, [sys.executable, worker],
                env_extra={"JAX_PLATFORMS": "cpu",
                           "MXNET_TEST_PLATFORM": "cpu",
                           "MXNET_TELEMETRY": "1",
                           "MXNET_TELEMETRY_PORT": "0",
                           "MXNET_TELEMETRY_TS": "0",
                           "MXNET_HEALTH": "1",
                           "MXNET_FLEET_DIR": fleet_dir},
                num_servers=1)

        job = threading.Thread(target=_run, daemon=True)
        job.start()
        try:
            fleet.start_collector(fleet_dir=fleet_dir, interval=0.3,
                                  debounce=60.0)
            port = telemetry.start_http_server(0)

            def fired():
                return telemetry.value("fleet_alerts_total",
                                       rule="straggler_skew_burn",
                                       severity="page")

            deadline = time.time() + 120.0
            while fired() < 1 and time.time() < deadline:
                time.sleep(0.3)
            assert fired() == 1, "straggler burn-rate page never fired"

            # merged view over HTTP: every process, rank-attributed
            doc = _get_json(port, "/fleetz?window=60")
            assert set(doc["targets"]) == {"worker0", "worker1", "server0"}
            for rank in ("worker0", "worker1"):
                key = timeseries.series_key("step_seconds_ewma", "value",
                                            {"rank": rank})
                assert key in doc["series"], sorted(doc["series"])
            # skew = slow/median = 0.2 / median([0.01, 0.2])
            assert doc["aggregates"]["straggler_skew"] == pytest.approx(
                0.2 / 0.105, rel=0.05)
            assert doc["aggregates"]["per_rank"]["worker1"][
                "step_seconds"] == pytest.approx(0.2, rel=0.05)

            # exactly once: the condition persists but debounce holds
            time.sleep(1.2)
            assert fired() == 1

            # the page POSTed the offending rank's flight-dump trigger
            dump = os.path.join(fleet_dir, "flight_worker1.json")
            deadline = time.time() + 15.0
            while not os.path.exists(dump) and time.time() < deadline:
                time.sleep(0.1)
            assert os.path.exists(dump), "offender flight dump missing"
            assert not os.path.exists(
                os.path.join(fleet_dir, "flight_worker0.json"))
            dumped = json.load(open(dump))
            assert dumped["reason"] == "fleet_alert.straggler_skew_burn"
            assert merge_traces.validate_flight_dump(dumped) == []

            events = [json.loads(line) for line in open(rl)
                      if line.strip()]
            alerts = [e for e in events if e["event"] == "fleet_alert"
                      and e["rule"] == "straggler_skew_burn"]
            assert len(alerts) == 1
            assert alerts[0]["offender"] == "worker1"
            assert alerts[0]["severity"] == "page"
            assert alerts[0]["flight_dump"] == dump
        finally:
            open(os.path.join(fleet_dir, "stop"), "w").close()
            job.join(120.0)
        assert not job.is_alive(), "dist job did not wind down"
        assert rc_box.get("rc") == 0
