"""CI schema guard for the input-pipeline benchmark: `bench_io --smoke`
must exit 0 and emit one JSON line per path (pipelined + bare) with the
stable field set other tooling parses."""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_io_smoke_schema():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.bench_io", "--smoke"],
        cwd=_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.startswith("{")]
    assert len(lines) == 2, proc.stdout
    assert [l["pipelined"] for l in lines] == [False, True]
    for line in lines:
        assert line["metric"] == "imagerecorditer_img_per_sec"
        assert line["value"] > 0
