"""Causal tracing layer: engine flow events, cross-process KVStore trace
propagation + merge_traces round-trip, jit-cache observability, and the
flight recorder (see docs/observability.md "Tracing")."""
import io
import json
import os
import signal
import struct
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, telemetry, tracing
from mxnet_tpu import engine as engine_mod
from mxnet_tpu import kvstore_server as kvs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore_server import KVStoreServer
from mxnet_tpu.ops import registry as op_registry
import mxnet_tpu as _mx
from mxnet_tpu import symbol as sym

import merge_traces


@pytest.fixture(autouse=True)
def _clean_tracing():
    telemetry.reset()
    tracing.disable()
    profiler.set_state("stop")
    with profiler._lock:
        profiler._events.clear()
    tracing.flight.clear()
    yield
    tracing.disable()
    telemetry.disable()
    profiler.set_state("stop")
    with profiler._lock:
        profiler._events.clear()
    tracing.flight.clear()
    telemetry.reset()


def _events():
    with profiler._lock:
        return list(profiler._events)


def _assert_flows_well_formed(events):
    """Every flow step/end has a matching start; start ids are unique."""
    starts = [e["id"] for e in events if e["ph"] == "s"]
    assert len(starts) == len(set(starts)), "duplicate flow-start ids"
    sset = set(starts)
    for e in events:
        if e["ph"] in ("t", "f"):
            assert e["id"] in sset, "dangling flow %s id %r" % (e["ph"],
                                                                e["id"])


# ---------------------------------------------------------------------------
# engine causality
# ---------------------------------------------------------------------------
class TestEngineFlows:
    def test_threaded_engine_flow_events(self):
        tracing.enable()
        profiler.set_state("run")
        eng = engine_mod.ThreadedEngine(2)
        a, b = eng.new_variable("a"), eng.new_variable("b")
        eng.push(lambda: None, mutable_vars=(a,), name="write_a")
        eng.push(lambda: None, const_vars=(a,), mutable_vars=(b,),
                 name="read_a_write_b")
        eng.wait_for_all()
        profiler.set_state("stop")
        ev = _events()
        _assert_flows_well_formed(ev)
        # one full s/t/f triple per push
        for ph in "stf":
            assert len([e for e in ev if e["ph"] == ph]) >= 2
        # the op span carries the Var names it waited on
        op = [e for e in ev if e["name"] == "read_a_write_b"][0]
        assert op["cat"] == "engine_op"
        assert op["args"]["const_vars"] == ["a"]
        assert op["args"]["mutable_vars"] == ["b"]
        # s, t and f of one flow share an id spanning push/exec/complete
        push = [e for e in ev if e["ph"] == "s"
                and e["id"] == op["args"]["flow_id"]]
        fin = [e for e in ev if e["ph"] == "f"
               and e["id"] == op["args"]["flow_id"]]
        assert push and fin
        eng.stop()

    def test_nested_push_joins_parent_trace(self):
        tracing.enable()
        profiler.set_state("run")
        eng = engine_mod.ThreadedEngine(2)
        v = eng.new_variable("outer_v")

        def outer():
            # pushed from the worker thread inside the outer op's span:
            # must inherit its trace
            eng.push(lambda: None, name="inner_op")

        eng.push(outer, mutable_vars=(v,), name="outer_op")
        eng.wait_for_all()
        profiler.set_state("stop")
        ev = _events()
        outer_span = [e for e in ev if e["name"] == "outer_op"][0]
        inner_span = [e for e in ev if e["name"] == "inner_op"][0]
        assert (inner_span["args"]["trace_id"]
                == outer_span["args"]["trace_id"])
        assert (inner_span["args"]["parent_id"]
                == outer_span["args"]["span_id"])
        eng.stop()

    def test_naive_engine_spans(self):
        tracing.enable()
        profiler.set_state("run")
        eng = engine_mod.NaiveEngine()
        v = eng.new_variable("nv")
        eng.push(lambda: None, mutable_vars=(v,), name="naive_op")
        profiler.set_state("stop")
        ev = _events()
        _assert_flows_well_formed(ev)
        op = [e for e in ev if e["name"] == "naive_op"][0]
        assert op["args"]["mutable_vars"] == ["nv"]

    def test_native_engine_flow_events(self):
        try:
            eng = engine_mod.NativeThreadedEngine(2)
        except RuntimeError:
            pytest.skip("native engine unavailable")
        tracing.enable()
        profiler.set_state("run")
        v = eng.new_variable("natv")
        eng.push_sync(lambda: None, mutable_vars=(v,), name="native_op")
        profiler.set_state("stop")
        ev = _events()
        _assert_flows_well_formed(ev)
        op = [e for e in ev if e["name"] == "native_op"][0]
        assert op["args"]["mutable_vars"] == ["natv"]
        assert [e for e in ev if e["ph"] == "f"
                and e["id"] == op["args"]["flow_id"]]
        eng.stop()

    def test_disabled_tracing_adds_no_events(self):
        profiler.set_state("run")
        eng = engine_mod.ThreadedEngine(2)
        v = eng.new_variable("q")
        eng.push(lambda: None, mutable_vars=(v,), name="quiet")
        eng.wait_for_all()
        profiler.set_state("stop")
        assert not [e for e in _events() if e["ph"] in "stf"]
        eng.stop()


# ---------------------------------------------------------------------------
# cross-process propagation: wire format
# ---------------------------------------------------------------------------
class _FakeSock:
    def __init__(self, data=b""):
        self._rx = io.BytesIO(data)
        self.sent = bytearray()

    def sendall(self, b):
        self.sent.extend(b)

    def recv(self, n):
        return self._rx.read(n)


def _frame_with_header(hdr_obj):
    header = json.dumps(hdr_obj).encode()
    payload = struct.pack("<I", len(header)) + header + struct.pack("<I", 0)
    return struct.pack("<Q", len(payload)) + payload


class TestWireTraceContext:
    def test_trace_ctx_roundtrip(self):
        s = _FakeSock()
        kvs.send_msg(s, ("push", "k", np.arange(3.0)),
                     trace_ctx={"t": "a.1", "s": "a.2"})
        msg, tc = kvs.recv_msg_tc(_FakeSock(bytes(s.sent)))
        assert msg[0] == "push" and msg[1] == "k"
        np.testing.assert_array_equal(msg[2], np.arange(3.0))
        assert tc == {"t": "a.1", "s": "a.2"}

    def test_old_format_frames_still_parse(self):
        # untraced send produces the original wire format: header is the
        # bare message list, not the {"m":..., "tc":...} wrapper
        s = _FakeSock()
        kvs.send_msg(s, ("pull", "k"))
        hlen = struct.unpack_from("<I", s.sent, 8)[0]
        assert isinstance(json.loads(bytes(s.sent[12:12 + hlen])), list)
        msg, tc = kvs.recv_msg_tc(_FakeSock(bytes(s.sent)))
        assert msg == ["pull", "k"] and tc is None
        # and the tc-dropping legacy API still works
        assert kvs.recv_msg(_FakeSock(bytes(s.sent))) == ["pull", "k"]

    @pytest.mark.parametrize("hdr", [
        {"m": ["pull", "k"], "tc": {"t": "x", "s": "y", "evil": "z"}},
        {"m": ["pull", "k"], "tc": {"t": "x" * 65, "s": "y"}},
        {"m": ["pull", "k"], "tc": {"t": ""}},
        {"m": ["pull", "k"], "tc": {"t": 5}},
        {"m": ["pull", "k"], "tc": ["not-a-dict"]},
        {"tc": {"t": "x"}},
        {"m": ["pull", "k"], "unknown_key": 1},
    ])
    def test_malformed_trace_ctx_rejected(self, hdr):
        before = telemetry.value("kvstore_frame_errors_total")
        with pytest.raises(MXNetError):
            kvs.recv_msg_tc(_FakeSock(_frame_with_header(hdr)))
        assert telemetry.value("kvstore_frame_errors_total") == before + 1

    def test_in_process_kv_propagation(self, monkeypatch):
        tracing.enable()
        profiler.set_state("run")
        srv = KVStoreServer(num_workers=1).start()
        monkeypatch.setenv("MXNET_PS_URI", "127.0.0.1")
        monkeypatch.setenv("MXNET_PS_PORT", str(srv.port))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_WORKER_ID", "0")
        try:
            kv = mx.kv.create("dist_async")
            kv.init("w", nd.array(np.ones(4, np.float32)))
            kv.push("w", nd.array(np.full(4, 2.0, np.float32)))
            out = nd.zeros(4)
            kv.pull("w", out=out)
            kv.close()
        finally:
            srv.shutdown()
        profiler.set_state("stop")
        ev = _events()
        _assert_flows_well_formed(ev)
        client = [e for e in ev if e["name"] == "KVStore::push"][0]
        server = [e for e in ev if e["name"] == "Server::push"][0]
        # handler adopted the worker's context: same trace, parent link,
        # and its flow-end matches the client span's flow-start
        assert server["args"]["trace_id"] == client["args"]["trace_id"]
        assert server["args"]["parent_id"] == client["args"]["span_id"]
        fins = [e for e in ev if e["ph"] == "f"
                and e["id"] == client["args"]["span_id"]]
        assert fins and fins[0]["bp"] == "e"


# ---------------------------------------------------------------------------
# 2-worker dist run + merge round-trip (acceptance scenario)
# ---------------------------------------------------------------------------
class TestDistTraceMerge:
    def test_two_worker_trace_merge(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import launch

        trace_dir = str(tmp_path / "traces")
        worker = os.path.join(REPO, "tests", "dist_trace_worker.py")
        rc = launch.launch_local(
            2, [sys.executable, worker],
            env_extra={"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu",
                       "MXNET_TRACING": "1", "MXNET_TRACE_DIR": trace_dir},
            num_servers=1)
        assert rc == 0
        files = [os.path.join(trace_dir, f)
                 for f in ("trace_worker0.json", "trace_worker1.json",
                           "trace_server.json")]
        # the server dumps between serve_forever returning and launcher
        # cleanup; give the race a moment
        deadline = time.time() + 10
        while (not all(os.path.exists(f) for f in files)
               and time.time() < deadline):
            time.sleep(0.1)
        assert all(os.path.exists(f) for f in files), os.listdir(trace_dir)

        merged_path = str(tmp_path / "merged.json")
        assert merge_traces.main(["-o", merged_path] + files) == 0
        assert merge_traces.main(["--validate", merged_path]) == 0
        merged = merge_traces.load_trace(merged_path)
        ev = merged["traceEvents"]

        # per-process rows keyed by rank/role
        names = {e["args"]["name"] for e in ev
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names == {"server", "worker 0", "worker 1"}

        # at least one worker push span flow-linked to a server handler
        # span: the client flow-start id reappears as a server-side
        # flow-end on the server's pid
        server_pid = [e["pid"] for e in ev if e["ph"] == "M"
                      and e["name"] == "process_name"
                      and e["args"]["name"] == "server"][0]
        push_spans = [e for e in ev if e["ph"] == "X"
                      and e["name"] == "KVStore::push"
                      and e["pid"] != server_pid]
        assert push_spans
        server_fins = {e["id"] for e in ev if e["ph"] == "f"
                       and e["pid"] == server_pid}
        linked = [e for e in push_spans
                  if e["args"]["span_id"] in server_fins]
        assert linked, "no worker push span flow-linked to a server span"
        handler_spans = [e for e in ev if e["ph"] == "X"
                         and e["name"] == "Server::push"
                         and e["pid"] == server_pid]
        assert handler_spans

    def test_merge_clock_alignment(self, tmp_path):
        def trace(t0, role, rank, ts):
            return {"traceEvents": [{"name": "x", "cat": "c", "ph": "X",
                                     "ts": ts, "dur": 1.0, "pid": 7,
                                     "tid": 1}],
                    "metadata": {"t0_unix_us": t0, "pid": 7,
                                 "rank": rank, "role": role}}

        # worker started 1000us after the server: its events shift +1000
        merged = merge_traces.merge([trace(5000.0, "server", 0, 10.0),
                                     trace(6000.0, "worker", 0, 10.0)])
        xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        by_pid = {e["pid"]: e["ts"] for e in xs}
        assert by_pid[1] == 10.0        # server is the earliest origin
        assert by_pid[100] == 1010.0    # worker shifted by the t0 delta

    def test_validate_catches_bad_flows(self, tmp_path):
        good = {"traceEvents": [
            {"name": "a", "cat": "c", "ph": "s", "id": "1", "ts": 1.0,
             "pid": 1, "tid": 1},
            {"name": "a", "cat": "c", "ph": "f", "id": "1", "ts": 2.0,
             "pid": 1, "tid": 1}]}
        assert merge_traces.validate_trace(good) == []
        bad = {"traceEvents": [
            {"name": "a", "cat": "c", "ph": "f", "id": "orphan", "ts": 1.0,
             "pid": 1, "tid": 1},
            {"name": "b", "cat": "c", "ph": "X", "ts": 1.0, "pid": 1,
             "tid": 1}]}  # X missing dur + orphan flow-end
        errs = merge_traces.validate_trace(bad)
        assert any("no matching start" in e for e in errs)
        assert any("dur" in e for e in errs)

        bad_path = str(tmp_path / "bad.json")
        with open(bad_path, "w") as f:
            json.dump(bad, f)
        assert merge_traces.main(["--validate", bad_path]) == 1


class TestValidateFlightDump:
    """--validate also schema-checks flight-recorder dumps (PR 11)."""

    def _dump(self):
        return {"reason": "test", "role": "local", "rank": "0",
                "unix_time": 1000.0, "pid": 1, "t0_unix_us": 0.0,
                "events": [{"name": "op", "ts_us": 1.0, "dur_us": 2.0,
                            "cat": "engine", "tid": 7, "args": None}],
                "programs": {"step": {"flops": 1e9, "arg_bytes": 8.0,
                                      "out_bytes": 8.0, "env": None}},
                "atlas": {"step": {"coverage_pct": 97.0,
                                   "scopes": [{"scope": "dense",
                                               "flops": 5e8}]}},
                "timeseries": {"window_seconds": 120.0, "interval": 1.0,
                               "series": {"g:value": {
                                   "metric": "g", "stat": "value",
                                   "labels": {},
                                   "points": [[999.0, 1.0],
                                              [1000.0, None]]}}}}

    def test_dispatch_and_clean_dump(self, tmp_path):
        doc = self._dump()
        assert merge_traces.is_flight_dump(doc)
        assert not merge_traces.is_flight_dump({"traceEvents": []})
        assert merge_traces.validate_flight_dump(doc) == []
        p = str(tmp_path / "flight.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        assert merge_traces.main(["--validate", p]) == 0

    def test_blocks_are_optional(self):
        doc = self._dump()
        for block in ("programs", "atlas", "timeseries"):
            del doc[block]
        assert merge_traces.validate_flight_dump(doc) == []

    def test_corrupted_blocks_reported_precisely(self, tmp_path):
        doc = self._dump()
        doc["programs"]["step"]["flops"] = "many"
        doc["atlas"]["step"]["scopes"][0]["flops"] = None
        doc["timeseries"]["series"]["g:value"]["points"][0] = [1.0]
        doc["events"][0].pop("dur_us")
        errs = merge_traces.validate_flight_dump(doc)
        assert any("programs[step]" in e and "flops" in e for e in errs)
        assert any("atlas[step].scopes[0]" in e for e in errs)
        assert any("timeseries[g:value].points[0]" in e for e in errs)
        assert any("events[0]" in e and "dur_us" in e for e in errs)
        p = str(tmp_path / "bad_flight.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        assert merge_traces.main(["--validate", p]) == 1


# ---------------------------------------------------------------------------
# compile observability
# ---------------------------------------------------------------------------
class TestJitCacheObservability:
    @pytest.fixture
    def temp_op(self):
        name = "_test_tracing_identity"

        @op_registry.register(name, env_keys=("MXNET_TRACING_TEST_FLAG",))
        def _identity(attrs, x):
            return x * 1.0

        yield op_registry.get_op(name)
        op_registry.OPS.pop(name, None)

    def test_hit_miss_counters_around_env_toggle(self, temp_op, monkeypatch):
        telemetry.enable()
        name = temp_op.name
        attrs = temp_op.parse_attrs({})
        x = np.ones(3, np.float32)

        monkeypatch.delenv("MXNET_TRACING_TEST_FLAG", raising=False)
        temp_op(attrs, x)
        assert telemetry.value("op_jit_cache_misses_total", op=name) == 1
        assert telemetry.value("op_jit_cache_hits_total", op=name) == 0
        entries0 = telemetry.value("op_jit_cache_entries")
        # first invocation observed into the compile-duration histogram
        assert telemetry.value("op_compile_seconds", op=name) == 1

        temp_op(attrs, x)
        assert telemetry.value("op_jit_cache_hits_total", op=name) == 1
        assert telemetry.value("op_jit_cache_misses_total", op=name) == 1

        # env_keys toggle: new cache key -> miss + new entry
        monkeypatch.setenv("MXNET_TRACING_TEST_FLAG", "1")
        temp_op(attrs, x)
        assert telemetry.value("op_jit_cache_misses_total", op=name) == 2
        assert telemetry.value("op_jit_cache_entries") == entries0 + 1
        assert telemetry.value("op_compile_seconds", op=name) == 2

        # toggling back serves the original (still-live) entry
        monkeypatch.delenv("MXNET_TRACING_TEST_FLAG")
        temp_op(attrs, x)
        assert telemetry.value("op_jit_cache_hits_total", op=name) == 2
        assert telemetry.value("op_jit_cache_misses_total", op=name) == 2

    def test_jit_metrics_in_metrics_scrape(self, temp_op):
        telemetry.enable()
        temp_op(temp_op.parse_attrs({}), np.ones(2, np.float32))
        text = telemetry.prometheus_text()
        assert 'op_jit_cache_misses_total{op="%s"} 1' % temp_op.name in text
        assert "op_jit_cache_hits_total" in text
        assert "op_jit_cache_entries" in text
        assert 'op_compile_seconds_count{op="%s"} 1' % temp_op.name in text

    def test_compile_span_recorded(self, temp_op):
        profiler.set_state("run")
        temp_op(temp_op.parse_attrs({}), np.ones(2, np.float32))
        temp_op(temp_op.parse_attrs({}), np.ones(2, np.float32))
        profiler.set_state("stop")
        spans = [e for e in _events()
                 if e["name"] == "XLA::Compile %s" % temp_op.name]
        assert len(spans) == 1  # only the first invocation compiles
        assert spans[0]["cat"] == "compile"

    def test_executor_first_run_flag(self):
        profiler.set_state("run")
        a = sym.var("a")
        ex = sym.exp(a).bind(mx.cpu(), {"a": nd.ones((2, 2))})
        ex.forward()
        ex.forward()
        profiler.set_state("stop")
        spans = [e for e in _events()
                 if e["name"] == "Executor::ForwardDispatch"]
        assert spans[0]["args"]["first_run"] is True
        assert spans[1]["args"]["first_run"] is False


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_is_always_warm(self):
        # profiler stopped, tracing disabled: spans still land in the ring
        assert not profiler.is_running()
        profiler.record_span("warm_span", 0.0, 5.0, "test")
        assert len(tracing.flight) == 1
        assert not _events()  # but not in the (stopped) profiler stream

    def test_dump_on_injected_engine_exception(self, tmp_path, monkeypatch):
        path = str(tmp_path / "flight.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", path)
        profiler.record_span("pre_crash_work", 0.0, 3.0, "test")
        eng = engine_mod.ThreadedEngine(2)
        v = eng.new_variable("crash_var")

        def boom():
            raise ValueError("injected op failure")

        eng.push(boom, mutable_vars=(v,), name="crash_op")
        eng.wait_for_all()
        doc = json.load(open(path))
        assert doc["reason"] == "engine_crash"
        names = [e["name"] for e in doc["events"]]
        assert "pre_crash_work" in names  # ring context preceding the crash
        crash = [e for e in doc["events"]
                 if e["name"] == "CRASH crash_op"][0]
        assert "injected op failure" in crash["args"]["error"]
        assert crash["args"]["wait_on"] == ["crash_var"]
        with pytest.raises(ValueError):
            eng.wait_for_var(v)
        eng.stop()

    def test_dump_on_mxnet_error(self, tmp_path, monkeypatch):
        path = str(tmp_path / "err.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", path)
        before = telemetry.value("flight_recorder_dumps_total",
                                 reason="mxnet_error")
        MXNetError("boom for the recorder")
        doc = json.load(open(path))
        assert doc["reason"] == "mxnet_error"
        assert any("boom for the recorder" in str(e.get("args"))
                   for e in doc["events"])
        assert telemetry.value("flight_recorder_dumps_total",
                               reason="mxnet_error") == before + 1
        # debounce: an immediate second error does not re-dump
        os.remove(path)
        MXNetError("again")
        assert not os.path.exists(path)

    def test_disabled_recorder_is_inert(self, tmp_path, monkeypatch):
        path = str(tmp_path / "no.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", path)
        monkeypatch.setattr(tracing.flight, "enabled", False)
        profiler.record_span("gone", 0.0, 1.0)
        assert len(tracing.flight) == 0
        MXNetError("ignored")
        tracing.flight.on_engine_crash("op", ValueError("x"))
        assert not os.path.exists(path)

    @pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                        reason="no SIGUSR2 on this platform")
    def test_dump_on_sigusr2(self, tmp_path, monkeypatch):
        path = str(tmp_path / "sig.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", path)
        tracing._install_sigusr2()
        profiler.record_span("before_signal", 0.0, 1.0, "test")
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.time() + 5
        while not os.path.exists(path) and time.time() < deadline:
            time.sleep(0.05)
        doc = json.load(open(path))
        assert doc["reason"] == "sigusr2"
        assert any(e["name"] == "before_signal" for e in doc["events"])


# ---------------------------------------------------------------------------
# profiler satellites: event cap + atomic dump semantics
# ---------------------------------------------------------------------------
class TestProfilerSatellites:
    def test_event_cap_and_dropped_counter(self, monkeypatch):
        monkeypatch.setattr(profiler, "_max_events", 5)
        profiler.set_state("run")
        for i in range(9):
            profiler.record_span("spam_%d" % i, 0.0, 1.0)
        profiler.set_state("stop")
        assert len(_events()) == 5
        assert telemetry.value("profiler_events_dropped_total") == 4

    def test_dump_atomic_and_finished_false_keeps_events(self, tmp_path):
        profiler.set_state("run")
        profiler.record_span("keepme", 0.0, 5.0)
        profiler.set_state("stop")
        path = str(tmp_path / "prof.json")
        assert profiler.dump(finished=False, filename=path) == path
        doc = json.load(open(path))
        assert any(e["name"] == "keepme" for e in doc["traceEvents"])
        meta = doc["metadata"]
        assert meta["pid"] == os.getpid() and meta["t0_unix_us"] > 0
        # snapshot dump did not clear, and left no temp residue
        assert any(e["name"] == "keepme" for e in _events())
        assert os.listdir(str(tmp_path)) == ["prof.json"]
        profiler.dump(finished=True, filename=path)
        assert not _events()

    def test_dump_process_trace_keyed_by_role(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("DMLC_WORKER_ID", "3")
        profiler.set_state("run")
        profiler.record_span("w", 0.0, 1.0)
        profiler.set_state("stop")
        path = tracing.dump_process_trace(role="worker")
        assert os.path.basename(path) == "trace_worker3.json"
        assert merge_traces.validate_trace(
            merge_traces.load_trace(path)) == []
