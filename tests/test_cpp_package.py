"""cpp-package: the header-only C++ frontend over the C API waist.

Parity model: reference cpp-package/ (§2.4) — NDArray + Operator builder
classes and a trainable MLP example (cpp-package/example/mlp.cpp), here
riding the imperative+autograd C ABI instead of Symbol/Executor.
"""
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXDIR = os.path.join(REPO, "cpp_package", "example")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("python3-config") is None,
    reason="no C++ toolchain")


def test_cpp_mlp_trains():
    r = subprocess.run(["make", "-C", EXDIR], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("cpp example build failed: %s" % r.stderr[-500:])
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([os.path.join(EXDIR, "mlp")], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MLP TRAIN OK" in r.stdout
