"""cpp-package: the header-only C++ frontend over the C API waist.

Parity model: reference cpp-package/ (§2.4) — NDArray + Operator builder
classes riding the imperative+autograd C ABI (mlp.cc), plus the round-5
symbolic half: Symbol/Executor classes over the MXSymbol*/MXExecutor* C
sections and the generated per-op wrappers (op.h, the
OpWrapperGenerator.py pattern) trained end-to-end by lenet.cc.
"""
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXDIR = os.path.join(REPO, "cpp_package", "example")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("python3-config") is None,
    reason="no C++ toolchain")


def _build():
    r = subprocess.run(["make", "-C", EXDIR], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("cpp example build failed: %s" % r.stderr[-500:])


def _run(binary):
    env = dict(os.environ)
    # PYTHONPATH = repo ONLY and JAX_PLATFORMS forced: an accelerator
    # sitecustomize on the inherited path re-registers the real backend,
    # and the axon client's teardown can crash an otherwise-successful
    # embedded-interpreter process at exit (rc -11 after "TRAIN OK")
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([os.path.join(EXDIR, binary)], env=env,
                          capture_output=True, text=True, timeout=600)


def test_cpp_mlp_trains():
    _build()
    r = _run("mlp")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MLP TRAIN OK" in r.stdout


def test_cpp_lenet_symbolic_trains():
    """LeNet through Symbol + SimpleBind + Executor + generated op.h —
    the reference cpp-package's symbolic workflow."""
    _build()
    r = _run("lenet")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "LENET SYMBOLIC TRAIN OK" in r.stdout


def test_generated_op_wrappers_current():
    """op.h is generated from the registry; regenerating must reproduce
    the checked-in header byte-for-byte (drift gate), and it must cover
    the whole registry."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "cpp_package", "scripts"))
    try:
        import gen_op_wrappers
    finally:
        sys.path.pop(0)
    text, n = gen_op_wrappers.generate()
    from mxnet_tpu.ops.registry import OPS
    assert n == len(OPS)
    with open(os.path.join(REPO, "cpp_package", "include", "mxnet-cpp",
                           "op.h")) as f:
        assert f.read() == text, \
            "op.h is stale: rerun cpp_package/scripts/gen_op_wrappers.py"
