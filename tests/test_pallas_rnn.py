"""Pallas fused-LSTM kernel vs the lax.scan reference recurrence.

Runs through the Pallas interpreter on CPU (same jaxpr the TPU compiles).
Reference analog: the reference cross-checks cuDNN RNN against the CPU
rnn_impl.h path (tests/python/gpu/test_operator_gpu.py RNN consistency).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops import pallas_rnn


def _scan_ref(xproj, h0, c0, R, bR):
    def step(carry, xp):
        h, c = carry
        gates = xp + h @ R.T + bR
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), xproj)
    return ys, hT, cT


@pytest.fixture(autouse=True)
def _interpret_mode():
    pallas_rnn.INTERPRET = True
    yield
    pallas_rnn.INTERPRET = False


def _rand_case(T=5, B=8, H=16, seed=0):
    rng = np.random.default_rng(seed)
    xproj = jnp.asarray(rng.standard_normal((T, B, 4 * H)), jnp.float32)
    h0 = jnp.asarray(rng.standard_normal((B, H)) * 0.3, jnp.float32)
    c0 = jnp.asarray(rng.standard_normal((B, H)) * 0.3, jnp.float32)
    R = jnp.asarray(rng.standard_normal((4 * H, H)) * 0.2, jnp.float32)
    bR = jnp.asarray(rng.standard_normal((4 * H,)) * 0.1, jnp.float32)
    return xproj, h0, c0, R, bR


def test_forward_matches_scan():
    args = _rand_case()
    ys_p, hT_p, cT_p = pallas_rnn.lstm_scan(*args)
    ys_r, hT_r, cT_r = _scan_ref(*args)
    np.testing.assert_allclose(np.asarray(ys_p), np.asarray(ys_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(hT_p), np.asarray(hT_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(cT_p), np.asarray(cT_r),
                               rtol=2e-5, atol=2e-5)


def test_backward_matches_scan():
    args = _rand_case(seed=3)

    def loss_p(xproj, h0, c0, R, bR):
        ys, hT, cT = pallas_rnn.lstm_scan(xproj, h0, c0, R, bR)
        # weight all three outputs so every cotangent path is exercised
        return (jnp.sum(ys * ys) + jnp.sum(jnp.sin(hT))
                + jnp.sum(cT * 0.5))

    def loss_r(xproj, h0, c0, R, bR):
        ys, hT, cT = _scan_ref(xproj, h0, c0, R, bR)
        return (jnp.sum(ys * ys) + jnp.sum(jnp.sin(hT))
                + jnp.sum(cT * 0.5))

    gp = jax.grad(loss_p, argnums=(0, 1, 2, 3, 4))(*args)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(*args)
    names = ["dxproj", "dh0", "dc0", "dR", "dbR"]
    for name, a, b in zip(names, gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4,
            err_msg=name)


def test_lstm_lowering_selects_scan_off_tpu():
    """Advisor r03 regression: the TPU-vs-other choice is made at
    LOWERING time (lax.platform_dependent), so a CPU compilation must
    take the scan branch even though the size gate is open and the host
    may have a TPU default backend.  The Mosaic branch errors at CPU
    lowering, so merely compiling+running here proves the selection."""
    pallas_rnn.INTERPRET = False     # defeat the autouse interpret fixture
    from mxnet_tpu.ops import rnn as rnn_ops

    # the gate is platform-free now: size/env eligibility only
    assert pallas_rnn.lstm_scan_available(8, 16)

    args = _rand_case(T=3)
    f = jax.jit(lambda *a: rnn_ops._cell_scan("lstm", *a))
    txt = f.lower(*args).compile().as_text()
    assert "tpu_custom_call" not in txt and "Mosaic" not in txt
    ys, hT, cT = f(*args)
    ys_r, hT_r, cT_r = _scan_ref(*args)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ys_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(cT), np.asarray(cT_r),
                               rtol=2e-5, atol=2e-5)
