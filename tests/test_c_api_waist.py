"""C API waist (N17): NDArray CRUD + imperative invoke from real C callers.

Parity model: reference include/mxnet/c_api.h Parts 0-2 (src/c_api/c_api.cc,
c_api_ndarray.cc) — the ABI every language binding rides.  Two consumers:
a pure-C binary (src/tests/c_api_test.c) in a fresh process where the
library bootstraps the embedded interpreter, and in-process ctypes where it
piggybacks on the running interpreter.
"""
import ctypes
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src")
LIB = os.path.join(REPO, "mxnet_tpu", "_native", "libmxnet_tpu_c.so")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("python3-config") is None,
    reason="no C++ toolchain")


def _make(target):
    r = subprocess.run(["make", "-C", SRC, target], capture_output=True,
                       text=True)
    if r.returncode != 0:
        pytest.skip("native build failed: %s" % r.stderr[-500:])


def test_c_binary_full_surface():
    """The C test binary exercises create/copy/invoke/save/load/list/error
    paths in a fresh process."""
    _make("./c_api_test")
    env = dict(os.environ)
    # PYTHONPATH = repo ONLY and JAX_PLATFORMS forced: an accelerator
    # sitecustomize on the inherited path re-registers the real backend,
    # and the axon client's teardown can crash an otherwise-successful
    # embedded-interpreter process at exit (rc -11 after "TRAIN OK")
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([os.path.join(SRC, "c_api_test")], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "C API TEST OK" in r.stdout


def test_c_binary_symbolic_surface():
    """The symbolic C consumer: MXSymbol create/compose/list/JSON/infer +
    MXExecutor bind/forward/backward training an MLP to convergence
    (round-5 addition — reference c_api.h Parts 3-4)."""
    _make("./c_api_sym_test")
    env = dict(os.environ)
    # PYTHONPATH = repo ONLY and JAX_PLATFORMS forced: an accelerator
    # sitecustomize on the inherited path re-registers the real backend,
    # and the axon client's teardown can crash an otherwise-successful
    # embedded-interpreter process at exit (rc -11 after "TRAIN OK")
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([os.path.join(SRC, "c_api_sym_test")], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all checks passed" in r.stdout


class TestInProcess:
    """ctypes consumer sharing this interpreter (the predict-ABI pattern)."""

    @pytest.fixture(scope="class")
    def lib(self):
        _make("../mxnet_tpu/_native/libmxnet_tpu_c.so")
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        # pointer/size_t params must be marshalled 64-bit: ctypes defaults
        # unannotated integer args to 32-bit c_int, which truncates handles
        # read back as plain ints (outs[0]) once the heap is above 4GB
        lib.MXNDArraySyncCopyFromCPU.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        lib.MXNDArraySyncCopyToCPU.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
        lib.MXNDArrayWaitToRead.argtypes = [ctypes.c_void_p]
        return lib

    def test_ndarray_roundtrip(self, lib):
        shape = (ctypes.c_uint32 * 2)(4, 5)
        h = ctypes.c_void_p()
        assert lib.MXNDArrayCreate(shape, 2, 1, 0, 0,
                                   ctypes.byref(h)) == 0
        vals = np.arange(20, dtype=np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(
            h, vals.ctypes.data_as(ctypes.c_void_p), 20) == 0
        out = np.zeros(20, np.float32)
        assert lib.MXNDArraySyncCopyToCPU(
            h, out.ctypes.data_as(ctypes.c_void_p), 20) == 0
        np.testing.assert_array_equal(out, vals)
        dim = ctypes.c_uint32()
        pdata = ctypes.POINTER(ctypes.c_uint32)()
        assert lib.MXNDArrayGetShape(h, ctypes.byref(dim),
                                     ctypes.byref(pdata)) == 0
        assert dim.value == 2 and pdata[0] == 4 and pdata[1] == 5
        lib.MXNDArrayFree(h)

    def test_invoke_matches_python(self, lib):
        """C-side op invoke produces the same numbers as the Python API."""
        rng = np.random.RandomState(0)
        x = rng.randn(3, 6).astype(np.float32)
        shape = (ctypes.c_uint32 * 2)(3, 6)
        h = ctypes.c_void_p()
        lib.MXNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h))
        lib.MXNDArraySyncCopyFromCPU(
            h, x.ctypes.data_as(ctypes.c_void_p), x.size)
        nout = ctypes.c_int()
        outs = ctypes.POINTER(ctypes.c_void_p)()
        keys = (ctypes.c_char_p * 1)(b"act_type")
        vals = (ctypes.c_char_p * 1)(b"sigmoid")
        assert lib.MXImperativeInvokeByName(
            b"Activation", 1, ctypes.byref(h), ctypes.byref(nout),
            ctypes.byref(outs), 1, keys, vals) == 0
        assert nout.value == 1
        got = np.zeros(x.size, np.float32)
        lib.MXNDArraySyncCopyToCPU(
            outs[0], got.ctypes.data_as(ctypes.c_void_p), x.size)
        want = mx.nd.Activation(mx.nd.array(x), act_type="sigmoid").asnumpy()
        np.testing.assert_allclose(got.reshape(3, 6), want, rtol=1e-6)
        lib.MXNDArrayFree(outs[0])
        lib.MXNDArrayFree(h)

    def test_short_buffer_errors_not_overruns(self, lib):
        """SyncCopyToCPU with a wrong element count must return -1
        (reference CHECK), never scale past the buffer."""
        shape = (ctypes.c_uint32 * 1)(8,)
        h = ctypes.c_void_p()
        lib.MXNDArrayCreate(shape, 1, 1, 0, 0, ctypes.byref(h))
        small = np.zeros(4, np.float32)
        r = lib.MXNDArraySyncCopyToCPU(
            h, small.ctypes.data_as(ctypes.c_void_p), 4)
        assert r != 0
        assert b"8" in lib.MXGetLastError()
        r = lib.MXNDArraySyncCopyFromCPU(
            h, small.ctypes.data_as(ctypes.c_void_p), 4)
        assert r != 0
        lib.MXNDArrayFree(h)

    def test_error_contract(self, lib):
        h = ctypes.c_void_p()
        nout = ctypes.c_int()
        outs = ctypes.POINTER(ctypes.c_void_p)()
        shape = (ctypes.c_uint32 * 1)(3,)
        lib.MXNDArrayCreate(shape, 1, 1, 0, 0, ctypes.byref(h))
        r = lib.MXImperativeInvokeByName(
            b"FullyConnected", 1, ctypes.byref(h), ctypes.byref(nout),
            ctypes.byref(outs), 0, None, None)
        assert r != 0
        assert b"num_hidden" in lib.MXGetLastError() or \
            b"required" in lib.MXGetLastError()
        lib.MXNDArrayFree(h)

    def test_autograd_through_abi(self, lib):
        """mark -> record -> invoke -> backward -> grad, all over C."""
        shape = (ctypes.c_uint32 * 2)(2, 3)
        h = ctypes.c_void_p()
        lib.MXNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h))
        x = np.arange(6, dtype=np.float32)
        # mark BEFORE the copy: SyncCopyFromCPU must mutate the handle's
        # array in place, not rebind it, or the marking would be lost
        assert lib.MXAutogradMarkVariables(1, ctypes.byref(h)) == 0
        lib.MXNDArraySyncCopyFromCPU(
            h, x.ctypes.data_as(ctypes.c_void_p), 6)
        prev = ctypes.c_int()
        assert lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)) == 0
        nout = ctypes.c_int(0)
        outs = ctypes.POINTER(ctypes.c_void_p)()
        assert lib.MXImperativeInvokeByName(
            b"square", 1, ctypes.byref(h), ctypes.byref(nout),
            ctypes.byref(outs), 0, None, None) == 0
        sq = ctypes.c_void_p(outs[0])
        nout = ctypes.c_int(0)
        outs = ctypes.POINTER(ctypes.c_void_p)()
        assert lib.MXImperativeInvokeByName(
            b"sum", 1, ctypes.byref(sq), ctypes.byref(nout),
            ctypes.byref(outs), 0, None, None) == 0
        loss = ctypes.c_void_p(outs[0])
        assert lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)) == 0
        assert lib.MXAutogradBackward(1, ctypes.byref(loss), 0) == 0
        g = ctypes.c_void_p()
        assert lib.MXNDArrayGetGrad(h, ctypes.byref(g)) == 0
        got = np.zeros(6, np.float32)
        lib.MXNDArraySyncCopyToCPU(
            g, got.ctypes.data_as(ctypes.c_void_p), 6)
        np.testing.assert_allclose(got, 2 * x)   # d(sum x^2)/dx = 2x
        for hh in (g, loss, sq, h):
            lib.MXNDArrayFree(hh)

    def test_out_supplied_invoke(self, lib):
        """Non-NULL *outputs = caller-supplied out arrays (reference
        contract); the result lands in the existing handle."""
        shape = (ctypes.c_uint32 * 1)(4,)
        h = ctypes.c_void_p()
        t = ctypes.c_void_p()
        lib.MXNDArrayCreate(shape, 1, 1, 0, 0, ctypes.byref(h))
        lib.MXNDArrayCreate(shape, 1, 1, 0, 0, ctypes.byref(t))
        x = np.arange(4, dtype=np.float32)
        lib.MXNDArraySyncCopyFromCPU(
            h, x.ctypes.data_as(ctypes.c_void_p), 4)
        sup = (ctypes.c_void_p * 1)(t)
        psup = ctypes.cast(sup, ctypes.POINTER(ctypes.c_void_p))
        nout = ctypes.c_int(1)
        keys = (ctypes.c_char_p * 1)(b"scalar")
        vals = (ctypes.c_char_p * 1)(b"3.0")
        assert lib.MXImperativeInvokeByName(
            b"_mul_scalar", 1, ctypes.byref(h), ctypes.byref(nout),
            ctypes.byref(psup), 1, keys, vals) == 0
        got = np.zeros(4, np.float32)
        lib.MXNDArraySyncCopyToCPU(
            t, got.ctypes.data_as(ctypes.c_void_p), 4)
        np.testing.assert_allclose(got, 3 * x)
        lib.MXNDArrayFree(h)
        lib.MXNDArrayFree(t)

    def test_op_listing(self, lib):
        n = ctypes.c_uint32()
        arr = ctypes.POINTER(ctypes.c_char_p)()
        assert lib.MXListAllOpNames(ctypes.byref(n), ctypes.byref(arr)) == 0
        names = {arr[i].decode() for i in range(n.value)}
        assert {"Convolution", "FullyConnected", "dot"} <= names
        from mxnet_tpu.ops.registry import list_ops
        assert names == set(list_ops())
