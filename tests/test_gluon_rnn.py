"""Gluon RNN layer/cell tests (ref: tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import rnn


def test_rnn_cells_shapes():
    for cell_cls, n_states in [(rnn.RNNCell, 1), (rnn.LSTMCell, 2),
                               (rnn.GRUCell, 1)]:
        cell = cell_cls(16, input_size=8, prefix="%s_" % cell_cls.__name__)
        cell.initialize()
        x = mx.nd.random.uniform(shape=(2, 8))
        states = cell.begin_state(batch_size=2)
        out, new_states = cell(x, states)
        assert out.shape == (2, 16)
        assert len(new_states) == n_states


def test_cell_unroll():
    cell = rnn.LSTMCell(8, input_size=4, prefix="lstm_")
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 4))  # NTC
    outputs, states = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 8)
    assert len(states) == 2


def test_fused_matches_unfused():
    layer = rnn.LSTM(8, num_layers=2, input_size=5)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(4, 3, 5))  # TNC
    out = layer(x)  # no initial state -> output only (ref rnn_layer.py:198)
    assert out.shape == (4, 3, 8)
    stack = layer._unfuse()
    outs, _ = stack.unroll(4, mx.nd.swapaxes(x, 0, 1), layout="NTC",
                           merge_outputs=True)
    np.testing.assert_allclose(
        out.asnumpy(), mx.nd.swapaxes(outs, 0, 1).asnumpy(),
        rtol=1e-4, atol=1e-5)


def test_gru_fused_matches_unfused():
    layer = rnn.GRU(8, input_size=5)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(4, 3, 5))
    out = layer(x)
    outs, _ = layer._unfuse().unroll(
        4, mx.nd.swapaxes(x, 0, 1), layout="NTC", merge_outputs=True)
    np.testing.assert_allclose(
        out.asnumpy(), mx.nd.swapaxes(outs, 0, 1).asnumpy(),
        rtol=1e-4, atol=1e-5)


def test_bidirectional_fused():
    layer = rnn.LSTM(8, num_layers=2, bidirectional=True, input_size=5)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(4, 3, 5))
    out, states = layer(x, layer.begin_state(3))
    assert out.shape == (4, 3, 16)
    assert states[0].shape == (4, 3, 8)


def test_rnn_layer_backward():
    layer = rnn.GRU(8, num_layers=1, input_size=5, layout="NTC")
    layer.initialize()
    x = mx.nd.random.uniform(shape=(2, 4, 5))
    with mx.autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(
        rnn.LSTMCell(4, input_size=3, prefix="l_"),
        rnn.LSTMCell(4, input_size=3, prefix="r_"))
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 5, 3))
    outputs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 5, 8)


def test_residual_cell():
    cell = rnn.ResidualCell(rnn.GRUCell(4, input_size=4, prefix="gru_"))
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 4))
    outputs, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 4)


def test_sequential_cells():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=4, prefix="l0_"))
    stack.add(rnn.LSTMCell(8, input_size=8, prefix="l1_"))
    stack.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 4))
    outputs, states = stack.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 8)
    assert len(states) == 4


def test_zoneout_cell():
    cell = rnn.ZoneoutCell(rnn.RNNCell(4, input_size=4, prefix="rnn_"),
                           zoneout_outputs=0.5, zoneout_states=0.5)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 4))
    outputs, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 4)


def test_dropout_cell():
    cell = rnn.DropoutCell(0.5)
    x = mx.nd.ones((2, 3, 4))
    outputs, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 4)


def test_vardrop_cell():
    from mxnet_tpu.gluon.contrib.rnn import VariationalDropoutCell
    cell = VariationalDropoutCell(
        rnn.GRUCell(4, input_size=4, prefix="gru_"), drop_inputs=0.3,
        drop_outputs=0.3)
    cell.initialize()
    with mx.autograd.record():
        outputs, _ = cell.unroll(
            3, mx.nd.ones((2, 3, 4)), layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 4)


def test_ntc_layout_layer():
    layer = rnn.LSTM(6, input_size=4, layout="NTC")
    layer.initialize()
    x = mx.nd.random.uniform(shape=(3, 5, 4))
    out, states = layer(x, layer.begin_state(3))
    assert out.shape == (3, 5, 6)
