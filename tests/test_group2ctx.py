"""Coarse model parallelism via ctx_group/group2ctx (ref:
AssignContext graph_executor.cc:315 + tests/python/unittest/
test_model_parallel.py): node groups execute on their assigned devices,
with explicit transfers at group boundaries."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _two_group_mlp():
    data = sym.var("data")
    with mx.AttrScope(ctx_group="dev1"):
        h = sym.FullyConnected(data, num_hidden=16, name="fc1")
        h = sym.Activation(h, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        h = sym.FullyConnected(h, num_hidden=8, name="fc2")
        out = sym.SoftmaxOutput(h, name="softmax")
    return out


def test_group2ctx_places_and_computes():
    import jax
    assert len(jax.devices()) >= 2
    net = _two_group_mlp()
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    ex = net.simple_bind(ctx=mx.cpu(0), group2ctx=g2c, data=(4, 12))
    for n, arr in ex.arg_dict.items():
        if n != "data":
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    x = np.random.uniform(size=(4, 12)).astype(np.float32)
    outs = ex.forward(is_train=True, data=mx.nd.array(x))
    # output produced by the dev2 group lives on cpu(1)
    out_dev = list(outs[0]._data.devices())[0]
    assert out_dev == mx.cpu(1).jax_device, out_dev

    # numerics match the ungrouped single-device executor
    ex1 = net.simple_bind(ctx=mx.cpu(0), data=(4, 12))
    for n in ex.arg_dict:
        if n != "data":
            ex1.arg_dict[n][:] = ex.arg_dict[n].asnumpy()
    outs1 = ex1.forward(is_train=True, data=mx.nd.array(x))
    np.testing.assert_allclose(outs[0].asnumpy(), outs1[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_group2ctx_backward_matches():
    import jax
    assert len(jax.devices()) >= 2
    net = _two_group_mlp()
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    ex = net.simple_bind(ctx=mx.cpu(0), group2ctx=g2c, data=(4, 12))
    ex1 = net.simple_bind(ctx=mx.cpu(0), data=(4, 12))
    rng = np.random.RandomState(0)
    for n in ex.arg_dict:
        v = rng.uniform(-0.1, 0.1, ex.arg_dict[n].shape) \
            if n != "data" else rng.uniform(size=ex.arg_dict[n].shape)
        ex.arg_dict[n][:] = v
        ex1.arg_dict[n][:] = v
    y = rng.randint(0, 8, size=(4,)).astype(np.float32)
    ex.arg_dict.get("softmax_label", ex.arg_dict["data"])  # label exists?
    for e in (ex, ex1):
        if "softmax_label" in e.arg_dict:
            e.arg_dict["softmax_label"][:] = y
        e.forward(is_train=True)
        e.backward()
    for n in ex.grad_dict:
        np.testing.assert_allclose(ex.grad_dict[n].asnumpy(),
                                   ex1.grad_dict[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_group2ctx_module_api_accepted():
    """Module(group2ctxs=...) runs a fit step without silently ignoring
    placement (the round-1 silent no-op finding)."""
    net = _two_group_mlp()
    mod = mx.mod.Module(net, label_names=("softmax_label",),
                        group2ctxs={"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    X = np.random.uniform(size=(32, 12)).astype(np.float32)
    y = np.random.randint(0, 8, 32).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    assert dict(mod.score(it, "acc"))  # runs end to end


def test_attr_scope_applies_to_operator_overloads():
    """Regression: nodes created by operator overloads (a * b) inside an
    AttrScope must inherit ctx_group like generated-function nodes do."""
    with mx.AttrScope(ctx_group="g1"):
        a = sym.var("a")
        b = sym.var("b")
        c = a * b + a
    for node, _ in c._outputs:
        assert node.attrs.get("ctx_group") == "g1"


def test_model_parallel_lstm_example_converges():
    """example/model-parallel/lstm trains with layers on 2 devices and
    perplexity drops (parity: example/model-parallel/lstm)."""
    import argparse
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "example", "model-parallel", "lstm",
        "lstm.py")
    spec = importlib.util.spec_from_file_location("mp_lstm_example", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    args = argparse.Namespace(num_layers=2, num_hidden=32, num_embed=16,
                              vocab=32, seq_len=8, batch_size=32,
                              num_epochs=3, lr=0.5)
    ppl = mod.train(args)
    assert ppl < 12.0, "model-parallel LSTM failed to learn: ppl %.1f" % ppl


def test_attr_precedence_and_variable_scope():
    """Op kwargs beat explicit attr dict; attr dict beats scope; variables
    inherit scope attrs (reference AttrScope semantics)."""
    with mx.AttrScope(ctx_group="g", __lr_mult__="0.0"):
        v = sym.var("w")
        fc = sym.FullyConnected(sym.var("x"), num_hidden=10,
                                attr={"num_hidden": "20",
                                      "ctx_group": "override"})
    assert v._outputs[0][0].attrs["__lr_mult__"] == "0.0"
    assert v._outputs[0][0].attrs["ctx_group"] == "g"
    node = fc._outputs[0][0]
    # the op parameter must NOT be clobbered by the attr dict
    assert node.parsed_attrs()["num_hidden"] == 10
    assert node.attrs["ctx_group"] == "override"


def test_group2ctx_bulks_into_segments():
    """Engine bulking (ref graph_executor.cc:1455): the 2-group MLP must
    compile into exactly 2 same-device segments — one jitted program per
    group, not one dispatch per op."""
    import jax
    net = _two_group_mlp()
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    ex = net.simple_bind(ctx=mx.cpu(0), group2ctx=g2c, data=(4, 12))
    for n, arr in ex.arg_dict.items():
        if n != "data":
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    x = np.random.uniform(size=(4, 12)).astype(np.float32)
    ex.forward(is_train=True, data=mx.nd.array(x))

    plan = ex._plan(True)
    segs = ex._segments(plan, ex._placements(plan))
    assert len(segs) == 2, [s.device for s in segs]
    assert segs[0].device == mx.cpu(0).jax_device
    assert segs[1].device == mx.cpu(1).jax_device
    # every step is inside a segment; nothing dispatches per-op
    assert sum(len(s.steps) for s in segs) == len(plan.steps)
    # the boundary carries the cross-group activation(s)
    assert len(segs[1].in_entries) >= 1
