"""Coarse model parallelism via ctx_group/group2ctx (ref:
AssignContext graph_executor.cc:315 + tests/python/unittest/
test_model_parallel.py): node groups execute on their assigned devices,
with explicit transfers at group boundaries."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _two_group_mlp():
    data = sym.var("data")
    with mx.AttrScope(ctx_group="dev1"):
        h = sym.FullyConnected(data, num_hidden=16, name="fc1")
        h = sym.Activation(h, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        h = sym.FullyConnected(h, num_hidden=8, name="fc2")
        out = sym.SoftmaxOutput(h, name="softmax")
    return out


def test_group2ctx_places_and_computes():
    import jax
    assert len(jax.devices()) >= 2
    net = _two_group_mlp()
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    ex = net.simple_bind(ctx=mx.cpu(0), group2ctx=g2c, data=(4, 12))
    for n, arr in ex.arg_dict.items():
        if n != "data":
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    x = np.random.uniform(size=(4, 12)).astype(np.float32)
    outs = ex.forward(is_train=True, data=mx.nd.array(x))
    # output produced by the dev2 group lives on cpu(1)
    out_dev = list(outs[0]._data.devices())[0]
    assert out_dev == mx.cpu(1).jax_device, out_dev

    # numerics match the ungrouped single-device executor
    ex1 = net.simple_bind(ctx=mx.cpu(0), data=(4, 12))
    for n in ex.arg_dict:
        if n != "data":
            ex1.arg_dict[n][:] = ex.arg_dict[n].asnumpy()
    outs1 = ex1.forward(is_train=True, data=mx.nd.array(x))
    np.testing.assert_allclose(outs[0].asnumpy(), outs1[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_group2ctx_backward_matches():
    import jax
    assert len(jax.devices()) >= 2
    net = _two_group_mlp()
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    ex = net.simple_bind(ctx=mx.cpu(0), group2ctx=g2c, data=(4, 12))
    ex1 = net.simple_bind(ctx=mx.cpu(0), data=(4, 12))
    rng = np.random.RandomState(0)
    for n in ex.arg_dict:
        v = rng.uniform(-0.1, 0.1, ex.arg_dict[n].shape) \
            if n != "data" else rng.uniform(size=ex.arg_dict[n].shape)
        ex.arg_dict[n][:] = v
        ex1.arg_dict[n][:] = v
    y = rng.randint(0, 8, size=(4,)).astype(np.float32)
    ex.arg_dict.get("softmax_label", ex.arg_dict["data"])  # label exists?
    for e in (ex, ex1):
        if "softmax_label" in e.arg_dict:
            e.arg_dict["softmax_label"][:] = y
        e.forward(is_train=True)
        e.backward()
    for n in ex.grad_dict:
        np.testing.assert_allclose(ex.grad_dict[n].asnumpy(),
                                   ex1.grad_dict[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_group2ctx_module_api_accepted():
    """Module(group2ctxs=...) runs a fit step without silently ignoring
    placement (the round-1 silent no-op finding)."""
    net = _two_group_mlp()
    mod = mx.mod.Module(net, label_names=("softmax_label",),
                        group2ctxs={"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    X = np.random.uniform(size=(32, 12)).astype(np.float32)
    y = np.random.randint(0, 8, 32).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    assert dict(mod.score(it, "acc"))  # runs end to end
