"""dist_async: the true parameter-server path (VERDICT r03 Missing #4).

Parity model: reference kvstore_dist_server.h async mode — immediate
server-side apply, no per-batch barrier, server-side pickled optimizer
(kvstore_server.py:55) — tested in-process against a live server thread
and end-to-end as a forked 1-server/2-worker job via tools/launch.py -s 1
(the tests/nightly/dist_sync_kvstore.py pattern).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore_server import KVStoreServer, recv_msg, send_msg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def server(monkeypatch):
    srv = KVStoreServer(num_workers=1).start()
    monkeypatch.setenv("MXNET_PS_URI", "127.0.0.1")
    monkeypatch.setenv("MXNET_PS_PORT", str(srv.port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    yield srv
    srv.shutdown()


class TestInProcess:
    def test_init_push_pull_assign(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("a", nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)))
        out = nd.zeros((2, 3))
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy().ravel(), np.arange(6))
        # no optimizer: push assigns (local-store default updater)
        kv.push("a", nd.ones((2, 3)) * 7)
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 7.0)
        kv.close()

    def test_first_init_wins(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((4,)))
        kv.init("w", nd.zeros((4,)))       # later init ignored (worker 1+)
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 1.0)
        kv.close()

    def test_server_side_optimizer_immediate_apply(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((3,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        kv.push("w", nd.ones((3,)))        # w <- w - 0.5*1
        out = nd.zeros((3,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.5)
        kv.push("w", nd.ones((3,)))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.0)
        assert server.push_count == 2
        kv.close()

    def test_first_optimizer_wins(self, server):
        """A straggler rank's set_optimizer must not rebuild the server
        Updater (that would wipe momentum state mid-training)."""
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((2,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))
        kv.push("w", nd.ones((2,)))        # momentum buffer now nonzero
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))  # straggler rank
        kv.push("w", nd.ones((2,)))
        out = nd.zeros((2,))
        kv.pull("w", out=out)
        # with momentum preserved: w = 1 - 0.5 - (0.5 + 0.45) = -0.45
        # if the straggler had reset the updater: w = 1 - 0.5 - 0.5 = 0.0
        np.testing.assert_allclose(out.asnumpy(), -0.45, atol=1e-6)
        kv.close()

    def test_compressed_push(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("g", nd.zeros((4,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.push("g", nd.array(np.array([0.9, -0.9, 0.1, 0.0], np.float32)))
        out = nd.zeros((4,))
        kv.pull("g", out=out)              # assign semantics, quantized
        np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
        kv.close()

    def test_errors_cross_the_wire(self, server):
        kv = mx.kv.create("dist_async")
        with pytest.raises(mx.MXNetError, match="before init"):
            kv.pull("nope", out=nd.zeros((1,)))
        # the connection survives an error reply
        kv.init("x", nd.ones((1,)))
        out = nd.zeros((1,))
        kv.pull("x", out=out)
        assert out.asnumpy()[0] == 1.0
        kv.close()


def test_two_workers_async_convergence():
    """1 server + 2 workers forked via the launcher; async SGD converges
    (end-to-end: role dispatch, retry-connect, server optimizer, stop)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    env = {"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu"}
    rc = launch.launch_local(
        2, [sys.executable, os.path.join(REPO, "tests",
                                         "dist_async_worker.py")],
        env_extra=env, num_servers=1)
    assert rc == 0
