"""dist_async: the true parameter-server path (VERDICT r03 Missing #4).

Parity model: reference kvstore_dist_server.h async mode — immediate
server-side apply, no per-batch barrier, server-side pickled optimizer
(kvstore_server.py:55) — tested in-process against a live server thread
and end-to-end as a forked 1-server/2-worker job via tools/launch.py -s 1
(the tests/nightly/dist_sync_kvstore.py pattern).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore_server import KVStoreServer, recv_msg, send_msg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def server(monkeypatch):
    srv = KVStoreServer(num_workers=1).start()
    monkeypatch.setenv("MXNET_PS_URI", "127.0.0.1")
    monkeypatch.setenv("MXNET_PS_PORT", str(srv.port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    yield srv
    srv.shutdown()


class TestInProcess:
    def test_init_push_pull_assign(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("a", nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)))
        out = nd.zeros((2, 3))
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy().ravel(), np.arange(6))
        # no optimizer: push assigns (local-store default updater)
        kv.push("a", nd.ones((2, 3)) * 7)
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 7.0)
        kv.close()

    def test_first_init_wins(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((4,)))
        kv.init("w", nd.zeros((4,)))       # later init ignored (worker 1+)
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 1.0)
        kv.close()

    def test_server_side_optimizer_immediate_apply(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((3,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        kv.push("w", nd.ones((3,)))        # w <- w - 0.5*1
        out = nd.zeros((3,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.5)
        kv.push("w", nd.ones((3,)))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.0)
        assert server.push_count == 2
        kv.close()

    def test_first_optimizer_wins(self, server):
        """A straggler rank's set_optimizer must not rebuild the server
        Updater (that would wipe momentum state mid-training)."""
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((2,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))
        kv.push("w", nd.ones((2,)))        # momentum buffer now nonzero
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))  # straggler rank
        kv.push("w", nd.ones((2,)))
        out = nd.zeros((2,))
        kv.pull("w", out=out)
        # with momentum preserved: w = 1 - 0.5 - (0.5 + 0.45) = -0.45
        # if the straggler had reset the updater: w = 1 - 0.5 - 0.5 = 0.0
        np.testing.assert_allclose(out.asnumpy(), -0.45, atol=1e-6)
        kv.close()

    def test_compressed_push(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("g", nd.zeros((4,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.push("g", nd.array(np.array([0.9, -0.9, 0.1, 0.0], np.float32)))
        out = nd.zeros((4,))
        kv.pull("g", out=out)              # assign semantics, quantized
        np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
        kv.close()

    def test_row_sparse_push_pull(self, server):
        """push_rsp / pull_rows: only touched rows cross the wire
        (reference kvstore_dist.h:228-291)."""
        from mxnet_tpu.ndarray.sparse import row_sparse_array
        kv = mx.kv.create("dist_async")
        kv.init("emb", nd.zeros((6, 3)))
        ids = np.array([1, 4], np.int64)
        rows = np.arange(6, dtype=np.float32).reshape(2, 3)
        # no optimizer: rsp push assigns the touched rows
        kv.push("emb", row_sparse_array((nd.array(rows), ids),
                                        shape=(6, 3)))
        dense = nd.zeros((6, 3))
        kv.pull("emb", out=dense)
        want = np.zeros((6, 3), np.float32)
        want[ids] = rows
        np.testing.assert_array_equal(dense.asnumpy(), want)
        # row_sparse_pull into a RowSparseNDArray gets exactly those rows
        out = row_sparse_array((nd.zeros((1, 3)), np.array([0])),
                               shape=(6, 3))
        kv.row_sparse_pull("emb", out=out, row_ids=nd.array(ids))
        np.testing.assert_array_equal(out.indices.asnumpy(), ids)
        np.testing.assert_array_equal(out.data.asnumpy(), rows)
        kv.close()

    def test_row_sparse_server_optimizer(self, server):
        """Server-side lazy update: an rsp push steps ONLY the touched
        rows (kvstore_dist_server.h ApplyUpdates on row-sparse)."""
        from mxnet_tpu.ndarray.sparse import row_sparse_array
        kv = mx.kv.create("dist_async")
        kv.init("emb", nd.ones((4, 2)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        ids = np.array([2], np.int64)
        kv.push("emb", row_sparse_array(
            (nd.ones((1, 2)), ids), shape=(4, 2)))
        out = nd.zeros((4, 2))
        kv.pull("emb", out=out)
        want = np.ones((4, 2), np.float32)
        want[2] = 0.5                  # only row 2 stepped
        np.testing.assert_allclose(out.asnumpy(), want)
        kv.close()

    def test_compressed_wire_is_packed(self, server):
        """The 2-bit push sends the PACKED word form: wire bytes for the
        gradient must be ~1/16 of f32, not a dequantized full array."""
        from mxnet_tpu import kvstore_server as ps
        kv = mx.kv.create("dist_async")
        n = 4096
        kv.init("big", nd.zeros((n,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        sent = []
        orig = ps.send_msg

        def spy(sock, obj):
            sent.append(obj)
            return orig(sock, obj)

        ps.send_msg = spy
        try:
            kv.push("big", nd.ones((n,)))
        finally:
            ps.send_msg = orig
        msg = [m for m in sent if m[0] == "push_2bit"][-1]
        words = np.asarray(msg[2])
        assert words.dtype == np.uint32 and words.size == n // 16
        out = nd.zeros((n,))
        kv.pull("big", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.5)
        kv.close()

    def test_wire_rejects_oversized_blob_header(self, server):
        """decode validates blob size against the declared shape (the
        non-pickle codec's safety check)."""
        from mxnet_tpu.kvstore_server import _decode
        with pytest.raises(mx.MXNetError, match="size mismatch"):
            _decode({"__nd__": 0, "dtype": "<f4", "shape": [100]},
                    [b"\x00" * 8])

    def test_errors_cross_the_wire(self, server):
        kv = mx.kv.create("dist_async")
        with pytest.raises(mx.MXNetError, match="before init"):
            kv.pull("nope", out=nd.zeros((1,)))
        # the connection survives an error reply
        kv.init("x", nd.ones((1,)))
        out = nd.zeros((1,))
        kv.pull("x", out=out)
        assert out.asnumpy()[0] == 1.0
        kv.close()


def test_two_workers_async_convergence():
    """1 server + 2 workers forked via the launcher; async SGD converges
    (end-to-end: role dispatch, retry-connect, server optimizer, stop)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    env = {"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu"}
    rc = launch.launch_local(
        2, [sys.executable, os.path.join(REPO, "tests",
                                         "dist_async_worker.py")],
        env_extra=env, num_servers=1)
    assert rc == 0
