"""dist_async: the true parameter-server path (VERDICT r03 Missing #4).

Parity model: reference kvstore_dist_server.h async mode — immediate
server-side apply, no per-batch barrier, server-side pickled optimizer
(kvstore_server.py:55) — tested in-process against a live server thread
and end-to-end as a forked 1-server/2-worker job via tools/launch.py -s 1
(the tests/nightly/dist_sync_kvstore.py pattern).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore_server import KVStoreServer, recv_msg, send_msg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def server(monkeypatch):
    srv = KVStoreServer(num_workers=1).start()
    monkeypatch.setenv("MXNET_PS_URI", "127.0.0.1")
    monkeypatch.setenv("MXNET_PS_PORT", str(srv.port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    yield srv
    srv.shutdown()


class TestInProcess:
    def test_init_push_pull_assign(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("a", nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)))
        out = nd.zeros((2, 3))
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy().ravel(), np.arange(6))
        # no optimizer: push assigns (local-store default updater)
        kv.push("a", nd.ones((2, 3)) * 7)
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 7.0)
        kv.close()

    def test_first_init_wins(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((4,)))
        kv.init("w", nd.zeros((4,)))       # later init ignored (worker 1+)
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 1.0)
        kv.close()

    def test_server_side_optimizer_immediate_apply(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((3,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        kv.push("w", nd.ones((3,)))        # w <- w - 0.5*1
        out = nd.zeros((3,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.5)
        kv.push("w", nd.ones((3,)))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.0)
        assert server.push_count == 2
        kv.close()

    def test_first_optimizer_wins(self, server):
        """A straggler rank's set_optimizer must not rebuild the server
        Updater (that would wipe momentum state mid-training)."""
        kv = mx.kv.create("dist_async")
        kv.init("w", nd.ones((2,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))
        kv.push("w", nd.ones((2,)))        # momentum buffer now nonzero
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))  # straggler rank
        kv.push("w", nd.ones((2,)))
        out = nd.zeros((2,))
        kv.pull("w", out=out)
        # with momentum preserved: w = 1 - 0.5 - (0.5 + 0.45) = -0.45
        # if the straggler had reset the updater: w = 1 - 0.5 - 0.5 = 0.0
        np.testing.assert_allclose(out.asnumpy(), -0.45, atol=1e-6)
        kv.close()

    def test_compressed_push(self, server):
        kv = mx.kv.create("dist_async")
        kv.init("g", nd.zeros((4,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.push("g", nd.array(np.array([0.9, -0.9, 0.1, 0.0], np.float32)))
        out = nd.zeros((4,))
        kv.pull("g", out=out)              # assign semantics, quantized
        np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
        kv.close()

    def test_row_sparse_push_pull(self, server):
        """push_rsp / pull_rows: only touched rows cross the wire
        (reference kvstore_dist.h:228-291)."""
        from mxnet_tpu.ndarray.sparse import row_sparse_array
        kv = mx.kv.create("dist_async")
        kv.init("emb", nd.zeros((6, 3)))
        ids = np.array([1, 4], np.int64)
        rows = np.arange(6, dtype=np.float32).reshape(2, 3)
        # no optimizer: rsp push assigns the touched rows
        kv.push("emb", row_sparse_array((nd.array(rows), ids),
                                        shape=(6, 3)))
        dense = nd.zeros((6, 3))
        kv.pull("emb", out=dense)
        want = np.zeros((6, 3), np.float32)
        want[ids] = rows
        np.testing.assert_array_equal(dense.asnumpy(), want)
        # row_sparse_pull into a RowSparseNDArray gets exactly those rows
        out = row_sparse_array((nd.zeros((1, 3)), np.array([0])),
                               shape=(6, 3))
        kv.row_sparse_pull("emb", out=out, row_ids=nd.array(ids))
        np.testing.assert_array_equal(out.indices.asnumpy(), ids)
        np.testing.assert_array_equal(out.data.asnumpy(), rows)
        kv.close()

    def test_row_sparse_server_optimizer(self, server):
        """Server-side lazy update: an rsp push steps ONLY the touched
        rows (kvstore_dist_server.h ApplyUpdates on row-sparse)."""
        from mxnet_tpu.ndarray.sparse import row_sparse_array
        kv = mx.kv.create("dist_async")
        kv.init("emb", nd.ones((4, 2)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        ids = np.array([2], np.int64)
        kv.push("emb", row_sparse_array(
            (nd.ones((1, 2)), ids), shape=(4, 2)))
        out = nd.zeros((4, 2))
        kv.pull("emb", out=out)
        want = np.ones((4, 2), np.float32)
        want[2] = 0.5                  # only row 2 stepped
        np.testing.assert_allclose(out.asnumpy(), want)
        kv.close()

    def test_compressed_wire_is_packed(self, server):
        """The 2-bit push sends the PACKED word form: wire bytes for the
        gradient must be ~1/16 of f32, not a dequantized full array."""
        from mxnet_tpu import kvstore_server as ps
        kv = mx.kv.create("dist_async")
        n = 4096
        kv.init("big", nd.zeros((n,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        sent = []
        orig = ps.send_msg

        def spy(sock, obj, **kw):
            sent.append(obj)
            return orig(sock, obj, **kw)

        ps.send_msg = spy
        try:
            kv.push("big", nd.ones((n,)))
        finally:
            ps.send_msg = orig
        msg = [m for m in sent if m[0] == "push_2bit"][-1]
        words = np.asarray(msg[2])
        assert words.dtype == np.uint32 and words.size == n // 16
        out = nd.zeros((n,))
        kv.pull("big", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.5)
        kv.close()

    def test_wire_rejects_oversized_blob_header(self, server):
        """decode validates blob size against the declared shape (the
        non-pickle codec's safety check)."""
        from mxnet_tpu.kvstore_server import _decode
        with pytest.raises(mx.MXNetError, match="size mismatch"):
            _decode({"__nd__": 0, "dtype": "<f4", "shape": [100]},
                    [b"\x00" * 8])

    def test_errors_cross_the_wire(self, server):
        kv = mx.kv.create("dist_async")
        with pytest.raises(mx.MXNetError, match="before init"):
            kv.pull("nope", out=nd.zeros((1,)))
        # the connection survives an error reply
        kv.init("x", nd.ones((1,)))
        out = nd.zeros((1,))
        kv.pull("x", out=out)
        assert out.asnumpy()[0] == 1.0
        kv.close()


class TestBucketing:
    """Gradient bucketing: dense multi-key push/pull coalesces into flat
    dtype-segregated buckets (O(params) -> O(buckets) wire messages) and
    must stay BIT-exact with the per-key frames it replaces."""

    SHAPES = [(100,), (200,), (300, 3), (5,), (7, 7)]

    def _init_keys(self, kv):
        vals = [nd.array(np.random.RandomState(i).randn(*s)
                         .astype(np.float32))
                for i, s in enumerate(self.SHAPES)]
        keys = list(range(len(self.SHAPES)))
        for k, v in zip(keys, vals):
            kv.init(k, v)
        return keys, vals

    def _spy(self, ps):
        sent = []
        orig = ps.send_msg

        def spy(sock, obj, **kw):
            sent.append(obj)
            return orig(sock, obj, **kw)

        return sent, spy, orig

    def test_bitexact_vs_perkey_and_message_count(self, server,
                                                  monkeypatch):
        from mxnet_tpu import kvstore_server as ps
        monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "4096")
        kv = mx.kv.create("dist_async")
        keys, vals = self._init_keys(kv)
        sent, spy, orig = self._spy(ps)
        ps.send_msg = spy
        try:
            kv.push(keys, [[v] for v in vals])
            n_push = len([m for m in sent if m[0] == "push_bucket"])
            assert n_push >= 1 and n_push < len(keys)
            assert not [m for m in sent if m[0] == "push"]
            sent.clear()
            outs = [nd.zeros(s) for s in self.SHAPES]
            kv.pull(keys, out=outs)
            n_pull = len([m for m in sent if m[0] == "pull_bucket"])
            assert n_pull >= 1 and n_pull < len(keys)
        finally:
            ps.send_msg = orig
        # per-key pull (bucketing disabled) must agree BIT-exactly
        monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "0")
        perkey = [nd.zeros(s) for s in self.SHAPES]
        kv.pull(keys, out=perkey)
        for v, o, o2 in zip(vals, outs, perkey):
            np.testing.assert_array_equal(o.asnumpy(), o2.asnumpy())
            np.testing.assert_array_equal(o.asnumpy(), v.asnumpy())
        kv.close()

    def test_singleton_stays_plain_push(self, server, monkeypatch):
        from mxnet_tpu import kvstore_server as ps
        monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "4096")
        kv = mx.kv.create("dist_async")
        kv.init("solo", nd.ones((4,)))
        sent, spy, orig = self._spy(ps)
        ps.send_msg = spy
        try:
            kv.push("solo", nd.ones((4,)) * 3)
            out = nd.zeros((4,))
            kv.pull("solo", out=out)
        finally:
            ps.send_msg = orig
        # a single key keeps the unchanged per-key wire format
        assert [m[0] for m in sent if m[0].startswith("push")] == ["push"]
        assert [m[0] for m in sent if m[0].startswith("pull")] == ["pull"]
        np.testing.assert_array_equal(out.asnumpy(), 3.0)
        kv.close()

    def test_pack_buckets_dtype_segregation(self):
        from mxnet_tpu.kvstore import pack_buckets
        entries = [("a", np.zeros(10, np.float32)),
                   ("b", np.zeros(10, np.float64)),
                   ("c", np.zeros(10, np.float32)),
                   ("d", np.zeros(10, np.float64))]
        buckets = pack_buckets(entries, 1 << 20)
        assert len(buckets) == 2
        for b in buckets:
            assert len({a.dtype.str for _, a in b}) == 1
        # order preserved within each dtype group
        assert [k for k, _ in buckets[0]] == ["a", "c"]
        assert [k for k, _ in buckets[1]] == ["b", "d"]
        # budget <= 0 disables: all singletons
        assert all(len(b) == 1 for b in pack_buckets(entries, 0))

    def test_malformed_bucket_frame_rejected(self, server):
        from mxnet_tpu import telemetry
        kv = mx.kv.create("dist_async")
        kv.init("a", nd.ones((4,)))
        e0 = telemetry.value("kvstore_frame_errors_total")
        # declared shapes need 999 values, payload has 4
        with pytest.raises(mx.MXNetError, match="shapes need"):
            kv._rpc("push_bucket", ["a"], [[999]],
                    np.zeros(4, np.float32))
        # frame errors count unconditionally (server thread is in-process)
        assert telemetry.value("kvstore_frame_errors_total") > e0
        # and the connection survives the rejected frame
        out = nd.zeros((4,))
        kv.pull("a", out=out)
        np.testing.assert_array_equal(out.asnumpy(), 1.0)
        kv.close()

    def test_oversized_bucket_rejected(self, server, monkeypatch):
        from mxnet_tpu import telemetry
        kv = mx.kv.create("dist_async")
        kv.init("a", nd.ones((100,)))
        kv.init("b", nd.ones((100,)))
        monkeypatch.setenv("MXNET_KVSTORE_MAX_BUCKET_BYTES", "64")
        e0 = telemetry.value("kvstore_frame_errors_total")
        with pytest.raises(mx.MXNetError, match="exceeds"):
            kv._rpc("push_bucket", ["a", "b"], [[100], [100]],
                    np.zeros(200, np.float32))
        assert telemetry.value("kvstore_frame_errors_total") > e0
        kv.close()

    def test_resnet50_param_set_message_count(self):
        """Acceptance: on ResNet-50's param set the bucketed push sends
        ~ceil(total_grad_bytes / bucket_bytes) messages instead of one
        per param."""
        from mxnet_tpu.kvstore import pack_buckets
        shapes = [(64, 3, 7, 7), (64,), (64,)]        # conv1 + bn1
        cin = 64
        for units, cout in zip([3, 4, 6, 3], [256, 512, 1024, 2048]):
            mid = cout // 4
            for u in range(units):
                for s in [(mid, cin, 1, 1), (mid,), (mid,),
                          (mid, mid, 3, 3), (mid,), (mid,),
                          (cout, mid, 1, 1), (cout,), (cout,)]:
                    shapes.append(s)
                if u == 0:             # projection shortcut
                    shapes += [(cout, cin, 1, 1), (cout,), (cout,)]
                cin = cout
        shapes += [(1000, 2048), (1000,)]              # fc
        total = sum(int(np.prod(s)) for s in shapes)
        assert 23e6 < total < 28e6     # it IS resnet50-sized
        entries = [("p%d" % i, s) for i, s in enumerate(shapes)]
        budget = 4 << 20
        buckets = pack_buckets(
            entries, budget,
            nbytes=lambda s: int(np.prod(s)) * 4,
            group=lambda s: "<f4")
        floor = -(-total * 4 // budget)                # ceil
        # greedy never splits a tensor, so every >4MB conv/fc weight is a
        # bucket of its own and boundaries waste some budget: allow 1.5x
        # the information-theoretic floor, still ~5x fewer than per-key
        assert floor <= len(buckets) <= (floor * 3 + 1) // 2
        assert len(buckets) * 4 < len(shapes)          # >> fewer messages


def test_two_workers_async_convergence():
    """1 server + 2 workers forked via the launcher; async SGD converges
    (end-to-end: role dispatch, retry-connect, server optimizer, stop)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    env = {"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu"}
    rc = launch.launch_local(
        2, [sys.executable, os.path.join(REPO, "tests",
                                         "dist_async_worker.py")],
        env_extra=env, num_servers=1)
    assert rc == 0


def test_two_workers_bucketed_push_pull():
    """1 server + 2 workers with a tiny bucket budget: bucketed push/pull
    is bit-exact vs per-key against the live server (server-side SGD
    updates commute, so the final weights have an analytic expectation)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    env = {"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu",
           "MXNET_KVSTORE_BUCKET_BYTES": "512"}
    rc = launch.launch_local(
        2, [sys.executable, os.path.join(REPO, "tests",
                                         "dist_bucket_worker.py")],
        env_extra=env, num_servers=1)
    assert rc == 0
