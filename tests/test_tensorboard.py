"""TensorBoard event-file writer (parity: contrib/tensorboard.py wrapping
SummaryWriter — here a self-contained writer producing real TFRecord-framed
Event protos that TensorBoard can read)."""
import glob
import os
import struct

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.contrib import tensorboard as tb


def _read_events(path):
    """Parse the TFRecord framing back, verifying both CRCs."""
    events = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        (hcrc,) = struct.unpack_from("<I", data, pos + 8)
        assert hcrc == tb._masked_crc(data[pos:pos + 8])
        payload = data[pos + 12:pos + 12 + length]
        (pcrc,) = struct.unpack_from("<I", data, pos + 12 + length)
        assert pcrc == tb._masked_crc(payload)
        events.append(tb.Event.parse(payload))
        pos += 12 + length + 4
    return events


def test_scalar_events_roundtrip(tmp_path):
    w = tb.SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, 1)
    w.add_scalar("loss", 0.25, 2)
    w.add_histogram("weights", np.random.RandomState(0).randn(100), 2)
    w.close()
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    events = _read_events(files[0])
    assert events[0].file_version == "brain.Event:2"
    scalars = [(e.step, e.summary.value[0].tag, e.summary.value[0].simple_value)
               for e in events[1:3]]
    assert scalars[0] == (1, "loss", 0.5)
    assert scalars[1] == (2, "loss", 0.25)
    histo = events[3].summary.value[0].histo
    assert histo.num == 100.0
    assert len(histo.bucket) == 30
    assert abs(sum(histo.bucket) - 100.0) < 1e-9


def test_log_metrics_callback(tmp_path):
    from mxnet_tpu.callback import BatchEndParam
    metric = mx.metric.create("acc")
    metric.update([mx.nd.array([0, 1])], [mx.nd.array([[0.9, 0.1],
                                                       [0.2, 0.8]])])
    cb = tb.LogMetricsCallback(str(tmp_path), prefix="train")
    cb(BatchEndParam(epoch=0, nbatch=1, eval_metric=metric))
    cb(BatchEndParam(epoch=0, nbatch=2, eval_metric=metric))
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    events = _read_events(files[0])
    tagged = [e for e in events if e.summary is not None and
              e.summary.value and e.summary.value[0].tag]
    assert tagged[0].summary.value[0].tag == "train-accuracy"
    assert abs(tagged[0].summary.value[0].simple_value - 1.0) < 1e-6
