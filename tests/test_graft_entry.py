"""Driver-gate simulation: the driver imports __graft_entry__ with jax
already initialized on whatever hardware exists (often ONE device) and calls
``dryrun_multichip(8)`` directly.  Round-1 failed exactly here
(MULTICHIP_r01 rc=1) — the function must self-force a virtual 8-device mesh.
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_from_single_device_parent():
    """Parent pinned to 1 CPU device => dryrun_multichip(8) must succeed via
    its subprocess fallback (the exact driver call pattern)."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "PYTHONPATH": REPO})
    code = ("import jax, __graft_entry__;"
            "assert len(jax.devices()) == 1, jax.devices();"
            "__graft_entry__.dryrun_multichip(8);"
            "print('GATE-OK')")
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "GATE-OK" in proc.stdout


def test_dryrun_multichip_in_process_when_devices_suffice():
    """With >= n devices already visible (the tests' 8-device virtual mesh),
    the body runs in-process — no subprocess indirection."""
    import jax
    import __graft_entry__
    assert len(jax.devices()) >= 8
    __graft_entry__.dryrun_multichip(8)
