"""Runtime lock-order sanitizer tests (mxnet_tpu/locksmith.py).

In-process: hand-built traced locks exercise the edge recorder and the
live ABBA detector on a deadlock-free interleaving (the two orders just
have to EXIST — sequentially in one thread is enough), and the
static-graph diff semantics (ok edge / inversion / unknown site).

Subprocess: the chaos and serving probes run under ``MXNET_LOCKCHECK=1``
with the static graph pre-dumped (``--dump-lock-graph``) so the exit
hook doesn't re-parse the tree per process; every per-pid report must
come back ok — zero cycles, zero inversions, zero unknown lock sites.
"""
import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu import locksmith  # noqa: E402

SITE_A = "mxnet_tpu/fake.py:10"
SITE_B = "mxnet_tpu/fake.py:20"


@pytest.fixture(autouse=True)
def clean_state(tmp_path, monkeypatch):
    # point the report's static-diff at a tiny pre-dumped graph so no
    # in-process test pays the full-tree parse in _load_static_graph
    path = tmp_path / "default_static.json"
    path.write_text(json.dumps(_static_graph([["la", "lb"]])))
    monkeypatch.setenv("MXNET_LOCKCHECK_STATIC", str(path))
    locksmith.reset()
    yield
    locksmith.reset()


def _traced(site):
    with locksmith._mu:
        locksmith._sites.setdefault(
            site, {"kind": "Lock", "rel": site.rsplit(":", 1)[0],
                   "line": int(site.rsplit(":", 1)[1])})
    return locksmith._TracedLock(threading.Lock(), site)


def _static_graph(edges):
    return {"version": 1,
            "locks": {"la": {}, "lb": {}},
            "sites": {SITE_A: "la", SITE_B: "lb"},
            "edges": edges}


class TestAbbaDetection:
    def test_abba_detected_without_deadlock(self, capsys):
        """A -> B then B -> A, sequentially in one thread: no deadlock
        ever happens, but both orders now exist — the live detector must
        record the cycle the moment the second edge is inserted."""
        a, b = _traced(SITE_A), _traced(SITE_B)
        with a:
            with b:
                pass
        assert not locksmith._cycles
        with b:
            with a:
                pass
        assert len(locksmith._cycles) == 1
        chain = locksmith._cycles[0]["chain"]
        assert chain[0] == chain[-1]
        assert {SITE_A, SITE_B} <= set(chain)
        rep = locksmith.report()
        assert not rep["ok"]
        assert rep["diff"]["cycles"]

    def test_consistent_order_is_clean(self):
        a, b = _traced(SITE_A), _traced(SITE_B)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert not locksmith._cycles
        assert [e[:2] for e in locksmith.report()["edges"]] == \
            [[SITE_A, SITE_B]]

    def test_hand_over_hand_release_order(self):
        """Releasing the OUTER lock first must not corrupt the held
        stack: the next acquisition only sees B held, so no A-edge."""
        a, b = _traced(SITE_A), _traced(SITE_B)
        a.acquire()
        b.acquire()
        a.release()              # outer released first
        c = _traced("mxnet_tpu/fake.py:30")
        c.acquire()
        c.release()
        b.release()
        edges = {tuple(e[:2]) for e in locksmith.report()["edges"]}
        assert (SITE_B, "mxnet_tpu/fake.py:30") in edges
        assert (SITE_A, "mxnet_tpu/fake.py:30") not in edges


class TestStaticDiff:
    def _report_against(self, edges, tmp_path, monkeypatch):
        path = tmp_path / "static.json"
        path.write_text(json.dumps(_static_graph(edges)))
        monkeypatch.setenv("MXNET_LOCKCHECK_STATIC", str(path))
        return locksmith.report()

    def test_edge_in_static_graph_ok(self, tmp_path, monkeypatch):
        a, b = _traced(SITE_A), _traced(SITE_B)
        with a:
            with b:
                pass
        rep = self._report_against([["la", "lb"]], tmp_path, monkeypatch)
        assert rep["static_graph"]
        assert rep["ok"], rep["diff"]
        assert not rep["diff"]["uncovered_edges"]

    def test_inverted_edge_fails(self, tmp_path, monkeypatch):
        a, b = _traced(SITE_A), _traced(SITE_B)
        with b:
            with a:
                pass
        rep = self._report_against([["la", "lb"]], tmp_path, monkeypatch)
        assert rep["diff"]["inversions"] == [["lb", "la"]]
        assert not rep["ok"]

    def test_uncovered_edge_is_informational(self, tmp_path, monkeypatch):
        a, b = _traced(SITE_A), _traced(SITE_B)
        with a:
            with b:
                pass
        rep = self._report_against([], tmp_path, monkeypatch)
        assert rep["diff"]["uncovered_edges"] == [["la", "lb"]]
        assert rep["ok"]     # observed ⊆ static does not hold in general

    def test_unknown_site_fails(self, tmp_path, monkeypatch):
        rogue = _traced("mxnet_tpu/rogue.py:1")
        with rogue:
            pass
        rep = self._report_against([], tmp_path, monkeypatch)
        assert rep["diff"]["unknown_locks"] == ["mxnet_tpu/rogue.py:1"]
        assert not rep["ok"]


def test_install_gated_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_LOCKCHECK", raising=False)
    assert not locksmith.installed()
    assert locksmith.install() is False
    assert threading.Lock is locksmith._real_lock


# ---------------------------------------------------------------------------
# probes under the sanitizer: empty static-vs-dynamic diff
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def static_graph_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("lockcheck") / "lockgraph.json"
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--dump-lock-graph"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    graph = json.loads(out.stdout)
    assert graph["version"] == 1 and graph["sites"]
    path.write_text(out.stdout)
    return str(path)


def _run_probe(script, tmp_path, static_graph_file, timeout):
    report_dir = str(tmp_path / "lockrep")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXNET_LOCKCHECK": "1",
        "MXNET_LOCKCHECK_STATIC": static_graph_file,
        "MXNET_LOCKCHECK_REPORT": report_dir,
    })
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)
    assert out.returncode == 0, out.stdout + out.stderr
    reports = []
    for name in sorted(os.listdir(report_dir)):
        with open(os.path.join(report_dir, name)) as fh:
            reports.append(json.load(fh))
    assert reports, "no lockcheck reports written"
    return reports


def _assert_clean(reports):
    for rep in reports:
        assert rep["enabled"] and rep["static_graph"]
        assert rep["sites"], "sanitizer saw no instrumented locks"
        diff = rep["diff"]
        assert rep["ok"], diff
        assert diff["cycles"] == []
        assert diff["inversions"] == []
        assert diff["unknown_locks"] == []


def test_chaos_probe_clean_under_lockcheck(tmp_path, static_graph_file):
    """Every process of the chaos probe (supervisor + forked gang) must
    exit with an empty static-vs-dynamic lock diff."""
    reports = _run_probe("chaos_probe.py", tmp_path, static_graph_file,
                         timeout=180)
    assert len(reports) > 1, "expected reports from the forked gang too"
    _assert_clean(reports)


def test_serving_probe_clean_under_lockcheck(tmp_path, static_graph_file):
    reports = _run_probe("serving_probe.py", tmp_path, static_graph_file,
                         timeout=120)
    _assert_clean(reports)
