"""dist_async worker for the run-ledger acceptance test: every process
(2 workers + 1 server) auto-enables its own JSONL ledger via
MXNET_RUNLOG_DIR at import, all sharing one MXNET_RUN_ID.  Each rank
seeds synthetic step times (rank 1 is 20x slower, past the straggler
band) so the workers write ``health_verdict`` transitions and the server
writes ``straggler`` edge events; the test then merges the per-process
files into one ordered timeline.

Launched by tests/test_runlog.py via tools/launch.py with MXNET_HEALTH=1,
MXNET_RUNLOG_DIR and MXNET_RUN_ID set.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import health, nd, runlog


def main():
    assert health.enabled, "worker must run with MXNET_HEALTH=1"
    assert runlog.enabled(), "worker must run with MXNET_RUNLOG_DIR set"
    # create() first: in a DMLC_ROLE=server process this enters the server
    # loop and never returns
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2

    step_s = 0.01 if rank == 0 else 0.2
    kv.init("w", nd.zeros((4, 2)))
    kv.barrier()
    for step in range(5):
        # synthetic closed window (see dist_health_worker.py): drives both
        # the worker's own verdict ledger event and the wire piggyback the
        # server's straggler table consumes
        health.monitor.observe_step(step_s)
        kv.push("w", nd.array(np.full((4, 2), rank + step, np.float32)))
        out = nd.zeros((4, 2))
        kv.pull("w", out=out)
    runlog.event("worker_done", steps=5, step_seconds=step_s)
    kv.barrier()
    if rank == 0:
        kv.send_command_to_servers(0, "")   # kStopServer
    kv.close()
    runlog.disable()                        # run_end + close
    print("rank %d ledger=%s" % (rank, runlog.path() or "closed"))
    if rank == 0:
        # keep the launcher's worker-liveness window open so the server
        # finishes its ledger shutdown events before cleanup kills it
        time.sleep(0.5)


if __name__ == "__main__":
    main()
