"""ONNX interchange: proto codec, export -> file -> import round trips.

Parity model: reference tests/python-pytest/onnx (onnx_import/export round
trips over real .onnx files) — here exercised with the self-contained
protobuf codec (mxnet_tpu/contrib/onnx_proto.py), so real serialized bytes
cross the boundary, not in-memory mocks.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu.contrib import onnx as onnx_mx
from mxnet_tpu.contrib import onnx_proto as P


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_proto_scalar_roundtrip():
    t = P.TensorProto(name="w", dims=[2, 3], data_type=P.TensorProto.FLOAT,
                      raw_data=np.arange(6, dtype=np.float32).tobytes())
    t2 = P.TensorProto.parse(t.serialize())
    assert t2.name == "w"
    assert list(t2.dims) == [2, 3]
    assert t2.data_type == 1
    np.testing.assert_array_equal(
        np.frombuffer(t2.raw_data, np.float32),
        np.arange(6, dtype=np.float32))


def test_proto_negative_and_packed_ints():
    a = P.AttributeProto(name="axis", i=-1, type=P.AttributeProto.INT)
    a2 = P.AttributeProto.parse(a.serialize())
    assert a2.i == -1
    a = P.AttributeProto(name="axes", ints=[0, -2, 5],
                         type=P.AttributeProto.INTS)
    a2 = P.AttributeProto.parse(a.serialize())
    assert list(a2.ints) == [0, -2, 5]


def test_proto_nested_model_roundtrip():
    node = P.NodeProto(op_type="Relu", input=["x"], output=["y"], name="r")
    g = P.GraphProto(name="g", node=[node],
                     input=[onnx_mx._vi("x", (1, 3))],
                     output=[onnx_mx._vi("y", (1, 3))])
    m = P.ModelProto(ir_version=4, producer_name="mxnet_tpu", graph=g,
                     opset_import=[P.OperatorSetIdProto(version=9)])
    m2 = P.ModelProto.parse(m.serialize())
    assert m2.ir_version == 4
    assert m2.opset_import[0].version == 9
    assert m2.graph.node[0].op_type == "Relu"
    assert m2.graph.input[0].type.tensor_type.shape.dim[1].dim_value == 3
    # unknown fields must be skipped, not fatal: append a field we don't
    # know (number 15, varint)
    raw = m.serialize() + bytes([(15 << 3) | 0, 7])
    m3 = P.ModelProto.parse(raw)
    assert m3.graph.node[0].op_type == "Relu"


# ---------------------------------------------------------------------------
# export -> import round trips (forward match)
# ---------------------------------------------------------------------------

def _random_params(sym, **input_shapes):
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    rng = np.random.RandomState(0)
    params = {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name in input_shapes:
            continue
        params[name] = (rng.uniform(-0.5, 0.5, size=shp)
                        .astype(np.float32))
    auxs = {}
    for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
        if name.endswith("moving_var"):
            auxs[name] = np.abs(rng.uniform(0.5, 1.5, size=shp)) \
                .astype(np.float32)
        else:
            auxs[name] = rng.uniform(-0.1, 0.1, size=shp) \
                .astype(np.float32)
    return params, auxs


def _forward(sym, params, auxs, data):
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null",
                         data=data.shape)
    ex.copy_params_from({k: nd.array(v) for k, v in params.items()},
                        {k: nd.array(v) for k, v in auxs.items()})
    return ex.forward(is_train=False, data=nd.array(data))[0].asnumpy()


def _roundtrip(sym, data_shape, tmp_path, atol=1e-4):
    params, auxs = _random_params(sym, data=data_shape)
    rng = np.random.RandomState(1)
    data = rng.uniform(-1, 1, size=data_shape).astype(np.float32)
    ref = _forward(sym, params, auxs, data)

    all_params = dict(params)
    all_params.update(auxs)
    path = str(tmp_path / "model.onnx")
    onnx_mx.export_model(sym, all_params, {"data": data_shape},
                         onnx_file=path)

    sym2, args2, auxs2 = onnx_mx.import_model(path)
    got = _forward(sym2,
                   {k: v.asnumpy() for k, v in args2.items()},
                   {k: v.asnumpy() for k, v in auxs2.items()}, data)
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=atol)
    return path


def _lenet():
    S = mx.symbol
    x = S.var("data")
    c1 = S.Convolution(x, kernel=(5, 5), num_filter=8, name="c1")
    a1 = S.Activation(c1, act_type="tanh", name="a1")
    p1 = S.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                   name="p1")
    c2 = S.Convolution(p1, kernel=(3, 3), num_filter=16, pad=(1, 1),
                       name="c2")
    a2 = S.Activation(c2, act_type="relu", name="a2")
    p2 = S.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="avg",
                   name="p2")
    f = S.Flatten(p2, name="flat")
    fc1 = S.FullyConnected(f, num_hidden=32, name="fc1")
    d = S.Dropout(fc1, p=0.5, name="drop")
    fc2 = S.FullyConnected(d, num_hidden=10, name="fc2")
    return S.softmax(fc2, axis=1, name="out")


def _mini_resnet():
    S = mx.symbol
    x = S.var("data")
    c0 = S.Convolution(x, kernel=(3, 3), pad=(1, 1), num_filter=8,
                       no_bias=True, name="c0")
    b0 = S.BatchNorm(c0, fix_gamma=False, name="b0")
    r0 = S.Activation(b0, act_type="relu", name="r0")
    # residual block
    c1 = S.Convolution(r0, kernel=(3, 3), pad=(1, 1), num_filter=8,
                       no_bias=True, name="c1")
    b1 = S.BatchNorm(c1, fix_gamma=False, name="b1")
    r1 = S.Activation(b1, act_type="relu", name="r1")
    c2 = S.Convolution(r1, kernel=(3, 3), pad=(1, 1), num_filter=8,
                       no_bias=True, name="c2")
    b2 = S.BatchNorm(c2, fix_gamma=False, name="b2")
    s = S.elemwise_add(b2, r0, name="res")
    r2 = S.Activation(s, act_type="relu", name="r2")
    g = S.Pooling(r2, global_pool=True, kernel=(1, 1), pool_type="avg",
                  name="gpool")
    f = S.Flatten(g, name="flat")
    fc = S.FullyConnected(f, num_hidden=10, name="fc")
    return S.softmax(fc, axis=1, name="out")


def test_lenet_roundtrip(tmp_path):
    _roundtrip(_lenet(), (2, 1, 28, 28), tmp_path)


def test_mini_resnet_roundtrip(tmp_path):
    path = _roundtrip(_mini_resnet(), (2, 3, 16, 16), tmp_path)
    meta = onnx_mx.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (2, 3, 16, 16))]
    assert len(meta["output_tensor_data"]) == 1


def test_model_zoo_resnet18_roundtrip(tmp_path):
    """Export/import a real model-zoo topology (resnet18_v1)."""
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1()
    net.initialize()
    data_shape = (1, 3, 32, 32)
    x = nd.array(np.random.RandomState(2)
                 .uniform(-1, 1, data_shape).astype(np.float32))
    net(x)  # materialize deferred params
    sym = net(mx.symbol.var("data"))
    params = {}
    for name, p in net.collect_params().items():
        params[name] = p.data().asnumpy()
    ref = net(x).asnumpy()

    path = str(tmp_path / "resnet18.onnx")
    onnx_mx.export_model(sym, params, {"data": data_shape},
                         onnx_file=path)
    sym2, args2, auxs2 = onnx_mx.import_model(path)
    got = _forward(sym2,
                   {k: v.asnumpy() for k, v in args2.items()},
                   {k: v.asnumpy() for k, v in auxs2.items()},
                   x.asnumpy())
    np.testing.assert_allclose(ref, got, rtol=1e-3, atol=1e-3)


def test_misc_op_roundtrip(tmp_path):
    """Elementwise/reshape/transpose/concat/reduce/clip export+import."""
    S = mx.symbol
    x = S.var("data")
    t = S.transpose(x, axes=(0, 2, 1))
    r = S.Reshape(t, shape=(0, -1))
    c = S.concat(r, r, dim=1)
    cl = S.clip(c, a_min=-0.5, a_max=0.5)
    m = S.mean(cl, axis=1, keepdims=True)
    out = S.broadcast_add(cl, m) * 2.0
    sym = S.exp(S.negative(out))
    _roundtrip(sym, (2, 3, 4), tmp_path)


def test_embedding_gather_roundtrip(tmp_path):
    S = mx.symbol
    x = S.var("data")
    e = S.Embedding(x, input_dim=11, output_dim=5, name="emb")
    sym = S.sum(e, axis=-1)
    params = {"emb_weight":
              np.random.RandomState(3).randn(11, 5).astype(np.float32)}
    data = np.array([[1, 2], [10, 0]], np.float32)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=(2, 2))
    ex.copy_params_from({k: nd.array(v) for k, v in params.items()}, {})
    ref = ex.forward(is_train=False, data=nd.array(data))[0].asnumpy()

    path = str(tmp_path / "emb.onnx")
    onnx_mx.export_model(sym, params, {"data": (2, 2)}, onnx_file=path)
    sym2, args2, _ = onnx_mx.import_model(path)
    ex2 = sym2.simple_bind(ctx=mx.cpu(), grad_req="null", data=(2, 2))
    ex2.copy_params_from(args2, {})
    got = ex2.forward(is_train=False, data=nd.array(data))[0].asnumpy()
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)


def test_import_unsupported_op_message(tmp_path):
    g = P.GraphProto(name="g", node=[
        P.NodeProto(op_type="NoSuchOp", input=["x"], output=["y"])],
        input=[onnx_mx._vi("x", (1,))],
        output=[onnx_mx._vi("y", (1,))])
    with pytest.raises(mx.base.MXNetError, match="NoSuchOp"):
        onnx_mx.import_graph(g)


def test_fc_no_flatten_roundtrip(tmp_path):
    """FullyConnected(flatten=False) must export as MatMul, not Gemm."""
    S = mx.symbol
    x = S.var("data")
    sym = S.FullyConnected(x, num_hidden=6, flatten=False, name="proj")
    _roundtrip(sym, (2, 3, 4), tmp_path)


def test_upsampling_roundtrip(tmp_path):
    """Upsample exports scales as an input (opset 9) and reimports."""
    S = mx.symbol
    x = S.var("data")
    sym = S.UpSampling(x, scale=2, sample_type="nearest", num_filter=1,
                       name="up")
    _roundtrip(sym, (1, 2, 4, 4), tmp_path)
    # fractional / unequal scales must raise, not silently truncate
    g = P.GraphProto(name="g", node=[
        P.NodeProto(op_type="Upsample", input=["x"], output=["y"],
                    attribute=[onnx_mx._attr("scales",
                                             (1.0, 1.0, 1.5, 1.5))])],
        input=[onnx_mx._vi("x", (1, 2, 4, 4))],
        output=[onnx_mx._vi("y", (1, 2, 6, 6))])
    with pytest.raises(mx.base.MXNetError, match="Upsample"):
        onnx_mx.import_graph(g)


def test_batchnorm_fix_gamma_export(tmp_path):
    """fix_gamma=True: exported model must behave as gamma==1 even when
    the stored gamma initializer is not 1."""
    S = mx.symbol
    x = S.var("data")
    sym = S.BatchNorm(x, fix_gamma=True, name="bn")
    rng = np.random.RandomState(0)
    params = {"bn_beta": rng.randn(3).astype(np.float32),
              "bn_gamma": np.full((3,), 7.0, np.float32)}  # ignored
    auxs = {"bn_moving_mean": rng.randn(3).astype(np.float32),
            "bn_moving_var": np.abs(rng.randn(3)).astype(np.float32) + .5}
    data = rng.randn(2, 3, 4, 4).astype(np.float32)
    ref = _forward(sym, params, auxs, data)

    all_params = dict(params)
    all_params.update(auxs)
    path = str(tmp_path / "bn.onnx")
    onnx_mx.export_model(sym, all_params, {"data": (2, 3, 4, 4)},
                         onnx_file=path)
    sym2, args2, auxs2 = onnx_mx.import_model(path)
    got = _forward(sym2,
                   {k: v.asnumpy() for k, v in args2.items()},
                   {k: v.asnumpy() for k, v in auxs2.items()}, data)
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-4)


def test_gemm_transb0_import():
    """Gemm with transB=0 (the default many exporters emit) must bind and
    produce x @ w (+ alpha/beta scaling) — regression: shape mismatch."""
    rng = np.random.RandomState(0)
    w = rng.randn(4, 6).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="Gemm", input=["x", "w", "b"],
                          output=["y"], name="gemm",
                          attribute=[onnx_mx._attr("alpha", 2.0),
                                     onnx_mx._attr("beta", 0.5)])],
        initializer=[onnx_mx._np_to_tensor("w", w),
                     onnx_mx._np_to_tensor("b", b)],
        input=[onnx_mx._vi("x", (3, 4))],
        output=[onnx_mx._vi("y", (3, 6))])
    sym, args, auxs = onnx_mx.import_graph(g)
    x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(3, 4))
    ex.copy_params_from(args, auxs)
    got = ex.forward(is_train=False, x=nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(got, 2.0 * (x @ w) + 0.5 * b,
                               rtol=1e-5, atol=1e-5)


def test_reduce_axes_as_input():
    """Opset-13 ReduceSum carries axes as input[1]; must not silently
    reduce over all axes."""
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="ReduceSum", input=["x", "ax"],
                          output=["y"])],
        initializer=[onnx_mx._np_to_tensor(
            "ax", np.asarray([1], np.int64))],
        input=[onnx_mx._vi("x", (2, 3))],
        output=[onnx_mx._vi("y", (2, 1))])
    sym, args, _ = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(2, 3))
    ex.copy_params_from(args, {})
    got = ex.forward(is_train=False,
                     x=nd.array(np.ones((2, 3), np.float32)))[0].asnumpy()
    np.testing.assert_allclose(got, np.full((2, 1), 3.0))


def test_shared_reshape_initializer():
    """Two Reshape nodes sharing one shape initializer (deduplicated
    constants) — regression: second import raised 'dynamic shape'."""
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="Reshape", input=["x", "shp"],
                          output=["a"]),
              P.NodeProto(op_type="Reshape", input=["a", "shp"],
                          output=["y"])],
        initializer=[onnx_mx._np_to_tensor(
            "shp", np.asarray([6], np.int64))],
        input=[onnx_mx._vi("x", (2, 3))],
        output=[onnx_mx._vi("y", (6,))])
    sym, args, _ = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(2, 3))
    ex.copy_params_from(args, {})
    out = ex.forward(is_train=False,
                     x=nd.array(np.arange(6, dtype=np.float32)
                                .reshape(2, 3)))[0].asnumpy()
    assert out.shape == (6,)


def test_export_bn_mean_var_raises():
    S = mx.symbol
    x = S.var("data")
    bn = S.BatchNorm(x, fix_gamma=False, output_mean_var=True, name="bn")
    sym = mx.symbol.Group([bn[0], bn[1]])
    with pytest.raises(mx.base.MXNetError, match="output_mean_var"):
        onnx_mx.export_graph(sym, {"bn_gamma": np.ones((3,), np.float32),
                                   "bn_beta": np.zeros((3,), np.float32),
                                   "bn_moving_mean":
                                       np.zeros((3,), np.float32),
                                   "bn_moving_var":
                                       np.ones((3,), np.float32)},
                             {"data": (2, 3, 4, 4)})


def test_pad_constant_value_input():
    """Opset-11 Pad carries the pad value as input[2]; must not pad 0."""
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="Pad", input=["x", "pads", "cval"],
                          output=["y"])],
        initializer=[
            onnx_mx._np_to_tensor("pads",
                                  np.asarray([0, 0, 0, 1], np.int64)),
            onnx_mx._np_to_tensor("cval", np.asarray(5.0, np.float32))],
        input=[onnx_mx._vi("x", (2, 2))],
        output=[onnx_mx._vi("y", (2, 3))])
    sym, args, _ = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(2, 2))
    ex.copy_params_from(args, {})
    out = ex.forward(is_train=False,
                     x=nd.array(np.ones((2, 2), np.float32)))[0].asnumpy()
    np.testing.assert_array_equal(out[:, -1], np.full((2,), 5.0))


def _np_lstm(X, W, R, B, h0=None, c0=None):
    """Numpy ONNX-semantics LSTM (iofc gate order), forward dir."""
    T, Bn, _ = X.shape
    H = R.shape[2]
    Wi, Wo, Wf, Wc = np.split(W[0], 4, axis=0)
    Ri, Ro, Rf, Rc = np.split(R[0], 4, axis=0)
    bW = B[0][:4 * H]
    bR = B[0][4 * H:]
    bWi, bWo, bWf, bWc = np.split(bW, 4)
    bRi, bRo, bRf, bRc = np.split(bR, 4)
    sig = lambda v: 1 / (1 + np.exp(-v))
    h = np.zeros((Bn, H), np.float32) if h0 is None else h0[0]
    c = np.zeros((Bn, H), np.float32) if c0 is None else c0[0]
    ys = []
    for t in range(T):
        x = X[t]
        i = sig(x @ Wi.T + bWi + h @ Ri.T + bRi)
        o = sig(x @ Wo.T + bWo + h @ Ro.T + bRo)
        f = sig(x @ Wf.T + bWf + h @ Rf.T + bRf)
        g = np.tanh(x @ Wc.T + bWc + h @ Rc.T + bRc)
        c = f * c + i * g
        h = o * np.tanh(c)
        ys.append(h.copy())
    return np.stack(ys)[:, None], h[None], c[None]


def test_onnx_lstm_import():
    rng = np.random.RandomState(0)
    T, B, I, H = 5, 3, 4, 6
    W = rng.randn(1, 4 * H, I).astype(np.float32) * 0.5
    R = rng.randn(1, 4 * H, H).astype(np.float32) * 0.5
    Bb = rng.randn(1, 8 * H).astype(np.float32) * 0.1
    X = rng.randn(T, B, I).astype(np.float32)
    ref_y, ref_h, ref_c = _np_lstm(X, W, R, Bb)

    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="LSTM", input=["x", "W", "R", "B"],
                          output=["y", "yh", "yc"], name="lstm",
                          attribute=[onnx_mx._attr("hidden_size", H)])],
        initializer=[onnx_mx._np_to_tensor("W", W),
                     onnx_mx._np_to_tensor("R", R),
                     onnx_mx._np_to_tensor("B", Bb)],
        input=[onnx_mx._vi("x", (T, B, I))],
        output=[onnx_mx._vi("y", (T, 1, B, H))])
    sym, args, auxs = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(T, B, I))
    ex.copy_params_from(args, auxs)
    got = ex.forward(is_train=False, x=nd.array(X))[0].asnumpy()
    np.testing.assert_allclose(got, ref_y, rtol=1e-4, atol=1e-5)


def test_onnx_gru_import_and_multi_output():
    """GRU (zrh -> rzn remap, linear_before_reset=1) with Y_h consumed."""
    rng = np.random.RandomState(1)
    T, B, I, H = 4, 2, 3, 5
    W = rng.randn(1, 3 * H, I).astype(np.float32) * 0.5
    R = rng.randn(1, 3 * H, H).astype(np.float32) * 0.5
    Bb = rng.randn(1, 6 * H).astype(np.float32) * 0.1
    X = rng.randn(T, B, I).astype(np.float32)

    # numpy ONNX GRU, linear_before_reset=1
    Wz, Wr, Wh = np.split(W[0], 3, axis=0)
    Rz, Rr, Rh = np.split(R[0], 3, axis=0)
    bWz, bWr, bWh = np.split(Bb[0][:3 * H], 3)
    bRz, bRr, bRh = np.split(Bb[0][3 * H:], 3)
    sig = lambda v: 1 / (1 + np.exp(-v))
    h = np.zeros((B, H), np.float32)
    for t in range(T):
        x = X[t]
        z = sig(x @ Wz.T + bWz + h @ Rz.T + bRz)
        r = sig(x @ Wr.T + bWr + h @ Rr.T + bRr)
        n = np.tanh(x @ Wh.T + bWh + r * (h @ Rh.T + bRh))
        h = (1 - z) * n + z * h
    ref_h = h[None]

    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="GRU", input=["x", "W", "R", "B"],
                          output=["y", "yh"], name="gru",
                          attribute=[onnx_mx._attr("hidden_size", H),
                                     onnx_mx._attr(
                                         "linear_before_reset", 1)])],
        initializer=[onnx_mx._np_to_tensor("W", W),
                     onnx_mx._np_to_tensor("R", R),
                     onnx_mx._np_to_tensor("B", Bb)],
        input=[onnx_mx._vi("x", (T, B, I))],
        output=[onnx_mx._vi("yh", (1, B, H))])
    sym, args, auxs = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(T, B, I))
    ex.copy_params_from(args, auxs)
    got = ex.forward(is_train=False, x=nd.array(X))[0].asnumpy()
    np.testing.assert_allclose(got, ref_h, rtol=1e-4, atol=1e-5)


def test_onnx_lstm_bidirectional_shape():
    rng = np.random.RandomState(2)
    T, B, I, H = 3, 2, 4, 5
    W = rng.randn(2, 4 * H, I).astype(np.float32) * 0.4
    R = rng.randn(2, 4 * H, H).astype(np.float32) * 0.4
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="LSTM", input=["x", "W", "R"],
                          output=["y"], name="bilstm",
                          attribute=[onnx_mx._attr("hidden_size", H),
                                     onnx_mx._attr("direction",
                                                   "bidirectional")])],
        initializer=[onnx_mx._np_to_tensor("W", W),
                     onnx_mx._np_to_tensor("R", R)],
        input=[onnx_mx._vi("x", (T, B, I))],
        output=[onnx_mx._vi("y", (T, 2, B, H))])
    sym, args, auxs = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(T, B, I))
    ex.copy_params_from(args, auxs)
    X = rng.randn(T, B, I).astype(np.float32)
    got = ex.forward(is_train=False, x=nd.array(X))[0].asnumpy()
    assert got.shape == (T, 2, B, H)
    assert np.isfinite(got).all()


def test_onnx_misc_new_converters():
    """Where/comparison/Expand/OneHot/reductions import and run."""
    g = P.GraphProto(
        name="g",
        node=[
            P.NodeProto(op_type="Greater", input=["x", "y"], output=["m"]),
            P.NodeProto(op_type="Where", input=["m", "x", "y"],
                        output=["w"]),
            P.NodeProto(op_type="ReduceL2", input=["w"], output=["out"],
                        attribute=[onnx_mx._attr("axes", (1,)),
                                   onnx_mx._attr("keepdims", 0)]),
        ],
        input=[onnx_mx._vi("x", (2, 3)), onnx_mx._vi("y", (2, 3))],
        output=[onnx_mx._vi("out", (2,))])
    sym, args, _ = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(2, 3),
                         y=(2, 3))
    x = np.array([[1, -2, 3], [0, 5, -6]], np.float32)
    y = np.array([[0, 0, 4], [1, 1, 1]], np.float32)
    got = ex.forward(is_train=False, x=nd.array(x),
                     y=nd.array(y))[0].asnumpy()
    expect = np.linalg.norm(np.where(x > y, x, y), axis=1)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_onnx_rnn_relu_activation():
    """STRINGS-typed activations attribute parses; Relu RNN computes relu
    recurrences (regression: silently imported as tanh)."""
    rng = np.random.RandomState(3)
    T, B, I, H = 3, 2, 3, 4
    W = np.abs(rng.randn(1, H, I)).astype(np.float32)
    R = np.abs(rng.randn(1, H, H)).astype(np.float32) * 0.1
    a = P.AttributeProto(name="activations", strings=[b"Relu"],
                         type=P.AttributeProto.STRINGS)
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="RNN", input=["x", "W", "R"],
                          output=["y"], name="rnn",
                          attribute=[onnx_mx._attr("hidden_size", H), a])],
        initializer=[onnx_mx._np_to_tensor("W", W),
                     onnx_mx._np_to_tensor("R", R)],
        input=[onnx_mx._vi("x", (T, B, I))],
        output=[onnx_mx._vi("y", (T, 1, B, H))])
    sym, args, auxs = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(T, B, I))
    ex.copy_params_from(args, auxs)
    X = np.abs(rng.randn(T, B, I)).astype(np.float32) * 2
    got = ex.forward(is_train=False, x=nd.array(X))[0].asnumpy()
    # relu recurrence on positive weights/inputs grows past tanh's bound
    assert got.max() > 1.5, got.max()


def test_onnx_stable_logsumexp_and_onehot_values():
    g = P.GraphProto(
        name="g",
        node=[
            P.NodeProto(op_type="ReduceLogSumExp", input=["x"],
                        output=["lse"],
                        attribute=[onnx_mx._attr("axes", (1,)),
                                   onnx_mx._attr("keepdims", 0)]),
            P.NodeProto(op_type="OneHot", input=["idx", "depth", "vals"],
                        output=["oh"]),
        ],
        initializer=[
            onnx_mx._np_to_tensor("depth", np.asarray([3], np.int64)),
            onnx_mx._np_to_tensor("vals",
                                  np.asarray([2.0, 10.0], np.float32))],
        input=[onnx_mx._vi("x", (2, 2)), onnx_mx._vi("idx", (2,))],
        output=[onnx_mx._vi("lse", (2,)), onnx_mx._vi("oh", (2, 3))])
    sym, args, _ = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(2, 2),
                         idx=(2,))
    ex.copy_params_from(args, {})
    x = np.array([[100.0, 0.0], [1.0, 2.0]], np.float32)
    outs = ex.forward(is_train=False, x=nd.array(x),
                      idx=nd.array(np.array([0, 2], np.float32)))
    lse = outs[0].asnumpy()
    assert np.isfinite(lse).all()
    np.testing.assert_allclose(
        lse, np.log(np.exp(x - x.max(1, keepdims=True)).sum(1))
        + x.max(1), rtol=1e-5)
    oh = outs[1].asnumpy()
    np.testing.assert_allclose(
        oh, np.array([[10, 2, 2], [2, 2, 10]], np.float32))


def test_onnx_lstm_peephole_raises():
    W = np.zeros((1, 16, 3), np.float32)
    R = np.zeros((1, 16, 4), np.float32)
    Pw = np.zeros((1, 12), np.float32)
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="LSTM",
                          input=["x", "W", "R", "", "", "", "", "P"],
                          output=["y"],
                          attribute=[onnx_mx._attr("hidden_size", 4)])],
        initializer=[onnx_mx._np_to_tensor("W", W),
                     onnx_mx._np_to_tensor("R", R),
                     onnx_mx._np_to_tensor("P", Pw)],
        input=[onnx_mx._vi("x", (2, 2, 3))],
        output=[onnx_mx._vi("y", (2, 1, 2, 4))])
    with pytest.raises(mx.base.MXNetError, match="peephole"):
        onnx_mx.import_graph(g)


def test_expand_rank_and_one_dims():
    """Expand with rank expansion and 1-dims (ONNX bidirectional
    broadcast) — regression: broadcast_to rejected both forms."""
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="Expand", input=["x", "shp"],
                          output=["y"]),
              P.NodeProto(op_type="Expand", input=["y", "shp2"],
                          output=["z"])],
        initializer=[
            onnx_mx._np_to_tensor("shp", np.asarray([2, 3], np.int64)),
            onnx_mx._np_to_tensor("shp2", np.asarray([1, 3], np.int64))],
        input=[onnx_mx._vi("x", (3,))],
        output=[onnx_mx._vi("z", (2, 3))])
    sym, args, _ = onnx_mx.import_graph(g)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", x=(3,))
    ex.copy_params_from(args, {})
    got = ex.forward(is_train=False,
                     x=nd.array(np.array([1, 2, 3], np.float32)))[0]
    np.testing.assert_allclose(got.asnumpy(),
                               np.tile([1, 2, 3], (2, 1)))
