"""NDArray core tests (parity model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), 0)
    b = nd.ones((2, 2), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 3), 7.5)
    assert np.allclose(c.asnumpy(), 7.5)
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = nd.arange(0, 10, 2)
    assert np.allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert np.allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    assert np.allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    assert np.allclose((a * b).asnumpy(), [[10, 40], [90, 160]])
    assert np.allclose((b / a).asnumpy(), [[10, 10], [10, 10]])
    assert np.allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((1 - a).asnumpy(), [[0, -1], [-2, -3]])
    assert np.allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    assert np.allclose((8 / a).asnumpy(), [[8, 4], [8 / 3, 2]])
    assert np.allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert np.allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])


def test_inplace():
    a = nd.ones((2, 2))
    a += 1
    assert np.allclose(a.asnumpy(), 2)
    a *= 3
    assert np.allclose(a.asnumpy(), 6)
    a /= 2
    assert np.allclose(a.asnumpy(), 3)
    a -= 1
    assert np.allclose(a.asnumpy(), 2)


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert np.allclose((a > b).asnumpy(), [0, 0, 1])
    assert np.allclose((a >= b).asnumpy(), [0, 1, 1])
    assert np.allclose((a == b).asnumpy(), [0, 1, 0])
    assert np.allclose((a != 2).asnumpy(), [1, 0, 1])


def test_broadcast():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = nd.array([1.0, 2.0])
    assert c.broadcast_to((3, 2)).shape == (3, 2)


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert np.allclose(a[1].asnumpy(), [4, 5, 6, 7])
    assert np.allclose(a[1:3].asnumpy(), np.arange(12).reshape(3, 4)[1:3])
    assert np.allclose(a[:, 2].asnumpy(), [2, 6, 10])
    a[0] = 100.0
    assert np.allclose(a[0].asnumpy(), 100)
    a[:] = 0.0
    assert np.allclose(a.asnumpy(), 0)
    a[1, 2] = 5.0
    assert a.asnumpy()[1, 2] == 5.0


def test_reshape_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((24,)).shape == (24,)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)
    assert a.reshape((6, 4)).shape == (6, 4)


def test_reductions():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.sum().asscalar() == 15
    assert np.allclose(a.sum(axis=0).asnumpy(), [3, 5, 7])
    assert np.allclose(a.sum(axis=1, keepdims=True).asnumpy(), [[3], [12]])
    assert a.max().asscalar() == 5
    assert a.min().asscalar() == 0
    assert np.isclose(a.mean().asscalar(), 2.5)
    assert np.allclose(a.argmax(axis=1).asnumpy(), [2, 2])
    n = a.norm().asscalar()
    assert np.isclose(n, np.sqrt((np.arange(6) ** 2).sum()), rtol=1e-5)


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    c = nd.dot(a, b)
    assert c.shape == (3, 5)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    # transpose flags
    d = nd.dot(a, b.T, transpose_b=True)
    assert np.allclose(d.asnumpy(), a.asnumpy() @ b.asnumpy(), rtol=1e-5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_copy_context():
    a = nd.ones((2, 2))
    b = a.copy()
    b += 1
    assert np.allclose(a.asnumpy(), 1)
    assert np.allclose(b.asnumpy(), 2)
    c = a.as_in_context(mx.cpu(0))
    assert c.context.device_type == "cpu"


def test_astype_scalar():
    a = nd.array([3.7])
    assert a.astype("int32").dtype == np.int32
    assert np.isclose(a.asscalar(), 3.7)
    assert float(a) == pytest.approx(3.7)


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs")
    a, b = nd.ones((2, 2)), nd.zeros((3,))
    nd.save(f, [a, b])
    loaded = nd.load(f)
    assert len(loaded) == 2
    assert np.allclose(loaded[0].asnumpy(), 1)
    nd.save(f, {"w": a, "b": b})
    d = nd.load(f)
    assert set(d) == {"w", "b"}


def test_take_one_hot_pick():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array([0, 2], dtype="int32")
    t = nd.take(w, idx)
    assert np.allclose(t.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = nd.one_hot(idx, 4)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0, 0], [0, 0, 1, 0]])
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    p = nd.pick(x, nd.array([0, 1]), axis=1)
    assert np.allclose(p.asnumpy(), [1, 4])


def test_elemwise_math():
    a = nd.array([1.0, 4.0, 9.0])
    assert np.allclose(nd.sqrt(a).asnumpy(), [1, 2, 3])
    assert np.allclose(nd.square(a).asnumpy(), [1, 16, 81])
    assert np.allclose(nd.exp(nd.zeros((2,))).asnumpy(), 1)
    assert np.allclose(nd.relu(nd.array([-1.0, 2.0])).asnumpy(), [0, 2])
    assert np.allclose(nd.clip(a, 2.0, 5.0).asnumpy(), [2, 4, 5])
    assert np.allclose(nd.add_n(a, a, a).asnumpy(), 3 * a.asnumpy())


def test_wait_sync():
    a = nd.ones((4, 4))
    b = a * 2
    b.wait_to_read()
    nd.waitall()
    assert np.allclose(b.asnumpy(), 2)
