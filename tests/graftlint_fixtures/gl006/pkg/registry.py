"""Known-good: the allowlisted choke point may open scopes, and calls
that merely LOOK like named_scope (other modules) stay silent."""
import jax
import contextlib


def choke_point(fn, scope):
    def wrapped(*arrays):
        with jax.named_scope(scope):
            return fn(*arrays)
    return wrapped


class _Scopes:
    @staticmethod
    def named_scope(name):
        return contextlib.nullcontext()


def not_jax(x):
    # same attribute name, non-jax provenance: silent
    with _Scopes.named_scope("NotJax:ok"):
        return x
