"""Known-bad: ops opening their own scopes corrupt atlas attribution."""
import jax
import jax as _jax
from jax import named_scope


def bad_dotted(x):
    with jax.named_scope("MyOp:custom"):
        return x + 1


def bad_aliased(x):
    with _jax.named_scope("MyOp:aliased"):
        return x * 2


def bad_bare(x):
    with named_scope("MyOp:bare"):
        return x - 1
