"""GL007 fixture: four doc-table failure modes + two silent reads.

- MXNET_FIX_OK       documented, default matches        -> silent
- MXNET_FIX_MISSING  read here, no doc row              -> undocumented
- (MXNET_FIX_GONE)   doc row, no read anywhere          -> ghost
- MXNET_FIX_DRIFT    doc default 3, code default 2      -> default-drift
- MXNET_FIX_MODDRIFT doc says pkg.other, read is here   -> module-drift
- MXNET_FIX_TAINTED  routed through a keyed accessor    -> silent
  (the env-taint pass must materialize it at the _knob call site)
"""
import os

OK = os.environ.get("MXNET_FIX_OK", "1")
MISSING = os.environ.get("MXNET_FIX_MISSING", "0")
DRIFT = os.environ.get("MXNET_FIX_DRIFT", "2")
MODDRIFT = os.environ.get("MXNET_FIX_MODDRIFT", "x")


def _knob(key, default=None):
    return os.environ.get(key, default)


def tainted():
    return _knob("MXNET_FIX_TAINTED")
