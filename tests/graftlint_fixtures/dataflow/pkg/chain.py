"""Dataflow-core fixture: a 3-hop env-key taint chain (top -> hop1 ->
hop2 -> read_env) and a with-statement lock alias (lk = _lk_a) whose
held set must order _lk_a before _lk_b."""
import os
import threading

_lk_a = threading.Lock()
_lk_b = threading.Lock()


def read_env(key):
    return os.environ.get(key)


def hop2(k):
    return read_env(k)


def hop1(name):
    return hop2(name)


def top():
    return hop1("MXNET_FIX_CHAIN")


def locked():
    lk = _lk_a
    with lk:
        with _lk_b:
            pass
