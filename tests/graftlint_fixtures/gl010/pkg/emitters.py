"""GL010 fixture: one documented emit, one undocumented emit, one
dynamic-name emit (flagged — only runlog.py's own shims may forward a
parameterized name)."""
from . import runlog as _runlog


def good(step):
    _runlog.event("fixture_documented", step=step)


def bad(step):
    _runlog.event("fixture_undocumented", step=step)


def dynamic(name):
    _runlog.event(name)
