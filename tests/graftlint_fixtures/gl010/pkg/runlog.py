"""GL010 fixture ledger: the module-level ``event`` forwarder passes a
parameterized name through — exempt from the dynamic-name finding
because this IS the runlog module."""


class _Log:
    def event(self, event_type, **fields):
        return (event_type, fields)


log = _Log()


def event(event_type, **fields):
    return log.event(event_type, **fields)
