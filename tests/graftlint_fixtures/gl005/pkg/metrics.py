from . import telemetry

GOOD = telemetry.counter("documented_total", "in the docs")
BAD = telemetry.gauge("undocumented_gauge", "missing from the docs")
