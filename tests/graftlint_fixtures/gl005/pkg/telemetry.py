class _Metric:
    pass


def counter(name, doc, labels=()):
    return _Metric()


def gauge(name, doc, labels=()):
    return _Metric()
