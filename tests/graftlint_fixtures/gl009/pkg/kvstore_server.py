"""GL009 fixture, server half: dispatch literals, wrapper-key
pack/parse sets, and context validators — one complete
(``_check_health_ctx``), one missing its completeness check
(``_check_trace_ctx``)."""

_TC_KEYS = frozenset(("t", "s"))
_HC_KEYS = frozenset(("r", "st"))
_MUTATING = frozenset(("push",))


def _frame_error(msg):
    raise ValueError(msg)


def _check_trace_ctx(tc):
    if set(tc) - _TC_KEYS:
        _frame_error("unknown trace keys")
    return tc


def _check_health_ctx(hc):
    if set(hc) - _HC_KEYS:
        _frame_error("unknown health keys")
    if set(hc) != _HC_KEYS:
        _frame_error("missing health keys")
    return hc


def _pack_payload(node, trace_ctx=None, health_ctx=None):
    node = {"m": node}
    if trace_ctx:
        node["tc"] = dict(trace_ctx)
    if health_ctx:
        node["h"] = dict(health_ctx)
    node["dbg"] = {}
    return node


def _parse_payload(hdr):
    extra = set(hdr) - {"m", "tc", "h", "zz"}
    if extra:
        _frame_error("unknown wrapper keys")
    tc = _check_trace_ctx(hdr["tc"]) if "tc" in hdr else None
    hc = _check_health_ctx(hdr["h"]) if "h" in hdr else None
    return hdr["m"], tc, hc


def handle(cmd, payload):
    if cmd == "push":
        return "ok"
    if cmd == "pull":
        return "ok"
    if cmd == "dead_cmd":
        return "ok"
    _frame_error("unknown command")
