"""GL009 fixture, client half: cmd literals via ``self._rpc``, a
health-context dict drifted against the server's key table, and a
replay-guarded op set drifted against the server's ``_MUTATING``."""

_SEQ_OPS = frozenset(("push", "extra_op"))


class Client:
    def _rpc(self, cmd, **kw):
        return cmd, kw

    def push(self, key, value):
        return self._rpc("push", key=key, value=value)

    def pull(self, key):
        return self._rpc("pull", key=key)

    def renamed(self):
        # server side was renamed; nothing compares against this cmd
        return self._rpc("renamed_cmd")

    def heartbeat(self):
        health_ctx = {"r": 1, "extra": 2}
        return self._rpc("push", health_ctx=health_ctx)
