"""GL009 fixture: the trace context the client sends rides in from
``flow_out`` — its dict keys are the client half of the ``tc`` wire
contract (the "x" key is the drift)."""


def flow_out(span):
    if span is None:
        return {"t": "0", "s": "0"}
    return {"t": span.trace_id, "s": span.span_id, "x": span.extra}
