"""GL011 fixture: callbacks under a lock (bad) vs snapshot-then-fire
(good) vs an in-project callee with a hook-shaped name (analysed for
real, not assumed hostile)."""
import threading


class Sched:
    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks = []
        self._level = 0

    def register(self, cb):
        with self._lock:
            self._callbacks.append(cb)

    def fire_bad(self, level):
        with self._lock:
            self._level = level
            for cb in self._callbacks:
                cb(level)

    def fire_hook_bad(self, hook):
        with self._lock:
            hook(self._level)

    def fire_good(self, level):
        with self._lock:
            self._level = level
            cbs = list(self._callbacks)
        for cb in cbs:
            cb(level)

    def _refresh_hook(self):
        return self._level

    def fire_internal_ok(self):
        with self._lock:
            self._refresh_hook()
