import os
import time

import jax
import numpy as np

from . import telemetry

STEPS = telemetry.counter("steps_total", "steps taken")


@jax.jit
def bad_step(x):
    STEPS.inc()
    t = time.time()
    r = np.random.rand()
    print("tracing")
    if os.environ.get("MXNET_TPU_FLAG"):
        x = x + 1
    return x + t + r


@jax.jit
def syncing(x):
    y = x.asnumpy()
    return y


@jax.jit
def good_step(x):
    return helper(x)


def helper(x):
    return x * 2


def host_path(x):
    # runs on the HOST through the callback below: must never be flagged
    print("host side")
    return x


@jax.jit
def with_callback(x):
    jax.debug.callback(host_path, x)
    return x
