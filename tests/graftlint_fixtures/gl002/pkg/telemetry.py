class _Metric:
    def inc(self, n=1):
        pass

    def labels(self, **kw):
        return self


def counter(name, doc, labels=()):
    return _Metric()


def gauge(name, doc, labels=()):
    return _Metric()


def histogram(name, doc, labels=()):
    return _Metric()
