"""GL008 fixture: thread discipline good/bad pairs.

Good: daemon ctor kwarg, joined local, self-daemonizing subclass,
late ``x.daemon = True``.  Bad: fire-and-forget non-daemon ctor
(unjoined), joined-but-hangable target (timeout-less queue.get), and a
non-daemon Thread subclass whose ``run`` reaches the same hang.
"""
import queue
import threading

_q = queue.Queue()


def work():
    pass


def drain():
    while True:
        item = _q.get()
        if item is None:
            break


def spawn_daemon():
    t = threading.Thread(target=drain, daemon=True)
    t.start()


def spawn_joined():
    t = threading.Thread(target=work)
    t.start()
    t.join(timeout=5)


def spawn_late_daemon():
    ld = threading.Thread(target=work)
    ld.daemon = True
    ld.start()


def spawn_bad():
    t2 = threading.Thread(target=work)
    t2.start()


def spawn_hang():
    h = threading.Thread(target=drain)
    h.start()
    h.join(timeout=5)


class GoodWorker(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)

    def run(self):
        work()


class BadWorker(threading.Thread):
    def run(self):
        drain()


def spawn_subclasses():
    GoodWorker().start()
    w = BadWorker()
    w.start()
