import threading


class Safe:
    def __init__(self):
        self._m1 = threading.Lock()
        self._m2 = threading.Lock()

    def one(self):
        with self._m1:
            with self._m2:
                pass

    def two(self):
        with self._m1:
            with self._m2:
                pass

    def fetch(self, sock):
        # blocking under a lock, but NOT in the hot-path module scope
        with self._m1:
            return sock.recv(64)
