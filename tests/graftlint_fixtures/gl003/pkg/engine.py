import threading


class Engine:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def ab(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def ba(self):
        with self._lock_b:
            with self._lock_a:
                pass

    def slow(self, sock):
        with self._lock_a:
            data = sock.recv(1024)
        return data


class CondEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def waiter(self, q):
        with self._cv:
            item = q.get()
        return item
