import os

import jax


class Exec:
    STEP_ENV_KEYS = ("MXNET_TPU_STEP_OK", "MXNET_TPU_STEP_DEAD")

    def build(self):
        def fn(x):
            if os.environ.get("MXNET_TPU_STEP_OK"):
                return x + 1
            if os.environ.get("MXNET_TPU_ROGUE"):
                return x - 1
            return x
        return jax.jit(fn)
