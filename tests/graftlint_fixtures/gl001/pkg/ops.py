import os

from .registry import register


@register("GoodOp", env_keys=("MXNET_TPU_GOOD",))
def good_op(x):
    if os.environ.get("MXNET_TPU_GOOD"):
        return x + 1
    return x


@register("LeakyOp")
def leaky_op(x):
    # read on the trace path with no env_keys declaration
    if os.environ.get("MXNET_TPU_LEAK"):
        return x * 2
    return x


@register("StaleOp", env_keys=("MXNET_TPU_STALE",))
def stale_op(x):
    return x


@register("DynOp")
def dyn_op(x):
    key = "MXNET_TPU_" + "DYN"
    if os.environ.get(key):
        return x
    return x
