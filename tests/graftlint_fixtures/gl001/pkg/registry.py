"""Stand-in for mxnet_tpu.ops.registry: only the decorator shape matters."""


def register(name, env_keys=(), **kwargs):
    def deco(fn):
        return fn
    return deco
