class DonationPool:
    def take(self, key):
        pass

    def give(self, key, handle, value):
        pass
