def audit_donation(name, donated):
    pass
