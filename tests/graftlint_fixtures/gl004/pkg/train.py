from functools import partial

import jax

from .health import audit_donation


def build_good():
    @partial(jax.jit, donate_argnums=(0,))
    def step(p, g):
        return p - g
    return step


def run_good(p, g):
    fn = build_good()
    out = fn(p, g)
    audit_donation("good", (p,))
    return out


class Trainer:
    def build(self):
        @partial(jax.jit, donate_argnums=(0,))
        def step(p, g):
            return p - g
        self._fn = step

    def step(self, p, g):
        out = self._fn(p, g)
        audit_donation("trainer", (p,))
        return out


def build_bad():
    @partial(jax.jit, donate_argnums=(0,))
    def step(p, g):
        return p - g
    return step


def build_call_site():
    def step(p, g):
        return p - g
    return jax.jit(step, donate_argnums=(0,))
