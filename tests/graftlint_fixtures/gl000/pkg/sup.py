import jax


@jax.jit
def suppressed_ok(x):
    # graftlint: disable=GL002 -- trace-time banner is intentional
    print("banner")
    return x


@jax.jit
def suppressed_noreason(x):
    print("banner")  # graftlint: disable=GL002
    return x
