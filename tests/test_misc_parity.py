"""Tests for the op-registry parity tail (histogram/ravel/slice-assign/
scatter/sampling/square_sum/adagrad/KL-reg/aliases).

Parity model: reference tests/python/unittest/test_operator.py sections
test_histogram, test_ravel, test_scatter_ops, test_multisample,
test_square_sum (test_sparse_operator.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_histogram_uniform_bins():
    h, e = nd._histogram(nd.array([0.1, 0.2, 0.6, 0.9, 1.5]),
                         bin_cnt=2, range=(0.0, 1.0))
    np.testing.assert_array_equal(h.asnumpy(), [2, 2])   # 1.5 out of range
    np.testing.assert_allclose(e.asnumpy(), [0.0, 0.5, 1.0])


def test_histogram_explicit_bins():
    h, _ = nd._histogram(nd.array([1.0, 2.0, 3.0, 4.0]),
                         nd.array([0.0, 2.5, 5.0]))
    np.testing.assert_array_equal(h.asnumpy(), [2, 2])


def test_ravel_unravel_roundtrip():
    coords = nd.array([[1., 0., 2.], [2., 1., 3.]])
    flat = nd.ravel_multi_index(coords, shape=(3, 4))
    np.testing.assert_allclose(flat.asnumpy(), [6., 1., 11.])
    back = nd.unravel_index(flat, shape=(3, 4))
    np.testing.assert_allclose(back.asnumpy(), coords.asnumpy())


def test_slice_assign():
    x = nd.zeros((4, 4))
    y = nd._slice_assign(x, nd.ones((2, 2)), begin=(1, 1), end=(3, 3))
    out = y.asnumpy()
    assert out[1:3, 1:3].sum() == 4 and out.sum() == 4
    z = nd._slice_assign_scalar(x, scalar=5.0, begin=(0, 0), end=(1, 4))
    assert z.asnumpy()[0].sum() == 20
    # NDArray __setitem__ lowers through the same path
    w = nd.zeros((3, 3))
    w[1:2, :] = 7.0
    assert w.asnumpy()[1].sum() == 21


def test_scatter_set_nd():
    lhs = nd.ones((3, 3))
    idx = nd.array([[0., 2.], [1., 0.]])
    out = nd._scatter_set_nd(lhs, nd.array([5., 6.]), idx, shape=(3, 3))
    o = out.asnumpy()
    # indexed cells set, everything else KEPT (indexing_op.cc:680)
    assert o[0, 1] == 5 and o[2, 0] == 6 and o.sum() == 11 + 7


def test_square_sum():
    x = nd.array([[1., 2.], [3., 4.]])
    np.testing.assert_allclose(
        nd._square_sum(x, axis=(1,)).asnumpy(), [5., 25.])
    np.testing.assert_allclose(float(nd._square_sum(x).asnumpy()), 30.)


def test_sparse_adagrad_rejects_wd():
    with pytest.raises(mx.MXNetError, match="does not support wd"):
        nd._sparse_adagrad_update(nd.ones((2,)), nd.ones((2,)),
                                  nd.zeros((2,)), lr=0.1, wd=1e-4)


def test_sparse_adagrad_update_writeback():
    w = nd.ones((3,))
    g = nd.array([1., 0., 2.])
    hist = nd.zeros((3,))
    w2 = nd._sparse_adagrad_update(w, g, hist, lr=0.1)
    h = hist.asnumpy()
    assert h[0] == 1.0 and h[1] == 0.0 and h[2] == 4.0
    out = w2.asnumpy()
    assert out[1] == 1.0 and out[0] < 1.0            # zero-grad row frozen


def test_sampling_tails():
    lam = nd.array([1.0, 10.0])
    s = nd.sample_exponential(lam, shape=(800,)).asnumpy()
    assert s.shape == (2, 800)
    m = s.mean(axis=1)
    assert 0.8 < m[0] < 1.2 and 0.08 < m[1] < 0.12
    p = nd.sample_poisson(nd.array([4.0]), shape=(800,)).asnumpy()
    assert 3.5 < p.mean() < 4.5
    numpy_var = p.var()
    assert 3.0 < numpy_var < 5.5                      # Poisson: var == mean
    b = nd.sample_negative_binomial(nd.array([5.0]), nd.array([0.5]),
                                    shape=(800,)).asnumpy()
    assert 4.0 < b.mean() < 6.0                       # k(1-p)/p = 5
    g = nd.sample_generalized_negative_binomial(
        nd.array([4.0]), nd.array([0.25]), shape=(800,)).asnumpy()
    assert 3.2 < g.mean() < 4.8


def test_kl_sparse_reg_gradient():
    # momentum=0 -> updated moving avg == this batch's per-unit mean
    x = nd.array(np.stack([np.full(4, 0.5, np.float32),
                           np.full(4, 0.25, np.float32)], axis=1))  # (4, 2)
    x.attach_grad()
    avg = nd.array([0.1, 0.1])
    with autograd.record():
        y = nd.IdentityAttachKLSparseReg(x, avg, sparseness_target=0.1,
                                         penalty=1.0, momentum=0.0)
        s = y.sum()
    s.backward()
    g = x.grad.asnumpy()
    # per-unit penalties: unit0 rho=0.5 -> 1.6; unit1 rho=0.25 -> 0.8
    np.testing.assert_allclose(g[:, 0], 1.0 + (-0.1 / 0.5 + 0.9 / 0.5),
                               rtol=1e-4)
    np.testing.assert_allclose(g[:, 1], 1.0 + (-0.1 / 0.25 + 0.9 / 0.75),
                               rtol=1e-4)
    # aux moving average written back per unit
    np.testing.assert_allclose(avg.asnumpy(), [0.5, 0.25], rtol=1e-5)


def test_reference_name_aliases():
    from mxnet_tpu.ops.registry import OPS
    for name in ("MakeLoss", "Reorg", "NewReorg", "_scatter_plus_scalar",
                 "_scatter_elemwise_div", "_grad_add", "cast_storage",
                 "_identity_with_attr_like_rhs"):
        assert name in OPS, name


def test_registry_covers_reference_surface():
    """Spot-check: every op family head from SURVEY.md N7 resolves."""
    from mxnet_tpu.ops.registry import OPS
    heads = ["Convolution", "FullyConnected", "Pooling", "BatchNorm",
             "RNN", "Embedding", "dot", "batch_dot", "topk", "sort",
             "_linalg_gemm", "_contrib_MultiBoxPrior", "_contrib_CTCLoss",
             "_contrib_quantize", "Custom", "_foreach", "BilinearSampler",
             "SpatialTransformer", "Correlation", "SVMOutput",
             "_image_to_tensor", "_sample_poisson", "_histogram"]
    missing = [h for h in heads if h not in OPS]
    assert not missing, missing
