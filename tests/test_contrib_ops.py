"""Tests for contrib operators (detection family + misc).

Parity model: tests/python/unittest/test_contrib_operator.py and
test_operator.py multibox/bounding-box/CTC sections of the reference.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_multibox_prior_layout():
    x = nd.zeros((1, 3, 2, 3))
    out = nd.contrib.MultiBoxPrior(x, sizes=(0.5,), ratios=(1.0,))
    a = out.asnumpy()
    assert a.shape == (1, 2 * 3 * 1, 4)
    # first anchor centred at ((0+.5)/3, (0+.5)/2) with w=.5*h/w/2, h=.5/2
    cx, cy = 0.5 / 3, 0.5 / 2
    w, h = 0.5 * 2 / 3 / 2, 0.5 / 2
    np.testing.assert_allclose(a[0, 0], [cx - w, cy - h, cx + w, cy + h],
                               atol=1e-6)


def test_multibox_prior_clip_and_count():
    x = nd.zeros((1, 3, 4, 4))
    out = nd.contrib.MultiBoxPrior(x, sizes=(0.9, 0.4), ratios=(1, 2, 0.5),
                                   clip=True)
    a = out.asnumpy()
    # anchors per pixel = num_sizes - 1 + num_ratios = 4
    assert a.shape == (1, 4 * 4 * 4, 4)
    assert a.min() >= 0.0 and a.max() <= 1.0


def test_multibox_target_basic():
    anchors = nd.array([[[0., 0., .5, .5], [.5, .5, 1., 1.],
                         [0., 0., 1., 1.]]])
    # one gt of class 1 overlapping anchor 0 region
    label = nd.array([[[1., .0, .0, .45, .45], [-1, -1, -1, -1, -1]]])
    cls_pred = nd.zeros((1, 3, 3))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    ct = cls_t.asnumpy()[0]
    # best-matching anchor gets class 1+1=2, others negative (0)
    assert ct[0] == 2.0
    assert ct[1] == 0.0 and ct[2] == 0.0
    lm = loc_m.asnumpy().reshape(3, 4)
    assert lm[0].all() and not lm[1].any() and not lm[2].any()


def test_multibox_target_no_gt():
    anchors = nd.array([[[0., 0., .5, .5], [.5, .5, 1., 1.]]])
    label = nd.full((1, 2, 5), -1.0)
    cls_pred = nd.zeros((1, 3, 2))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    assert (cls_t.asnumpy() == -1.0).all()      # ignore_label everywhere
    assert (loc_m.asnumpy() == 0).all()
    assert (loc_t.asnumpy() == 0).all()


def test_multibox_target_negative_mining():
    anchors = nd.array([[[0., 0., .5, .5], [.5, .5, 1., 1.],
                         [0., .5, .5, 1.], [.5, 0., 1., .5]]])
    label = nd.array([[[0., .0, .0, .5, .5]]])
    cls_pred = nd.zeros((1, 2, 4))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, negative_mining_ratio=1.0,
        negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 1.0                          # positive
    # exactly 1 negative mined (ratio 1:1), rest ignore
    assert (ct == 0).sum() == 1
    assert (ct == -1).sum() == 2


def test_multibox_detection_roundtrip():
    # anchors + zero loc_pred + variance decode = anchors themselves
    anchors = nd.array([[[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]]])
    cls_prob = nd.array([[[0.1, 0.2], [0.9, 0.8]]])   # class 1 wins both
    loc_pred = nd.zeros((1, 8))
    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       nms_threshold=0.5).asnumpy()[0]
    assert out.shape == (2, 6)
    # both kept (no overlap), sorted by score desc
    np.testing.assert_allclose(out[0], [0, 0.9, 0.1, 0.1, 0.4, 0.4],
                               atol=1e-5)
    np.testing.assert_allclose(out[1], [0, 0.8, 0.6, 0.6, 0.9, 0.9],
                               atol=1e-5)


def test_multibox_detection_threshold_and_nms():
    anchors = nd.array([[[0., 0., 1., 1.], [0.02, 0., 1.02, 1.],
                         [0.5, 0.5, 0.6, 0.6]]])
    cls_prob = nd.array([[[0.1, 0.1, 0.9], [0.9, 0.8, 0.05]]])
    loc_pred = nd.zeros((1, 12))
    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       threshold=0.5,
                                       nms_threshold=0.5).asnumpy()[0]
    ids = out[:, 0]
    # overlapping duplicate suppressed, low-score anchor dropped
    assert (ids >= 0).sum() == 1


def test_multibox_detection_topk_keeps_fields():
    # beyond-top-k rows lose their id but keep score/coords
    # (multibox_detection.cc:155-160 semantics)
    anchors = nd.array([[[0., 0., .1, .1], [0.4, 0.4, .5, .5],
                         [0.8, 0.8, .9, .9]]])
    cls_prob = nd.array([[[0.1, 0.2, 0.3], [0.9, 0.8, 0.7]]])
    loc_pred = nd.zeros((1, 12))
    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       nms_topk=2,
                                       nms_threshold=0.5).asnumpy()[0]
    assert out[0, 0] == 0 and out[1, 0] == 0
    assert out[2, 0] == -1                      # id dropped
    np.testing.assert_allclose(out[2, 1], 0.7)  # but score kept


def test_multibox_detection_background_id():
    anchors = nd.array([[[0.1, 0.1, 0.4, 0.4]]])
    loc_pred = nd.zeros((1, 4))
    # background last: class 0 and 1 are foreground
    cls_prob = nd.array([[[0.1], [0.7], [0.2]]])
    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       background_id=2).asnumpy()[0]
    assert out[0, 0] == 1 and abs(out[0, 1] - 0.7) < 1e-6


def test_bipartite_matching_topk():
    score = nd.array([[[0.9, 0.1], [0.2, 0.8]]])
    rowm, _ = nd.contrib.bipartite_matching(score, threshold=0.05, topk=1)
    assert (rowm.asnumpy() >= 0).sum() == 1


def test_box_nms():
    dets = nd.array([[[0, 0.9, 0, 0, 1, 1],
                      [0, 0.8, 0.05, 0, 1.05, 1],
                      [1, 0.7, 2, 2, 3, 3]]])
    out, = [nd.contrib.box_nms(dets, overlap_thresh=0.5, id_index=0)]
    o = out.asnumpy()[0]
    assert o.shape == (3, 6)
    # survivors compacted to the front in score order; trailing rows -1
    np.testing.assert_allclose(o[0, 1], 0.9)
    np.testing.assert_allclose(o[1, 1], 0.7)     # different class survives
    assert (o[2] == -1).all()                    # suppressed duplicate gone


def test_box_nms_valid_thresh_topk():
    dets = nd.array([[[0.9, 0, 0, 1, 1],
                      [0.05, 2, 2, 3, 3],
                      [0.8, 5, 5, 6, 6]]])
    out = nd.contrib.box_nms(dets, overlap_thresh=0.5, valid_thresh=0.1,
                             coord_start=1, score_index=0, topk=1)
    o = out.asnumpy()[0]
    assert (o[0] >= 0).all()
    assert (o[1:] == -1).all()


def test_box_iou():
    l = nd.array([[0., 0., 1., 1.]])
    r = nd.array([[0.5, 0., 1.5, 1.], [2., 2., 3., 3.]])
    out = nd.contrib.box_iou(l, r).asnumpy()
    np.testing.assert_allclose(out, [[1. / 3, 0.]], atol=1e-6)


def test_bipartite_matching():
    score = nd.array([[[0.9, 0.1], [0.2, 0.8]]])
    rowm, colm = nd.contrib.bipartite_matching(score, threshold=0.5)
    np.testing.assert_allclose(rowm.asnumpy(), [[0., 1.]])
    np.testing.assert_allclose(colm.asnumpy(), [[0., 1.]])
    # below threshold -> unmatched
    rowm2, _ = nd.contrib.bipartite_matching(
        nd.array([[[0.4, 0.1], [0.2, 0.3]]]), threshold=0.5)
    assert (rowm2.asnumpy() == -1).all()


def test_roi_pooling():
    feat = nd.array(np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8))
    rois = nd.array([[0, 0, 0, 7, 7]])
    out = nd.ROIPooling(feat, rois, pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    np.testing.assert_allclose(out[0, 0], [[27, 31], [59, 63]])


def test_roi_align_matches_interior():
    feat = nd.array(np.ones((1, 2, 8, 8), np.float32) * 3.0)
    rois = nd.array([[0, 1, 1, 6, 6]])
    out = nd.contrib.ROIAlign(feat, rois, pooled_size=(3, 3),
                              spatial_scale=1.0).asnumpy()
    np.testing.assert_allclose(out, np.full((1, 2, 3, 3), 3.0), atol=1e-5)


def test_psroi_pooling_constant():
    # constant per position-sensitive channel -> each output channel constant
    data = np.zeros((1, 2 * 9, 6, 6), np.float32)
    for d in range(2):
        for g in range(9):
            data[0, d * 9 + g] = d * 10 + g
    out = nd.contrib.PSROIPooling(nd.array(data), nd.array([[0, 0, 0, 5, 5]]),
                                  spatial_scale=1.0, output_dim=2,
                                  pooled_size=3).asnumpy()
    expect = np.arange(9).reshape(3, 3)
    np.testing.assert_allclose(out[0, 0], expect, atol=1e-5)
    np.testing.assert_allclose(out[0, 1], expect + 10, atol=1e-5)


def test_deformable_conv_zero_offset_equals_conv():
    rng = np.random.RandomState(0)
    dat = nd.array(rng.randn(2, 4, 6, 6).astype(np.float32))
    off = nd.zeros((2, 2 * 9, 6, 6))
    wt = nd.array(rng.randn(8, 4, 3, 3).astype(np.float32))
    dc = nd.contrib.DeformableConvolution(dat, off, wt, kernel=(3, 3),
                                          pad=(1, 1), num_filter=8,
                                          no_bias=True)
    conv = nd.Convolution(dat, wt, kernel=(3, 3), pad=(1, 1), num_filter=8,
                          no_bias=True)
    np.testing.assert_allclose(dc.asnumpy(), conv.asnumpy(), atol=1e-4)


def test_deformable_conv_shift_offset():
    # offset of exactly +1 in x == shifting the sampled image
    dat = np.zeros((1, 1, 5, 5), np.float32)
    dat[0, 0, 2, 3] = 1.0
    off = np.zeros((1, 2, 5, 5), np.float32)
    off[0, 1] = 1.0                              # dx = +1 for the 1x1 tap
    wt = np.ones((1, 1, 1, 1), np.float32)
    out = nd.contrib.DeformableConvolution(
        nd.array(dat), nd.array(off), nd.array(wt), kernel=(1, 1),
        num_filter=1, no_bias=True).asnumpy()
    assert out[0, 0, 2, 2] == 1.0 and out[0, 0, 2, 3] == 0.0


def test_proposal_shapes_and_batch_index():
    rng = np.random.RandomState(0)
    cls_prob = nd.array(rng.rand(1, 2 * 12, 4, 4).astype(np.float32))
    bbox = nd.array((rng.randn(1, 4 * 12, 4, 4) * 0.1).astype(np.float32))
    iminfo = nd.array([[64., 64., 1.0]])
    rois = nd.contrib.Proposal(cls_prob, bbox, iminfo,
                               rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10)
    r = rois.asnumpy()
    assert r.shape == (10, 5)
    assert (r[:, 0] == 0).all()
    assert (r[:, 1:3] >= 0).all() and (r[:, 3:] <= 63).all()
    rois2, scores = nd.contrib.Proposal(cls_prob, bbox, iminfo,
                                        rpn_pre_nms_top_n=50,
                                        rpn_post_nms_top_n=10,
                                        output_score=True)
    assert scores.shape == (10, 1)


def test_multi_proposal_batch():
    rng = np.random.RandomState(1)
    cls_prob = nd.array(rng.rand(2, 24, 4, 4).astype(np.float32))
    bbox = nd.array((rng.randn(2, 48, 4, 4) * 0.1).astype(np.float32))
    iminfo = nd.array([[64., 64., 1.], [64., 64., 1.]])
    rois = nd.contrib.MultiProposal(cls_prob, bbox, iminfo,
                                    rpn_pre_nms_top_n=50,
                                    rpn_post_nms_top_n=5).asnumpy()
    assert rois.shape == (10, 5)
    assert (rois[:5, 0] == 0).all() and (rois[5:, 0] == 1).all()


# ---------------------------------------------------------------------------
# misc contrib
# ---------------------------------------------------------------------------
def test_ctc_loss_simple():
    # T=2, A=2 (blank=0, one symbol), label = [1]: paths for "1":
    # (1,1), (1,blank), (blank,1) -> p = p1(1)p2(1)+p1(1)p2(0)+p1(0)p2(1)
    logits = np.zeros((2, 1, 2), np.float32)     # uniform 0.5 probs
    label = np.array([[1., 0.]], np.float32)
    loss = nd.contrib.CTCLoss(nd.array(logits), nd.array(label)).asnumpy()
    np.testing.assert_allclose(loss[0], -np.log(0.75), atol=1e-5)


def test_ctc_loss_blank_last():
    logits = np.zeros((2, 1, 2), np.float32)
    label = np.array([[0., -1.]], np.float32)    # symbol 0, blank = A-1
    loss = nd.contrib.CTCLoss(nd.array(logits), nd.array(label),
                              blank_label="last").asnumpy()
    np.testing.assert_allclose(loss[0], -np.log(0.75), atol=1e-5)


def test_ctc_loss_gradient_flows():
    rng = np.random.RandomState(0)
    data = nd.array(rng.randn(6, 2, 5).astype(np.float32))
    label = nd.array([[1, 2, 0], [3, 1, 2]])
    data.attach_grad()
    with mx.autograd.record():
        loss = nd.contrib.CTCLoss(data, label)
        s = loss.sum()
    s.backward()
    g = data.grad.asnumpy()
    assert np.abs(g).sum() > 0 and np.isfinite(g).all()


def test_fft_ifft_roundtrip():
    rng = np.random.RandomState(0)
    d = rng.randn(3, 8).astype(np.float32)
    f = nd.contrib.fft(nd.array(d))
    assert f.shape == (3, 16)
    back = nd.contrib.ifft(f).asnumpy() / 8
    np.testing.assert_allclose(back, d, atol=1e-4)
    # fft of constant = DC spike
    c = nd.contrib.fft(nd.array(np.ones((1, 4), np.float32))).asnumpy()
    np.testing.assert_allclose(c[0, 0], 4.0, atol=1e-5)
    assert np.abs(c[0, 2:]).max() < 1e-5


def test_count_sketch():
    data = nd.array([[1., 2., 3.]])
    h = nd.array([[0, 2, 0]])
    s = nd.array([[1, -1, 1]])
    out = nd.contrib.count_sketch(data, h, s, out_dim=3).asnumpy()
    np.testing.assert_allclose(out, [[4., 0., -2.]])


def test_khatri_rao():
    a = nd.array([[1., 2.], [3., 4.]])
    b = nd.array([[1., 0.], [0., 1.]])
    out = nd.khatri_rao(a, b).asnumpy()
    np.testing.assert_allclose(out, [[1, 0], [0, 2], [3, 0], [0, 4]])


def test_quadratic():
    out = nd.contrib.quadratic(nd.array([1., 2.]), a=1, b=2, c=3).asnumpy()
    np.testing.assert_allclose(out, [6., 11.])


def test_div_sqrt_dim():
    x = nd.array(np.ones((2, 16), np.float32))
    out = nd.contrib.div_sqrt_dim(x).asnumpy()
    np.testing.assert_allclose(out, np.full((2, 16), 0.25), atol=1e-6)


def test_adaptive_avg_pooling():
    img = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = nd.contrib.AdaptiveAvgPooling2D(img, output_size=(2, 2)).asnumpy()
    np.testing.assert_allclose(out.reshape(4), [2.5, 4.5, 10.5, 12.5])
    glob = nd.contrib.AdaptiveAvgPooling2D(img).asnumpy()
    np.testing.assert_allclose(glob.reshape(1), [7.5])


def test_bilinear_resize():
    img = nd.array(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = nd.contrib.BilinearResize2D(img, height=3, width=3).asnumpy()
    np.testing.assert_allclose(out[0, 0],
                               [[0, .5, 1], [1, 1.5, 2], [2, 2.5, 3]],
                               atol=1e-6)


def test_bilinear_resize_grad():
    img = nd.array(np.random.rand(1, 1, 4, 4).astype(np.float32))
    img.attach_grad()
    with mx.autograd.record():
        out = nd.contrib.BilinearResize2D(img, height=8, width=8)
        s = out.sum()
    s.backward()
    g = img.grad.asnumpy()
    np.testing.assert_allclose(g.sum(), 64.0, rtol=1e-4)


def test_channel_operator():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(1, 6, 1, 2) )
    gmax = nd.contrib.ChannelOperator(x, op_type="Group_Max", group=3)
    assert gmax.shape == (1, 2, 1, 2)
    np.testing.assert_allclose(gmax.asnumpy()[0, :, 0, 0], [4., 10.])
    sm = nd.contrib.ChannelOperator(x, op_type="Group_Softmax", group=3)
    assert sm.shape == x.shape
    s = sm.asnumpy().reshape(2, 3, 2).sum(axis=1)
    np.testing.assert_allclose(s, np.ones((2, 2)), atol=1e-5)


def test_symbol_contrib_compose():
    data = mx.sym.var("data")
    out = mx.sym.contrib.BilinearResize2D(data, height=4, width=4)
    ex = out.bind(mx.cpu(), {"data": nd.ones((1, 1, 2, 2))})
    y = ex.forward()[0]
    assert y.shape == (1, 1, 4, 4)
