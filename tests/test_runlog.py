"""Durable run ledger (mxnet_tpu/runlog.py).

Covers the JSONL line schema and per-process seq ordering, rotation,
torn-line-tolerant merge, the env snapshot (step cache-key flags always
present), write-failure accounting, the module-level enable/disable
lifecycle, and the 2-worker dist_async acceptance run: every process
writes its own ledger and the merge produces one ordered timeline with
rank-attributed health verdicts and server-side straggler edges.
"""
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mxnet_tpu import runlog, telemetry


@pytest.fixture(autouse=True)
def _clean():
    runlog.disable()
    telemetry.reset()
    yield
    runlog.disable()
    telemetry.reset()


def _lines(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _counter_value(name, label=None):
    fam = telemetry.registry().get(name)
    for lv, v in (fam.samples() if fam is not None else []):
        if label is None or lv == (label,):
            return v
    return 0.0


# ---------------------------------------------------------------------------
# RunLog object
# ---------------------------------------------------------------------------
class TestRunLog:
    def test_line_schema_and_seq(self, tmp_path):
        log = runlog.RunLog(str(tmp_path / "r.jsonl"), run_id="rid-1")
        assert log.event("alpha", k=1)
        assert log.event("beta", nested={"a": [1, 2]})
        log.close()
        recs = _lines(log.path)
        assert [r["event"] for r in recs] == ["alpha", "beta"]
        assert [r["seq"] for r in recs] == [0, 1]
        for r in recs:
            assert r["run_id"] == "rid-1"
            assert isinstance(r["ts"], float)
            assert r["role"] == "local" and r["rank"] == "0"
        assert recs[1]["nested"] == {"a": [1, 2]}

    def test_payload_cannot_mask_envelope(self, tmp_path):
        log = runlog.RunLog(str(tmp_path / "r.jsonl"), run_id="rid-2")
        log.event("x", run_id="spoof", ts="spoof", seq="spoof")
        log.close()
        rec = _lines(log.path)[0]
        assert rec["run_id"] == "rid-2"
        assert isinstance(rec["ts"], float) and rec["seq"] == 0

    def test_unserializable_payload_falls_back_to_str(self, tmp_path):
        log = runlog.RunLog(str(tmp_path / "r.jsonl"))
        assert log.event("odd", obj=object()) is True
        log.close()
        assert "object object" in _lines(log.path)[0]["obj"]

    def test_rotation(self, tmp_path):
        p = str(tmp_path / "r.jsonl")
        log = runlog.RunLog(p, max_bytes=1500)
        n = 0
        while not os.path.exists(p + ".1") and n < 200:
            assert log.event("tick", i=n)
            n += 1
        log.close()
        assert os.path.exists(p) and os.path.exists(p + ".1")
        # stop right after the first rotation: no line lost across the
        # boundary, seq stays monotonic through the rename
        recs = runlog.merge([p + ".1", p])
        assert [r["i"] for r in recs] == list(range(n))
        assert [r["seq"] for r in recs] == list(range(n))

    def test_write_failure_counts_drop(self, tmp_path):
        d = tmp_path / "blocked"
        d.mkdir()
        log = runlog.RunLog(str(d))  # path is a directory: open() fails
        before = _counter_value("runlog_write_errors_total")
        assert log.event("doomed") is False
        assert _counter_value("runlog_write_errors_total") == before + 1


# ---------------------------------------------------------------------------
# env snapshot + module lifecycle
# ---------------------------------------------------------------------------
class TestModuleLifecycle:
    def test_enable_writes_run_start_with_step_env_keys(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.delenv("MXNET_TPU_FUSED_STEP", raising=False)
        p = str(tmp_path / "m.jsonl")
        log = runlog.enable(p, run_id="rid-m")
        assert runlog.enabled() and runlog.run_id() == "rid-m"
        assert runlog.path() == p
        assert runlog.enable("ignored") is log        # idempotent
        runlog.event("custom", x=1)
        runlog.disable()
        assert not runlog.enabled() and runlog.event("late") is False
        recs = _lines(p)
        assert [r["event"] for r in recs] == ["run_start", "custom",
                                              "run_end"]
        env = recs[0]["env"]
        # cache-key flags snapshotted even when unset: "unset" is a state
        assert env["MXNET_TPU_FUSED_STEP"] == ""
        assert recs[0]["pid"] == os.getpid()
        assert isinstance(recs[0]["argv"], list)

    def test_enable_without_path_or_env_is_noop(self, monkeypatch):
        monkeypatch.delenv("MXNET_RUNLOG_PATH", raising=False)
        monkeypatch.delenv("MXNET_RUNLOG_DIR", raising=False)
        assert runlog.enable() is None
        assert not runlog.enabled()

    def test_default_path_from_dir(self, tmp_path, monkeypatch):
        monkeypatch.delenv("MXNET_RUNLOG_PATH", raising=False)
        monkeypatch.setenv("MXNET_RUNLOG_DIR", str(tmp_path))
        log = runlog.enable()
        name = os.path.basename(log.path)
        assert name == "runlog_local0_%d.jsonl" % os.getpid()
        runlog.disable()

    def test_events_counter_labelled_by_type(self, tmp_path):
        runlog.enable(str(tmp_path / "m.jsonl"))
        runlog.event("bench_result", value=1.0)
        assert _counter_value("runlog_events_total", "run_start") == 1.0
        assert _counter_value("runlog_events_total", "bench_result") == 1.0


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------
class TestMerge:
    def test_merge_orders_and_attributes_source(self, tmp_path):
        a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        la = runlog.RunLog(a, run_id="rid")
        lb = runlog.RunLog(b, run_id="rid")
        la.event("first")
        time.sleep(0.01)
        lb.event("second")
        time.sleep(0.01)
        la.event("third")
        la.close(); lb.close()
        recs = runlog.merge([a, b])
        assert [r["event"] for r in recs] == ["first", "second", "third"]
        assert [r["source"] for r in recs] == ["a.jsonl", "b.jsonl",
                                               "a.jsonl"]

    def test_merge_skips_torn_lines_and_missing_files(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        log = runlog.RunLog(p)
        log.event("ok")
        log.close()
        with open(p, "a") as f:
            f.write('{"ts": 1.0, "event": "torn')   # simulated power loss
        recs = runlog.merge([p, str(tmp_path / "nope.jsonl")])
        assert [r["event"] for r in recs] == ["ok"]

    def test_merge_cli(self, tmp_path, capsys):
        p = str(tmp_path / "c.jsonl")
        log = runlog.RunLog(p, run_id="rid-cli")
        log.event("one")
        log.close()
        assert runlog.main(["merge", p]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert json.loads(out[0])["event"] == "one"
        assert runlog.main(["merge"]) == 2       # usage error


# ---------------------------------------------------------------------------
# 2-worker dist_async ledger acceptance run
# ---------------------------------------------------------------------------
class TestDistLedger:
    def test_two_worker_merged_timeline(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import launch

        ldir = str(tmp_path / "ledgers")
        worker = os.path.join(REPO, "tests", "dist_runlog_worker.py")
        rc = launch.launch_local(
            2, [sys.executable, worker],
            env_extra={"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu",
                       "MXNET_HEALTH": "1",
                       "MXNET_RUNLOG_DIR": ldir,
                       "MXNET_RUN_ID": "dist-accept"},
            num_servers=1)
        assert rc == 0
        # the server writes its shutdown events between serve_forever
        # returning and launcher cleanup; give the race a moment
        deadline = time.time() + 10
        files = []
        while time.time() < deadline:
            files = sorted(os.listdir(ldir))
            if len(files) == 3 and any("server" in f for f in files):
                break
            time.sleep(0.1)
        assert len(files) == 3, files
        roles = [f.split("_")[1] for f in files]
        assert sorted(roles) == ["server0", "worker0", "worker1"]

        recs = runlog.merge([os.path.join(ldir, f) for f in files])
        assert all(r["run_id"] == "dist-accept" for r in recs)
        # one ordered timeline: ts never decreases
        ts = [r["ts"] for r in recs]
        assert ts == sorted(ts)
        # every process opened its ledger
        starts = [r for r in recs if r["event"] == "run_start"]
        assert len(starts) == 3
        assert {(r["role"], r["rank"]) for r in starts} == {
            ("server", "0"), ("worker", "0"), ("worker", "1")}
        # rank-attributed health verdicts from BOTH workers
        verdicts = [r for r in recs if r["event"] == "health_verdict"]
        assert {r["rank"] for r in verdicts} == {"0", "1"}
        assert all(r["role"] == "worker" for r in verdicts)
        by_rank = {r["rank"]: r for r in verdicts}
        assert by_rank["0"]["step_seconds"] == pytest.approx(0.01)
        assert by_rank["1"]["step_seconds"] == pytest.approx(0.2)
        # the server attributed rank 1 as the straggler (edge event)
        edges = [r for r in recs if r["event"] == "straggler"]
        assert edges and all(r["role"] == "server" for r in edges)
        assert any(r["worker_rank"] == "1" and r["straggler"] is True
                   for r in edges)
        assert not any(r["worker_rank"] == "0" and r["straggler"] is True
                       for r in edges)
        # server shutdown wrote the final table + run_end
        tables = [r for r in recs if r["event"] == "straggler_table"]
        assert tables and tables[-1]["workers"]["1"]["straggler"] is True
        assert [r for r in recs if r["event"] == "run_end"]
        # both workers completed their synthetic phase
        done = [r for r in recs if r["event"] == "worker_done"]
        assert {r["rank"] for r in done} == {"0", "1"}
