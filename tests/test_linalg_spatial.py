"""Tests for linalg ops, spatial-transform ops, and _foreach control flow.

Parity model: reference tests/python/unittest/test_operator.py sections
test_laop*, test_stn, test_bilinear_sampler, test_grid_generator,
test_correlation, test_svmoutput; tests/python/unittest/test_contrib_control_flow.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestLinalg:
    def test_gemm(self):
        rng = np.random.RandomState(0)
        a = rng.randn(2, 3, 4).astype(np.float32)
        b = rng.randn(2, 4, 5).astype(np.float32)
        c = rng.randn(2, 3, 5).astype(np.float32)
        out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                             alpha=2.0, beta=0.5).asnumpy()
        np.testing.assert_allclose(out, 2 * np.matmul(a, b) + 0.5 * c,
                                   rtol=1e-4, atol=1e-4)
        # transpose flags
        out2 = nd.linalg_gemm2(nd.array(a), nd.array(c),
                               transpose_a=True, transpose_b=False).asnumpy()
        np.testing.assert_allclose(
            out2, np.matmul(a.transpose(0, 2, 1), c), rtol=1e-4, atol=1e-4)

    def test_potrf_potri(self):
        spd = np.array([[[4., 2.], [2., 3.]]], np.float32)
        l = nd.linalg_potrf(nd.array(spd))
        lv = l.asnumpy()
        np.testing.assert_allclose(np.matmul(lv, lv.transpose(0, 2, 1)), spd,
                                   atol=1e-4)
        assert np.allclose(np.triu(lv[0], 1), 0)
        inv = nd.linalg_potri(l).asnumpy()
        np.testing.assert_allclose(np.matmul(inv, spd),
                                   np.eye(2)[None], atol=1e-3)

    def test_trmm_trsm(self):
        rng = np.random.RandomState(1)
        l = np.tril(rng.rand(1, 3, 3) + 1.0).astype(np.float32)
        b = rng.randn(1, 3, 2).astype(np.float32)
        tr = nd.linalg_trmm(nd.array(l), nd.array(b)).asnumpy()
        np.testing.assert_allclose(tr, np.matmul(l, b), rtol=1e-4, atol=1e-4)
        ts = nd.linalg_trsm(nd.array(l), nd.array(tr)).asnumpy()
        np.testing.assert_allclose(ts, b, rtol=1e-3, atol=1e-3)
        # rightside + transpose roundtrip
        br = rng.randn(1, 2, 3).astype(np.float32)
        tr2 = nd.linalg_trmm(nd.array(l), nd.array(br), rightside=True,
                             transpose=True).asnumpy()
        np.testing.assert_allclose(tr2, np.matmul(br, l.transpose(0, 2, 1)),
                                   rtol=1e-4, atol=1e-4)
        ts2 = nd.linalg_trsm(nd.array(l), nd.array(tr2), rightside=True,
                             transpose=True).asnumpy()
        np.testing.assert_allclose(ts2, br, rtol=1e-3, atol=1e-3)

    def test_sumlogdiag_syrk(self):
        spd = np.array([[[4., 2.], [2., 3.]]], np.float32)
        out = nd.linalg_sumlogdiag(nd.array(spd)).asnumpy()
        np.testing.assert_allclose(out, [np.log(4) + np.log(3)], rtol=1e-5)
        a = np.random.RandomState(0).randn(1, 2, 4).astype(np.float32)
        sy = nd.linalg_syrk(nd.array(a), alpha=1.5).asnumpy()
        np.testing.assert_allclose(sy, 1.5 * np.matmul(a, a.transpose(0, 2, 1)),
                                   rtol=1e-4, atol=1e-4)

    def test_gelqf(self):
        a = np.random.RandomState(2).randn(1, 2, 4).astype(np.float32)
        l, q = nd.linalg_gelqf(nd.array(a))
        lv, qv = l.asnumpy(), q.asnumpy()
        np.testing.assert_allclose(np.matmul(lv, qv), a, atol=1e-3)
        np.testing.assert_allclose(np.matmul(qv, qv.transpose(0, 2, 1)),
                                   np.eye(2)[None], atol=1e-3)
        assert np.allclose(np.triu(lv[0], 1), 0, atol=1e-5)
        assert (np.diag(lv[0]) >= 0).all()

    def test_syevd(self):
        spd = np.array([[[4., 2.], [2., 3.]]], np.float32)
        u, w = nd.linalg_syevd(nd.array(spd))
        uv, wv = u.asnumpy(), w.asnumpy()
        assert wv[0, 0] <= wv[0, 1]                       # ascending
        rec = np.matmul(uv.transpose(0, 2, 1) * wv[:, None, :], uv)
        np.testing.assert_allclose(rec, spd, atol=1e-3)

    def test_gemm_gradient(self):
        a = nd.array(np.random.rand(1, 2, 3).astype(np.float32))
        b = nd.array(np.random.rand(1, 3, 2).astype(np.float32))
        c = nd.array(np.zeros((1, 2, 2), np.float32))
        a.attach_grad()
        with mx.autograd.record():
            out = nd.linalg_gemm(a, b, c)
            s = out.sum()
        s.backward()
        expect = np.matmul(np.ones((1, 2, 2), np.float32),
                           b.asnumpy().transpose(0, 2, 1))
        np.testing.assert_allclose(a.grad.asnumpy(), expect, rtol=1e-4,
                                   atol=1e-4)


class TestSpatial:
    def test_bilinear_sampler_identity(self):
        img = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                             indexing="ij")
        grid = nd.array(np.stack([xs, ys])[None].astype(np.float32))
        out = nd.BilinearSampler(img, grid).asnumpy()
        np.testing.assert_allclose(out, img.asnumpy(), atol=1e-3)

    def test_bilinear_sampler_outside_is_zero(self):
        img = nd.array(np.ones((1, 1, 4, 4), np.float32))
        grid = nd.array(np.full((1, 2, 2, 2), -3.0, np.float32))
        out = nd.BilinearSampler(img, grid).asnumpy()
        np.testing.assert_allclose(out, 0.0)

    def test_grid_generator_affine(self):
        ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                             indexing="ij")
        theta = nd.array([[1., 0., 0., 0., 1., 0.]])
        out = nd.GridGenerator(theta, transform_type="affine",
                               target_shape=(4, 4)).asnumpy()
        np.testing.assert_allclose(out[0, 0], xs, atol=1e-4)
        np.testing.assert_allclose(out[0, 1], ys, atol=1e-4)
        # translation shifts x by 0.5
        theta2 = nd.array([[1., 0., 0.5, 0., 1., 0.]])
        out2 = nd.GridGenerator(theta2, transform_type="affine",
                                target_shape=(4, 4)).asnumpy()
        np.testing.assert_allclose(out2[0, 0], xs + 0.5, atol=1e-4)

    def test_grid_generator_warp(self):
        ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                             indexing="ij")
        out = nd.GridGenerator(nd.zeros((1, 2, 4, 4)),
                               transform_type="warp").asnumpy()
        np.testing.assert_allclose(out[0, 0], xs, atol=1e-5)
        np.testing.assert_allclose(out[0, 1], ys, atol=1e-5)

    def test_spatial_transformer_identity(self):
        img = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        theta = nd.array([[1., 0., 0., 0., 1., 0.]])
        out = nd.SpatialTransformer(img, theta, target_shape=(4, 4)).asnumpy()
        np.testing.assert_allclose(out, img.asnumpy(), atol=1e-3)

    def test_spatial_transformer_grad(self):
        img = nd.array(np.random.rand(1, 1, 4, 4).astype(np.float32))
        theta = nd.array([[1., 0., 0.1, 0., 1., -0.1]])
        img.attach_grad()
        theta.attach_grad()
        with mx.autograd.record():
            out = nd.SpatialTransformer(img, theta, target_shape=(4, 4))
            s = out.sum()
        s.backward()
        assert np.isfinite(img.grad.asnumpy()).all()
        assert np.abs(theta.grad.asnumpy()).sum() > 0

    def test_correlation_self_center(self):
        rng = np.random.RandomState(0)
        d = nd.array(rng.randn(1, 3, 8, 8).astype(np.float32))
        out = nd.Correlation(d, d, kernel_size=1, max_displacement=2,
                             stride1=1, stride2=1, pad_size=2).asnumpy()
        assert out.shape == (1, 25, 8, 8)
        expect = (d.asnumpy() ** 2).sum(axis=1)[0] / 3
        np.testing.assert_allclose(out[0, 12], expect, atol=1e-2, rtol=1e-2)

    def test_correlation_subtract(self):
        d = nd.array(np.ones((1, 2, 4, 4), np.float32))
        out = nd.Correlation(d, d, kernel_size=1, max_displacement=1,
                             stride1=1, stride2=1, pad_size=1,
                             is_multiply=False).asnumpy()
        # center displacement: |a-a| = 0
        np.testing.assert_allclose(out[0, 4], 0.0, atol=1e-6)

    def test_svm_output_l1(self):
        dat = nd.array(np.array([[0.5, -0.5, 0.2]], np.float32))
        lab = nd.array([0.])
        dat.attach_grad()
        with mx.autograd.record():
            out = nd.SVMOutput(dat, lab, margin=1.0, use_linear=True)
        np.testing.assert_allclose(out.asnumpy(), dat.asnumpy())
        out.backward()
        np.testing.assert_allclose(dat.grad.asnumpy(), [[-1., 1., 1.]])

    def test_svm_output_l2(self):
        dat = nd.array(np.array([[0.5, -2.0]], np.float32))
        lab = nd.array([0.])
        dat.attach_grad()
        with mx.autograd.record():
            out = nd.SVMOutput(dat, lab, margin=1.0)
        out.backward()
        g = dat.grad.asnumpy()
        # k: margin > 0.5 -> -2*(1-0.5) = -1; other: margin > 2.0 false -> 0
        np.testing.assert_allclose(g, [[-1., 0.]], atol=1e-5)


class TestForeach:
    def test_foreach_imperative(self):
        data = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
        outs, states = nd.contrib.foreach(
            lambda x, s: (x + s[0], [x + s[0]]), data, [nd.zeros((2,))])
        np.testing.assert_allclose(states[0].asnumpy(), [6., 9.])
        np.testing.assert_allclose(outs.asnumpy()[-1], [6., 9.])
        assert outs.shape == (3, 2)

    def test_foreach_symbolic_scan(self):
        import mxnet_tpu.symbol as sym
        data = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
        d = sym.var("d")
        w = sym.var("w")
        outs_s, st_s = sym.contrib.foreach(
            lambda x, s: (x * w + s[0], [x * w + s[0]]), d, [sym.var("s0")])
        ex = outs_s.bind(mx.cpu(), {"d": data, "s0": nd.zeros((2,)),
                                    "w": nd.array([2., 1.])})
        y = ex.forward()[0].asnumpy()
        expect, s = [], np.zeros(2)
        for i in range(3):
            s = data.asnumpy()[i] * np.array([2., 1.]) + s
            expect.append(s.copy())
        np.testing.assert_allclose(y, np.stack(expect), rtol=1e-5)

    def test_foreach_symbolic_json_roundtrip(self):
        import mxnet_tpu.symbol as sym
        d = sym.var("d")
        outs_s, _ = sym.contrib.foreach(
            lambda x, s: (x * 2.0, [s[0] + x.sum()]), d, [sym.var("s0")])
        js = outs_s.tojson()
        back = sym.load_json(js)
        data = nd.array(np.ones((2, 3), np.float32))
        ex = back.bind(mx.cpu(), {"d": data, "s0": nd.zeros((1,))})
        y = ex.forward()[0].asnumpy()
        np.testing.assert_allclose(y, np.full((2, 3), 2.0))
