#!/usr/bin/env python
"""Subprocess entry for the program-cache warm-restart tests.

Runs a tiny MLP Module training step (forward_backward + fused update)
with the persistent program cache pointed at ``MXNET_PROGRAM_CACHE_DIR``
(inherited from the parent test), then prints one JSON line of the
counters the parent asserts on:

- ``puts`` / ``misses`` / ``disk_hits`` — program-cache stats; a warm
  restart must show puts == misses == 0 with disk_hits > 0.
- ``repeat_op_jit_misses`` — op_jit_cache_misses_total delta across a
  REPEAT step (steady state must be fully cached in-process).
- ``compile_spans`` / ``restore_spans`` — profiler ``XLA::Compile`` vs
  ``XLA::Restore`` span counts; post-restore the compile count is zero.

The process boundary is the point: process A (cold) compiles and
persists, process B (same cache dir) must restore everything.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import profiler, program_cache, telemetry

    telemetry.enable()
    profiler.set_state("run")

    S = mx.symbol
    h = S.Activation(S.FullyConnected(S.var("data"), num_hidden=8,
                                      name="fc1"), act_type="relu")
    sym = S.SoftmaxOutput(S.FullyConnected(h, num_hidden=4, name="fc2"),
                          S.var("softmax_label"), name="softmax")
    batch = 2
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",), context=[mx.cpu()])
    mod.bind(data_shapes=[("data", (batch, 8))],
             label_shapes=[("softmax_label", (batch,))])
    mx.random.seed(0)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rs = np.random.RandomState(1)
    xarr = mx.nd.array(rs.uniform(size=(batch, 8)).astype(np.float32))
    yarr = mx.nd.array(rs.randint(0, 4, (batch,)).astype(np.float32))

    class _B:
        data = [xarr]
        label = [yarr]

    def step():
        mod.forward_backward(_B)
        mod.update()
        return float(mod.get_outputs()[0].asnumpy().ravel()[0])

    def op_misses():
        fam = telemetry.registry().get("op_jit_cache_misses_total")
        return 0 if fam is None else sum(
            c.get() for c in fam._children.values())

    loss0 = step()
    m0 = op_misses()
    step()
    profiler.set_state("stop")
    spans = list(profiler._events)
    s = program_cache.stats()
    print(json.dumps({
        "ok": bool(np.isfinite(loss0)),
        "cache_enabled": bool(s.get("enabled")),
        "puts": int(s.get("puts", 0)),
        "misses": int(s.get("misses", 0)),
        "disk_hits": int(s.get("disk_hits", 0)),
        "errors": int(s.get("errors", 0)),
        "repeat_op_jit_misses": int(op_misses() - m0),
        "compile_spans": sum(
            1 for e in spans
            if str(e.get("name", "")).startswith("XLA::Compile")),
        "restore_spans": sum(
            1 for e in spans
            if str(e.get("name", "")).startswith("XLA::Restore")),
    }))


if __name__ == "__main__":
    main()
