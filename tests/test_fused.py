"""Tests for FusedTrainer (whole-train-step compilation, fused.py).

This is the bench.py path: one donated-buffer XLA executable per step.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd


def _net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    return net


def test_fused_trainer_converges():
    rng = np.random.RandomState(0)
    net = _net()
    x = nd.array(rng.rand(16, 5).astype(np.float32))
    net(x)
    ft = mx.FusedTrainer(net, "softmax_cross_entropy", "sgd",
                         {"learning_rate": 0.5, "momentum": 0.9})
    y = nd.array(rng.randint(0, 3, (16,)).astype(np.float32))
    losses = [float(ft.step(x, y).asnumpy()) for _ in range(80)]
    assert losses[-1] < losses[0] * 0.2
    assert all(np.isfinite(losses))


def test_fused_matches_gluon_trainer_step():
    """One fused step == one eager Trainer step (same math, one program)."""
    rng = np.random.RandomState(1)
    x = nd.array(rng.rand(8, 5).astype(np.float32))
    y = nd.array(rng.randint(0, 3, (8,)).astype(np.float32))

    nets = []
    for _ in range(2):
        mx.random.seed(7)
        net = _net()
        net(x)
        nets.append(net)
    # copy params so both start identical (names differ across instances
    # — the global name scope keeps counting — so map positionally)
    src = nets[0].collect_params()
    dst = nets[1].collect_params()
    pairs = list(zip(src.values(), dst.values()))
    for a, b in pairs:
        b.data()._data = a.data()._data

    ft = mx.FusedTrainer(nets[0], "softmax_cross_entropy", "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    l_fused = float(ft.step(x, y).asnumpy())
    ft.sync_params()

    trainer = gluon.Trainer(nets[1].collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with mx.autograd.record():
        loss = loss_fn(nets[1](x), y)
    loss.backward()
    trainer.step(8)   # Trainer rescales grads by 1/batch internally
    l_eager = float(loss.mean().asnumpy())

    np.testing.assert_allclose(l_fused, l_eager, rtol=1e-4)
    # fused applies raw mean-loss gradients; Trainer applies
    # rescale_grad=1/batch over a summed loss — same update direction;
    # compare the parameters after accounting for identical math
    for a, b in pairs:
        np.testing.assert_allclose(a.data().asnumpy(), b.data().asnumpy(),
                                   rtol=1e-3, atol=1e-4)


def test_fused_lr_schedule_no_retrace():
    net = _net()
    x = nd.random.uniform(shape=(4, 5))
    net(x)
    ft = mx.FusedTrainer(net, optimizer_params={"learning_rate": 0.1})
    y = nd.array(np.zeros(4, np.float32))
    ft.step(x, y)
    compiled_before = ft._jstep._cache_size() \
        if hasattr(ft._jstep, "_cache_size") else None
    ft.set_learning_rate(0.01)
    ft.step(x, y)
    if compiled_before is not None:
        assert ft._jstep._cache_size() == compiled_before


def test_fused_rejects_unknown_optimizer():
    net = _net()
    x = nd.random.uniform(shape=(2, 5))
    net(x)
    with pytest.raises(mx.MXNetError, match="sgd"):
        mx.FusedTrainer(net, optimizer="adam")


def test_fused_sync_params_back_to_eager():
    net = _net()
    x = nd.random.uniform(shape=(4, 5))
    net(x)
    before = net.collect_params()
    name = [k for k in before if k.endswith("weight")][0]
    w_before = before[name].data().asnumpy().copy()
    ft = mx.FusedTrainer(net, optimizer_params={"learning_rate": 0.5})
    y = nd.array(np.ones(4, np.float32))
    for _ in range(3):
        ft.step(x, y)
    ft.sync_params()
    w_after = net.collect_params()[name].data().asnumpy()
    assert not np.allclose(w_before, w_after)
    net(x)  # eager forward works with synced params


def test_fused_sync_then_continue_training():
    """Regression: sync_params must write COPIES — step() donates the state
    buffers, so handing Parameters the originals leaves the Block holding
    deleted XLA arrays after sync -> step -> read (advisor round-1 high)."""
    net = _net()
    x = nd.random.uniform(shape=(4, 5))
    net(x)
    ft = mx.FusedTrainer(net, optimizer_params={"learning_rate": 0.1})
    y = nd.array(np.zeros(4, np.float32))
    ft.step(x, y)
    ft.sync_params()          # mid-training sync (e.g. checkpoint)
    ft.step(x, y)             # donates the state buffers again
    for p in net.collect_params().values():
        p.data().asnumpy()    # must not raise 'Array has been deleted'
    net(x)
