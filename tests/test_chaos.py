"""Fault-tolerance acceptance tests (ISSUE 13 tentpole): retry math,
frame replay idempotence, corrupt-frame loud-reject, and the chaos gang
runs — kill a worker and kill the server mid-run under 2-worker
dist_async; training must resume on the durable server's rehydrated
state and converge, with zero hung processes."""
import os
import socket
import struct
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, nd
from mxnet_tpu.kvstore import backoff_delay
from mxnet_tpu.kvstore_server import (KVStoreServer, _check_trace_ctx,
                                      _pack_payload, _parse_payload,
                                      recv_msg, send_msg)
from mxnet_tpu.parallel.elastic import ElasticRunner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "chaos_worker.py")


def test_backoff_delay_math():
    """Exponential envelope with +/-50% jitter, capped."""

    # jitter factor spans [0.5, 1.5) of the exponential term
    assert backoff_delay(0, base=0.1, rng=lambda: 0.0) == \
        pytest.approx(0.05)
    assert backoff_delay(0, base=0.1, rng=lambda: 1.0) == \
        pytest.approx(0.15)
    assert backoff_delay(3, base=0.1, cap=10.0, rng=lambda: 0.5) == \
        pytest.approx(0.8)
    # the cap bounds the exponential term, not the jittered result's tail
    assert backoff_delay(50, base=0.1, cap=2.0, rng=lambda: 1.0) == \
        pytest.approx(3.0)
    for attempt in range(20):
        d = backoff_delay(attempt, base=0.05, cap=2.0)
        assert 0.0 < d <= 3.0


def test_replayed_push_frame_applies_once(monkeypatch):
    """A retried (rank, seq) push frame — its ack was lost, not the apply
    — must be acked without a second apply (the at-most-once contract the
    client retry loop leans on)."""
    srv = KVStoreServer(num_workers=1).start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        send_msg(s, ["init", "w", np.zeros(3, np.float32)])
        assert recv_msg(s) == ["ok"]
        frame = ["push", "w", np.ones(3, np.float32) * 5]
        qc = {"r": "0.deadbeef", "s": 1}
        send_msg(s, frame, seq_ctx=qc)
        assert recv_msg(s) == ["ok"]
        assert srv.push_count == 1
        send_msg(s, frame, seq_ctx=qc)      # identical replay
        assert recv_msg(s) == ["ok"]        # acked ...
        assert srv.push_count == 1          # ... but not re-applied
        # same lane, next seq: applies normally
        send_msg(s, frame, seq_ctx={"r": "0.deadbeef", "s": 2})
        assert recv_msg(s) == ["ok"]
        assert srv.push_count == 2
        # a NEW incarnation of the same rank gets a fresh dedup lane:
        # its seq restarts at 0 and must not be shadowed
        send_msg(s, frame, seq_ctx={"r": "0.12ab34cd", "s": 0})
        assert recv_msg(s) == ["ok"]
        assert srv.push_count == 3
        s.close()
    finally:
        srv.shutdown()


def test_trace_ctx_missing_fields_rejected_loudly():
    """GL009 (wire-contract lint) caught _check_trace_ctx rejecting
    unknown keys but never checking completeness: a frame with a
    half-built trace context sailed through validation.  Missing fields
    must be a loud frame error like every other framing violation."""
    assert _check_trace_ctx({"t": "a" * 8, "s": "b" * 8}) == \
        {"t": "a" * 8, "s": "b" * 8}
    for tc in ({}, {"t": "a" * 8}, {"s": "b" * 8}):
        with pytest.raises(mx.base.MXNetError):
            _check_trace_ctx(tc)
    # end to end: the packed frame with the incomplete context is
    # rejected at parse, not silently accepted
    payload = _pack_payload(["push", "w", np.zeros(2, np.float32)],
                            trace_ctx={"t": "a" * 8})
    with pytest.raises(mx.base.MXNetError):
        _parse_payload(payload)


def test_corrupted_header_rejected_loudly():
    """chaos.corrupt flips a byte in the header region; the receiver's
    framing validation must reject, never silently mis-parse tensors."""
    payload = _pack_payload(["push", "w", np.arange(4, dtype=np.float32)])
    # deterministic worst spot: the header-length field itself
    bad = bytearray(payload)
    bad[0] ^= 0xFF
    with pytest.raises(mx.base.MXNetError):
        _parse_payload(bytes(bad))
    # the chaos primitive only ever touches the first 64 bytes
    os.environ["MXNET_CHAOS_SEED"] = "7"
    try:
        for _ in range(32):
            mutated = chaos.corrupt(payload)
            assert len(mutated) == len(payload)
            diff = [i for i, (a, b) in enumerate(zip(payload, mutated))
                    if a != b]
            assert len(diff) == 1 and diff[0] < 64
    finally:
        del os.environ["MXNET_CHAOS_SEED"]


def _run_gang(tmp_path, chaos_env, total_steps=60, max_restarts=2):
    logdir = str(tmp_path / "log")
    durable = str(tmp_path / "durable")
    os.makedirs(logdir)
    env = dict(os.environ)
    env.pop("MXNET_CHAOS_ONLY_GEN", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXNET_PS_URI": "127.0.0.1",
        "MXNET_PS_PORT": str(_free_port()),
        "MXNET_KVSTORE_DURABLE_DIR": durable,
        "MXNET_KVSTORE_SNAPSHOT_EVERY": "10",
        "MXNET_KVSTORE_OP_TIMEOUT": "5",
        "MXNET_KVSTORE_MAX_RETRIES": "2",
        "MXNET_KVSTORE_RETRY_BACKOFF": "0.05",
        "MXNET_CHAOS": "1",
        "MXNET_CHAOS_ONLY_GEN": "0",
    })
    env.update(chaos_env)
    runner = ElasticRunner(
        [sys.executable, WORKER, logdir, str(total_steps)],
        nworkers=3, max_restarts=max_restarts, env=env,
        poll_interval=0.1)
    restarts = runner.run()
    return logdir, restarts


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _losses(logdir, rank):
    out = []
    with open(os.path.join(logdir, "loss_rank%d.log" % rank)) as f:
        for line in f:
            gen, step, loss = line.split()
            out.append((int(gen), int(step), float(loss)))
    return out


def _assert_resumed_trajectory(logdir):
    """Generation 1 must pick up the dead generation's loss level, not
    restart from the untrained one."""
    for rank in (0, 1):
        rows = _losses(logdir, rank)
        gen0 = [l for g, _, l in rows if g == 0]
        gen1 = [l for g, _, l in rows if g == 1]
        assert gen0 and gen1, "expected both generations to log"
        assert gen1[0] < gen0[0] * 0.5, (
            "gen1 started at loss %g vs gen0's initial %g — resumed "
            "training should continue the trajectory, not restart"
            % (gen1[0], gen0[0]))
    with open(os.path.join(logdir, "final.txt")) as f:
        assert float(f.read()) < 0.05


def test_worker_death_gang_recovers(tmp_path):
    """kill -9 a worker mid-run (gen 0): the supervisor restarts the
    gang, the durable server rehydrates, training converges."""
    logdir, restarts = _run_gang(
        tmp_path, {"MXNET_CHAOS_DIE_AT_STEP": "8"})
    assert restarts == 1
    _assert_resumed_trajectory(logdir)


@pytest.mark.slow
def test_server_death_gang_recovers(tmp_path):
    """kill -9 the parameter server mid-run: workers' bounded ops fail
    over (timeout -> retry -> reconnect -> give up nonzero), the gang
    restarts, the server rehydrates from snapshot+journal, training
    converges.  Nothing may hang: every blocking call carries
    MXNET_KVSTORE_OP_TIMEOUT."""
    logdir, restarts = _run_gang(
        tmp_path, {"MXNET_CHAOS_DIE_AT_PUSH": "25",
                   "MXNET_KVSTORE_OP_TIMEOUT": "2"})
    assert restarts == 1
    _assert_resumed_trajectory(logdir)
