"""Program Atlas (mxnet_tpu/atlas.py + tools/program_atlas.py).

Covers the scope-name contract surviving into lowered modules, >=90%
flop coverage on a ResNet-style plan, call-site dedup and flop-model
goldens on hand-written MLIR, the --diff tool, the /programz endpoint,
flight-recorder program/atlas blocks, and the zero-extra-compile
regression (analysis must never touch XLA).
"""
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import atlas, health, nd, telemetry, tracing

S = mx.symbol


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    health.reset()
    atlas.reset()
    yield
    health.disable()
    telemetry.disable()
    telemetry.reset()
    health.reset()
    atlas.reset()


def _residual_net():
    """ResNet-style symbol: conv stem, two residual conv/BN blocks,
    global pool, FC head, softmax loss."""
    def block(data, n, name):
        c1 = S.Convolution(data, num_filter=n, kernel=(3, 3), pad=(1, 1),
                           no_bias=True, name=name + "_conv1")
        b1 = S.BatchNorm(c1, name=name + "_bn1")
        a1 = S.Activation(b1, act_type="relu", name=name + "_relu1")
        c2 = S.Convolution(a1, num_filter=n, kernel=(3, 3), pad=(1, 1),
                           no_bias=True, name=name + "_conv2")
        b2 = S.BatchNorm(c2, name=name + "_bn2")
        return S.Activation(b2 + data, act_type="relu", name=name + "_out")

    data = S.var("data")
    stem = S.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                         no_bias=True, name="stem_conv")
    body = block(block(stem, 8, "res1"), 8, "res2")
    pool = S.Pooling(body, global_pool=True, pool_type="avg", name="pool")
    fc = S.FullyConnected(S.Flatten(pool), num_hidden=10, name="fc")
    return S.SoftmaxOutput(fc, S.var("softmax_label"), name="softmax")


def _run_fwdbwd():
    """One train fwd+bwd on the residual net -> "fwdbwd" registration."""
    ex = _residual_net().simple_bind(mx.cpu(), data=(2, 8, 8, 8),
                                     softmax_label=(2,))
    ex.forward(is_train=True)
    ex.backward()
    return ex


# ---------------------------------------------------------------------------
# scope naming contract
# ---------------------------------------------------------------------------
class TestScopeNames:
    def test_scope_name_sanitized(self):
        assert atlas.scope_name("Convolution", "stage1 conv/1") == \
            "Convolution:stage1_conv_1"
        assert atlas.scope_name("FullyConnected") == "FullyConnected:~"

    def test_optimizer_scope_uses_hook_then_class(self):
        from mxnet_tpu import optimizer as opt
        sgd = opt.SGD(learning_rate=0.1)
        assert atlas.optimizer_scope(sgd.fused_update) == "Optimizer::SGD"

        class Custom(opt.SGD):
            def atlas_scope_name(self):
                return "SGD(momentum)"

        c = Custom(learning_rate=0.1)
        assert atlas.optimizer_scope(c.fused_update) == \
            "Optimizer::SGD_momentum_"

    def test_innermost_token_wins_through_autodiff_wrappers(self):
        name = ("jit(f)/jit(main)/transpose(jvp(FullyConnected:fc1))/"
                "Activation:relu1/dot_general")
        toks = atlas._SCOPE_TOKEN_RE.findall(name)
        assert toks[-1] == "Activation:relu1"


# ---------------------------------------------------------------------------
# analyze_text goldens (hand-written MLIR: no jax involved)
# ---------------------------------------------------------------------------
GOLDEN_MLIR = """\
#loc1 = loc("jit(f)/jit(main)/FullyConnected:fc1/dot_general"("a":1:1))
#loc2 = loc("jit(f)/jit(main)/transpose(jvp(FullyConnected:fc1))/dot_general"("a":2:2))
#loc3 = loc("jit(f)/jit(main)/GradSync/add"("a":3:3))
#loc4 = loc("jit(f)/jit(main)/Optimizer::SGD/mul"("a":4:4))
#loc5 = loc(unknown)
module @jit_f {
  func.func public @main(%arg0: tensor<4x8xf32>, %arg1: tensor<8x16xf32>) -> tensor<4x16xf32> {
    %0 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<4x8xf32>, tensor<8x16xf32>) -> tensor<4x16xf32> loc(#loc1)
    %1 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<4x8xf32>, tensor<8x16xf32>) -> tensor<4x16xf32> loc(#loc2)
    %2 = stablehlo.add %1, %1 : tensor<4x16xf32> loc(#loc3)
    %3 = stablehlo.multiply %2, %2 : tensor<4x16xf32> loc(#loc4)
    %4 = call @helper(%3) : (tensor<4x16xf32>) -> tensor<4x16xf32> loc(#loc1)
    %5 = call @helper(%4) : (tensor<4x16xf32>) -> tensor<4x16xf32> loc(#loc5)
    return %5 : tensor<4x16xf32> loc(#loc5)
  }
  func.func private @helper(%arg0: tensor<4x16xf32>) -> tensor<4x16xf32> {
    %0 = stablehlo.exponential %arg0 : tensor<4x16xf32> loc(#loc4)
    return %0 : tensor<4x16xf32> loc(#loc5)
  }
}
"""


class TestAnalyzeText:
    def test_golden_attribution(self):
        atl = atlas.analyze_text("golden", GOLDEN_MLIR)
        fc = atl.scopes["FullyConnected:fc1"]
        # two 4x8 @ 8x16 dot_generals (the transpose(jvp(...)) wrapper
        # resolves to the same layer token): 2*64*8 each, plus one
        # call-site-charged helper body (exp over 64 elems)
        assert fc.flops == 2 * (2.0 * 64 * 8) + 64
        assert fc.calls == 1
        assert atl.scopes["GradSync"].flops == 64
        # own multiply (64) + the UNscoped second call merging helper's
        # internal Optimizer::SGD attribution (64)
        assert atl.scopes["Optimizer::SGD"].flops == 128
        # no cost_analysis denominator: coverage is vs the parsed total,
        # and the unknown-loc call contributed no unattributed flops
        assert atl.coverage() == pytest.approx(1.0)

    def test_call_site_dedup_charges_caller(self):
        # the shared private func body carries only its first caller's
        # internal locations — a scoped call site must own the cost, not
        # leak it into the body's own scope a second time
        atl = atlas.analyze_text("golden", GOLDEN_MLIR)
        assert atl.scopes["Optimizer::SGD"].flops < 3 * 64

    def test_unknown_scope_is_unattributed(self):
        asm = (
            '#loc9 = loc(unknown)\n'
            'module @m {\n'
            '  func.func public @main(%arg0: tensor<2x2xf32>) -> '
            'tensor<2x2xf32> {\n'
            '    %0 = stablehlo.add %arg0, %arg0 : tensor<2x2xf32> '
            'loc(#loc9)\n'
            '    return %0 : tensor<2x2xf32> loc(#loc9)\n'
            '  }\n'
            '}\n')
        atl = atlas.analyze_text("u", asm)
        assert not atl.scopes
        assert atl.unattributed.flops == 4
        assert atl.coverage() == 0.0

    def test_conv_flops_from_dim_numbers(self):
        asm = (
            '#loc1 = loc("jit(f)/jit(main)/Convolution:c1/conv"("a":1:1))\n'
            'module @m {\n'
            '  func.func public @main(%arg0: tensor<1x4x8x8xf32>, '
            '%arg1: tensor<16x4x3x3xf32>) -> tensor<1x16x8x8xf32> {\n'
            '    %0 = stablehlo.convolution(%arg0, %arg1) '
            'dim_numbers = [b, f, 0, 1]x[o, i, 0, 1]->[b, f, 0, 1], '
            'window = {pad = [[1, 1], [1, 1]]} : '
            '(tensor<1x4x8x8xf32>, tensor<16x4x3x3xf32>) -> '
            'tensor<1x16x8x8xf32> loc(#loc1)\n'
            '    return %0 : tensor<1x16x8x8xf32> loc(#loc1)\n'
            '  }\n'
            '}\n')
        atl = atlas.analyze_text("c", asm)
        # 2 * out_numel(1*16*8*8) * (i=4 * kh=3 * kw=3)
        assert atl.scopes["Convolution:c1"].flops == 2.0 * 1024 * 36


# ---------------------------------------------------------------------------
# live lowerings: coverage, scope presence, zero extra compiles
# ---------------------------------------------------------------------------
class TestLiveAttribution:
    def test_resnet_style_coverage_and_scope_presence(self):
        health.enable()
        _run_fwdbwd()
        atl = atlas.get("fwdbwd")
        assert atl is not None
        # acceptance bar: >=90% of cost_analysis flops attributed to
        # named scopes (fwd AND bwd ride the same layer scopes via vjp)
        assert atl.coverage() >= 0.90
        # every op type in the plan surfaces as a named scope
        for op_type in ("Convolution", "BatchNorm", "Activation",
                        "Pooling", "FullyConnected", "SoftmaxOutput"):
            assert any(s.startswith(op_type + ":") for s in atl.scopes), \
                "no scope for op type %s in %s" % (op_type,
                                                   sorted(atl.scopes))
        # the ranked table is flop-sorted with shares against the total
        rows = atl.table(top_k=5)
        assert rows == sorted(rows, key=lambda r: -r["flops"])
        assert all(0.0 <= r["flops_share"] <= 1.0 for r in rows)

    def test_eager_op_scope_is_anonymous_node(self):
        # the registry choke point stamps "<OpType>:~" into single-op
        # jits, where no graph node name exists
        import jax.numpy as jnp
        from mxnet_tpu.ops import registry
        op = registry.get_op("Activation")
        attrs = op.parse_attrs({"act_type": "relu"})
        x = jnp.ones((2, 2), jnp.float32)
        op(attrs, x)  # first call installs the jitted cache entry
        jfn = next(v for v in op._jit_cache.values()
                   if hasattr(v, "lower"))
        asm = jfn.lower(x).compiler_ir().operation.get_asm(
            enable_debug_info=True)
        assert "Activation:~" in asm

    def test_zero_extra_compiles(self, monkeypatch):
        # analysis is serialization-only: poison AOT compile and prove
        # registration + atlas still succeed end to end
        import jax
        monkeypatch.delenv("MXNET_HEALTH_DEEP", raising=False)

        def boom(self, *a, **k):
            raise AssertionError("AOT compile during atlas/health analysis")

        monkeypatch.setattr(jax.stages.Lowered, "compile", boom)
        health.enable()
        _run_fwdbwd()
        assert atlas.get("fwdbwd") is not None
        assert health.programs()["fwdbwd"].flops > 0

    def test_fused_step_has_optimizer_scope_and_env(self):
        from mxnet_tpu.io import DataBatch
        from mxnet_tpu.module import Module
        health.enable()
        data = S.var("data")
        fc1 = S.FullyConnected(data, num_hidden=8, name="fc1")
        act = S.Activation(fc1, act_type="relu", name="relu1")
        fc2 = S.FullyConnected(act, num_hidden=4, name="fc2")
        sym = S.SoftmaxOutput(fc2, S.var("softmax_label"), name="softmax")
        mod = Module(sym, context=mx.cpu())
        mod.bind(data_shapes=[("data", (2, 6))],
                 label_shapes=[("softmax_label", (2,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        batch = DataBatch(data=[nd.array(np.random.rand(2, 6))],
                          label=[nd.array(np.array([1, 2], np.float32))])
        mod.forward_backward(batch)
        mod.update()
        prog = next((n for n in ("mesh_step", "step", "update")
                     if atlas.get(n) is not None), None)
        assert prog is not None, "no step/update program analyzed: %s" % (
            sorted(atlas.atlases()),)
        atl = atlas.get(prog)
        assert any(s.startswith("Optimizer::SGD") for s in atl.scopes)
        # env snapshot of the step cache-key flags rides the cost record
        env = health.programs()[prog].env
        assert "MXNET_TPU_FUSED_STEP" in env


# ---------------------------------------------------------------------------
# diff tool (golden)
# ---------------------------------------------------------------------------
SNAP_A = {"step": {"scopes": [
    {"scope": "Convolution:c1", "flops": 1000.0, "bytes": 100},
    {"scope": "Optimizer::SGD", "flops": 50.0, "bytes": 10},
    {"scope": "Activation:r1", "flops": 5.0, "bytes": 5},
]}}
SNAP_B = {"step": {"scopes": [
    {"scope": "Convolution:c1", "flops": 400.0, "bytes": 60},
    {"scope": "Optimizer::SGD", "flops": 50.0, "bytes": 10},
    {"scope": "GradSync", "flops": 20.0, "bytes": 8},
    {"scope": "Activation:r1", "flops": 5.0, "bytes": 5},
]}}

GOLDEN_DIFF = [
    {"program": "step", "scope": "Convolution:c1",
     "flops_a": 1000.0, "flops_b": 400.0,
     "delta_flops": -600.0, "delta_bytes": -40},
    {"program": "step", "scope": "GradSync",
     "flops_a": 0.0, "flops_b": 20.0,
     "delta_flops": 20.0, "delta_bytes": 8},
]


class TestDiff:
    def test_golden(self):
        # unchanged scopes (Optimizer, Activation) are skipped; rows rank
        # by |delta flops|
        assert atlas.diff(SNAP_A, SNAP_B) == GOLDEN_DIFF

    def test_cli_diff_json(self, tmp_path, capsys):
        from tools import program_atlas as cli
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(SNAP_A))
        b.write_text(json.dumps(SNAP_B))
        rc = cli.main(["--diff", str(a), str(b), "--format", "json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out) == GOLDEN_DIFF

    def test_cli_renders_flight_dump_atlas_block(self, tmp_path, capsys):
        from tools import program_atlas as cli
        dump = tmp_path / "dump.json"
        dump.write_text(json.dumps(
            {"reason": "manual", "events": [],
             "atlas": {"step": {"total_flops": 10.0, "coverage_pct": 95.0,
                                "n_scopes": 1, "n_instructions": 3,
                                "scopes": [{"scope": "Convolution:c1",
                                            "flops": 9.5, "bytes": 4,
                                            "instructions": 2, "calls": 0,
                                            "flops_share": 0.95,
                                            "bytes_share": 1.0}]}}}))
        rc = cli.main([str(dump)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Convolution:c1" in out


# ---------------------------------------------------------------------------
# /programz + flight-recorder embedding
# ---------------------------------------------------------------------------
class TestExposure:
    def test_programz_endpoint(self):
        health.enable()
        _run_fwdbwd()
        port = telemetry.start_http_server(port=0)
        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/programz?top_k=3" % port,
                    timeout=10) as resp:
                doc = json.loads(resp.read().decode())
        finally:
            telemetry.stop_http_server()
        assert "fwdbwd" in doc["programs"]
        assert "env" in doc["programs"]["fwdbwd"]
        atl = doc["atlas"]["fwdbwd"]
        assert atl["coverage_pct"] >= 90.0
        assert len(atl["scopes"]) <= 3

    def test_flight_dump_carries_programs_and_atlas(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH",
                           str(tmp_path / "fr.json"))
        health.enable()
        _run_fwdbwd()
        path = tracing.flight.dump("manual")
        with open(path) as f:
            doc = json.load(f)
        assert "fwdbwd" in doc["programs"]
        assert doc["programs"]["fwdbwd"]["env"] is not None
        assert doc["atlas"]["fwdbwd"]["coverage_pct"] >= 90.0

    def test_flight_dump_programs_survive_atlas_off(self, tmp_path,
                                                    monkeypatch):
        # satellite contract: the programs snapshot does NOT depend on
        # the atlas being enabled
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH",
                           str(tmp_path / "fr.json"))
        monkeypatch.setattr(atlas, "enabled", False)
        health.enable()
        _run_fwdbwd()
        assert atlas.get("fwdbwd") is None
        path = tracing.flight.dump("manual")
        with open(path) as f:
            doc = json.load(f)
        assert "fwdbwd" in doc["programs"]
        assert "atlas" not in doc

    def test_atlas_metrics_exported(self):
        health.enable()
        _run_fwdbwd()
        assert telemetry.value("atlas_scope_coverage_pct",
                               program="fwdbwd") >= 90.0
        assert telemetry.value("atlas_scopes", program="fwdbwd") > 0
