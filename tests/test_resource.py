"""N15 resource manager: per-context RNG streams + temp workspace.

Reference parity: src/resource.cc, include/mxnet/resource.h:42-46 —
ResourceRequest{kRandom,kTempSpace,kParallelRandom}, per-device pools,
global reseed via mx.random.seed, rotating temp-space slots.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import resource
from mxnet_tpu.resource import Resource, ResourceManager, ResourceRequest


def _rm():
    return ResourceManager.get()


class TestRandomResource:
    def test_request_kinds(self):
        rm = _rm()
        for t in (ResourceRequest.kRandom, ResourceRequest.kTempSpace,
                  ResourceRequest.kParallelRandom):
            res = rm.request(mx.cpu(0), ResourceRequest(t))
            assert isinstance(res, Resource)
            assert res.req.type == t
        # int shorthand accepted
        res = rm.request(mx.cpu(0), ResourceRequest.kRandom)
        assert res.req.type == ResourceRequest.kRandom

    def test_seed_reproducible_stream(self):
        rm = _rm()
        rm.seed(42)
        r = rm.request(mx.cpu(0), ResourceRequest(ResourceRequest.kRandom))
        a = [np.asarray(r.get_random()) for _ in range(3)]
        rm.seed(42)
        b = [np.asarray(r.get_random()) for _ in range(3)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        # and the stream advances (no repeated keys)
        assert not np.array_equal(a[0], a[1])

    def test_distinct_contexts_distinct_streams(self):
        rm = _rm()
        rm.seed(7)
        k0 = rm.request(mx.cpu(0),
                        ResourceRequest(ResourceRequest.kRandom)).get_random()
        rm.seed(7)
        k1 = rm.request(mx.cpu(1),
                        ResourceRequest(ResourceRequest.kRandom)).get_random()
        assert not np.array_equal(np.asarray(k0), np.asarray(k1))

    def test_wrong_kind_raises(self):
        rm = _rm()
        r = rm.request(mx.cpu(0), ResourceRequest(ResourceRequest.kRandom))
        with pytest.raises(TypeError):
            r.get_space((4,))
        t = rm.request(mx.cpu(0),
                       ResourceRequest(ResourceRequest.kTempSpace))
        with pytest.raises(TypeError):
            t.get_random()

    def test_mx_random_seed_rides_manager(self):
        """mx.random.seed / mx.nd.random draws come from the kRandom
        resource stream (random.py delegates to the manager)."""
        mx.random.seed(123)
        a = mx.nd.random.uniform(shape=(5,)).asnumpy()
        mx.random.seed(123)
        b = mx.nd.random.uniform(shape=(5,)).asnumpy()
        np.testing.assert_array_equal(a, b)
        c = mx.nd.random.uniform(shape=(5,)).asnumpy()
        assert not np.array_equal(b, c)

    def test_per_context_seed(self):
        """mx.random.seed(s, ctx) reseeds only that device's stream."""
        rm = _rm()
        rm.seed(1)
        r0 = rm.request(mx.cpu(0), ResourceRequest(ResourceRequest.kRandom))
        r1 = rm.request(mx.cpu(1), ResourceRequest(ResourceRequest.kRandom))
        a0 = np.asarray(r0.get_random())
        _ = r1.get_random()
        rm.seed(1, mx.cpu(1))         # cpu(1) restarts, cpu(0) continues
        b0 = np.asarray(r0.get_random())
        assert not np.array_equal(a0, b0)       # cpu(0) stream advanced
        rm.seed(1)
        np.testing.assert_array_equal(np.asarray(r0.get_random()), a0)

    def test_current_key_is_stable_peek(self):
        mx.random.seed(9)
        k1 = np.asarray(mx.random.current_key())
        k2 = np.asarray(mx.random.current_key())
        np.testing.assert_array_equal(k1, k2)
        mx.random.next_key()
        k3 = np.asarray(mx.random.current_key())
        assert not np.array_equal(k1, k3)

    def test_parallel_random_fold_in(self):
        rm = _rm()
        rm.seed(0)
        pr = rm.request(mx.cpu(0),
                        ResourceRequest(ResourceRequest.kParallelRandom))
        base = pr.get_parallel_random()
        lanes = [jax.random.fold_in(base, i) for i in range(4)]
        draws = [float(jax.random.uniform(k, ())) for k in lanes]
        assert len(set(draws)) == 4


class TestTempSpace:
    def test_reuse_and_grow(self):
        rm = _rm()
        ws = rm.request(mx.cpu(0),
                        ResourceRequest(ResourceRequest.kTempSpace))
        a = ws.get_space((16,), np.float32)
        a[:] = 3.0
        b = ws.get_space((8,), np.float32)
        # same slot, fits -> same backing memory
        assert b.base is a.base or b.base is a.base.base or \
            np.shares_memory(a, b)
        big = ws.get_space((1024,), np.float64)
        assert big.nbytes == 1024 * 8
        assert big.shape == (1024,)
        # after growth, small requests reuse the grown buffer
        c = ws.get_space((4, 4), np.float32)
        assert np.shares_memory(c, big)

    def test_exclusive_slots_distinct(self):
        """Independent kTempSpace resources never share backing memory —
        two concurrent IO producers can't corrupt each other's staging."""
        rm = _rm()
        req = ResourceRequest(ResourceRequest.kTempSpace)
        r1 = rm.request(mx.cpu(0), req)
        r2 = rm.request(mx.cpu(0), req)
        assert r1.id != r2.id
        a = r1.get_space((8,), np.float32)
        b = r2.get_space((8,), np.float32)
        assert not np.shares_memory(a, b)

    def test_slot_reclaimed_on_gc(self):
        import gc
        rm = _rm()
        ws = rm.request(mx.cpu(0),
                        ResourceRequest(ResourceRequest.kTempSpace))
        ws.get_space((1024,))
        key = [k for k in rm.stats() if "cpu(0)" in k][0]
        live0 = rm.stats()[key]["live_slots"]
        del ws
        gc.collect()
        assert rm.stats()[key]["live_slots"] == live0 - 1

    def test_stats_counters(self):
        rm = _rm()
        ws = rm.request(mx.cpu(0),
                        ResourceRequest(ResourceRequest.kTempSpace))
        ws.get_space((4,))
        ws.get_space((4,))
        st = rm.stats()
        key = [k for k in st if "cpu(0)" in k]
        assert key and st[key[0]]["space_reuses"] >= 1
        assert st[key[0]]["live_slots"] >= 1


class TestIOIntegration:
    def test_imagerecorditer_uses_workspace(self, tmp_path):
        """The record-iter batch staging rides the temp-space pool:
        iterating epochs reuses the staging buffer instead of fresh
        allocation per batch."""
        cv2 = pytest.importorskip("cv2")
        root = tmp_path / "imgs"
        root.mkdir()
        for i in range(4):
            cv2.imwrite(str(root / ("%d.jpg" % i)),
                        np.full((20, 20, 3), i * 40, np.uint8))
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import im2rec
        finally:
            sys.path.pop(0)
        prefix = str(tmp_path / "flat")
        im2rec.make_list(prefix, str(root), shuffle=False)
        im2rec.pack(prefix, str(root))
        it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 16, 16), batch_size=2)
        assert it._workspace.req.type == ResourceRequest.kTempSpace
        before = _rm().stats()
        n = 0
        for _ in range(2):
            it.reset()
            for batch in it:
                assert batch.data[0].shape == (2, 3, 16, 16)
                n += 1
        after = _rm().stats()
        key = [k for k in after if "cpu(0)" in k][0]
        assert n >= 2
        # at least one batch after the first reused the staging buffer
        assert after[key]["space_reuses"] > before.get(
            key, {"space_reuses": 0})["space_reuses"]

    def test_nd_array_never_aliases_workspace(self):
        """nd.array must copy: jax.device_put zero-copy-aliases aligned host
        arrays on the CPU backend at some sizes (16KB observed), so a reused
        workspace fed to nd.array without a guaranteed copy would corrupt
        already-returned batches."""
        rm = _rm()
        ws = rm.request(mx.cpu(0),
                        ResourceRequest(ResourceRequest.kTempSpace))
        for n in (256, 4096, 1 << 16):   # spans the zero-copy regimes
            v = ws.get_space((n,), np.float32)
            v[:] = 1.0
            x = mx.nd.array(v)
            x.wait_to_read()
            v[:] = 9.0
            np.testing.assert_array_equal(x.asnumpy(), 1.0)

    def test_batches_not_corrupted_by_reuse(self, tmp_path):
        """Reused staging must not corrupt already-returned batches (the
        device copy happens before the buffer is overwritten)."""
        cv2 = pytest.importorskip("cv2")
        root = tmp_path / "imgs"
        root.mkdir()
        vals = [10, 200]
        for i, v in enumerate(vals):
            cv2.imwrite(str(root / ("%d.jpg" % i)),
                        np.full((16, 16, 3), v, np.uint8))
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import im2rec
        finally:
            sys.path.pop(0)
        prefix = str(tmp_path / "two")
        im2rec.make_list(prefix, str(root), shuffle=False)
        im2rec.pack(prefix, str(root))
        it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 16, 16), batch_size=1)
        b0 = it.next().data[0].asnumpy()
        b1 = it.next().data[0].asnumpy()
        # JPEG is lossy; the two flat images are far apart so means are
        # well-separated iff b0 wasn't overwritten by b1's staging
        assert abs(b0.mean() - vals[0]) < 30
        assert abs(b1.mean() - vals[1]) < 30
