"""Gluon Block/nn/loss/Trainer tests.

Reference analog: tests/python/unittest/test_gluon.py (SURVEY.md §4.1).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=[mx.cpu(0)])
    assert len(p.list_data()) == 1
    assert len(p.list_grad()) == 1
    assert p.data(mx.cpu(0)).context == mx.cpu(0)
    assert p.data(mx.cpu(0)).shape == (10, 10)
    assert p.var().name == "weight"
    p.reset_ctx(ctx=[mx.cpu(1), mx.cpu(2)])
    assert set(p.list_ctx()) == {mx.cpu(1), mx.cpu(2)}


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())


def test_constant():
    class Test(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.value = np.asarray([[1, 2], [3, 4]])
            self.const = self.params.get_constant("const", self.value)

        def hybrid_forward(self, F, x, const):
            return x + const

    test = Test()
    test.initialize()
    trainer = gluon.Trainer(test.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5})
    with mx.autograd.record():
        x = mx.nd.ones((2, 2))
        x.attach_grad()
        y = test(x)
        y.backward()
    trainer.step(1)
    assert (test.const.data().asnumpy() == test.value).all()
    assert (x.grad.asnumpy() == 1).all()


def test_basic():
    model = nn.Sequential()
    model.add(nn.Dense(128, activation="tanh", in_units=10, flatten=False))
    model.add(nn.Dropout(0.5))
    model.add(nn.Dense(64, activation="tanh", in_units=256))
    model.add(nn.Dense(32, in_units=64))
    model.add(nn.Activation("relu"))

    # symbol
    x = mx.sym.var("data")
    y = model(x)
    assert len(y.list_arguments()) == 7

    # ndarray
    model.initialize()
    x = mx.nd.zeros((32, 2, 10))
    out = model(x)
    assert out.shape == (32, 32)

    params = model.collect_params()
    [params[k].grad() for k in params if k.endswith("weight")]


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test_")
    inputs = mx.sym.var("data")
    outputs = model(inputs)
    assert set(model.collect_params().keys()) == \
        {"test_weight", "test_bias"}
    assert outputs.list_outputs() == ["test_tanh_fwd_output"]
    args, outs, auxs = outputs.infer_shape(data=(2, 3, 10))
    assert outs == [(2, 3, 128)]

    model = nn.Dense(128, activation="relu", in_units=30, flatten=True,
                     prefix="test2_")
    inputs = mx.sym.var("data")
    outputs = model(inputs)
    assert set(model.collect_params().keys()) == \
        {"test2_weight", "test2_bias"}
    args, outs, auxs = outputs.infer_shape(data=(17, 2, 5, 3))
    assert outs == [(17, 128)]


def test_hybrid_sequential_save_load(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 8))
    y0 = net(x)
    path = str(tmp_path / "m.params")
    net.save_parameters(path)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(16, activation="relu"))
        net2.add(nn.Dense(4))
    net2.load_parameters(path)
    y1 = net2(x)
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-5)


def test_hybridize_consistency():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.MaxPool2D())
        net.add(nn.Flatten())
        net.add(nn.Dense(8))
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 8, 8))
    y0 = net(x)
    net.hybridize()
    y1 = net(x)
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_hybrid_export_import(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(2, 6))
    y0 = net(x)
    path = str(tmp_path / "exported")
    net.export(path)
    net2 = gluon.SymbolBlock.imports(
        path + "-symbol.json", ["data"], path + "-0000.params")
    y1 = net2(x)
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-5)


def test_conv_layers():
    for layer, shape in [
            (nn.Conv1D(4, 3), (1, 2, 10)),
            (nn.Conv2D(4, 3, groups=2), (1, 2, 10, 10)),
            (nn.Conv3D(4, 3), (1, 2, 10, 10, 10)),
            (nn.Conv1DTranspose(4, 3), (1, 2, 10)),
            (nn.Conv2DTranspose(4, 3, strides=2), (1, 2, 10, 10)),
            (nn.MaxPool1D(2), (1, 2, 10)),
            (nn.AvgPool2D((2, 2)), (1, 2, 10, 10)),
            (nn.GlobalAvgPool2D(), (1, 2, 10, 10)),
            (nn.GlobalMaxPool1D(), (1, 2, 10))]:
        layer.initialize()
        out = layer(mx.nd.random.uniform(shape=shape))
        assert out.shape[0] == 1


def test_norm_layers():
    x = mx.nd.random.uniform(shape=(2, 4, 5))
    ln = nn.LayerNorm(in_channels=5)
    ln.initialize()
    out = ln(x).asnumpy()
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)

    inorm = nn.InstanceNorm(in_channels=4)
    inorm.initialize()
    assert inorm(x).shape == x.shape

    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    with mx.autograd.record():
        y = bn(x)
    assert y.shape == x.shape


def test_losses():
    pred = mx.nd.random.uniform(shape=(4, 10))
    label_idx = mx.nd.array([1, 2, 3, 4])
    label_dense = mx.nd.random.uniform(shape=(4, 10))
    losses = [
        (gluon.loss.L2Loss(), label_dense),
        (gluon.loss.L1Loss(), label_dense),
        (gluon.loss.SigmoidBinaryCrossEntropyLoss(), label_dense),
        (gluon.loss.SoftmaxCrossEntropyLoss(), label_idx),
        (gluon.loss.KLDivLoss(from_logits=False), label_dense),
        (gluon.loss.HuberLoss(), label_dense),
        (gluon.loss.HingeLoss(), label_dense),
        (gluon.loss.SquaredHingeLoss(), label_dense),
        (gluon.loss.LogisticLoss(), label_dense),
        (gluon.loss.PoissonNLLLoss(), label_dense),
    ]
    for loss_fn, label in losses:
        L = loss_fn(pred, label)
        assert L.ndim == 0 or L.shape[0] == 4, type(loss_fn).__name__
        assert np.isfinite(L.asnumpy()).all(), type(loss_fn).__name__


def test_softmax_ce_loss_value():
    pred = mx.nd.array([[1e10, -1e10, 0], [0, 1e10, -1e10]])
    label = mx.nd.array([0, 1])
    L = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    np.testing.assert_allclose(L.asnumpy(), 0, atol=1e-5)


def test_trainer_sgd_matches_manual():
    w = gluon.Parameter("w", shape=(3,))
    w.initialize(init="ones", ctx=mx.cpu())
    trainer = gluon.Trainer({"w": w}, "sgd", {"learning_rate": 0.5})
    with mx.autograd.record():
        loss = (w.data() * mx.nd.array([1., 2., 3.])).sum()
    loss.backward()
    trainer.step(1)
    np.testing.assert_allclose(
        w.data().asnumpy(), 1 - 0.5 * np.array([1., 2., 3.]), rtol=1e-6)


def test_trainer_save_load_states(tmp_path):
    w = gluon.Parameter("w", shape=(3,))
    w.initialize(ctx=mx.cpu())
    tr = gluon.Trainer({"w": w}, "adam", {"learning_rate": 0.1})
    with mx.autograd.record():
        loss = (w.data() ** 2).sum()
    loss.backward()
    tr.step(1)
    path = str(tmp_path / "t.states")
    tr.save_states(path)
    tr.load_states(path)


def test_split_and_load():
    x = mx.nd.arange(12).reshape((4, 3))
    parts = gluon.split_and_load(x, [mx.cpu(0), mx.cpu(1)])
    assert [p.shape for p in parts] == [(2, 3), (2, 3)]


def test_block_repr_and_summary(capsys):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize()
    repr(net)
    net.summary(mx.nd.ones((1, 3)))
    out = capsys.readouterr().out
    assert "Total params" in out


def test_lambda_blocks():
    net = nn.HybridLambda(lambda F, x: F.relu(x))
    out = net(mx.nd.array([-1.0, 1.0]))
    np.testing.assert_allclose(out.asnumpy(), [0.0, 1.0])
    net2 = nn.Lambda("relu")
    np.testing.assert_allclose(
        net2(mx.nd.array([-2.0, 2.0])).asnumpy(), [0.0, 2.0])


def test_zero_grad():
    p = gluon.Parameter("w", shape=(2,))
    p.initialize(ctx=mx.cpu())
    with mx.autograd.record():
        L = (p.data() * 2).sum()
    L.backward()
    assert p.grad().asnumpy().sum() != 0
    p.zero_grad()
    assert p.grad().asnumpy().sum() == 0


def test_bfloat16_training_step():
    """bf16 end-to-end: cast net, hybridize, fwd+bwd+mp-SGD (the conv
    transpose used to break on mixed-dtype cotangents)."""
    import numpy as np
    from mxnet_tpu import gluon, nd, autograd
    import mxnet_tpu as mx
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Dense(3))
    net.initialize()
    x32 = nd.random.uniform(shape=(2, 3, 8, 8))
    net(x32)                       # materialize params
    net.cast("bfloat16")
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1,
                             "multi_precision": True})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = x32.astype("bfloat16")
    y = nd.array(np.array([0, 1], np.float32))
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(2)
        losses.append(float(loss.mean().astype("float32").asnumpy()))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
