"""Worker for the preempt-resume bit-exactness test (tests/test_elastic.py).

Trains a small MLP through Module.fit with the async checkpointer wired
(MXNET_CKPT_DIR / MXNET_CKPT_EVERY_N_STEPS).  The test runs it three
ways: uninterrupted (reference), chaos-SIGTERMed mid-epoch (preemption:
the handler writes a final sync checkpoint and exits 0), and resumed
(chaos off via MXNET_ELASTIC_RESTART=1).  The resumed run's final params
must equal the uninterrupted run's bit for bit.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.io import NDArrayIter  # noqa: E402
from mxnet_tpu.module import Module  # noqa: E402

OUT = sys.argv[1]
NUM_EPOCH = int(sys.argv[2]) if len(sys.argv) > 2 else 2


def main():
    rng = np.random.RandomState(0)
    centers = rng.randn(3, 10) * 3
    X = np.zeros((200, 10), np.float32)
    y = np.zeros((200,), np.float32)
    for i in range(200):
        c = i % 3
        X[i] = centers[c] + rng.randn(10) * 0.5
        y[i] = c

    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=3, name="fc2")
    net = sym.SoftmaxOutput(h, name="softmax")

    mx.random.seed(7)
    mod = Module(net, context=mx.cpu())
    it = NDArrayIter(X, y, batch_size=20)
    mod.fit(it, num_epoch=NUM_EPOCH, optimizer="adam",
            optimizer_params=(("learning_rate", 0.01),))
    arg, aux = mod.get_params()
    np.savez(OUT, **{k: v.asnumpy() for k, v in
                     list(arg.items()) + list(aux.items())})


if __name__ == "__main__":
    main()
