"""Tests for the Python custom-op bridge, test_utils, and image ops.

Parity model: reference tests/python/unittest/test_operator.py
(test_custom_op), test_gluon_data_vision (image ops).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.symbol as sym
from mxnet_tpu import test_utils as tu


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0],
                    mx.nd.array(1.0 / (1.0 + np.exp(-x))))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(gy * y * (1 - y)))


@mx.operator.register("test_sigmoid_custom")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return _Sigmoid()


def test_custom_op_forward_backward():
    x = nd.array(np.array([[-1.0, 0.0, 2.0]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(x, op_type="test_sigmoid_custom")
        s = y.sum()
    s.backward()
    ey = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y.asnumpy(), ey, rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(), ey * (1 - ey), rtol=1e-5)


def test_custom_op_symbolic():
    d = sym.var("data")
    out = sym.Custom(d, op_type="test_sigmoid_custom", name="sig")
    x = nd.array(np.array([[0.5, -0.5]], np.float32))
    ex = out.bind(mx.cpu(), {"data": x})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               1 / (1 + np.exp(-x.asnumpy())), rtol=1e-5)


@mx.operator.register("test_scale_custom")
class _ScaleProp(mx.operator.CustomOpProp):
    def __init__(self, scale="1.0"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def create_operator(self, ctx, shapes, dtypes):
        prop = self

        class Op(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * prop.scale)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0], out_grad[0] * prop.scale)

        return Op()


def test_custom_op_string_kwargs():
    z = nd.Custom(nd.array([1., 2.]), op_type="test_scale_custom",
                  scale="3.0")
    np.testing.assert_allclose(z.asnumpy(), [3., 6.])


def test_custom_op_multi_output():
    @mx.operator.register("test_split2_custom")
    class Split2Prop(mx.operator.CustomOpProp):
        def list_outputs(self):
            return ["half", "double"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0], in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Op(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] / 2)
                    self.assign(out_data[1], req[1], in_data[0] * 2)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0],
                                out_grad[0] / 2 + out_grad[1] * 2)

            return Op()

    x = nd.array([2., 4.])
    h, d = nd.Custom(x, op_type="test_split2_custom")
    np.testing.assert_allclose(h.asnumpy(), [1., 2.])
    np.testing.assert_allclose(d.asnumpy(), [4., 8.])


def test_custom_op_unregistered_raises():
    with pytest.raises(mx.MXNetError):
        nd.Custom(nd.array([1.]), op_type="never_registered_xyz")


class TestTestUtils:
    def test_assert_almost_equal_raises(self):
        with pytest.raises(AssertionError):
            tu.assert_almost_equal(np.ones(3), np.zeros(3))
        tu.assert_almost_equal(np.ones(3), np.ones(3) + 1e-9, atol=1e-6)

    def test_check_numeric_gradient(self):
        a = sym.var("a")
        b = sym.var("b")
        out = sym.broadcast_mul(a, b) + sym.sin(a)
        loc = {"a": np.random.rand(2, 3).astype(np.float32),
               "b": np.random.rand(2, 3).astype(np.float32)}
        tu.check_numeric_gradient(out, loc, numeric_eps=1e-3, rtol=0.05,
                                  atol=1e-3)

    def test_check_numeric_gradient_catches_wrong_grad(self):
        # SVMOutput's backward ignores the head gradient -> finite
        # differences of the identity forward disagree with the hinge grad
        d = sym.var("d")
        out = sym.SVMOutput(d, sym.var("label"))
        with pytest.raises(AssertionError):
            tu.check_numeric_gradient(
                out, {"d": np.random.rand(2, 3).astype(np.float32),
                      "label": np.zeros(2, np.float32)},
                grad_nodes=["d"], rtol=0.01, atol=1e-3)

    def test_check_symbolic_forward_backward(self):
        a = sym.var("a")
        x = np.random.rand(2, 3).astype(np.float32)
        tu.check_symbolic_forward(sym.square(a), {"a": x}, [x ** 2])
        tu.check_symbolic_backward(sym.square(a), {"a": x},
                                   [np.ones_like(x)], [2 * x])

    def test_rand_ndarray_stypes(self):
        d = tu.rand_ndarray((4, 5))
        assert d.shape == (4, 5)
        rs = tu.rand_ndarray((6, 3), "row_sparse", density=0.5)
        assert rs.stype == "row_sparse"
        csr = tu.rand_ndarray((6, 3), "csr", density=0.3)
        assert csr.stype == "csr"

    def test_check_consistency(self):
        a = sym.var("a")
        tu.check_consistency(sym.exp(a), [{"ctx": mx.cpu(), "a": (3, 2)},
                                          {"ctx": mx.cpu(), "a": (3, 2)}])


class TestImageOps:
    def test_to_tensor(self):
        img = nd.array(np.full((4, 5, 3), 255, np.uint8))
        t = nd.image.to_tensor(img)
        assert t.shape == (3, 4, 5)
        np.testing.assert_allclose(t.asnumpy(), 1.0, atol=1e-6)
        batch = nd.array(np.zeros((2, 4, 5, 3), np.uint8))
        tb = nd.image.to_tensor(batch)
        assert tb.shape == (2, 3, 4, 5)

    def test_normalize(self):
        x = nd.array(np.ones((3, 2, 2), np.float32))
        out = nd.image.normalize(x, mean=(0.5, 0.5, 0.5),
                                 std=(0.25, 0.5, 1.0))
        np.testing.assert_allclose(out.asnumpy()[:, 0, 0], [2., 1., 0.5],
                                   rtol=1e-5)

    def test_transforms_backed_by_image_ops(self):
        from mxnet_tpu.gluon.data.vision import transforms
        t = transforms.Compose([transforms.ToTensor(),
                                transforms.Normalize(0.5, 0.25)])
        img = nd.array(np.full((4, 4, 3), 128, np.uint8))
        out = t(img)
        assert out.shape == (3, 4, 4)
        np.testing.assert_allclose(out.asnumpy(),
                                   (128 / 255 - 0.5) / 0.25, rtol=1e-4)


def test_custom_op_stress_in_process():
    """Round-4 structural-fix regression: >=50 train iterations through the
    ordered-io_callback bridge in ONE interpreter, callbacks doing real
    eager mx.nd work (the re-entrant-dispatch pattern that wedged the r03
    pure_callback bridge ~1/20 runs), no timeout/retry machinery."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    class NdSwish(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0]
            # deliberate jax re-entry from the worker thread
            self.assign(out_data[0], req[0], x * nd.Activation(
                x, act_type="sigmoid"))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            x = in_data[0]
            s = nd.Activation(x, act_type="sigmoid")
            self.assign(in_grad[0], req[0],
                        out_grad[0] * (s + x * s * (1 - s)))

    @mx.operator.register("_stress_swish")
    class NdSwishProp(mx.operator.CustomOpProp):
        def create_operator(self, ctx, in_shapes, in_dtypes):
            return NdSwish()

    x = nd.array(np.random.RandomState(0).randn(16, 8).astype(np.float32))
    w = nd.array(np.random.RandomState(1).randn(8, 8).astype(np.float32))
    w.attach_grad()
    losses = []
    for _ in range(60):
        with mx.autograd.record():
            h = nd.dot(x, w)
            y = nd.Custom(h, op_type="_stress_swish")
            loss = (y * y).sum()
        loss.backward()
        w[:] = w - 0.001 * w.grad
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
