"""Autograd tests (parity model: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2.0
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(nd.log(x) * 2.0)  # = x^2
        z = y.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-5)


def test_multiple_inputs():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    assert np.allclose(a.grad.asnumpy(), [4, 5])
    assert np.allclose(b.grad.asnumpy(), [1, 2])


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3.0 * x
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30, 300])


def test_grad_req_add():
    x = nd.array([1.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g], "add")
    for _ in range(3):
        with autograd.record():
            y = 2.0 * x
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_not_recording_outside_scope():
    x = nd.array([1.0])
    x.attach_grad()
    y = x * 2.0  # not recorded
    assert getattr(y, "_ag_entry") is None
    with autograd.record():
        assert autograd.is_recording()
        z = x * 2.0
    assert getattr(z, "_ag_entry") is not None


def test_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        with autograd.pause():
            y = x * 2.0
        z = x * 3.0
    assert getattr(y, "_ag_entry") is None
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [3.0])


def test_train_mode_flags():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_dropout_respects_mode():
    x = nd.ones((100, 100))
    out_eval = nd.Dropout(x, p=0.5)
    assert np.allclose(out_eval.asnumpy(), 1.0)
    with autograd.record():
        out_train = nd.Dropout(x, p=0.5)
    frac = (out_train.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_grad_function():
    x = nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    g = autograd.grad([y], [x])[0]
    assert np.allclose(g.asnumpy(), [4.0, 6.0])
    # .grad buffer untouched by functional grad API
    assert np.allclose(x.grad.asnumpy(), 0.0)


def test_retain_graph():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 5.0
    y.backward(retain_graph=True)
    assert np.allclose(x.grad.asnumpy(), [5.0])
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [5.0])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_backward_through_conv():
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    w = nd.array(np.random.rand(4, 3, 3, 3).astype(np.float32))
    b = nd.zeros((4,))
    for v in (x, w, b):
        v.attach_grad()
    with autograd.record():
        y = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, pad=(1, 1))
        loss = (y * y).sum()
    loss.backward()
    assert x.grad.shape == x.shape
    assert w.grad.shape == w.shape
    assert b.grad.shape == b.shape
    assert float(nd.abs(w.grad).sum().asscalar()) > 0
