"""Time-series telemetry (mxnet_tpu/telemetry/timeseries.py).

Covers tier rollup arithmetic (driven with a fake clock — no sleeping),
counter->rate derivation through the shared WindowedRate, histogram
p50/p99 sampling with the +Inf overflow stored as null, the trailing
window a flight dump embeds (fine tier extended backwards by coarser
tiers), sparkline/ASCII rendering, the /timeseriesz endpoint, the
sampler thread lifecycle, and the no-jax-in-the-sample-path guarantee.
"""
import json
import math
import os
import sys
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mxnet_tpu import telemetry, tracing
from mxnet_tpu.telemetry import timeseries
from mxnet_tpu.telemetry.registry import MetricRegistry
from mxnet_tpu.telemetry.timeseries import (TimeSeriesStore, render_ascii,
                                            series_key, sparkline)


@pytest.fixture(autouse=True)
def _clean():
    timeseries.stop()
    telemetry.reset()
    timeseries.store().clear()
    yield
    telemetry.disable()
    timeseries.stop()
    telemetry.reset()
    timeseries.store().clear()


def _fresh(interval=1.0, tiers=((1, 8), (4, 8))):
    """A store over its own registry: small tiers keep tests readable."""
    reg = MetricRegistry()
    return reg, TimeSeriesStore(reg, interval=interval, tiers=tiers)


# ---------------------------------------------------------------------------
# sparkline / key / rendering
# ---------------------------------------------------------------------------
class TestRendering:
    def test_sparkline_shape(self):
        s = sparkline([0.0, 1.0, 2.0, 3.0])
        assert s[0] == "▁" and s[-1] == "█" and len(s) == 4

    def test_sparkline_gaps_and_nonfinite(self):
        assert sparkline([1.0, None, 2.0]) == "▁ █"
        assert sparkline([1.0, float("inf"), 2.0]) == "▁ █"

    def test_sparkline_constant_and_empty(self):
        assert sparkline([5.0, 5.0]) == "▁▁"
        assert sparkline([]) == ""
        assert sparkline([None, None]) == "  "

    def test_sparkline_width_keeps_newest(self):
        assert sparkline([9.0] + [0.0, 1.0], width=2) == sparkline([0.0, 1.0])

    def test_series_key(self):
        assert series_key("m", "rate", {}) == "m:rate"
        assert series_key("m", "p50", {"b": "2", "a": "1"}) \
            == "m:p50{a=1,b=2}"

    def test_render_ascii(self):
        reg, st = _fresh()
        g = reg.gauge("depth", "")
        for i in range(4):
            g.set(float(i))
            st.sample_once(now=100.0 + i)
        txt = render_ascii(st.snapshot())
        line = [ln for ln in txt.splitlines() if "depth:value" in ln][0]
        assert "▁" in line and "█" in line and "last=3" in line


# ---------------------------------------------------------------------------
# tier rollup + sampling semantics (fake clock throughout)
# ---------------------------------------------------------------------------
class TestStore:
    def test_gauge_tier_rollup(self):
        reg, st = _fresh(tiers=((1, 8), (4, 8)))
        g = reg.gauge("q", "")
        for i in range(8):
            g.set(float(i))
            st.sample_once(now=100.0 + i)
        snap = st.snapshot()["q:value"]
        fine, coarse = snap["tiers"]
        assert fine["resolution"] == 1.0 and coarse["resolution"] == 4.0
        assert [p[1] for p in fine["points"]] == [float(i) for i in range(8)]
        # coarse points are the means of each 4-sample window
        assert [p[1] for p in coarse["points"]] == [1.5, 5.5]
        assert snap["kind"] == "gauge" and snap["stat"] == "value"

    def test_ring_capacity_evicts_oldest(self):
        reg, st = _fresh(tiers=((1, 4),))
        g = reg.gauge("q", "")
        for i in range(10):
            g.set(float(i))
            st.sample_once(now=100.0 + i)
        pts = st.snapshot()["q:value"]["tiers"][0]["points"]
        assert [p[1] for p in pts] == [6.0, 7.0, 8.0, 9.0]

    def test_counter_becomes_rate(self):
        reg, st = _fresh()
        c = reg.counter("ops_total", "")
        c.inc(0)                           # materialize the child
        st.sample_once(now=100.0)          # first observation: no window yet
        c.inc(50)
        st.sample_once(now=110.0)          # 50 ops / 10 s
        pts = st.snapshot()["ops_total:rate"]["tiers"][0]["points"]
        assert pts[0][1] is None
        assert pts[1][1] == pytest.approx(5.0)

    def test_labelled_counter_per_child_series(self):
        reg, st = _fresh()
        c = reg.counter("ev_total", "", labelnames=("kind",))
        c.labels(kind="a").inc()
        c.labels(kind="b").inc(3)
        st.sample_once(now=100.0)
        c.labels(kind="a").inc(2)
        st.sample_once(now=101.0)
        snap = st.snapshot()
        assert snap["ev_total:rate{kind=a}"]["labels"] == {"kind": "a"}
        a = snap["ev_total:rate{kind=a}"]["tiers"][0]["points"]
        b = snap["ev_total:rate{kind=b}"]["tiers"][0]["points"]
        assert a[-1][1] == pytest.approx(2.0)
        assert b[-1][1] == pytest.approx(0.0)

    def test_histogram_quantiles_and_count_rate(self):
        reg, st = _fresh()
        h = reg.histogram("lat", "", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        st.sample_once(now=100.0)
        h.observe(0.5)
        st.sample_once(now=101.0)
        snap = st.snapshot()
        p50 = snap["lat:p50"]["tiers"][0]["points"]
        # interpolated within the (0.1, 1.0] bucket: 0.1 + 1.5/3 * 0.9
        assert p50[-1][1] == pytest.approx(0.55)
        rate = snap["lat:rate"]["tiers"][0]["points"]
        assert rate[-1][1] == pytest.approx(1.0)   # 1 obs in 1 s
        assert snap["lat:p99"]["kind"] == "histogram"

    def test_overflow_quantile_stored_as_null(self):
        reg, st = _fresh()
        h = reg.histogram("lat", "", buckets=(0.1, 1.0))
        h.observe(99.0)                             # lands in +Inf bucket
        st.sample_once(now=100.0)
        p99 = st.snapshot()["lat:p99"]["tiers"][0]["points"]
        assert p99[-1][1] is None
        # and the whole snapshot stays strict-JSON serializable
        assert "Infinity" not in json.dumps(st.snapshot())

    def test_nonfinite_gauge_stored_as_null(self):
        reg, st = _fresh()
        g = reg.gauge("ratio", "")
        g.set(float("nan"))
        st.sample_once(now=100.0)
        g.set(2.0)
        st.sample_once(now=101.0)
        pts = st.snapshot()["ratio:value"]["tiers"][0]["points"]
        assert pts[0][1] is None and pts[1][1] == 2.0

    def test_snapshot_window_and_prefix_filter(self):
        reg, st = _fresh()
        reg.gauge("a_g", "").set(1.0)
        reg.gauge("b_g", "").set(2.0)
        for i in range(5):
            st.sample_once(now=100.0 + i)
        snap = st.snapshot(prefix="a_")
        assert set(snap) == {"a_g:value"}
        snap = st.snapshot(window_seconds=2.0, now=104.0)
        assert len(snap["b_g:value"]["tiers"][0]["points"]) == 3  # t>=102

    def test_self_metrics_registered(self):
        reg, st = _fresh()
        st.sample_once(now=100.0)
        assert reg.get("timeseries_samples_total").samples()[0][1] == 1.0
        st.sample_once(now=101.0)
        assert reg.get("timeseries_series").samples()[0][1] == len(st)

    def test_clear_and_len(self):
        reg, st = _fresh()
        reg.gauge("g", "").set(1.0)
        st.sample_once(now=100.0)
        assert len(st) > 0
        st.clear()
        assert len(st) == 0


# ---------------------------------------------------------------------------
# trailing window (the flight-dump block)
# ---------------------------------------------------------------------------
class TestTrailing:
    def test_trailing_covers_window_from_fine_tier(self):
        reg, st = _fresh(tiers=((1, 512), (10, 512)))
        g = reg.gauge("g", "")
        for i in range(130):
            g.set(float(i))
            st.sample_once(now=1000.0 + i)
        doc = st.trailing(window_seconds=60.0, now=1000.0 + 129)
        pts = doc["series"]["g:value"]["points"]
        assert len(pts) >= 60          # >= 60 s of 1 s-resolution history
        assert pts[-1][1] == 129.0
        assert doc["window_seconds"] == 60.0 and doc["interval"] == 1.0

    def test_trailing_extends_with_coarse_tier(self):
        # fine ring only holds 8 points; the 120 s window must be carried
        # by the coarse tier behind it
        reg, st = _fresh(tiers=((1, 8), (10, 64)))
        g = reg.gauge("g", "")
        for i in range(100):
            g.set(float(i))
            st.sample_once(now=1000.0 + i)
        pts = st.trailing(window_seconds=90.0,
                          now=1000.0 + 99)["series"]["g:value"]["points"]
        ts = [p[0] for p in pts]
        assert ts == sorted(ts)
        assert ts[0] <= 1000.0 + 99 - 80   # reaches well past the fine ring
        assert pts[-1][1] == 99.0          # newest point is fine-tier exact
        assert min(ts) >= 1000.0 + 99 - 90 - 10  # but bounded by the window

    def test_trailing_empty_store(self):
        _, st = _fresh()
        assert st.trailing(window_seconds=60.0, now=100.0)["series"] == {}


# ---------------------------------------------------------------------------
# sampler thread + module singleton + endpoint
# ---------------------------------------------------------------------------
class TestSamplerLifecycle:
    def test_start_stop_idempotent(self):
        st = timeseries.start(interval=0.05)
        assert timeseries.running()
        assert timeseries.start() is st     # second start: same store
        import threading
        names = [t.name for t in threading.enumerate()]
        assert names.count("mxtpu-telemetry-ts") == 1
        timeseries.stop()
        timeseries.stop()                   # idempotent
        assert not timeseries.running()

    def test_sampler_actually_samples(self):
        telemetry.gauge("live_g", "").set(7.0)
        timeseries.start(interval=0.02)
        deadline = 100
        while "live_g:value" not in timeseries.snapshot() and deadline:
            import time
            time.sleep(0.02)
            deadline -= 1
        assert "live_g:value" in timeseries.snapshot()
        timeseries.stop()

    def test_enable_env_gate(self, monkeypatch):
        monkeypatch.setenv("MXNET_TELEMETRY_TS", "0")
        telemetry.enable()
        assert not timeseries.running()
        monkeypatch.setenv("MXNET_TELEMETRY_TS", "1")
        telemetry.enable()
        assert timeseries.running()
        telemetry.disable()
        assert not timeseries.running()

    def test_no_jax_in_sample_path(self):
        # the zero-extra-XLA-compiles property is structural: the sampler
        # is pure host arithmetic and must never grow a jax import
        src = open(timeseries.__file__.rstrip("c")).read()
        assert "import jax" not in src and "from jax" not in src
        assert "jax" not in dir(timeseries)

    def test_timeseriesz_endpoint(self):
        telemetry.gauge("srv_g", "").set(3.0)
        timeseries.store().sample_once()
        port = telemetry.start_http_server(port=0)
        try:
            doc = json.loads(urllib.request.urlopen(
                "http://127.0.0.1:%d/timeseriesz" % port, timeout=5).read())
            assert doc["running"] is False
            assert doc["interval"] == timeseries.store().interval
            assert "srv_g:value" in doc["series"]
            body = urllib.request.urlopen(
                "http://127.0.0.1:%d/timeseriesz?format=ascii&prefix=srv_"
                % port, timeout=5).read().decode()
            assert "srv_g:value" in body and "last=3" in body
            doc = json.loads(urllib.request.urlopen(
                "http://127.0.0.1:%d/timeseriesz?prefix=nomatch" % port,
                timeout=5).read())
            assert doc["series"] == {}
        finally:
            telemetry.stop_http_server()


# ---------------------------------------------------------------------------
# flight-recorder integration
# ---------------------------------------------------------------------------
class TestFlightDump:
    def test_dump_embeds_trailing_window(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH",
                           str(tmp_path / "flight.json"))
        telemetry.gauge("fd_g", "").set(1.25)
        timeseries.store().sample_once()
        path = tracing.flight.dump(reason="test_ts_embed")
        doc = json.load(open(path))
        assert "timeseries" in doc
        assert doc["timeseries"]["window_seconds"] >= 60.0
        assert "fd_g:value" in doc["timeseries"]["series"]
        # the embedded block passes the merge_traces schema check
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import merge_traces
        assert merge_traces.is_flight_dump(doc)
        assert merge_traces.validate_flight_dump(doc) == []
