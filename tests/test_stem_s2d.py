"""Space-to-depth stem-conv rewrite: exactness vs the direct conv.

The rewrite (ops/nn.py:_stem_s2d_conv) turns thin-input stride-2 convs
(ResNet 7x7s2 RGB stem) into stride-1 convs on 4x the channels — measured
2.5x faster on TPU (docs/perf_analysis.md round 3).  It must be EXACT.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops import nn as opsnn


def _attrs(k, pad, O):
    return {"kernel": (k, k), "stride": (2, 2), "dilate": (1, 1),
            "pad": (pad, pad), "num_filter": O, "num_group": 1,
            "no_bias": True}


@pytest.mark.parametrize("k,pad,H,C,O", [
    (7, 3, 224, 3, 64),    # the ResNet stem
    (7, 2, 32, 3, 8),      # asymmetric-tap variant
    (3, 1, 16, 4, 6),
    (5, 2, 20, 2, 4),
])
def test_s2d_conv_exact(k, pad, H, C, O):
    rng = np.random.default_rng(k * 100 + pad)
    n = 2 if H <= 64 else 1
    x = jnp.asarray(rng.standard_normal((n, C, H, H)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((O, C, k, k)), jnp.float32)
    ref = jax.lax.conv_general_dilated(
        x, w, (2, 2), [(pad, pad)] * 2,
        dimension_numbers=opsnn._conv_dnums(2))
    got = opsnn._stem_s2d_conv(_attrs(k, pad, O), x, w)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_s2d_conv_gradients_exact():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((8, 3, 7, 7)), jnp.float32)
    attrs = _attrs(7, 3, 8)

    def f_ref(x, w):
        return jnp.sum(jax.nn.relu(jax.lax.conv_general_dilated(
            x, w, (2, 2), [(3, 3)] * 2,
            dimension_numbers=opsnn._conv_dnums(2))))

    def f_s2d(x, w):
        return jnp.sum(jax.nn.relu(opsnn._stem_s2d_conv(attrs, x, w)))

    gr = jax.grad(f_ref, (0, 1))(x, w)
    gs = jax.grad(f_s2d, (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gs[0]), np.asarray(gr[0]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gs[1]), np.asarray(gr[1]),
                               rtol=1e-4, atol=1e-4)


def test_eligibility_gate():
    x = jnp.zeros((2, 3, 32, 32))
    assert opsnn._stem_s2d_eligible(_attrs(7, 3, 8), x, 2)
    # stride 1, wide channels, odd spatial, groups: all ineligible
    a = _attrs(7, 3, 8); a["stride"] = (1, 1)
    assert not opsnn._stem_s2d_eligible(a, x, 2)
    assert not opsnn._stem_s2d_eligible(
        _attrs(7, 3, 8), jnp.zeros((2, 64, 32, 32)), 2)
    assert not opsnn._stem_s2d_eligible(
        _attrs(7, 3, 8), jnp.zeros((2, 3, 33, 32)), 2)
    a = _attrs(7, 3, 8); a["num_group"] = 3
    assert not opsnn._stem_s2d_eligible(a, x, 2)
