"""Systematic finite-difference gradient sweep over core operators.

Parity model: reference tests/python/unittest/test_operator.py — the
largest suite, whose backbone is ``check_numeric_gradient`` applied per
op.  Here one parameterized sweep covers the op families' analytic VJPs
against central differences (test_utils.check_numeric_gradient), plus
symbolic forward golden checks for a few ops with closed forms.
"""
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu import test_utils as tu


def _u(shape, lo=-1.0, hi=1.0, rng=None):
    rng = rng or np.random
    return rng.uniform(lo, hi, shape).astype(np.float64)


# (name, symbol builder, location builder)
CASES = [
    ("FullyConnected",
     lambda: sym.FullyConnected(sym.var("data"), sym.var("w"),
                                sym.var("b"), num_hidden=3),
     lambda r: {"data": _u((2, 4), rng=r), "w": _u((3, 4), rng=r),
                "b": _u((3,), rng=r)}),
    ("Convolution",
     lambda: sym.Convolution(sym.var("data"), sym.var("w"),
                             kernel=(3, 3), num_filter=2, pad=(1, 1),
                             no_bias=True),
     lambda r: {"data": _u((1, 2, 5, 5), rng=r),
                "w": _u((2, 2, 3, 3), rng=r)}),
    ("Deconvolution",
     lambda: sym.Deconvolution(sym.var("data"), sym.var("w"),
                               kernel=(2, 2), num_filter=2, no_bias=True),
     lambda r: {"data": _u((1, 2, 3, 3), rng=r),
                "w": _u((2, 2, 2, 2), rng=r)}),
    ("Pooling_max",
     lambda: sym.Pooling(sym.var("data"), kernel=(2, 2), stride=(2, 2),
                         pool_type="max"),
     lambda r: {"data": _u((1, 2, 4, 4), rng=r) +
                np.arange(32).reshape(1, 2, 4, 4) * 0.05}),
    ("Pooling_avg",
     lambda: sym.Pooling(sym.var("data"), kernel=(2, 2), stride=(2, 2),
                         pool_type="avg"),
     lambda r: {"data": _u((1, 2, 4, 4), rng=r)}),
    ("Activation_tanh",
     lambda: sym.Activation(sym.var("data"), act_type="tanh"),
     lambda r: {"data": _u((3, 4), rng=r)}),
    ("softmax",
     lambda: sym.softmax(sym.var("data"), axis=-1),
     lambda r: {"data": _u((3, 5), rng=r)}),
    ("LayerNorm",
     lambda: sym.LayerNorm(sym.var("data"), sym.var("g"), sym.var("b")),
     lambda r: {"data": _u((3, 6), rng=r),
                "g": _u((6,), 0.5, 1.5, rng=r), "b": _u((6,), rng=r)}),
    ("dot",
     lambda: sym.dot(sym.var("a"), sym.var("b")),
     lambda r: {"a": _u((3, 4), rng=r), "b": _u((4, 2), rng=r)}),
    ("batch_dot",
     lambda: sym.batch_dot(sym.var("a"), sym.var("b")),
     lambda r: {"a": _u((2, 3, 4), rng=r), "b": _u((2, 4, 2), rng=r)}),
    ("broadcast_mul",
     lambda: sym.broadcast_mul(sym.var("a"), sym.var("b")),
     lambda r: {"a": _u((3, 4), rng=r), "b": _u((1, 4), rng=r)}),
    ("elemwise_div",
     lambda: sym.elemwise_div(sym.var("a"), sym.var("b")),
     lambda r: {"a": _u((3, 4), rng=r),
                "b": _u((3, 4), 0.5, 1.5, rng=r)}),
    ("exp", lambda: sym.exp(sym.var("data")),
     lambda r: {"data": _u((3, 4), rng=r)}),
    ("log", lambda: sym.log(sym.var("data")),
     lambda r: {"data": _u((3, 4), 0.5, 2.0, rng=r)}),
    ("sqrt", lambda: sym.sqrt(sym.var("data")),
     lambda r: {"data": _u((3, 4), 0.5, 2.0, rng=r)}),
    ("sum_axis",
     lambda: sym.sum(sym.var("data"), axis=1),
     lambda r: {"data": _u((3, 4), rng=r)}),
    ("mean_keepdims",
     lambda: sym.mean(sym.var("data"), axis=(1, 2), keepdims=True),
     lambda r: {"data": _u((2, 3, 4), rng=r)}),
    ("transpose",
     lambda: sym.transpose(sym.var("data"), axes=(1, 0, 2)),
     lambda r: {"data": _u((2, 3, 4), rng=r)}),
    ("Reshape",
     lambda: sym.Reshape(sym.var("data"), shape=(4, 6)),
     lambda r: {"data": _u((2, 3, 4), rng=r)}),
    ("Concat",
     lambda: sym.concat(sym.var("a"), sym.var("b"), dim=1),
     lambda r: {"a": _u((2, 3), rng=r), "b": _u((2, 2), rng=r)}),
    ("slice_axis",
     lambda: sym.slice_axis(sym.var("data"), axis=1, begin=1, end=3),
     lambda r: {"data": _u((2, 4), rng=r)}),
    ("clip",
     lambda: sym.clip(sym.var("data"), a_min=-0.4, a_max=0.4),
     lambda r: {"data": _u((3, 4), rng=r) * 2},),
    ("LeakyReLU_leaky",
     lambda: sym.LeakyReLU(sym.var("data"), act_type="leaky", slope=0.3),
     lambda r: {"data": _u((3, 4), rng=r) + 0.1}),
    ("Embedding",
     lambda: sym.Embedding(sym.var("idx"), sym.var("w"), input_dim=7,
                           output_dim=3),
     lambda r: {"idx": np.array([[1, 3], [6, 0]], np.float64),
                "w": _u((7, 3), rng=r)}),
    ("L2Normalization",
     lambda: sym.L2Normalization(sym.var("data")),
     lambda r: {"data": _u((2, 5), 0.3, 1.0, rng=r)}),
    ("smooth_l1",
     lambda: sym.smooth_l1(sym.var("data"), scalar=1.0),
     lambda r: {"data": _u((3, 4), rng=r) * 3}),
]


@pytest.mark.parametrize("name,builder,loc", CASES,
                         ids=[c[0] for c in CASES])
def test_numeric_gradient(name, builder, loc):
    rng = np.random.RandomState(zlib.crc32(name.encode()))
    location = loc(rng)
    grad_nodes = None
    if name == "Embedding":
        grad_nodes = ["w"]        # integer indices have no gradient
    tu.check_numeric_gradient(builder(), location, numeric_eps=1e-3,
                              rtol=1e-2, atol=1e-3,
                              grad_nodes=grad_nodes)


def test_forward_golden_values():
    """Closed-form forward checks (check_symbolic_forward pattern)."""
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    tu.check_symbolic_forward(sym.exp(sym.var("data")), {"data": x},
                              [np.exp(x)])
    tu.check_symbolic_forward(
        sym.softmax(sym.var("data"), axis=-1), {"data": x},
        [np.exp(x) / np.exp(x).sum(-1, keepdims=True)])
    tu.check_symbolic_forward(
        sym.L2Normalization(sym.var("data")), {"data": x},
        [x / np.linalg.norm(x, axis=1, keepdims=True)], rtol=1e-4)


def test_backward_golden_values():
    """check_symbolic_backward pattern: closed-form gradients."""
    x = np.array([[0.5, -0.5], [1.5, -2.0]], np.float32)
    og = np.ones_like(x)
    tu.check_symbolic_backward(sym.exp(sym.var("data")), {"data": x},
                               [og], {"data": np.exp(x)})
    tu.check_symbolic_backward(
        sym.clip(sym.var("data"), a_min=-1.0, a_max=1.0), {"data": x},
        [og], {"data": (np.abs(x) <= 1.0).astype(np.float32)})


def test_shifted_gemm_conv_matches_lax_conv(monkeypatch):
    """MXNET_TPU_CONV_SHIFTED_GEMM=1 probing path (round-4 bottleneck
    probe; default OFF — e2e-rejected, see ops/nn.py docstring): the 9
    shifted-GEMM formulation must match lax.conv exactly, fwd + grad."""
    import os
    import numpy as np
    from mxnet_tpu import nd, symbol as sym, test_utils as tu
    from mxnet_tpu.ops.registry import OPS

    r = np.random.RandomState(3)
    x = r.randn(2, 5, 8, 8).astype(np.float32)
    w = (r.randn(6, 5, 3, 3) * 0.2).astype(np.float32)

    def run():
        OPS["Convolution"]._jit_cache.clear()
        return nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                              num_filter=6, pad=(1, 1),
                              no_bias=True).asnumpy()

    monkeypatch.setenv("MXNET_TPU_CONV_SHIFTED_GEMM", "0")
    ref = run()
    try:
        monkeypatch.setenv("MXNET_TPU_CONV_SHIFTED_GEMM", "1")
        got = run()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

        s = sym.Convolution(sym.var("x"), sym.var("w"), kernel=(3, 3),
                            num_filter=4, pad=(1, 1), no_bias=True)
        tu.check_numeric_gradient(
            sym.sum(s), {"x": r.randn(2, 3, 5, 5) * 0.5,
                         "w": r.randn(4, 3, 3, 3) * 0.3},
            rtol=2e-2, atol=2e-2)
    finally:
        # executables traced with flag=1 must never leak into later tests
        monkeypatch.setenv("MXNET_TPU_CONV_SHIFTED_GEMM", "0")
        OPS["Convolution"]._jit_cache.clear()
