"""dist_async worker: async-SGD least squares through the parameter server.

Launched by tests/test_dist_async_kvstore.py via tools/launch.py -s 1.
Each worker trains on its own shard with server-side SGD (set_optimizer ->
update_on_kvstore): push(grad) applies immediately on the server, pull
fetches possibly-staler-than-sync weights — the async semantics under
test.  Rank 0 verifies convergence and stops the server.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import nd


def main():
    # create() first: in a DMLC_ROLE=server process this enters the server
    # loop and never returns (reference kvstore_server.py behavior)
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2

    rng = np.random.RandomState(100 + rank)
    w_true = np.array([[1.0], [-2.0], [3.0]], np.float32)
    X = rng.randn(256, 3).astype(np.float32)
    y = X @ w_true

    kv.init("w", nd.zeros((3, 1)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))
    kv.barrier()                       # both workers see the optimizer

    w = nd.zeros((3, 1))
    for step in range(150):
        kv.pull("w", out=w)
        i = (step * 32) % 224
        xb, yb = nd.array(X[i:i + 32]), nd.array(y[i:i + 32])
        grad = nd.dot(xb.T, nd.dot(xb, w) - yb) / 32
        kv.push("w", grad)             # server applies immediately

    kv.barrier()
    kv.pull("w", out=w)
    err = float(np.abs(w.asnumpy() - w_true).max())
    print("rank %d final err %.4f" % (rank, err))
    assert err < 0.05, "async training did not converge: %.4f" % err
    kv.barrier()
    if rank == 0:
        kv.send_command_to_servers(0, "")   # kStopServer
    kv.close()


if __name__ == "__main__":
    main()
