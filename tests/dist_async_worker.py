"""dist_async worker: async-SGD least squares through the parameter server.

Launched by tests/test_dist_async_kvstore.py via tools/launch.py -s 1.
Each worker trains on its own shard with server-side SGD (set_optimizer ->
update_on_kvstore): push(grad) applies immediately on the server, pull
fetches possibly-staler-than-sync weights — the async semantics under
test.  Rank 0 verifies convergence and stops the server.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import nd


def main():
    # create() first: in a DMLC_ROLE=server process this enters the server
    # loop and never returns (reference kvstore_server.py behavior)
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2

    rng = np.random.RandomState(100 + rank)
    w_true = np.array([[1.0], [-2.0], [3.0]], np.float32)
    X = rng.randn(256, 3).astype(np.float32)
    y = X @ w_true

    kv.init("w", nd.zeros((3, 1)))
    # round 5: a row-sparse embedding rides the PS too (reference
    # kvstore_dist.h row-sparse push/pull) — each worker pulls/pushes only
    # the rows its batch touches
    from mxnet_tpu.ndarray.sparse import row_sparse_array
    E_ROWS, E_DIM = 16, 4
    t_emb = (np.arange(E_ROWS * E_DIM, dtype=np.float32)
             .reshape(E_ROWS, E_DIM) / 10.0)
    kv.init("emb", nd.zeros((E_ROWS, E_DIM)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))
    kv.barrier()                       # both workers see the optimizer

    w = nd.zeros((3, 1))
    for step in range(150):
        kv.pull("w", out=w)
        i = (step * 32) % 224
        xb, yb = nd.array(X[i:i + 32]), nd.array(y[i:i + 32])
        grad = nd.dot(xb.T, nd.dot(xb, w) - yb) / 32
        kv.push("w", grad)             # server applies immediately

        # sparse task: pull the touched rows, step them toward t_emb
        ids = np.unique(rng.randint(0, E_ROWS, size=6)).astype("int64")
        rows_out = nd.zeros((E_ROWS, E_DIM))
        kv.row_sparse_pull("emb", out=rows_out, row_ids=nd.array(ids))
        cur = rows_out.asnumpy()[ids]
        g_rows = cur - t_emb[ids]      # d/dE of 0.5||E - T||^2 on rows
        kv.push("emb", row_sparse_array((nd.array(g_rows), ids),
                                        shape=(E_ROWS, E_DIM)))

    kv.barrier()
    kv.pull("w", out=w)
    err = float(np.abs(w.asnumpy() - w_true).max())
    emb_out = nd.zeros((E_ROWS, E_DIM))
    kv.pull("emb", out=emb_out)
    emb_err = float(np.abs(emb_out.asnumpy() - t_emb).max())
    print("rank %d final err %.4f emb_err %.4f" % (rank, err, emb_err))
    assert err < 0.05, "async training did not converge: %.4f" % err
    assert emb_err < 0.1, "sparse async did not converge: %.4f" % emb_err

    # round 5 phase 2: a 2-bit-compressed dense param over the PS wire
    # (reference kvstore_dist.h:336-359) — error feedback makes the
    # quantized stream unbiased, so async LS still converges, to the
    # coarser threshold-scale tolerance
    kv.init("wc", nd.zeros((3, 1)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.barrier()
    wc = nd.zeros((3, 1))
    for step in range(250):
        kv.pull("wc", out=wc)
        i = (step * 32) % 224
        xb, yb = nd.array(X[i:i + 32]), nd.array(y[i:i + 32])
        grad = nd.dot(xb.T, nd.dot(xb, wc) - yb) / 32
        kv.push("wc", grad)            # packed 2-bit on the wire
    kv.barrier()
    kv.pull("wc", out=wc)
    cerr = float(np.abs(wc.asnumpy() - w_true).max())
    print("rank %d compressed err %.4f" % (rank, cerr))
    assert cerr < 0.2, "compressed async did not converge: %.4f" % cerr
    kv.barrier()
    if rank == 0:
        kv.send_command_to_servers(0, "")   # kStopServer
    kv.close()


if __name__ == "__main__":
    main()
