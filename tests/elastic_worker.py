"""Worker for the elastic-training test (parity model: beyond-reference
§5.3 — checkpoint-resume under supervised gang restart).

Trains a tiny linear regression; on restart generation 0, rank 0 kills
itself partway through (simulated hardware failure).  The relaunched gang
must resume from the latest checkpoint, not step 0.  Each incarnation
appends "rank start_step gen" to progress.log for the test to assert on.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.parallel.elastic import run_elastic  # noqa: E402

CKPT = sys.argv[1]
TOTAL = int(sys.argv[2])
FAIL_AT = int(sys.argv[3])

RANK = int(os.environ["MXNET_ELASTIC_RANK"])
GEN = int(os.environ["MXNET_ELASTIC_RESTART"])


def train_fn(start, total, save, restored):
    rng = np.random.RandomState(0)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    true_w = np.asarray([1.0, -2.0, 3.0, 0.5], np.float32)
    y = X @ true_w

    w = restored["w"] if restored else jnp.zeros((4,), jnp.float32)
    with open(os.path.join(CKPT, "progress.log"), "a") as f:
        f.write("%d %d %d\n" % (RANK, start, GEN))
    for step in range(start, total):
        grad = X.T @ (np.asarray(w) @ X.T - y) / len(X)
        w = w - 0.1 * jnp.asarray(grad)
        if RANK == 0 and (step + 1) % 5 == 0:
            save(step + 1, {"w": w})
        if GEN == 0 and RANK == 0 and step + 1 == FAIL_AT:
            os._exit(1)  # simulated failure AFTER a checkpoint exists
    if RANK == 0:
        loss = float(((np.asarray(w) @ X.T - y) ** 2).mean())
        with open(os.path.join(CKPT, "final.txt"), "w") as f:
            f.write("%g\n" % loss)
    return {"w": w}


run_elastic(train_fn, CKPT, TOTAL)
