"""Gluon data API tests (ref: tests/python/unittest/test_gluon_data.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import data as gdata


def test_array_dataset():
    X = np.random.uniform(size=(10, 20))
    Y = np.random.uniform(size=(10,))
    dataset = gdata.ArrayDataset(X, Y)
    assert len(dataset) == 10
    x, y = dataset[3]
    np.testing.assert_allclose(x, X[3])


def test_simple_dataset_transform():
    ds = gdata.SimpleDataset(list(range(10))).transform(lambda x: x * 2)
    assert ds[4] == 8
    ds2 = gdata.ArrayDataset(np.arange(6).reshape(3, 2),
                             np.arange(3)).transform_first(lambda x: x + 1)
    x, y = ds2[0]
    np.testing.assert_allclose(x, [1, 2])
    assert y == 0


def test_samplers():
    assert list(gdata.SequentialSampler(5)) == [0, 1, 2, 3, 4]
    assert sorted(gdata.RandomSampler(5)) == [0, 1, 2, 3, 4]
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 1]
    assert len(bs) == 3
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "discard")
    assert [len(b) for b in bs] == [3, 3]
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "rollover")
    assert [len(b) for b in bs] == [3, 3]
    assert [len(b) for b in bs] == [3, 3]  # rolled-over element reused


def test_dataloader():
    X = np.random.uniform(size=(24, 5)).astype("float32")
    Y = np.arange(24).astype("float32")
    dataset = gdata.ArrayDataset(X, Y)
    for workers in (0, 2):
        loader = gdata.DataLoader(dataset, batch_size=8,
                                  num_workers=workers)
        batches = list(loader)
        assert len(batches) == 3
        xs = np.concatenate([b[0].asnumpy() for b in batches])
        np.testing.assert_allclose(xs, X, rtol=1e-6)


def test_dataloader_shuffle():
    X = np.arange(20).astype("float32")
    dataset = gdata.SimpleDataset(list(X))
    loader = gdata.DataLoader(dataset, batch_size=4, shuffle=True)
    seen = np.concatenate([b.asnumpy() for b in loader])
    assert sorted(seen.tolist()) == X.tolist()


def test_dataloader_error_propagation():
    class Bad(gdata.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            raise RuntimeError("boom")

    loader = gdata.DataLoader(Bad(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError):
        list(loader)


def test_record_file_dataset(tmp_path):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(5):
        w.write_idx(i, b"record%d" % i)
    w.close()
    ds = gdata.RecordFileDataset(rec)
    assert len(ds) == 5
    assert ds[3] == b"record3"


def test_vision_transforms():
    from mxnet_tpu.gluon.data.vision import transforms
    im = mx.nd.array(
        np.random.randint(0, 255, (32, 32, 3)).astype(np.uint8))
    t = transforms.ToTensor()
    out = t(im)
    assert out.shape == (3, 32, 32)
    assert out.asnumpy().max() <= 1.0

    norm = transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.1, 0.1, 0.1))
    out2 = norm(out)
    assert out2.shape == (3, 32, 32)

    resize = transforms.Resize(16)
    assert resize(im).shape == (16, 16, 3)

    crop = transforms.CenterCrop(20)
    assert crop(im).shape == (20, 20, 3)

    rrc = transforms.RandomResizedCrop(16, scale=(0.5, 1.0))
    assert rrc(im).shape == (16, 16, 3)

    flip = transforms.RandomFlipLeftRight()
    assert flip(im).shape == im.shape

    jitter = transforms.RandomColorJitter(0.1, 0.1, 0.1, 0.1)
    assert jitter(im.astype("float32")).shape == im.shape

    comp = transforms.Compose([transforms.ToTensor(),
                               transforms.Normalize(0.5, 0.2)])
    assert comp(im).shape == (3, 32, 32)


def test_image_module(tmp_path):
    import cv2
    from mxnet_tpu import image
    arr = np.random.randint(0, 255, (40, 50, 3)).astype(np.uint8)
    path = str(tmp_path / "x.jpg")
    cv2.imwrite(path, arr)
    im = image.imread(path)
    assert im.shape == (40, 50, 3)
    with open(path, "rb") as f:
        im2 = image.imdecode(f.read())
    assert im2.shape == (40, 50, 3)
    assert image.imresize(im, 20, 10).shape == (10, 20, 3)
    assert image.resize_short(im, 20).shape[1] >= 20
    out, _ = image.center_crop(im, (30, 30))
    assert out.shape == (30, 30, 3)
    augs = image.CreateAugmenter((3, 24, 24), rand_crop=True,
                                 rand_mirror=True, mean=True, std=True)
    x = im
    for aug in augs:
        x = aug(x)
    assert x.shape == (24, 24, 3)


def test_image_iter(tmp_path):
    import cv2
    from mxnet_tpu import image, recordio
    rec = str(tmp_path / "im.rec")
    idx = str(tmp_path / "im.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(8):
        arr = np.random.randint(0, 255, (32, 32, 3)).astype(np.uint8)
        packed = recordio.pack_img(
            recordio.IRHeader(0, float(i % 4), i, 0), arr)
        w.write_idx(i, packed)
    w.close()
    it = image.ImageIter(batch_size=4, data_shape=(3, 28, 28),
                         path_imgrec=rec, path_imgidx=idx, shuffle=True)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 28, 28)
    assert batch.label[0].shape == (4,)
    it.reset()
    n = sum(1 for _ in iter(it.next, None) if False) if False else 0
    count = 0
    it.reset()
    try:
        while True:
            it.next()
            count += 1
    except StopIteration:
        pass
    assert count == 2
