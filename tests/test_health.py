"""Continuous training health monitor (mxnet_tpu/health.py).

Covers the shared MFU helpers bench.py now delegates to, lowering-only
program cost accounting (XLA cost analysis + runtime donation audit),
step-phase verdict attribution, the EWMA+MAD anomaly trip with its
flight-recorder dump,
the KVStore wire health header (worker -> server straggler table, loud
validation), the serving /healthz verdict, the metric-name lint against
docs/observability.md, and the 2-worker dist straggler acceptance run.
"""
import json
import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import health, nd, telemetry, tracing
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore_server import (KVStoreServer, _check_health_ctx,
                                      recv_msg_full, send_msg)

S = mx.symbol


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    health.reset()
    yield
    health.disable()
    telemetry.disable()
    telemetry.reset()
    health.reset()


# ---------------------------------------------------------------------------
# shared MFU helpers (the code bench.py's two hand-rolled blocks became)
# ---------------------------------------------------------------------------
class TestHelpers:
    def test_peak_table(self, monkeypatch):
        monkeypatch.delenv("MXNET_HEALTH_PEAK_TFLOPS", raising=False)
        monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
        # platform=None keeps bench.py's historical quote-against-tpu-peak
        assert health.peak_tflops("bfloat16") == 197.0
        assert health.peak_tflops("float32") == 99.0
        assert health.peak_tflops("int8") == 99.0       # unknown -> f32
        assert health.peak_tflops("float32", platform="cpu") == 0.25

    def test_peak_env_overrides(self, monkeypatch):
        monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.0")
        assert health.peak_tflops("bfloat16") == 123.0
        # the health-specific knob wins over the bench one
        monkeypatch.setenv("MXNET_HEALTH_PEAK_TFLOPS", "7.5")
        assert health.peak_tflops("bfloat16") == 7.5

    def test_achieved_and_fraction(self):
        # 1000 items/s at 1 GFLOP/item = 1 TFLOP/s; 50% of a 2-TFLOP peak
        assert health.achieved_tflops(1000.0, 1e9) == pytest.approx(1.0)
        assert health.mfu_fraction(1000.0, 1e9, 2.0) == pytest.approx(0.5)
        assert health.mfu_fraction(1000.0, 1e9, 0.0) == 0.0

    def test_mfu_impossible(self):
        assert health.mfu_impossible(1.3, "tpu")
        assert not health.mfu_impossible(1.1, "tpu")
        # CPU peaks are a convention, not a measurement: never "impossible"
        assert not health.mfu_impossible(5.0, "cpu")


# ---------------------------------------------------------------------------
# program cost accounting
# ---------------------------------------------------------------------------
class TestProgramRegistration:
    def test_disabled_is_noop(self):
        import jax.numpy as jnp
        import jax
        fn = jax.jit(lambda a: a + 1)
        assert not health.enabled
        assert health.register_program("p", fn, (jnp.ones((4,)),)) is None
        assert health.programs() == {}

    def test_non_jitted_fn_skipped(self):
        health.enable()
        assert health.register_program("p", lambda a: a, (1,)) is None

    def test_cost_and_memory_metrics(self):
        import jax
        import jax.numpy as jnp
        health.enable()
        fn = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((64, 64), jnp.float32)
        pc = health.register_program("matmul", fn, (a, a))
        assert pc is not None
        # 64x64x64 MACs at 2 flops each
        assert pc.flops == pytest.approx(2 * 64 ** 3, rel=0.5)
        assert pc.arg_bytes == 2 * 64 * 64 * 4
        assert pc.out_bytes == 64 * 64 * 4
        # default mode is lowering-only: temp accounting needs the
        # MXNET_HEALTH_DEEP opt-in (it pays an extra compile)
        assert pc.temp_bytes is None
        assert telemetry.value("program_flops", program="matmul") == pc.flops
        assert telemetry.value("program_hbm_bytes", program="matmul",
                               kind="args") == pc.arg_bytes
        assert telemetry.value("program_hbm_bytes", program="matmul",
                               kind="output") == pc.out_bytes
        # registration never compiles; the normal call right after still
        # works and produces the same numbers
        np.testing.assert_allclose(np.asarray(fn(a, a)), np.full((64, 64),
                                   64.0), rtol=1e-5)

    def test_deep_mode_reports_temp_bytes(self, monkeypatch):
        import jax
        import jax.numpy as jnp
        monkeypatch.setenv("MXNET_HEALTH_DEEP", "1")
        health.enable()
        fn = jax.jit(lambda a, b: (a @ b) @ (a + b))
        a = jnp.ones((32, 32), jnp.float32)
        pc = health.register_program("deep", fn, (a, a))
        assert pc is not None
        assert pc.temp_bytes is not None and pc.temp_bytes >= 0
        assert telemetry.value("program_hbm_bytes", program="deep",
                               kind="temp") == pc.temp_bytes

    def test_program_flops_total_sums_tuple(self):
        import jax
        import jax.numpy as jnp
        health.enable()
        x = jnp.ones((8, 8), jnp.float32)
        health.register_program("pa", jax.jit(lambda a: a @ a), (x,))
        health.register_program("pb", jax.jit(lambda a: a @ a), (x,))
        fa = health.program_flops_total("pa")
        assert fa > 0
        assert health.program_flops_total(("pa", "pb")) == pytest.approx(
            2 * fa)
        assert health.program_flops_total(("pa", "missing")) == fa
        assert health.program_flops_total(None) == 0.0

    def test_donation_audit_honored(self):
        # runtime truth: a donated jit call invalidates the donated input,
        # the audit sees freed bytes and no leak
        import jax
        import jax.numpy as jnp
        health.enable()
        fn = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
        a = jnp.ones((16, 16), jnp.float32)
        b = jnp.ones((16, 16), jnp.float32)
        health.register_program("don_ok", fn, (a, b), donated=True)
        fn(a, b).block_until_ready()
        freed, leaked = health.audit_donation("don_ok", (a,))
        assert freed == 16 * 16 * 4 and leaked == 0
        pc = health.programs()["don_ok"]
        assert pc.donated_bytes == freed
        assert not pc.donation_leak
        assert telemetry.value("program_donated_bytes",
                               program="don_ok") == freed
        assert telemetry.value("program_donation_leaks_total",
                               program="don_ok") == 0.0

    def test_donation_audit_flags_leak(self):
        # a program that never consumed its "donated" inputs: every byte
        # survives execution, the counter trips
        import jax
        import jax.numpy as jnp
        health.enable()
        fn = jax.jit(lambda a, b: a + b)  # no donation actually wired
        a = jnp.ones((8, 8), jnp.float32)
        b = jnp.ones((8, 8), jnp.float32)
        health.register_program("don_leak", fn, (a, b), donated=True)
        fn(a, b).block_until_ready()
        freed, leaked = health.audit_donation("don_leak", (a,))
        assert freed == 0 and leaked == 8 * 8 * 4
        pc = health.programs()["don_leak"]
        assert pc.donation_leak
        assert telemetry.value("program_donation_leaks_total",
                               program="don_leak") == 1.0


# ---------------------------------------------------------------------------
# step monitor: verdict attribution, MFU, anomaly trip
# ---------------------------------------------------------------------------
class TestStepMonitor:
    def test_verdict_attribution(self):
        health.enable()
        m = health.monitor
        m.note_phase("input", 0.08)
        m.observe_step(0.1)
        assert telemetry.value("step_health_verdict",
                               cause="input_bound") == 1.0
        assert telemetry.value("step_health_verdict",
                               cause="compute_bound") == 0.0
        # phase accumulators reset per window: the next quiet window is
        # compute-bound again
        m.observe_step(0.1)
        assert telemetry.value("step_health_verdict",
                               cause="compute_bound") == 1.0
        m.note_phase("sync", 0.09)
        m.observe_step(0.1)
        assert telemetry.value("step_health_verdict",
                               cause="sync_bound") == 1.0

    def test_mfu_gauge_sane_on_cpu(self):
        import jax
        import jax.numpy as jnp
        health.enable()
        a = jnp.ones((64, 64), jnp.float32)
        health.register_program("step", jax.jit(lambda x: x @ x), (a,))
        health.monitor.observe_step(0.05, program="step")
        mfu = telemetry.value("step_mfu_pct")
        # 524288 flops over 50ms against the 0.25-TFLOP cpu convention:
        # tiny but strictly positive, and nowhere near impossible
        assert 0.0 < mfu < 120.0
        snap = health.monitor.snapshot()
        assert snap["mfu_pct"] == pytest.approx(mfu)
        assert snap["samples"] == 1

    def test_anomaly_trip_and_flight_dump(self, tmp_path, monkeypatch):
        dump = str(tmp_path / "flight.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", dump)
        health.enable()
        m = health.monitor
        for _ in range(20):
            m.observe_step(0.01)
        assert telemetry.value("health_anomalies_total",
                               cause="compute_bound") == 0.0
        m.observe_step(0.1)        # 10x the EWMA: way past band and 2x
        assert telemetry.value("health_anomalies_total",
                               cause="compute_bound") == 1.0
        assert os.path.exists(dump)
        events = json.load(open(dump))["events"]
        anom = [e for e in events if e.get("name") == "Health::Anomaly"]
        assert anom and anom[0]["args"]["cause"] == "compute_bound"
        assert anom[0]["args"]["step_seconds"] == pytest.approx(0.1)
        assert telemetry.value("flight_recorder_dumps_total",
                               reason="health_anomaly") == 1.0
        # ledger marks the anomalous window
        assert health.monitor.snapshot()["ledger"][-1]["anomaly"]

    def test_anomaly_debounced(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH",
                           str(tmp_path / "f.json"))
        health.enable()
        m = health.monitor
        for _ in range(20):
            m.observe_step(0.01)
        m.observe_step(0.1)
        m.observe_step(0.1)        # inside the 5s debounce: no second trip
        assert telemetry.value("health_anomalies_total",
                               cause="compute_bound") == 1.0

    def test_steady_steps_never_trip(self):
        health.enable()
        m = health.monitor
        for _ in range(50):
            m.observe_step(0.01 + np.random.uniform(-0.0005, 0.0005))
        fam = telemetry.registry().get("health_anomalies_total")
        assert all(v == 0.0 for _, v in fam.samples())

    def test_ewma_tracks_step_time(self):
        health.enable()
        for _ in range(30):
            health.monitor.observe_step(0.02)
        assert telemetry.value("step_seconds_ewma") == pytest.approx(
            0.02, rel=0.05)


# ---------------------------------------------------------------------------
# worker straggler table + wire header
# ---------------------------------------------------------------------------
class TestWorkerTable:
    def test_straggler_band(self):
        health.enable()
        w = health.workers
        w.update("0", 0.01)
        # single rank: no verdict possible
        assert "straggler" not in w.snapshot()["0"]
        w.update("1", 0.2)         # 0.2 > 1.75 * median(0.105)
        snap = w.snapshot()
        assert snap["0"]["straggler"] is False
        assert snap["1"]["straggler"] is True
        assert telemetry.value("worker_step_seconds", rank="1") == 0.2
        assert telemetry.value("worker_straggler_verdict", rank="1") == 1.0
        assert telemetry.value("worker_straggler_verdict", rank="0") == 0.0

    def test_close_ranks_not_flagged(self):
        health.enable()
        w = health.workers
        w.update("0", 0.010)
        w.update("1", 0.012)       # 20% apart: inside the 1.75x band
        snap = w.snapshot()
        assert not snap["0"]["straggler"] and not snap["1"]["straggler"]


class TestWireHealthHeader:
    def test_check_health_ctx_accepts(self):
        assert _check_health_ctx({"r": "3", "st": 0.25}) == \
            {"r": "3", "st": 0.25}

    @pytest.mark.parametrize("hc", [
        "notadict",
        {"r": "0"},                          # missing st
        {"r": "0", "st": 0.1, "x": 1},       # unknown key
        {"r": "", "st": 0.1},                # empty rank
        {"r": "abc", "st": 0.1},             # non-digit rank
        {"r": "1" * 17, "st": 0.1},          # rank too long
        {"r": "0", "st": -1.0},              # negative step
        {"r": "0", "st": 1e7},               # absurd step
        {"r": "0", "st": True},              # bool is not a number here
    ])
    def test_check_health_ctx_loud_rejects(self, hc):
        telemetry.enable()
        before = telemetry.value("kvstore_frame_errors_total")
        with pytest.raises(MXNetError):
            _check_health_ctx(hc)
        assert telemetry.value("kvstore_frame_errors_total") == before + 1

    def test_header_roundtrip_in_process(self, monkeypatch):
        """Worker with health on piggybacks its step time; the in-process
        server lands it in the (shared) WorkerTable."""
        health.enable()
        srv = KVStoreServer(num_workers=1).start()
        monkeypatch.setenv("MXNET_PS_URI", "127.0.0.1")
        monkeypatch.setenv("MXNET_PS_PORT", str(srv.port))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_WORKER_ID", "0")
        try:
            kv = mx.kv.create("dist_async")
            health.monitor.observe_step(0.042)   # the latest closed window
            kv.init("w", nd.ones((4,)))
            out = nd.zeros((4,))
            kv.pull("w", out=out)
            kv.close()
        finally:
            srv.shutdown()
        assert telemetry.value("worker_step_seconds",
                               rank="0") == pytest.approx(0.042)

    def test_no_header_before_first_step(self, monkeypatch):
        """Health on but no step observed yet: nothing to report, the
        frame stays headerless for `h` and the table stays empty."""
        health.enable()
        srv = KVStoreServer(num_workers=1).start()
        monkeypatch.setenv("MXNET_PS_URI", "127.0.0.1")
        monkeypatch.setenv("MXNET_PS_PORT", str(srv.port))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_WORKER_ID", "0")
        try:
            kv = mx.kv.create("dist_async")
            kv.init("w", nd.ones((4,)))
            kv.close()
        finally:
            srv.shutdown()
        assert health.workers.snapshot() == {}


# ---------------------------------------------------------------------------
# /statusz
# ---------------------------------------------------------------------------
class TestStatusz:
    def test_snapshot_shape(self):
        import jax
        import jax.numpy as jnp
        health.enable()
        a = jnp.ones((8, 8), jnp.float32)
        health.register_program("step", jax.jit(lambda x: x @ x), (a,))
        health.monitor.observe_step(0.03, program="step")
        health.workers.update("0", 0.03)
        doc = json.loads(json.dumps(health.statusz()))   # JSON-able
        assert doc["enabled"] is True
        assert doc["platform"] == "cpu"
        assert doc["peak_tflops"] > 0
        assert "step" in doc["programs"]
        assert doc["programs"]["step"]["flops"] > 0
        assert doc["step"]["cause"] == "compute_bound"
        assert doc["workers"]["0"]["step_seconds"] == pytest.approx(0.03)

    def test_statusz_http_endpoint(self):
        health.enable()
        import urllib.request
        port = telemetry.start_http_server(port=0)
        try:
            body = urllib.request.urlopen(
                "http://127.0.0.1:%d/statusz" % port, timeout=5).read()
            doc = json.loads(body)
            assert doc["enabled"] is True
            assert "programs" in doc and "step" in doc and "workers" in doc
        finally:
            telemetry.stop_http_server()


# ---------------------------------------------------------------------------
# live training-step integration: on_step wiring + program registration
# ---------------------------------------------------------------------------
class TestTrainingIntegration:
    def test_fused_trainer_registers_and_steps(self):
        from mxnet_tpu import gluon
        health.enable()
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"))
        net.add(gluon.nn.Dense(4))
        net.initialize()
        net.hybridize()
        x = nd.array(np.random.rand(4, 6).astype(np.float32))
        y = nd.array(np.random.randint(0, 4, (4,)))
        net(x).wait_to_read()
        ft = mx.FusedTrainer(net, "softmax_cross_entropy", "sgd",
                             {"learning_rate": 0.1})
        for _ in range(3):
            ft.step(x, y)
        progs = health.programs()
        assert "fused_trainer_step" in progs
        assert progs["fused_trainer_step"].flops > 0
        # whole-step program donates its state buffers; the runtime audit
        # after the first dispatch must see them actually invalidated
        # (a leak here is the broken-donation-chain bug)
        assert progs["fused_trainer_step"].donation_requested
        assert progs["fused_trainer_step"].donated_bytes is not None
        assert progs["fused_trainer_step"].donated_bytes > 0
        assert not progs["fused_trainer_step"].donation_leak
        # two closed windows from three dispatches
        assert health.monitor.snapshot()["samples"] == 2

    def test_module_step_records_program(self):
        from mxnet_tpu.module import Module
        health.enable()
        data = S.var("data")
        net = S.FullyConnected(data, num_hidden=4, name="fc")
        net = S.SoftmaxOutput(net, name="softmax")
        mod = Module(net, context=mx.cpu())
        mod.bind(data_shapes=[("data", (4, 6))],
                 label_shapes=[("softmax_label", (4,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        from mxnet_tpu.io import DataBatch
        batch = DataBatch(data=[nd.array(np.random.rand(4, 6))],
                          label=[nd.array(np.zeros(4))])
        for _ in range(3):
            mod.forward(batch)
            mod.backward()
            mod.update()
        assert health.monitor.snapshot()["samples"] >= 1
        # some step program (fused single-device or split) was registered
        assert health.programs()


# ---------------------------------------------------------------------------
# serving /healthz verdict
# ---------------------------------------------------------------------------
def _tiny_server(**kwargs):
    x = S.var("data")
    out = S.FullyConnected(x, num_hidden=4, no_bias=True, name="fc")
    params = {"fc_weight": nd.array(np.ones((4, 8), np.float32))}
    from mxnet_tpu.serving import ModelServer
    kwargs.setdefault("max_batch_size", 8)
    kwargs.setdefault("batch_timeout_ms", 5)
    return ModelServer(out.tojson(), params,
                       example_shapes={"data": (8,)}, **kwargs)


class TestServingHealth:
    def test_fresh_server_is_serving(self):
        srv = _tiny_server()
        doc = srv.health()
        assert doc["status"] == "serving"
        assert doc["causes"] == []
        assert doc["queue_saturation"] == 0.0
        assert doc["post_warmup_compiles"] is None   # not warmed yet

    def test_deadline_miss_rate_degrades(self):
        srv = _tiny_server()
        for _ in range(15):
            srv._recent_outcomes.append("deadline")
        assert srv.health()["status"] == "serving"   # < 20 samples
        for _ in range(10):
            srv._recent_outcomes.append("deadline")
        doc = srv.health()
        assert doc["status"] == "degraded"
        assert "deadline_misses" in doc["causes"]
        assert doc["deadline_miss_rate"] == 1.0

    def test_mixed_outcomes_below_threshold(self):
        srv = _tiny_server()
        for _ in range(30):
            srv._recent_outcomes.append("ok")
        for _ in range(10):
            srv._recent_outcomes.append("deadline")
        assert srv.health()["status"] == "serving"   # 25% < 50%

    def test_stopped_degrades(self):
        srv = _tiny_server()
        srv.start(warmup=False)
        srv.stop(drain=False)
        doc = srv.health()
        assert doc["status"] == "degraded"
        assert "stopped" in doc["causes"]

    def test_healthz_http_codes(self):
        import urllib.error
        import urllib.request
        from mxnet_tpu import serving
        srv = _tiny_server()
        port = serving.start_http_server(srv, port=0)
        try:
            r = urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % port, timeout=5)
            assert r.status == 200
            assert json.loads(r.read())["status"] == "serving"
            for _ in range(25):
                srv._recent_outcomes.append("deadline")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    "http://127.0.0.1:%d/healthz" % port, timeout=5)
            assert ei.value.code == 503
            doc = json.loads(ei.value.read())
            assert doc["status"] == "degraded"
            assert "deadline_misses" in doc["causes"]
        finally:
            serving.stop_http_server()


# metric-name lint moved to graftlint GL005 (tools/graftlint, exercised by
# tests/test_graftlint.py): the static scan covers EVERY telemetry
# instrument in the tree, not just the modules an import list remembers.


# ---------------------------------------------------------------------------
# probe smoke (slow: runs the whole bench in a subprocess)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_probe_health_smoke():
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "probe_health.py"),
         "--smoke"],
        cwd=REPO, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["ok"] is True and rec["probe"] == "health"


# ---------------------------------------------------------------------------
# 2-worker dist straggler acceptance run
# ---------------------------------------------------------------------------
class TestDistStraggler:
    def test_two_worker_straggler_verdict(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import launch

        snap_path = str(tmp_path / "health_snapshot.json")
        worker = os.path.join(REPO, "tests", "dist_health_worker.py")
        rc = launch.launch_local(
            2, [sys.executable, worker],
            env_extra={"JAX_PLATFORMS": "cpu", "MXNET_TEST_PLATFORM": "cpu",
                       "MXNET_HEALTH": "1",
                       "MXNET_HEALTH_SNAPSHOT_PATH": snap_path},
            num_servers=1)
        assert rc == 0
        # the server writes between serve_forever returning and launcher
        # cleanup; give the race a moment
        deadline = time.time() + 10
        while not os.path.exists(snap_path) and time.time() < deadline:
            time.sleep(0.1)
        assert os.path.exists(snap_path)
        table = json.load(open(snap_path))["workers"]
        assert set(table) == {"0", "1"}
        assert table["0"]["step_seconds"] == pytest.approx(0.01)
        assert table["1"]["step_seconds"] == pytest.approx(0.2)
        # rank 1 reports 20x rank 0: far past the 1.75x-median band
        assert table["1"]["straggler"] is True
        assert table["0"]["straggler"] is False
