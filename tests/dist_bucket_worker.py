"""dist_async bucketed-push worker: 1 server + 2 workers with a tiny
MXNET_KVSTORE_BUCKET_BYTES so multi-key traffic actually buckets.

Launched by tests/test_dist_async_kvstore.py via tools/launch.py -s 1.
Server runs SGD (its per-push updates commute: the final weight is
w0 - lr * sum of every worker's pushed grads, order-independent), so the
bucketed result has an analytic expectation AND must agree bit-exactly
with a per-key pull of the same server state.  Exits nonzero on failure.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import nd

SHAPES = [(64,), (128,), (32, 4), (9,), (10, 10)]
LR = 0.125          # power of two: SGD arithmetic is exact in f32
STEPS = 5


def main():
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2
    assert int(os.environ.get("MXNET_KVSTORE_BUCKET_BYTES", "0")) > 0, \
        "launcher must set a small bucket size for this test"

    keys = list(range(len(SHAPES)))
    inits = [np.full(s, 1.0, np.float32) for s in SHAPES]
    for k, w0 in zip(keys, inits):
        kv.init(k, nd.array(w0))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=LR))
    kv.barrier()                       # both workers see the optimizer

    # deterministic rank-dependent grads; each step's batched push rides
    # the bucketed wire (tiny bucket budget -> multi-key frames)
    grads = [np.full(s, 0.5 * (rank + 1), np.float32) for s in SHAPES]
    for _ in range(STEPS):
        kv.push(keys, [nd.array(g) for g in grads])
    kv.barrier()                       # every push applied server-side

    # bucketed pull vs per-key pull of the SAME server state: bit-exact
    outs = [nd.zeros(s) for s in SHAPES]
    kv.pull(keys, out=outs)
    os.environ["MXNET_KVSTORE_BUCKET_BYTES"] = "0"
    perkey = [nd.zeros(s) for s in SHAPES]
    kv.pull(keys, out=perkey)
    for k, o, p in zip(keys, outs, perkey):
        if not (o.asnumpy() == p.asnumpy()).all():
            raise AssertionError("bucketed pull != per-key pull: %r" % k)

    # analytic: w = 1 - lr * steps * (0.5 + 1.0) from the two workers
    expect = 1.0 - LR * STEPS * (0.5 + 1.0)
    for k, o, s in zip(keys, outs, SHAPES):
        want = np.full(s, expect, np.float32)
        if not (o.asnumpy() == want).all():
            raise AssertionError(
                "key %r: got %r want %r" % (k, o.asnumpy().ravel()[:3],
                                            expect))
    print("rank %d bucketed async ok (w=%.4f)" % (rank, expect))

    kv.barrier()
    if rank == 0:
        kv.send_command_to_servers(0, "")   # kStopServer
    kv.close()


if __name__ == "__main__":
    main()
