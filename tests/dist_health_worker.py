"""dist_async worker for the health straggler test: each rank seeds a
synthetic step time (rank 1 is 20x slower — well past the 1.75x straggler
band), then a few push/pull round-trips piggyback ``{rank, step_seconds}``
on the KVStore wire header for the server's :class:`WorkerTable`.

Launched by tests/test_health.py via tools/launch.py with MXNET_HEALTH=1
and MXNET_HEALTH_SNAPSHOT_PATH set; the server process (same env) writes
the aggregated worker table when the stop command shuts it down.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import health, nd


def main():
    assert health.enabled, "worker must run with MXNET_HEALTH=1"
    # create() first: in a DMLC_ROLE=server process this enters the server
    # loop and never returns
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2

    step_s = 0.01 if rank == 0 else 0.2
    kv.init("w", nd.zeros((4, 2)))
    kv.barrier()
    for step in range(5):
        # synthetic closed window: what on_step() would record at the
        # trainer dispatch site, without sleeping 0.2s per step
        health.monitor.observe_step(step_s)
        kv.push("w", nd.array(np.full((4, 2), rank + step, np.float32)))
        out = nd.zeros((4, 2))
        kv.pull("w", out=out)
    kv.barrier()
    if rank == 0:
        kv.send_command_to_servers(0, "")   # kStopServer
    kv.close()
    print("rank %d reported step_seconds=%s" % (rank, step_s))
    if rank == 0:
        # keep the launcher's worker-liveness window open so the server
        # finishes its snapshot dump before cleanup kills it
        time.sleep(0.5)


if __name__ == "__main__":
    main()
