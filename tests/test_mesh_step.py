"""Mesh-native GSPMD fused training step (MXNET_TPU_MESH_STEP).

Parity contract: the mesh-fused global program — batch sharded ``P('dp')``,
params/opt-state placed per NamedSharding, all donated — must produce the
SAME numbers as the single-device fused step.  On the CPU harness (8
virtual devices via conftest's ``--xla_force_host_platform_device_count``)
we assert BIT-exactness, params AND optimizer state: the test data/weights
are integer-valued and every hyperparameter is dyadic, so each f32
intermediate is exactly representable and any reduction reordering the
mesh could introduce would show up as a 1-ulp diff.  ``nag``'s update
algebra is not reassociation-stable, so it (and adam/rmsprop, which divide)
get allclose instead.

Plus the mechanics: donation genuinely frees the previous mesh buffers,
the mesh signature participates in the step-program jit-cache key, DP×TP
``ShardingRules`` actually shard the parameter handles, the telemetry
counter says ``mesh_fused``, and the flag-off / mesh→eager interop paths
fall back seamlessly.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fused_step as fused
from mxnet_tpu import telemetry
from mxnet_tpu import optimizer as opt
from mxnet_tpu.optimizer import fused_state_leaves

NDEV = 8
CTX8 = [mx.cpu(i) for i in range(NDEV)]


class _Batch:
    def __init__(self, x, y):
        self.data = [mx.nd.array(x)]
        self.label = [mx.nd.array(y)]


def _build_module(ctxs, batch=8, feat=4, hid=4, out=2):
    """Tiny FC regression net in the exact-f32 regime: weights drawn from
    {-1, 0, 1} so every product/sum stays integer-valued for a few steps."""
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=hid, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=out, name="fc2")
    label = mx.sym.Variable("softmax_label")
    net = mx.sym.LinearRegressionOutput(fc2, label, name="lin")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",), context=ctxs)
    mod.bind(data_shapes=[("data", (batch, feat))],
             label_shapes=[("softmax_label", (batch, out))])
    mod.init_params()
    rs = np.random.RandomState(42)
    args = {n: mx.nd.array(rs.randint(-1, 2, v.shape).astype(np.float32))
            for n, v in mod.get_params()[0].items()}
    mod.set_params(args, {})
    return mod


def _collect(mod):
    """(params, states-by-name) snapshots; the mesh path keeps sibling
    slots aliased to the base slot, so mapping through idx2name collapses
    both layouts to one comparable dict."""
    args = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    states = {}
    idx2name = mod._optimizer.idx2name
    for slot, st in sorted(mod._updater.states.items()):
        name = idx2name.get(slot)
        leaves = fused_state_leaves(st)
        if name and name not in states and leaves:
            states[name] = [np.asarray(l.asnumpy()) for l in leaves]
    return args, states


def _run(monkeypatch, ctxs, opt_name, okw, steps, mesh_flag="1",
         batch=8, feat=4, out=2, mesh_axes=None, rules_fn=None):
    monkeypatch.setenv(fused.ENV_FLAG, "1")
    monkeypatch.setenv(fused.MESH_ENV_FLAG, mesh_flag)
    mod = _build_module(ctxs, batch=batch, feat=feat, out=out)
    if mesh_axes is not None:
        rules = rules_fn(mod) if rules_fn is not None else None
        mod.set_mesh(mesh_axes, rules)
    okw = dict(okw)
    okw.setdefault("rescale_grad", 0.125)
    mod.init_optimizer(kvstore="local", optimizer=opt_name,
                       optimizer_params=okw)
    rs = np.random.RandomState(7)
    for _ in range(steps):
        x = rs.randint(0, 2, (batch, feat)).astype(np.float32)
        y = rs.randint(-1, 2, (batch, out)).astype(np.float32)
        mod.forward_backward(_Batch(x, y))
        mod.update()
    return mod


def _assert_bitexact(mod8, mod1):
    a8, s8 = _collect(mod8)
    a1, s1 = _collect(mod1)
    assert sorted(a8) == sorted(a1)
    for k in a1:
        assert np.array_equal(a8[k], a1[k]), \
            "param %s: maxdiff %g" % (k, np.abs(a8[k] - a1[k]).max())
    assert sorted(s8) == sorted(s1)
    for k in s1:
        assert len(s8[k]) == len(s1[k]), "state arity %s" % k
        for j, (x, y) in enumerate(zip(s8[k], s1[k])):
            assert np.array_equal(x, y), \
                "state %s[%d]: maxdiff %g" % (k, j, np.abs(x - y).max())


def _assert_close(mod8, mod1, rtol=2e-5, atol=1e-6):
    a8, s8 = _collect(mod8)
    a1, s1 = _collect(mod1)
    for k in a1:
        np.testing.assert_allclose(a8[k], a1[k], rtol=rtol, atol=atol,
                                   err_msg=k)
    for k in s1:
        for j, (x, y) in enumerate(zip(s8[k], s1[k])):
            np.testing.assert_allclose(x, y, rtol=rtol, atol=atol,
                                       err_msg="state %s[%d]" % (k, j))


# configs whose trajectories stay exactly representable in f32 for the
# step counts used (dyadic lr/momentum/wd, integer data/weights)
EXACT_CONFIGS = [
    ("sgd", {"learning_rate": 0.5, "momentum": 0.5}, 3),
    ("sgd", {"learning_rate": 0.25}, 2),
]
EXACT_CONFIGS_SLOW = [
    ("sgd", {"learning_rate": 0.25, "momentum": 0.5}, 2),
    ("sgd", {"learning_rate": 0.25, "momentum": 0.5, "wd": 0.25}, 2),
]
CLOSE_CONFIGS_SLOW = [
    ("nag", {"learning_rate": 0.25, "momentum": 0.5}, 3),
    ("adam", {"learning_rate": 0.01}, 3),
    ("rmsprop", {"learning_rate": 0.01}, 3),
]


class TestMeshParity:
    @pytest.mark.parametrize("name,kwargs,steps", EXACT_CONFIGS,
                             ids=["sgd_mom", "sgd"])
    def test_bitexact_vs_single_device(self, monkeypatch, name, kwargs,
                                       steps):
        telemetry.enable()
        try:
            mesh0 = telemetry.value("step_dispatch_total", path="mesh_fused")
            mod8 = _run(monkeypatch, CTX8, name, kwargs, steps)
            assert telemetry.value("step_dispatch_total",
                                   path="mesh_fused") == mesh0 + steps
        finally:
            telemetry.disable()
        mod1 = _run(monkeypatch, [mx.cpu(0)], name, kwargs, steps)
        _assert_bitexact(mod8, mod1)

    @pytest.mark.slow
    @pytest.mark.parametrize("name,kwargs,steps", EXACT_CONFIGS_SLOW,
                             ids=["sgd_mom_lr25", "sgd_mom_wd"])
    def test_bitexact_sweep(self, monkeypatch, name, kwargs, steps):
        mod8 = _run(monkeypatch, CTX8, name, kwargs, steps)
        mod1 = _run(monkeypatch, [mx.cpu(0)], name, kwargs, steps)
        _assert_bitexact(mod8, mod1)

    @pytest.mark.slow
    @pytest.mark.parametrize("name,kwargs,steps", CLOSE_CONFIGS_SLOW,
                             ids=["nag", "adam", "rmsprop"])
    def test_allclose_sweep(self, monkeypatch, name, kwargs, steps):
        mod8 = _run(monkeypatch, CTX8, name, kwargs, steps)
        mod1 = _run(monkeypatch, [mx.cpu(0)], name, kwargs, steps)
        _assert_close(mod8, mod1)


class TestMeshMechanics:
    def test_donation_frees_old_buffers(self, monkeypatch):
        mod = _run(monkeypatch, CTX8, "sgd",
                   {"learning_rate": 0.25, "momentum": 0.5}, steps=1)
        ex = mod._exec_group.execs[0]
        old_w = ex.arg_dict["fc1_weight"]._data
        base = mod._optimizer.slot_index(
            mod._param_names.index("fc1_weight"), NDEV, 0)
        old_s = fused_state_leaves(mod._updater.states[base])[0]._data
        rs = np.random.RandomState(9)
        mod.forward_backward(_Batch(
            rs.randint(0, 2, (8, 4)).astype(np.float32),
            rs.randint(-1, 2, (8, 2)).astype(np.float32)))
        mod.update()
        # the second mesh step donated the first step's outputs: both the
        # param and the opt-state buffer are genuinely dead, not copied
        assert old_w.is_deleted()
        assert old_s.is_deleted()
        assert np.isfinite(ex.arg_dict["fc1_weight"].asnumpy()).all()

    def test_flag_off_falls_back_to_fused(self, monkeypatch):
        telemetry.enable()
        try:
            mesh0 = telemetry.value("step_dispatch_total", path="mesh_fused")
            fused0 = telemetry.value("step_dispatch_total", path="fused")
            _run(monkeypatch, CTX8, "sgd", {"learning_rate": 0.25},
                 steps=2, mesh_flag="0")
            assert telemetry.value("step_dispatch_total",
                                   path="mesh_fused") == mesh0
            assert telemetry.value("step_dispatch_total",
                                   path="fused") == fused0 + 2
        finally:
            telemetry.disable()

    def test_mesh_then_eager_interop_bitexact(self, monkeypatch):
        """One mesh step, then (flag flipped off) one per-device step: the
        de-mesh restores per-device layout exactly — the combined
        trajectory matches two single-device fused steps bit-for-bit."""
        mod8 = _run(monkeypatch, CTX8, "sgd",
                    {"learning_rate": 0.25, "momentum": 0.5}, steps=1)
        monkeypatch.setenv(fused.MESH_ENV_FLAG, "0")
        rs = np.random.RandomState(7)
        rs.randint(0, 2, (8, 4)), rs.randint(-1, 2, (8, 2))  # step-1 draws
        x = rs.randint(0, 2, (8, 4)).astype(np.float32)
        y = rs.randint(-1, 2, (8, 2)).astype(np.float32)
        mod8.forward_backward(_Batch(x, y))
        mod8.update()
        mod1 = _run(monkeypatch, [mx.cpu(0)], "sgd",
                    {"learning_rate": 0.25, "momentum": 0.5}, steps=2)
        _assert_bitexact(mod8, mod1)

    def test_outputs_served_from_mesh_step(self, monkeypatch):
        mod = _run(monkeypatch, CTX8, "sgd", {"learning_rate": 0.25},
                   steps=1)
        outs = mod.get_outputs()
        assert len(outs) == 1 and outs[0].shape == (8, 2)
        assert np.isfinite(outs[0].asnumpy()).all()

    def test_mesh_change_is_new_cache_key(self, monkeypatch):
        from mxnet_tpu.parallel.mesh import make_mesh, megatron_rules
        mod = _run(monkeypatch, CTX8, "sgd", {"learning_rate": 0.25},
                   steps=1)
        ex = mod._exec_group.execs[0]
        keys1 = {k for k in ex._jitted if k[0] == "step"}
        assert len(keys1) == 1
        devices = [c.jax_device for c in CTX8]
        mesh = make_mesh({"dp": 4, "tp": 2}, devices=devices)
        mod.set_mesh({"dp": 4, "tp": 2}, megatron_rules(mesh))
        rs = np.random.RandomState(9)
        mod.forward_backward(_Batch(
            rs.randint(0, 2, (8, 4)).astype(np.float32),
            rs.randint(-1, 2, (8, 2)).astype(np.float32)))
        mod.update()
        # regression: a different mesh/sharding signature must be a NEW
        # compiled step program, never a silent reuse of the dp=8 closure
        keys2 = {k for k in ex._jitted if k[0] == "step"}
        assert len(keys2) == 2 and keys1 < keys2


class TestDpTp:
    def test_megatron_rules_shard_params(self, monkeypatch):
        from mxnet_tpu.parallel.mesh import make_mesh, megatron_rules
        from jax.sharding import PartitionSpec as P

        def rules(mod):
            devices = [c.jax_device for c in CTX8]
            return megatron_rules(make_mesh({"dp": 4, "tp": 2},
                                            devices=devices))

        telemetry.enable()
        try:
            mesh0 = telemetry.value("step_dispatch_total", path="mesh_fused")
            mod = _run(monkeypatch, CTX8, "sgd",
                       {"learning_rate": 0.25, "momentum": 0.5}, steps=2,
                       mesh_axes={"dp": 4, "tp": 2}, rules_fn=rules)
            assert telemetry.value("step_dispatch_total",
                                   path="mesh_fused") == mesh0 + 2
        finally:
            telemetry.disable()
        ex = mod._exec_group.execs[0]
        # fc weights really live sharded on tp; biases replicated
        assert ex.arg_dict["fc1_weight"]._data.sharding.spec == P("tp", None)
        assert ex.arg_dict["fc1_bias"]._data.sharding.spec == P()
        # and the DP×TP trajectory still matches the single-device oracle
        mod1 = _run(monkeypatch, [mx.cpu(0)], "sgd",
                    {"learning_rate": 0.25, "momentum": 0.5}, steps=2)
        _assert_bitexact(mod, mod1)


class TestTrainerMesh:
    def _run(self, monkeypatch, ctxs, steps=3):
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        monkeypatch.setenv(fused.MESH_ENV_FLAG, "1")
        mx.random.seed(3)
        np.random.seed(3)
        net = nn.Sequential()
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(4))
        net.initialize(mx.init.Xavier(), ctx=ctxs)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore="device")
        rs = np.random.RandomState(11)
        n = len(ctxs)
        for _ in range(steps):
            x = rs.uniform(-1, 1, (16, 10)).astype(np.float32)
            b = 16 // n
            xs = [mx.nd.array(x[k * b:(k + 1) * b], ctx=ctxs[k])
                  for k in range(n)]
            losses = []
            with autograd.record():
                for xk in xs:
                    out = net(xk)
                    losses.append((out * out).sum())
            for l in losses:
                l.backward()
            tr.step(16)
        return [p.list_data()[0].asnumpy()
                for _, p in sorted(net.collect_params().items())]

    def test_parity_and_dispatch(self, monkeypatch):
        telemetry.enable()
        try:
            mesh0 = telemetry.value("step_dispatch_total", path="mesh_fused")
            p8 = self._run(monkeypatch, CTX8)
            assert telemetry.value("step_dispatch_total",
                                   path="mesh_fused") == mesh0 + 3
        finally:
            telemetry.disable()
        p1 = self._run(monkeypatch, [mx.cpu(0)])
        assert len(p8) == len(p1)
        for i, (a, b) in enumerate(zip(p8, p1)):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6,
                                       err_msg="param %d" % i)


class TestIoSharding:
    def test_ndarrayiter_num_parts(self):
        x = np.arange(24, dtype=np.float32).reshape(12, 2)
        y = np.arange(12, dtype=np.float32)
        parts = []
        for r in range(3):
            it = mx.io.NDArrayIter(x, y, batch_size=2, num_parts=3,
                                   part_index=r)
            assert it.num_data == 4
            rows = np.concatenate([b.data[0].asnumpy()
                                   for b in it], axis=0)
            parts.append(rows)
        np.testing.assert_array_equal(np.concatenate(parts, axis=0), x)

    def test_ndarrayiter_part_index_validated(self):
        x = np.zeros((8, 2), dtype=np.float32)
        with pytest.raises(mx.base.MXNetError):
            mx.io.NDArrayIter(x, batch_size=2, num_parts=2, part_index=2)

    def test_prefetching_iter_places_on_sharding(self):
        from mxnet_tpu.parallel.mesh import make_mesh, data_parallel_sharding
        mesh = make_mesh({"dp": NDEV},
                         devices=[c.jax_device for c in CTX8])
        bsh = data_parallel_sharding(mesh)
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        base = mx.io.NDArrayIter(x, np.zeros(16, np.float32), batch_size=8)
        it = mx.io.PrefetchingIter(base, sharding=bsh)
        batch = next(it)
        # the producer thread landed the batch pre-sharded on the mesh
        assert batch.data[0]._data.sharding == bsh
        np.testing.assert_array_equal(batch.data[0].asnumpy(), x[:8])
        for _ in it:   # drain so the daemon producer exits cleanly
            pass

    def test_host_shard_hint_single_host(self):
        from mxnet_tpu.parallel.mesh import host_shard_hint
        assert host_shard_hint() == (0, 1)

    def test_dp_trainer_caches_batch_sharding(self):
        from mxnet_tpu.parallel.mesh import make_mesh
        from mxnet_tpu.parallel.data_parallel import DataParallelTrainer
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = make_mesh({"dp": NDEV},
                         devices=[c.jax_device for c in CTX8])
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc1")
        net = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                                   name="softmax")
        tr = DataParallelTrainer(net, mesh, lr=0.1,
                                 data_names=("data",),
                                 label_names=("softmax_label",))
        assert tr._batch_sharding == NamedSharding(mesh, P("dp"))
        tr.init_params(data=(16, 6))
        rs = np.random.RandomState(0)
        x = mx.nd.array(rs.uniform(size=(16, 6)).astype(np.float32))
        y = mx.nd.array(rs.randint(0, 4, (16,)).astype(np.float32))
        loss = tr.step({"data": x, "softmax_label": y})
        assert np.isfinite(float(loss))
