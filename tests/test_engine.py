"""Engine tests (parity model: tests/cpp/engine/threaded_engine_test.cc +
tests/python/unittest/test_engine.py + test_exc_handling.py)."""
import threading
import time

import pytest

from mxnet_tpu import engine as eng


@pytest.fixture(params=["naive", "threaded"])
def engine(request):
    if request.param == "naive":
        yield eng.NaiveEngine()
        return
    e = eng.ThreadedEngine(num_workers=4)
    yield e
    e.stop()


def test_push_and_wait(engine):
    if isinstance(engine, eng.NaiveEngine):
        results = []
        v = engine.new_variable("v")
        engine.push(lambda: results.append(1), mutable_vars=(v,))
        engine.wait_for_var(v)
        assert results == [1]
        return
    results = []
    v = engine.new_variable("v")
    for i in range(10):
        engine.push(lambda i=i: results.append(i), mutable_vars=(v,))
    engine.wait_for_all()
    # writes to one var must serialize in push order
    assert results == list(range(10))


def test_read_write_ordering():
    e = eng.ThreadedEngine(num_workers=8)
    v = e.new_variable("shared")
    log = []
    lock = threading.Lock()

    def w(tag):
        def fn():
            time.sleep(0.002)
            with lock:
                log.append(tag)
        return fn

    e.push(w("w0"), mutable_vars=(v,))
    for i in range(4):
        e.push(w("r%d" % i), const_vars=(v,))
    e.push(w("w1"), mutable_vars=(v,))
    e.push(w("r4"), const_vars=(v,))
    e.wait_for_all()
    assert log[0] == "w0"
    assert set(log[1:5]) == {"r0", "r1", "r2", "r3"}
    assert log[5] == "w1"
    assert log[6] == "r4"
    e.stop()


def test_parallel_reads_concurrent():
    e = eng.ThreadedEngine(num_workers=4)
    v = e.new_variable()
    barrier = threading.Barrier(3, timeout=5)

    def read():
        barrier.wait()  # passes only if >=3 reads run concurrently

    for _ in range(3):
        e.push(read, const_vars=(v,))
    e.wait_for_all()
    e.stop()


def test_independent_vars_parallel():
    e = eng.ThreadedEngine(num_workers=4)
    barrier = threading.Barrier(2, timeout=5)
    v1, v2 = e.new_variable(), e.new_variable()
    e.push(lambda: barrier.wait(), mutable_vars=(v1,))
    e.push(lambda: barrier.wait(), mutable_vars=(v2,))
    e.wait_for_all()
    e.stop()


def test_exception_propagation(engine):
    v = engine.new_variable("v")

    def boom():
        raise ValueError("async boom")

    if isinstance(engine, eng.NaiveEngine):
        with pytest.raises(ValueError):
            engine.push(boom, mutable_vars=(v,))
        return
    engine.push(boom, mutable_vars=(v,))
    with pytest.raises(ValueError, match="async boom"):
        engine.wait_for_var(v)
    # exception cleared after rethrow (reference semantics)
    engine.push(lambda: None, mutable_vars=(v,))
    engine.wait_for_var(v)


def test_dependency_chain():
    e = eng.ThreadedEngine(num_workers=4)
    a, b = e.new_variable("a"), e.new_variable("b")
    state = {}
    e.push(lambda: state.__setitem__("x", 1), mutable_vars=(a,))
    e.push(lambda: state.__setitem__("y", state["x"] + 1),
           const_vars=(a,), mutable_vars=(b,))
    e.push(lambda: state.__setitem__("z", state["y"] + 1), const_vars=(b,))
    e.wait_for_all()
    assert state == {"x": 1, "y": 2, "z": 3}
    e.stop()


def test_env_selects_engine(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    eng.set_engine(None)
    assert isinstance(eng.get(), eng.NaiveEngine)
    eng.set_engine(None)
    monkeypatch.delenv("MXNET_ENGINE_TYPE")
    # default = ThreadedEnginePerDevice: the native C++ engine when the
    # library is built, the Python pool otherwise
    assert isinstance(eng.get(), (eng.NativeThreadedEngine,
                                  eng.ThreadedEngine))
    eng.set_engine(None)
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "ThreadedEngine")
    assert type(eng.get()) is eng.ThreadedEngine
    eng.set_engine(None)


def test_multithreaded_imperative_ops_race():
    """Concurrent imperative op streams from many Python threads must not
    corrupt results or drop exceptions (parity:
    tests/nightly/test_tlocal_racecondition.py + test_thread_local.py —
    the engine's thread-safety contract)."""
    import threading

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    errs = []
    results = [None] * 8

    def worker(tid):
        try:
            rng = np.random.RandomState(tid)
            a = nd.array(rng.rand(32, 32).astype(np.float32))
            b = nd.array(rng.rand(32, 32).astype(np.float32))
            acc = nd.zeros((32, 32))
            for i in range(30):
                c = nd.dot(a, b)
                acc = acc + c * (1.0 / (i + 1))
                if i % 7 == 0:
                    acc.wait_to_read()
            # autograd inside a thread (thread-local recording state)
            w = nd.array(rng.rand(16, 8).astype(np.float32))
            w.attach_grad()
            with mx.autograd.record():
                loss = (nd.dot(nd.ones((4, 16)), w) ** 2).sum()
            loss.backward()
            assert w.grad is not None
            results[tid] = float(acc.asnumpy().sum())
        except Exception as e:  # pragma: no cover
            errs.append((tid, repr(e)))

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    hung = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not hung, "worker threads deadlocked: %s" % hung
    assert not errs, errs
    # each thread's result must match its own serial recomputation
    for tid in range(8):
        rng = np.random.RandomState(tid)
        a = rng.rand(32, 32).astype(np.float32)
        b = rng.rand(32, 32).astype(np.float32)
        acc = np.zeros((32, 32), np.float32)
        for i in range(30):
            acc = acc + (a @ b) * (1.0 / (i + 1))
        np.testing.assert_allclose(results[tid], acc.sum(), rtol=1e-3)
