"""Test config: run on an 8-device virtual CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): one op/suite
parameterized by backend; multi-device tests run on virtual host devices
(``--xla_force_host_platform_device_count=8``), the analog of the reference's
process-level fake cluster (tests/nightly/test_all.sh).
"""
import os

_platform = os.environ.get("MXNET_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# jax may already be imported (sitecustomize registers accelerator plugins at
# interpreter start and captures JAX_PLATFORMS from the outer env), so update
# the live config too — this must happen before any backend initializes.
import jax

jax.config.update("jax_platforms", _platform)

import numpy as np
import pytest


def pytest_configure(config):
    # tier-1 runs with `-m 'not slow'`; register the marker so the probe
    # smoke tests don't warn as unknown
    config.addinivalue_line(
        "markers", "slow: long-running (excluded from tier-1 via -m 'not slow')")


@pytest.fixture(autouse=True)
def _seed_everything():
    """Analog of the reference @with_seed() fixture (tests/python/unittest/
    common.py:97-130): deterministic per-test seeds."""
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
