"""Tests for predict API, ONNX import, contrib.text, im2rec.

Parity model: reference c_predict_api usage, tests/python-pytest/onnx,
tests/python/unittest/test_contrib_text.py, tools/im2rec flows.
"""
import os
import subprocess
import sys
from collections import Counter

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.symbol as sym

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestPredictor:
    def _toy_model(self, tmp_path):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="fc")
        out = sym.softmax(fc, name="softmax")
        rng = np.random.RandomState(0)
        params = {"arg:fc_weight": nd.array(rng.randn(4, 6)
                                            .astype(np.float32)),
                  "arg:fc_bias": nd.array(rng.randn(4).astype(np.float32))}
        json_path = str(tmp_path / "m-symbol.json")
        with open(json_path, "w") as f:
            f.write(out.tojson())
        params_path = str(tmp_path / "m-0001.params")
        nd.save(params_path, params)
        return out, params, json_path, params_path

    def test_create_forward_get_output(self, tmp_path):
        out, params, json_path, params_path = self._toy_model(tmp_path)
        pred = mx.predictor.Predictor(json_path, params_path,
                                      input_shapes={"data": (2, 6)})
        x = np.random.RandomState(1).rand(2, 6).astype(np.float32)
        pred.set_input("data", x)
        pred.forward()
        got = pred.get_output(0).asnumpy()
        # reference executor answer
        ex = out.bind(mx.cpu(), {"data": nd.array(x),
                                 "fc_weight": params["arg:fc_weight"],
                                 "fc_bias": params["arg:fc_bias"]})
        np.testing.assert_allclose(got, ex.forward()[0].asnumpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_reshape(self, tmp_path):
        _, _, json_path, params_path = self._toy_model(tmp_path)
        pred = mx.predictor.Predictor(json_path, params_path,
                                      input_shapes={"data": (2, 6)})
        pred2 = pred.reshape({"data": (5, 6)})
        pred2.forward(data=np.zeros((5, 6), np.float32))
        assert pred2.get_output(0).shape == (5, 4)

    def test_errors(self, tmp_path):
        _, _, json_path, params_path = self._toy_model(tmp_path)
        with pytest.raises(mx.MXNetError):
            mx.predictor.Predictor(json_path, params_path, input_shapes={})
        pred = mx.predictor.Predictor(json_path, params_path,
                                      input_shapes={"data": (1, 6)})
        with pytest.raises(mx.MXNetError):
            pred.get_output(0)
        with pytest.raises(mx.MXNetError):
            pred.set_input("bogus", np.zeros((1, 6)))


# ---------------------------------------------------------------------------
# ONNX import: duck-typed GraphProto mocks (no onnx package needed)
# ---------------------------------------------------------------------------
class _Attr:
    def __init__(self, name, **kw):
        self.name = name
        self.type = kw.pop("type", 0)
        self.f = kw.pop("f", 0.0)
        self.i = kw.pop("i", 0)
        self.s = kw.pop("s", b"")
        self.ints = kw.pop("ints", ())
        self.floats = kw.pop("floats", ())


class _Node:
    def __init__(self, op_type, inputs, outputs, name="", attrs=()):
        self.op_type = op_type
        self.input = list(inputs)
        self.output = list(outputs)
        self.name = name
        self.attribute = list(attrs)


class _Tensor:
    def __init__(self, name, arr):
        self.name = name
        arr = np.asarray(arr, np.float32)
        self.dims = list(arr.shape)
        self.data_type = 1
        self.raw_data = arr.tobytes()
        self.float_data = ()
        self.int64_data = ()
        self.int32_data = ()
        self.double_data = ()


class _VI:
    def __init__(self, name):
        self.name = name


class _Graph:
    def __init__(self, nodes, inputs, outputs, initializers):
        self.node = nodes
        self.input = inputs
        self.output = outputs
        self.initializer = initializers


class TestONNXImport:
    def test_mlp_graph(self):
        rng = np.random.RandomState(0)
        w = rng.randn(6, 4).astype(np.float32)   # Gemm B, transB=0: (in,out)
        b = rng.randn(4).astype(np.float32)
        graph = _Graph(
            nodes=[
                _Node("Gemm", ["x", "w", "b"], ["h"], name="fc1"),
                _Node("Relu", ["h"], ["a"]),
                _Node("Softmax", ["a"], ["y"],
                      attrs=[_Attr("axis", type=2, i=1)]),
            ],
            inputs=[_VI("x"), _VI("w"), _VI("b")],
            outputs=[_VI("y")],
            initializers=[_Tensor("w", w), _Tensor("b", b)])
        s, args, auxs = mx.contrib.onnx.import_graph(graph)
        x = rng.rand(2, 6).astype(np.float32)
        ex = s.bind(mx.cpu(), {"x": nd.array(x), **args})
        got = ex.forward()[0].asnumpy()
        ref = x @ w + b
        ref = np.maximum(ref, 0)
        ref = np.exp(ref) / np.exp(ref).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_conv_pool_graph(self):
        rng = np.random.RandomState(1)
        w = rng.randn(2, 3, 3, 3).astype(np.float32)
        graph = _Graph(
            nodes=[
                _Node("Conv", ["x", "w"], ["c"], name="conv0", attrs=[
                    _Attr("kernel_shape", ints=(3, 3)),
                    _Attr("pads", ints=(1, 1, 1, 1)),
                    _Attr("strides", ints=(1, 1))]),
                _Node("Relu", ["c"], ["r"]),
                _Node("MaxPool", ["r"], ["p"], attrs=[
                    _Attr("kernel_shape", ints=(2, 2)),
                    _Attr("strides", ints=(2, 2))]),
                _Node("Flatten", ["p"], ["f"]),
            ],
            inputs=[_VI("x"), _VI("w")],
            outputs=[_VI("f")],
            initializers=[_Tensor("w", w)])
        s, args, auxs = mx.contrib.onnx.import_graph(graph)
        x = rng.rand(1, 3, 8, 8).astype(np.float32)
        ex = s.bind(mx.cpu(), {"x": nd.array(x), **args})
        out = ex.forward()[0]
        assert out.shape == (1, 2 * 4 * 4)
        ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                             pad=(1, 1), num_filter=2, no_bias=True)
        ref = nd.Pooling(nd.relu(ref), kernel=(2, 2), stride=(2, 2),
                         pool_type="max").asnumpy().reshape(1, -1)
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)

    def test_unsupported_op(self):
        graph = _Graph(nodes=[_Node("NonMaxSuppression", ["x"], ["y"])],
                       inputs=[_VI("x")], outputs=[_VI("y")],
                       initializers=[])
        with pytest.raises(mx.MXNetError, match="unsupported ONNX op"):
            mx.contrib.onnx.import_graph(graph)


class TestContribText:
    def test_count_and_vocab(self):
        counter = mx.contrib.text.count_tokens_from_str(
            "a b b c c c\nd", to_lower=True)
        assert counter == Counter({"c": 3, "b": 2, "a": 1, "d": 1})
        vocab = mx.contrib.text.Vocabulary(counter, min_freq=2,
                                           reserved_tokens=["<pad>"])
        # <unk>, <pad>, then by frequency
        assert vocab.idx_to_token == ["<unk>", "<pad>", "c", "b"]
        assert vocab.to_indices(["c", "zzz"]) == [2, 0]
        assert vocab.to_tokens(3) == "b"
        assert len(vocab) == 4

    def test_custom_embedding(self, tmp_path):
        path = tmp_path / "emb.txt"
        path.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
        emb = mx.contrib.text.CustomEmbedding(str(path))
        assert emb.vec_len == 3
        v = emb.get_vecs_by_tokens("world").asnumpy()
        np.testing.assert_allclose(v, [4., 5., 6.])
        unk = emb.get_vecs_by_tokens("zzz").asnumpy()
        np.testing.assert_allclose(unk, [0., 0., 0.])
        emb.update_token_vectors("hello", nd.array([[9., 9., 9.]]))
        np.testing.assert_allclose(
            emb.get_vecs_by_tokens("hello").asnumpy(), 9.0)


class TestIm2Rec:
    def test_list_pack_read(self, tmp_path):
        cv2 = pytest.importorskip("cv2")
        root = tmp_path / "imgs"
        for cls in ("cat", "dog"):
            (root / cls).mkdir(parents=True)
            for i in range(3):
                img = np.random.RandomState(i).randint(
                    0, 255, (16, 16, 3), np.uint8)
                cv2.imwrite(str(root / cls / ("%d.jpg" % i)), img)
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import im2rec
        finally:
            sys.path.pop(0)
        prefix = str(tmp_path / "data")
        classes = im2rec.make_list(prefix, str(root), shuffle=False)
        assert len(classes) == 2
        n = im2rec.pack(prefix, str(root))
        assert n == 6
        # read back through MXIndexedRecordIO + unpack_img
        from mxnet_tpu import recordio
        r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                       "r")
        header, img = recordio.unpack_img(r.read_idx(r.keys[0]))
        assert img.shape == (16, 16, 3)
        assert header.label in (0.0, 1.0)
        r.close()

    def test_imagerecorditer_reads_packed(self, tmp_path):
        cv2 = pytest.importorskip("cv2")
        root = tmp_path / "imgs"
        root.mkdir()
        for i in range(4):
            cv2.imwrite(str(root / ("%d.jpg" % i)),
                        np.full((20, 20, 3), i * 40, np.uint8))
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import im2rec
        finally:
            sys.path.pop(0)
        prefix = str(tmp_path / "flat")
        im2rec.make_list(prefix, str(root), shuffle=False)
        im2rec.pack(prefix, str(root))
        it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 16, 16), batch_size=2)
        batch = it.next()
        assert batch.data[0].shape == (2, 3, 16, 16)


def test_storage_manager_surface():
    """N2 storage manager: pool-env translation, census, lifecycle."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import storage

    # env translation (pure dict, no process effects)
    env = {"MXNET_GPU_MEM_POOL_TYPE": "Unpooled",
           "MXNET_GPU_MEM_POOL_RESERVE": "20",
           "MXNET_TPU_PREALLOCATE": "0"}
    applied = storage.apply_pool_env(env)
    assert applied["XLA_PYTHON_CLIENT_ALLOCATOR"] == "platform"
    assert applied["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.80"
    assert applied["XLA_PYTHON_CLIENT_PREALLOCATE"] == "false"
    # never overwrites explicit XLA settings
    env2 = {"MXNET_GPU_MEM_POOL_RESERVE": "50",
            "XLA_PYTHON_CLIENT_MEM_FRACTION": "0.33"}
    assert "XLA_PYTHON_CLIENT_MEM_FRACTION" not in \
        storage.apply_pool_env(env2)
    assert env2["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.33"

    # live-array census sees a new allocation.  The census is a point-in-time
    # count over every live jax array in the process; unrelated arrays can be
    # collected between the two samples (prior tests' prefetch threads, RNG
    # key churn), so retry the delta a few times rather than demand one
    # window be quiescent.
    import gc
    keep = None
    for attempt in range(3):
        keep = None        # drop the prior attempt's array before sampling c0
        gc.collect()
        c0, b0 = storage.live_arrays()
        keep = mx.nd.array(np.ones((64, 64), np.float32))
        keep.wait_to_read()
        c1, b1 = storage.live_arrays()
        if c1 >= c0 + 1 and b1 >= b0 + 64 * 64 * 4:
            break
    else:
        raise AssertionError("census never saw the allocation: "
                             "%d->%d arrays, %d->%d bytes" % (c0, c1, b0, b1))

    # memory_info returns (free, total); CPU backends report (0, 0)
    free, total = storage.memory_info()
    assert free >= 0 and total >= 0

    # release_all drops executable caches without touching live arrays
    storage.release_all()
    np.testing.assert_allclose(keep.asnumpy(), 1.0)
    assert storage.report().startswith("Device") or "Device" in \
        storage.report()
