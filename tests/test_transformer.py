"""Transformer LM workload (models/) — composition + parity pins.

ISSUE 20: the decoder LM must be ONE model family across every
execution strategy — symbol graph (Module fused step), functional
blocks (pipeline/ring/MoE composition), flash vs reference attention —
with parity tests pinning that they all compute the same math.  Runs on
the virtual 8-device CPU mesh from conftest.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.models import get_config
from mxnet_tpu.models.transformer import (transformer_block, transformer_lm,
                                          init_block_params, block_apply,
                                          pipeline_transformer,
                                          long_context_attention,
                                          moe_transformer_ffn)

CFG = get_config("tiny", seq_len=16)


# ---------------------------------------------------------------------------
# symbol graph <-> functional block
# ---------------------------------------------------------------------------
def _bind_block(B):
    x = mx.sym.Variable("data")
    blk = transformer_block(x, CFG, 0, "")
    exe = blk.simple_bind(mx.cpu(0), grad_req="null",
                          data=(B, CFG.seq_len, CFG.d_model))
    return exe


_SYM2FN = {
    "l0_ln1_gamma": "ln1_gamma", "l0_ln1_beta": "ln1_beta",
    "l0_attn_query_weight": "query_weight",
    "l0_attn_key_weight": "key_weight",
    "l0_attn_value_weight": "value_weight",
    "l0_attn_out_proj_weight": "out_proj_weight",
    "l0_ln2_gamma": "ln2_gamma", "l0_ln2_beta": "ln2_beta",
    "l0_ffn_fc1_weight": "fc1_weight", "l0_ffn_fc1_bias": "fc1_bias",
    "l0_ffn_down_weight": "down_weight", "l0_ffn_down_bias": "down_bias",
}


def test_symbol_block_matches_functional_block():
    """The Symbol block (what Module trains) and block_apply (what the
    pipeline/parallel paths run) are the same math: same registry op
    implementations, so the outputs agree to fp32 roundoff."""
    B = 2
    exe = _bind_block(B)
    rng = np.random.RandomState(0)
    params = init_block_params(CFG, rng)
    assert set(_SYM2FN.keys()) | {"data"} == set(exe.arg_dict.keys())
    for sym_name, fn_name in _SYM2FN.items():
        arr = np.asarray(params[fn_name], np.float32)
        assert exe.arg_dict[sym_name].shape == arr.shape, sym_name
        exe.arg_dict[sym_name][:] = arr
    x = rng.standard_normal(
        (B, CFG.seq_len, CFG.d_model)).astype(np.float32)
    exe.arg_dict["data"][:] = x
    got = exe.forward(is_train=False)[0].asnumpy()
    want = np.asarray(block_apply(CFG, params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# Module training: fused vs eager step parity + descent
# ---------------------------------------------------------------------------
def _train_losses(monkeypatch, fused, steps=3, B=4):
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1" if fused else "0")
    net = transformer_lm(CFG)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",),
                        context=[mx.cpu(0)])
    mod.bind(data_shapes=[("data", (B, CFG.seq_len))],
             label_shapes=[("softmax_label", (B, CFG.seq_len))])
    mx.random.seed(11)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    it = mx.io.SyntheticLMIter(CFG.vocab_size, CFG.seq_len, batch_size=B,
                               num_batches=steps, seed=3)
    losses = []
    for batch in it:
        mod.forward_backward(batch)
        mod.update()
        losses.append(float(mod.get_outputs()[0].asnumpy().ravel()[0]))
    return losses


def test_fused_vs_eager_step_parity(monkeypatch):
    """The whole LM step — streaming CE head included — takes the fused
    single-program path and the eager multi-program path to the same
    loss trajectory."""
    eager = _train_losses(monkeypatch, fused=False)
    fused = _train_losses(monkeypatch, fused=True)
    np.testing.assert_allclose(fused, eager, rtol=1e-5, atol=1e-6)


def test_transformer_lm_loss_descends(monkeypatch):
    """Repeated batch: the full graph (embedding -> blocks -> CE) must
    actually learn, not just run."""
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    B = 4
    net = transformer_lm(CFG)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",),
                        context=[mx.cpu(0)])
    mod.bind(data_shapes=[("data", (B, CFG.seq_len))],
             label_shapes=[("softmax_label", (B, CFG.seq_len))])
    mx.random.seed(5)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    rng = np.random.RandomState(0)
    toks = rng.randint(0, CFG.vocab_size, (B, CFG.seq_len))

    class _B:
        data = [mx.nd.array(toks.astype(np.float32))]
        label = [mx.nd.array(np.roll(toks, -1, axis=1).astype(np.float32))]

    losses = []
    for _ in range(8):
        mod.forward_backward(_B)
        mod.update()
        losses.append(float(mod.get_outputs()[0].asnumpy().ravel()[0]))
    assert losses[-1] < losses[0] - 0.5, losses


# ---------------------------------------------------------------------------
# parallel composition parity
# ---------------------------------------------------------------------------
def test_long_context_ring_matches_blockwise_8dev():
    """Sequence-parallel attention over the 8-way `sp` mesh vs the
    single-device blockwise scan — same numbers, shard count included
    in neither."""
    from mxnet_tpu.parallel.ring_attention import blockwise_attention
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    r = np.random.default_rng(4)
    B, H, T, D = 1, 2, 1024, 16
    q, k, v = (jnp.asarray(r.standard_normal((B, H, T, D)) * 0.5,
                           jnp.float32) for _ in range(3))
    from mxnet_tpu.parallel import make_mesh
    mesh = make_mesh({"sp": 8})
    got = long_context_attention(q, k, v, mesh, axis="sp", causal=True,
                                 block_size=128)
    ref = blockwise_attention(q, k, v, block_size=128, causal=True,
                              use_pallas=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_moe_transformer_ffn_expert_parallel_parity():
    """The MoE FFN drop-in keeps (B, T, D) shape and the expert-parallel
    mesh path matches the local all-experts reference."""
    from mxnet_tpu.parallel.moe import init_moe_params
    from mxnet_tpu.parallel import make_mesh
    rng = np.random.RandomState(6)
    params = init_moe_params(rng, d_model=16, d_hidden=32, num_experts=8)
    x = jnp.asarray(rng.randn(2, 16, 16).astype(np.float32))
    ref = moe_transformer_ffn(x, params, mesh=None, k=2,
                              capacity_factor=8.0)
    assert ref.shape == x.shape
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices for the expert-parallel path")
    mesh = make_mesh({"ep": 4}, devices=jax.devices()[:4])
    out = moe_transformer_ffn(x, params, mesh=mesh, axis="ep", k=2,
                              capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_transformer_matches_sequential():
    """Four transformer blocks as GPipe stages vs applying the same
    blocks in sequence."""
    from mxnet_tpu.parallel import make_mesh
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    stages = 4
    rng = np.random.RandomState(8)
    per_stage = [init_block_params(CFG, rng) for _ in range(stages)]
    stacked = {k: jnp.stack([p[k] for p in per_stage])
               for k in per_stage[0]}
    x = jnp.asarray(rng.randn(8, CFG.seq_len, CFG.d_model)
                    .astype(np.float32) * 0.5)
    mesh = make_mesh({"pp": stages}, devices=jax.devices()[:stages])
    got = pipeline_transformer(mesh, "pp", CFG, stacked, x, n_micro=4)
    ref = x
    for p in per_stage:
        ref = block_apply(CFG, p, ref)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# env-gated dispatch is part of the jit cache key
# ---------------------------------------------------------------------------
def test_flash_env_flip_retraces_not_stale(monkeypatch):
    """MXNET_TPU_FLASH_ATTENTION is in the MultiHeadAttention op's
    env_keys: flipping it between forwards on a LIVE executor must
    re-trace (jit-cache miss) instead of replaying the stale variant —
    the GL001/GL002 contract, pinned behaviorally."""
    from mxnet_tpu import telemetry
    from mxnet_tpu import health as _health
    telemetry.enable()
    monkeypatch.delenv("MXNET_TPU_FLASH_ATTENTION", raising=False)
    B = 2
    exe = _bind_block(B)
    rng = np.random.RandomState(1)
    for name in _SYM2FN:
        exe.arg_dict[name][:] = (rng.standard_normal(
            exe.arg_dict[name].shape).astype(np.float32) * 0.05)
    exe.arg_dict["data"][:] = rng.standard_normal(
        (B, CFG.seq_len, CFG.d_model)).astype(np.float32)

    exe.forward(is_train=False)[0].asnumpy()
    warm, _ = _health._compile_totals()
    exe.forward(is_train=False)[0].asnumpy()   # same env: pure cache hit
    hit, _ = _health._compile_totals()
    assert hit == warm
    monkeypatch.setenv("MXNET_TPU_FLASH_ATTENTION", "0")
    exe.forward(is_train=False)[0].asnumpy()   # flipped env: must miss
    flipped, _ = _health._compile_totals()
    assert flipped > hit


# ---------------------------------------------------------------------------
# megatron sharding rules cover the model's parameter names
# ---------------------------------------------------------------------------
def test_megatron_rules_shard_transformer_names():
    """Row-parallel names (out_proj/down) must NOT be claimed by the
    column rule — the regex-order regression this PR fixed."""
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.mesh import megatron_rules, P
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_mesh({"dp": -1, "tp": 2})
    rules = megatron_rules(mesh)
    d = CFG.d_model
    assert rules.spec_for("tfm_l0_attn_query_weight", (d, d)) \
        == P("tp", None)
    assert rules.spec_for("tfm_l0_attn_out_proj_weight", (d, d)) \
        == P(None, "tp")
    assert rules.spec_for("tfm_l0_ffn_fc1_weight", (CFG.d_ff, d)) \
        == P("tp", None)
    assert rules.spec_for("tfm_l0_ffn_down_weight", (d, CFG.d_ff)) \
        == P(None, "tp")
    assert rules.spec_for("tfm_tok_embedding_weight",
                          (CFG.vocab_size, d)) == P(None, "tp")
    assert rules.spec_for("tfm_l0_ln1_gamma", (d,)) == P()
