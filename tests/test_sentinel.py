"""Perf regression sentinel (tools/sentinel.py).

Covers the tolerance-band arithmetic (direction, relative vs absolute
bands, zero-tolerance metrics, NEW/MISSING handling, worst-first
ranking), every normalizer shape (driver wrapper, multichip, serving,
run-ledger JSONL, canonical passthrough), round merging, the CLI
(verdict table + exit code, --normalize, --update-baseline refusal and
seeding, --smoke), and the end-to-end acceptance property: a ~20%
injected throughput regression exits nonzero with a ranked table.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import sentinel


def _base(**metrics):
    m = {"resnet50_img_per_sec": 1000.0, "resnet50_step_spread_pct": 1.0}
    m.update(metrics)
    return {"round": "rB", "source": "base", "kind": "bench",
            "metrics": m, "context": {}}


def _cand(**metrics):
    doc = _base(**metrics)
    doc["source"] = "cand"
    return doc


def _row(rows, name):
    return next(r for r in rows if r["metric"] == name)


# ---------------------------------------------------------------------------
# compare semantics
# ---------------------------------------------------------------------------
class TestCompare:
    def test_identical_passes(self):
        rows = sentinel.compare(_base(), _base())
        assert all(r["verdict"] == "PASS" for r in rows)
        assert sentinel.verdict_exit(rows) == 0

    def test_twenty_pct_regression_fails_ranked_first(self):
        rows = sentinel.compare(_base(),
                                _cand(resnet50_img_per_sec=800.0))
        assert rows[0]["metric"] == "resnet50_img_per_sec"
        assert rows[0]["verdict"] == "FAIL"
        assert rows[0]["delta_pct"] == pytest.approx(-20.0)
        assert sentinel.verdict_exit(rows) == 1

    def test_within_band_wobble_passes(self):
        rows = sentinel.compare(_base(),
                                _cand(resnet50_img_per_sec=970.0))
        assert sentinel.verdict_exit(rows) == 0

    def test_past_half_band_warns(self):
        # band = 10% of 1000 -> 100; an 80-point drop is past half of it
        rows = sentinel.compare(_base(),
                                _cand(resnet50_img_per_sec=920.0))
        r = _row(rows, "resnet50_img_per_sec")
        assert r["verdict"] == "WARN"
        assert sentinel.verdict_exit(rows) == 0

    def test_improvement_always_passes(self):
        rows = sentinel.compare(_base(),
                                _cand(resnet50_img_per_sec=5000.0))
        assert sentinel.verdict_exit(rows) == 0

    def test_lower_is_better_absolute_slack(self):
        # spread band is 3 absolute points, not relative: 1 -> 3.5 FAILs
        rows = sentinel.compare(_base(),
                                _cand(resnet50_step_spread_pct=4.5))
        assert _row(rows, "resnet50_step_spread_pct")["verdict"] == "FAIL"
        rows = sentinel.compare(_base(),
                                _cand(resnet50_step_spread_pct=2.0))
        assert _row(rows, "resnet50_step_spread_pct")["verdict"] == "PASS"
        # and improvement (smaller spread) passes
        rows = sentinel.compare(_base(),
                                _cand(resnet50_step_spread_pct=0.1))
        assert _row(rows, "resnet50_step_spread_pct")["verdict"] == "PASS"

    def test_zero_tolerance_metric(self):
        rows = sentinel.compare(_base(post_warmup_compiles=0.0),
                                _cand(post_warmup_compiles=1.0))
        r = _row(rows, "post_warmup_compiles")
        assert r["verdict"] == "FAIL" and r["excess"] == float("inf")

    def test_new_metric_is_informational(self):
        rows = sentinel.compare(_base(), _cand(shiny_new_metric=5.0))
        assert _row(rows, "shiny_new_metric")["verdict"] == "NEW"
        assert sentinel.verdict_exit(rows) == 0

    def test_missing_metric_warns_not_fails(self):
        cand = _cand()
        del cand["metrics"]["resnet50_step_spread_pct"]
        rows = sentinel.compare(_base(), cand)
        assert _row(rows, "resnet50_step_spread_pct")["verdict"] == "MISSING"
        assert sentinel.verdict_exit(rows) == 0

    def test_unknown_metric_gets_default_band(self):
        assert sentinel.band_of("never_seen") == sentinel.DEFAULT_BAND
        rows = sentinel.compare(_base(mystery=100.0), _cand(mystery=50.0))
        assert _row(rows, "mystery")["verdict"] == "FAIL"  # -50% > 15%

    def test_markdown_table(self):
        rows = sentinel.compare(_base(),
                                _cand(resnet50_img_per_sec=800.0))
        md = sentinel.markdown_table(rows, _base(), _cand())
        assert "**REGRESSION**" in md and "**FAIL**" in md
        assert "| resnet50_img_per_sec (^) |" in md
        md_ok = sentinel.markdown_table(sentinel.compare(_base(), _base()),
                                        _base(), _base())
        assert "**OK**" in md_ok

    def test_merged_source_renders_joined(self):
        merged = sentinel.merge_rounds([_base(), _cand()])
        assert merged["source"] == ["base", "cand"]
        md = sentinel.markdown_table([], _base(), merged)
        assert "base+cand" in md


# ---------------------------------------------------------------------------
# normalizers
# ---------------------------------------------------------------------------
class TestNormalize:
    def test_driver_wrapper(self, tmp_path):
        doc = {"n": 9, "cmd": "python bench.py", "rc": 0, "tail": "",
               "parsed": {"value": 2452.0, "mfu_pct": 30.6,
                          "step_spread_pct": 0.7,
                          "window_scaling_ratio": 1.99,
                          "lstm": {"value": 460779.8, "mfu_pct": 39.8},
                          "health": {"monitor_overhead_pct": 0.5,
                                     "sampler_overhead_pct": 0.2},
                          "atlas": {"a": {"coverage_pct": 98.0},
                                    "b": {"coverage_pct": 91.0}}}}
        p = tmp_path / "BENCH_r09.json"
        p.write_text(json.dumps(doc))
        n = sentinel.normalize(str(p))
        assert n["round"] == "r09" and n["kind"] == "bench"
        m = n["metrics"]
        assert m["resnet50_img_per_sec"] == 2452.0
        assert m["lstm_tokens_per_sec"] == 460779.8
        assert m["sampler_overhead_pct"] == 0.2
        assert m["atlas_coverage_pct"] == 91.0       # worst program wins
        assert "unvalidated" not in n["context"]

    def test_unvalidated_record_flagged(self):
        n = sentinel.normalize({"parsed": {"value": 70464.0}}, "BENCH_r01")
        assert n["context"]["unvalidated"] is True

    def test_lstm_error_block_skipped(self):
        n = sentinel.normalize(
            {"parsed": {"value": 1.0, "lstm": {"error": "oom"}}}, "r02")
        assert "lstm_tokens_per_sec" not in n["metrics"]

    def test_multichip(self):
        n = sentinel.normalize({"value": 3.17, "scaling_efficiency": 0.11,
                                "platform": "cpu-virtual", "n_devices": 8},
                               "MULTICHIP_r06.json")
        assert n["kind"] == "multichip"
        assert n["metrics"]["multichip_img_per_sec"] == 3.17
        assert n["metrics"]["multichip_scaling_efficiency"] == 0.11
        assert n["context"]["platform"] == "cpu-virtual"

    def test_serving(self):
        n = sentinel.normalize({"p99_ms": 12.5, "throughput_rps": 800.0,
                                "post_warmup_compiles": 0}, "serving.json")
        assert n["kind"] == "serving"
        assert n["metrics"]["serving_p99_ms"] == 12.5
        assert n["metrics"]["post_warmup_compiles"] == 0.0

    def test_canonical_passthrough(self):
        n = sentinel.normalize(_base(), "x")
        assert n["metrics"] == _base()["metrics"]

    def test_unknown_shape_is_empty_not_fatal(self):
        n = sentinel.normalize({"what": "ever"}, "junk.json")
        assert n["kind"] == "unknown" and n["metrics"] == {}

    def test_nonfinite_values_dropped(self):
        n = sentinel.normalize({"parsed": {"value": float("nan"),
                                           "mfu_pct": 30.0}}, "r03")
        assert "resnet50_img_per_sec" not in n["metrics"]
        assert n["metrics"]["resnet50_mfu_pct"] == 30.0

    def test_ledger_extraction(self, tmp_path):
        from mxnet_tpu import runlog
        p = str(tmp_path / "ledger.jsonl")
        log = runlog.RunLog(p, run_id="rid-s")
        log.event("run_start", env={"MXNET_TPU_FUSED_STEP": "1"})
        log.event("bench_result", metric="img/sec", value=2000.0,
                  result={"value": 2000.0, "mfu_pct": 25.0,
                          "window_scaling_ratio": 2.0})
        log.event("healthz", status="degraded", post_warmup_compiles=2)
        log.event("bench_result", metric="img/sec", value=2100.0,
                  result={"value": 2100.0, "mfu_pct": 26.0,
                          "window_scaling_ratio": 2.0})
        log.close()
        with open(p, "a") as f:
            f.write('{"torn')                        # reader must survive
        n = sentinel.normalize(p)
        assert n["kind"] == "ledger"
        assert n["metrics"]["resnet50_img_per_sec"] == 2100.0  # last wins
        assert n["metrics"]["post_warmup_compiles"] == 2.0
        assert n["context"]["run_id"] == "rid-s"
        assert n["context"]["step_env"] == {"MXNET_TPU_FUSED_STEP": "1"}


# ---------------------------------------------------------------------------
# CLI end-to-end
# ---------------------------------------------------------------------------
class TestCLI:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_regression_exits_nonzero_with_table(self, tmp_path):
        b = self._write(tmp_path, "baseline.json", _base())
        c = self._write(tmp_path, "cand.json",
                        _cand(resnet50_img_per_sec=800.0))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "sentinel.py"),
             "--baseline", b, "--candidate", c],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "**REGRESSION**" in proc.stdout
        assert proc.stdout.index("resnet50_img_per_sec") \
            < proc.stdout.index("resnet50_step_spread_pct")

    def test_identical_exits_zero(self, tmp_path):
        b = self._write(tmp_path, "baseline.json", _base())
        c = self._write(tmp_path, "cand.json", _base())
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "sentinel.py"),
             "--baseline", b, "--candidate", c, "--format", "json"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0
        doc = json.loads(proc.stdout)
        assert doc["regression"] is False

    def test_update_baseline_refuses_on_fail(self, tmp_path):
        b = self._write(tmp_path, "baseline.json", _base())
        c = self._write(tmp_path, "cand.json",
                        _cand(resnet50_img_per_sec=500.0))
        rc = sentinel.main(["--baseline", b, "--candidate", c,
                            "--update-baseline"])
        assert rc == 1
        assert json.load(open(b))["metrics"]["resnet50_img_per_sec"] \
            == 1000.0                                 # untouched

    def test_update_baseline_promotes_on_pass(self, tmp_path):
        b = self._write(tmp_path, "baseline.json", _base())
        c = self._write(tmp_path, "cand.json",
                        _cand(resnet50_img_per_sec=1200.0))
        assert sentinel.main(["--baseline", b, "--candidate", c,
                              "--update-baseline"]) == 0
        assert json.load(open(b))["metrics"]["resnet50_img_per_sec"] \
            == 1200.0

    def test_missing_baseline_seeds_with_flag(self, tmp_path):
        b = str(tmp_path / "fresh" / "baseline.json")
        c = self._write(tmp_path, "cand.json", _base())
        assert sentinel.main(["--baseline", b, "--candidate", c]) == 2
        assert sentinel.main(["--baseline", b, "--candidate", c,
                              "--update-baseline"]) == 0
        assert json.load(open(b))["metrics"]["resnet50_img_per_sec"] \
            == 1000.0

    def test_normalize_mode_writes_canonical(self, tmp_path):
        self._write(tmp_path, "BENCH_r07.json",
                    {"parsed": {"value": 5.0, "window_scaling_ratio": 2.0}})
        out = tmp_path / "canon"
        rc = sentinel.main(["--normalize", str(tmp_path / "BENCH_r07.json"),
                            "-o", str(out)])
        assert rc == 0
        doc = json.load(open(out / "bench_r07.canonical.json"))
        assert doc["metrics"]["resnet50_img_per_sec"] == 5.0

    def test_smoke(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "sentinel.py"),
             "--smoke"], capture_output=True, text=True, timeout=60,
            cwd=REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rec == {"probe": "sentinel", "ok": True}

    def test_committed_baseline_is_valid(self):
        # the repo ships a baseline; it must stay canonical and self-pass
        assert os.path.exists(sentinel.DEFAULT_BASELINE)
        doc = json.load(open(sentinel.DEFAULT_BASELINE))
        assert doc["metrics"]
        assert sentinel.verdict_exit(sentinel.compare(doc, doc)) == 0
