"""Worker script for the multi-process dist kvstore test.

Parity model: tests/nightly/dist_sync_kvstore.py — each of N forked workers
pushes rank-dependent values and asserts the exact cross-rank sums, incl.
a gradient-compression round and a barrier.  Launched by
tools/launch.py-style env (DMLC_*) from tests/test_dist_kvstore.py.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def main():
    kv = mx.kv.create("dist_sync")
    rank = kv.rank
    nw = kv.num_workers
    assert nw == int(os.environ["DMLC_NUM_WORKER"]), (nw, os.environ)

    # dense push/pull: sum of (rank+1)*ones across ranks
    kv.init("dense", nd.zeros((4, 3)))
    kv.push("dense", nd.ones((4, 3)) * (rank + 1))
    out = nd.zeros((4, 3))
    kv.pull("dense", out=out)
    expect = sum(r + 1 for r in range(nw))
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)

    kv.barrier()

    # second round with an updater-free assign of a different key
    kv.init("k2", nd.zeros((2,)))
    kv.push("k2", nd.array([float(rank), 1.0]))
    out2 = nd.zeros((2,))
    kv.pull("k2", out=out2)
    np.testing.assert_allclose(out2.asnumpy(),
                               [sum(range(nw)), float(nw)], rtol=1e-6)

    # gradient compression: each worker pushes 0.9 with threshold 0.5 ->
    # each contributes +0.5 -> sum = 0.5 * nw; residual 0.4 carries over
    kv2 = mx.kv.create("dist_sync")
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv2.init("c", nd.zeros((3,)))
    kv2.push("c", nd.ones((3,)) * 0.9)
    outc = nd.zeros((3,))
    kv2.pull("c", out=outc)
    np.testing.assert_allclose(outc.asnumpy(), 0.5 * nw, rtol=1e-6)
    # second push: residual 0.4 + 0.2 grad = 0.6 -> quantized +0.5 again
    kv2.push("c", nd.ones((3,)) * 0.2)
    kv2.pull("c", out=outc)
    np.testing.assert_allclose(outc.asnumpy(), 0.5 * nw, rtol=1e-6)

    print("WORKER_%d_OK" % rank)
    return 0


if __name__ == "__main__":
    sys.exit(main())
