"""Device-memory observability (mxnet_tpu/memwatch.py).

Covers the owner-tagged ledger across the Module eager / Module fused /
gluon Trainer paths (tag handles survive every buffer-repoint site:
kvstore push, updater writeback, donation pools), the per-device sharded
census fix in ``storage.live_arrays``, the leak sentinel aging window
with its flight-dump embedding, the OOM pre-flight projection against
``bytes_limit``, the forced RESOURCE_EXHAUSTED forensics dump
(``reason=oom``), serving hot-swap hygiene (old weight generation leaves
the ledger), and the donation-audit cross-check.

Assertions are written against *our* arrays (tagged-handle checks,
owner_bytes sums) rather than global census coverage, because
``jax.live_arrays()`` is process-global and a full pytest run carries
live buffers from every other test file.  The >=90% whole-process
coverage contract is asserted by ``tools/memwatch.py --smoke`` in a
fresh interpreter.
"""
import gc
import json
import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import health, memwatch, nd, storage, telemetry, tracing
from mxnet_tpu import fused_step as fused

S = mx.symbol


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    health.reset()
    memwatch.reset()
    memwatch.enable(census_thread=False)
    yield
    memwatch.disable()
    memwatch.reset()
    health.disable()
    health.reset()
    telemetry.disable()
    telemetry.reset()
    gc.collect()


def _build_module(batch=8):
    data = S.Variable("data")
    label = S.Variable("softmax_label")
    fc1 = S.FullyConnected(data, num_hidden=16, name="fc1")
    act = S.Activation(fc1, act_type="relu")
    fc2 = S.FullyConnected(act, num_hidden=4, name="fc2")
    out = S.SoftmaxOutput(fc2, label, name="softmax")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (batch, 10))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    return mod


class _Batch:
    def __init__(self, batch=8, seed=0):
        rs = np.random.RandomState(seed)
        self.data = [nd.array(rs.randn(batch, 10).astype(np.float32))]
        self.label = [nd.array(
            rs.randint(0, 4, (batch,)).astype(np.float32))]


def _tagged_ids():
    """Live id set of the ledger (weakref-validated, like the census)."""
    out = {}
    for key, (owner, det, ref) in list(memwatch._tags.items()):
        a = ref() if ref is not None else None
        if a is not None and id(a) == key:
            out[key] = owner
    return out


def _train(mod, steps=3):
    for i in range(steps):
        b = _Batch(seed=100 + i)
        mod.forward(b)
        mod.backward()
        mod.update()


# ---------------------------------------------------------------------------
# owner-tagged ledger across the three update paths
# ---------------------------------------------------------------------------
class TestLedgerModule:
    @pytest.mark.parametrize("flag", ["0", "1"])
    def test_all_handles_tagged_after_training(self, monkeypatch, flag):
        """Every buffer the module owns is in the ledger with the right
        owner AFTER training steps — i.e. the tags survive the eager
        updater / kvstore push / fused donation repoints."""
        monkeypatch.setenv(fused.ENV_FLAG, flag)
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd", optimizer_params=(
            ("momentum", 0.9), ("learning_rate", 0.01)))
        _train(mod)
        tags = _tagged_ids()
        ex = mod._exec_group.execs[0]
        for name, arr in ex.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            assert tags.get(id(arr._data)) == "params", \
                "%s (%s path) untagged" % (name, flag)
        # host master copies ride in the params budget too
        for name, arr in mod._arg_params.items():
            assert tags.get(id(arr._data)) == "params", name
        assert memwatch.owner_bytes("params") >= sum(
            a._data.nbytes for a in ex.arg_dict.values()
            if a is not None)

    def test_eager_grads_and_kvstore_retagged(self, monkeypatch):
        monkeypatch.setenv(fused.ENV_FLAG, "0")
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd")
        _train(mod)
        tags = _tagged_ids()
        ex = mod._exec_group.execs[0]
        for name, g in ex.grad_dict.items():
            assert tags.get(id(g._data)) == "activations", name
        # the local kvstore's aggregation buffers are repointed every
        # push — they must stay on the ledger (owner: opt_state)
        for key, arr in mod._kvstore._store.items():
            assert tags.get(id(arr._data)) == "opt_state", key
        # adopted input batches are io
        assert memwatch.owner_bytes("io") > 0

    def test_census_owner_sums_and_gauges(self, monkeypatch):
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd", optimizer_params=(
            ("momentum", 0.9),))
        _train(mod)
        snap = memwatch.census()
        total = sum(rec["bytes"] for rec in snap["owners"].values())
        assert total == snap["total_bytes"]
        assert snap["tagged_bytes"] + snap["untagged_bytes"] == total
        for owner in ("params", "opt_state", "io"):
            assert snap["owners"][owner]["bytes"] > 0, owner
            assert telemetry.value("memwatch_owner_bytes", owner=owner) \
                == snap["owners"][owner]["bytes"]
        # device gauges follow the census (CPU: census fallback source)
        dev = next(iter(snap["devices"]))
        st = snap["devices"][dev]
        assert st["bytes_in_use"] > 0
        assert st["peak_bytes_in_use"] >= st["bytes_in_use"]
        assert telemetry.value("device_bytes_in_use", device=dev) \
            == st["bytes_in_use"]

    def test_trainer_fused_params_and_state_tagged(self, monkeypatch):
        from mxnet_tpu import autograd
        from mxnet_tpu.gluon import nn, Trainer
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        net = nn.Sequential()
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
        net.initialize(ctx=mx.cpu())
        tr = Trainer(net.collect_params(), "adam",
                     {"learning_rate": 0.01})
        for i in range(3):
            rs = np.random.RandomState(i)
            x = nd.array(rs.randn(8, 10).astype(np.float32))
            with autograd.record():
                y = net(x)
                loss = (y * y).sum()
            loss.backward()
            tr.step(8)
        tags = _tagged_ids()
        for name, p in net.collect_params().items():
            assert tags.get(id(p.data()._data)) == "params", name
        # adam slots (mean/var per param) live in the donation pool
        assert memwatch.owner_bytes("opt_state") > 0

    def test_disabled_tag_is_noop(self):
        memwatch.disable()
        assert memwatch.tag("params", nd.array(np.zeros(4))) == 0
        assert memwatch._tags == {}

    def test_retag_overwrites_and_untag_drops(self):
        a = nd.array(np.zeros((4, 4), np.float32))
        assert memwatch.tag("io", a) == 1
        memwatch.tag("checkpoint", a)
        assert _tagged_ids()[id(a._data)] == "checkpoint"
        memwatch.untag(a)
        assert id(a._data) not in memwatch._tags


# ---------------------------------------------------------------------------
# satellite: sharded per-device census (storage.live_arrays)
# ---------------------------------------------------------------------------
class TestShardedCensus:
    def test_sharded_array_not_multiply_counted(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = jax.devices()
        assert len(devs) == 8, "conftest forces an 8-device CPU mesh"
        mesh = Mesh(np.array(devs), ("d",))
        before = {d: storage.live_arrays(d)[1] for d in devs}
        x = jax.device_put(jnp.zeros((8, 64), jnp.float32),
                           NamedSharding(mesh, P("d")))
        after = {d: storage.live_arrays(d)[1] for d in devs}
        shard = x.nbytes // 8
        for d in devs:
            assert after[d] - before[d] == shard, str(d)
        # per-device shard bytes sum to the global figure — the old code
        # counted the full nbytes on every holding device (8x)
        assert sum(storage.device_nbytes(x, d) for d in devs) == x.nbytes
        del x

    def test_single_device_array_full_bytes(self):
        import jax
        a = nd.array(np.zeros((16, 16), np.float32))
        d = next(iter(a._data.devices()))
        assert storage.device_nbytes(a._data, d) == a._data.nbytes
        other = [dv for dv in jax.devices() if dv != d][0]
        assert storage.device_nbytes(a._data, other) == 0


# ---------------------------------------------------------------------------
# leak sentinel
# ---------------------------------------------------------------------------
class TestLeakSentinel:
    def test_untagged_survivor_flagged_within_k(self, monkeypatch,
                                                tmp_path):
        import jax.numpy as jnp
        monkeypatch.setenv("MXNET_MEMWATCH_LEAK_GENERATIONS", "2")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH",
                           str(tmp_path / "flight.json"))
        # big enough to guarantee a top-offenders slot among any
        # leftover process noise
        leak = jnp.zeros((512, 512), jnp.float32) + 1
        before = telemetry.value("memory_leak_suspects_total") or 0.0
        memwatch.census()                       # first seen (age 0)
        snap = memwatch.census()                # age 1 < K: not yet
        assert not any(s["shape"] == [512, 512] for s in snap["suspects"])
        snap = memwatch.census()                # age 2 >= K: flagged
        ours = [s for s in snap["suspects"] if s["shape"] == [512, 512]]
        assert ours and ours[0]["age"] >= 2
        assert ours[0]["dtype"] == "float32"
        assert ours[0]["device"]
        assert telemetry.value("memory_leak_suspects_total") > before
        # flagged once: another census must not re-count it
        count = telemetry.value("memory_leak_suspects_total")
        memwatch.census()
        assert telemetry.value("memory_leak_suspects_total") == count
        # ...and it lands in a flight dump via the forensics block
        path = tracing.flight.dump(reason="manual")
        doc = json.load(open(path))
        sus = doc["memwatch"]["census"]["suspects"]
        assert any(s["shape"] == [512, 512] for s in sus)
        del leak

    def test_tiny_arrays_below_floor_never_suspects(self, monkeypatch):
        import jax.numpy as jnp
        monkeypatch.setenv("MXNET_MEMWATCH_LEAK_GENERATIONS", "1")
        # 64 f32 = 256 bytes, under MXNET_MEMWATCH_LEAK_MIN_BYTES: RNG
        # keys and loss scalars must churn below the sentinel's radar
        crumb = jnp.zeros((64,), jnp.float32) + 3
        before = telemetry.value("memory_leak_suspects_total") or 0.0
        for _ in range(4):
            snap = memwatch.census()
        assert not any(s["shape"] == [64] for s in snap["suspects"])
        assert (telemetry.value("memory_leak_suspects_total") or 0.0) \
            == before
        del crumb

    def test_tagged_arrays_never_suspects(self):
        a = nd.array(np.zeros((256, 256), np.float32))
        memwatch.tag("io", a)
        for _ in range(5):
            snap = memwatch.census()
        assert not any(s["shape"] == [256, 256] for s in snap["suspects"])

    def test_likely_owner_by_shape_match(self):
        import jax.numpy as jnp
        tagged = nd.array(np.zeros((133, 70), np.float32))
        memwatch.tag("serving", tagged)
        memwatch.census()
        leak = jnp.zeros((133, 70), jnp.float32) + 1
        snap = memwatch.census()
        ours = [s for s in snap["suspects"] if s["shape"] == [133, 70]]
        # age below window -> not in the table yet; age it
        for _ in range(4):
            snap = memwatch.census()
        ours = [s for s in snap["suspects"] if s["shape"] == [133, 70]]
        assert ours and ours[0]["likely_owner"] == "serving"
        del leak


# ---------------------------------------------------------------------------
# OOM pre-flight
# ---------------------------------------------------------------------------
class TestPreflight:
    def _pc(self, name="big_step", arg=6 << 20, out=2 << 20):
        return health.ProgramCost(name, flops=1.0, arg_bytes=arg,
                                  out_bytes=out, temp_bytes=None,
                                  donation_requested=False)

    def test_risk_trips_verdict_and_counter(self, monkeypatch):
        monkeypatch.setattr(storage, "bytes_limit",
                            lambda device=None: 4 << 20)
        v = memwatch.preflight(self._pc())
        assert v["risk"] and v["need_bytes"] == 8 << 20
        assert v["bytes_limit"] == 4 << 20
        assert telemetry.value("memwatch_preflight_risks_total",
                               program="big_step") == 1.0
        assert telemetry.value("step_health_verdict",
                               cause="oom_risk") == 1.0
        assert telemetry.value("health_anomalies_total",
                               cause="oom_risk") == 1.0

    def test_roomy_limit_passes(self, monkeypatch):
        monkeypatch.setattr(storage, "bytes_limit",
                            lambda device=None: 1 << 40)
        v = memwatch.preflight(self._pc())
        assert v is not None and not v["risk"]
        fam = telemetry.registry().get("memwatch_preflight_risks_total")
        assert telemetry.value("memwatch_preflight_risks_total",
                               program="big_step") in (None, 0.0)

    def test_no_limit_known_is_silent(self, monkeypatch):
        monkeypatch.setattr(storage, "bytes_limit", lambda device=None: 0)
        assert memwatch.preflight(self._pc()) is None

    def test_register_program_reaches_preflight(self, monkeypatch):
        """health.register_program hands every program to preflight —
        no caller opts in separately."""
        import jax
        import jax.numpy as jnp
        monkeypatch.setattr(storage, "bytes_limit",
                            lambda device=None: 1)
        health.enable()
        memwatch.census()
        fn = jax.jit(lambda x: x * 2.0)
        x = jnp.zeros((64, 64), jnp.float32)
        health.register_program("preflight_probe", fn, (x,))
        assert telemetry.value("memwatch_preflight_risks_total",
                               program="preflight_probe") == 1.0

    def test_fraction_knob(self, monkeypatch):
        monkeypatch.setattr(storage, "bytes_limit",
                            lambda device=None: 10 << 20)
        monkeypatch.setenv("MXNET_MEMWATCH_PREFLIGHT_FRACTION", "0.5")
        v = memwatch.preflight(self._pc())      # 8 MiB > 0.5 * 10 MiB
        assert v["risk"]


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------
class TestOOMForensics:
    def test_is_oom_classifier(self):
        assert memwatch.is_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes"))
        assert not memwatch.is_oom(ValueError("shape mismatch"))

    def test_forced_resource_exhausted_dumps(self, monkeypatch, tmp_path):
        """A RESOURCE_EXHAUSTED escaping the executor dispatch produces
        one reason=oom flight dump embedding ledger + device stats +
        the last registered program."""
        dump = str(tmp_path / "oom_flight.json")
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH", dump)
        monkeypatch.setenv(fused.ENV_FLAG, "0")
        health.enable()
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd")
        _train(mod, steps=1)                    # registers programs
        ex = mod._exec_group.execs[0]

        def boom(is_train):
            def fn(*a, **k):
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory allocating "
                    "9999999999 bytes")
            return fn

        monkeypatch.setattr(type(ex), "_fwd_fn", lambda self, t: boom(t))
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            mod.forward(_Batch())
        assert telemetry.value("memwatch_oom_total", site="executor") \
            == 1.0
        assert telemetry.value("flight_recorder_dumps_total",
                               reason="oom") == 1.0
        doc = json.load(open(dump))
        mw = doc["memwatch"]
        assert mw["census"]["owners"]["params"]["bytes"] > 0
        assert mw["census"]["devices"]
        assert mw["last_program"] is not None
        assert mw["last_program"]["arg_bytes"] > 0

    def test_nested_catch_sites_dump_once(self, monkeypatch, tmp_path):
        """serving's catch wraps the executor's: the same exception
        object must not double-count or double-dump."""
        monkeypatch.setenv("MXNET_FLIGHT_RECORDER_PATH",
                           str(tmp_path / "f.json"))
        exc = RuntimeError("RESOURCE_EXHAUSTED: oom")
        assert memwatch.on_oom(exc, site="executor") is not None
        assert memwatch.on_oom(exc, site="serving") is None
        assert telemetry.value("memwatch_oom_total", site="executor") \
            == 1.0
        assert telemetry.value("memwatch_oom_total",
                               site="serving") in (None, 0.0)

    def test_donation_audit_cross_check(self, monkeypatch):
        """The fused path's donated buffers: health's donation audit
        sees no leak, and memwatch agrees — the donated generation is
        not lingering as untagged census bytes."""
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        health.enable()
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd", optimizer_params=(
            ("momentum", 0.9),))
        _train(mod)
        leaks = [n for n, pc in health.programs().items()
                 if pc.donation_leak]
        assert leaks == [], "donation audit flagged %s" % leaks
        snap = memwatch.census()
        # every fused-path param/slot generation but the live one was
        # donated away; the live one is tagged, so none of the module's
        # param-shaped buffers sit in the suspects table
        ex = mod._exec_group.execs[0]
        shapes = [list(a._data.shape) for n, a in ex.arg_dict.items()
                  if n not in ("data", "softmax_label")]
        for s in snap["suspects"]:
            assert s["shape"] not in shapes, s


# ---------------------------------------------------------------------------
# serving hot-swap hygiene
# ---------------------------------------------------------------------------
class TestServingHygiene:
    def _server(self, scale=0.5, **kw):
        from mxnet_tpu.serving import ModelServer
        x = S.var("data")
        out = S.FullyConnected(x, num_hidden=4, no_bias=True, name="fc")
        params = {"fc_weight": nd.array(
            np.full((4, 8), scale, np.float32))}
        kw.setdefault("max_batch_size", 4)
        kw.setdefault("batch_timeout_ms", 5)
        srv = ModelServer(out.tojson(), params,
                          example_shapes={"data": (8,)}, **kw)
        return srv, params

    def test_swap_drops_old_generation(self):
        import weakref
        srv, pa = self._server(0.5)
        srv.start()
        try:
            x = np.ones(8, np.float32)
            assert np.all(srv.predict({"data": x})[0] == 4.0)
            old_bytes = memwatch.owner_bytes("serving", detail=srv.name)
            assert old_bytes > 0
            old_refs = []
            for pred in set(srv._predictors.values()):
                for arr in pred._executor.arg_dict.values():
                    if arr is not None:
                        old_refs.append(weakref.ref(arr._data))
            pb = {"fc_weight": nd.array(np.full((4, 8), 1.5, np.float32))}
            srv.swap_params(pb)
            assert np.all(srv.predict({"data": x})[0] == 12.0)
            gc.collect()
            survivors = [r for r in old_refs
                         if r() is not None and not r().is_deleted()]
            # the swapped-in weight repoints every bucket executor; the
            # old generation's weight buffers must be collectable (input
            # placeholders may live on)
            assert len(survivors) < len(old_refs), \
                "no old-generation buffer was released"
            # and the ledger follows: serving bytes reflect the new
            # generation, not old+new
            assert memwatch.owner_bytes("serving", detail=srv.name) \
                <= old_bytes
        finally:
            srv.stop()

    def test_swap_under_load_no_leak_growth(self):
        srv, pa = self._server(0.5)
        pb = {"fc_weight": nd.array(np.full((4, 8), 1.5, np.float32))}
        srv.start()
        try:
            x = np.ones((2, 8), np.float32)
            srv.predict({"data": x})
            gc.collect()
            base = memwatch.owner_bytes("serving", detail=srv.name)
            for i in range(20):
                srv.swap_params([pa, pb][i % 2])
                srv.predict({"data": x})
            gc.collect()
            after = memwatch.owner_bytes("serving", detail=srv.name)
            # 20 swaps must not accrete weight generations: the serving
            # footprint stays within 2x of one generation
            assert after <= 2 * base, (base, after)
        finally:
            srv.stop()

    def test_stats_and_health_carry_memory_block(self):
        srv, _ = self._server()
        srv.start()
        try:
            st = srv.stats()
            assert st["memory"]["enabled"] is True
            assert st["memory"]["serving_bytes"] > 0
            assert srv.health()["memory"]["serving_bytes"] \
                == st["memory"]["serving_bytes"]
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# surfaces: /memz, snapshot, census thread
# ---------------------------------------------------------------------------
class TestSurfaces:
    def test_memz_endpoint(self):
        import urllib.request
        from mxnet_tpu.telemetry import export as texp
        a = nd.array(np.zeros((32, 32), np.float32))
        memwatch.tag("io", a)
        port = texp.start_http_server(0, telemetry.registry())
        try:
            body = urllib.request.urlopen(
                "http://127.0.0.1:%d/memz?refresh=1" % port,
                timeout=10).read()
            doc = json.loads(body)
            assert doc["enabled"] is True
            assert doc["owners"]["io"]["bytes"] >= a._data.nbytes
            assert doc["devices"]
        finally:
            texp.stop_http_server()

    def test_snapshot_caches_until_refresh(self):
        s1 = memwatch.snapshot()
        s2 = memwatch.snapshot()
        assert s2["generation"] == s1["generation"]
        s3 = memwatch.snapshot(refresh=True)
        assert s3["generation"] == s1["generation"] + 1

    def test_census_thread_lifecycle(self, monkeypatch):
        monkeypatch.setenv("MXNET_MEMWATCH_INTERVAL", "0.05")
        memwatch.start()
        assert memwatch.running()
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if memwatch.snapshot().get("generation", 0) >= 2:
                break
            time.sleep(0.05)
        assert memwatch.snapshot()["generation"] >= 2
        memwatch.stop()
        assert not memwatch.running()

    def test_census_prunes_dead_entries(self):
        a = nd.array(np.zeros((8, 8), np.float32))
        memwatch.tag("io", a)
        key = id(a._data)
        del a
        gc.collect()
        memwatch.census()
        assert key not in memwatch._tags
