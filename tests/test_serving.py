"""Inference serving: dynamic batcher, ModelServer, HTTP endpoint.

Covers the batcher's bucket/queue semantics, bit-identical parity between
batched serving and single-request ``Predictor.forward`` (per bucket and
at padded non-bucket sizes), the compile-count contract (one program per
declared bucket, asserted via ``op_jit_cache_misses_total``), deadline
expiry before execution, queue-full rejection, graceful drain, hot-swap
atomicity under concurrent load, the Predictor satellites (device
``set_input``, object-sharing ``reshape``), tracing flow links, and the
HTTP endpoint.  The closed-loop load test runs under the ``slow`` marker.
"""
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import nd, serving, telemetry, tracing
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ndarray.ndarray import NDArray
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import (DeadlineExceededError, DynamicBatcher,
                               ModelServer, QueueFullError, Request,
                               ServerClosedError, ServingError,
                               pow2_buckets)

S = mx.symbol


def _mlp():
    """data (n, 8) -> FC16 relu -> FC5 softmax; fixed random params."""
    x = S.var("data")
    h = S.Activation(S.FullyConnected(x, num_hidden=16, name="fc1"),
                     act_type="relu")
    out = S.softmax(S.FullyConnected(h, num_hidden=5, name="fc2"),
                    axis=1, name="prob")
    rng = np.random.RandomState(7)
    shapes, _, _ = out.infer_shape(data=(1, 8))
    params = {n: nd.array(rng.uniform(-0.5, 0.5, s).astype(np.float32))
              for n, s in zip(out.list_arguments(), shapes) if n != "data"}
    return out, params


def _linear(scale):
    """data (n, 8) -> FC4 no-bias with W = scale * ones: every output
    element equals ``8 * scale`` for an all-ones input row."""
    x = S.var("data")
    out = S.FullyConnected(x, num_hidden=4, no_bias=True, name="fc")
    params = {"fc_weight": nd.array(np.full((4, 8), scale, np.float32))}
    return out, params


def _make_server(**kwargs):
    sym, params = _mlp()
    kwargs.setdefault("max_batch_size", 8)
    kwargs.setdefault("batch_timeout_ms", 20)
    srv = ModelServer(sym.tojson(), params, example_shapes={"data": (8,)},
                      **kwargs)
    return srv, sym, params


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    yield
    serving.stop_http_server()
    telemetry.disable()
    tracing.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# batcher semantics (no model involved)
# ---------------------------------------------------------------------------
class TestBatcher:
    def test_pow2_buckets(self):
        assert pow2_buckets(8) == (1, 2, 4, 8)
        assert pow2_buckets(1) == (1,)
        assert pow2_buckets(6) == (1, 2, 4, 6)

    def test_bucket_for(self):
        b = DynamicBatcher((1, 2, 4, 8), 8, 1.0, 16)
        assert [b.bucket_for(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
        assert b.bucket_for(9) is None

    def test_bucket_max_mismatch_rejected(self):
        with pytest.raises(ServingError, match="max_batch_size"):
            DynamicBatcher((1, 2, 4), 8, 1.0, 16)

    def test_oversized_request_rejected(self):
        b = DynamicBatcher((1, 2), 2, 1.0, 16)
        with pytest.raises(ServingError, match="split"):
            b.put(Request({"data": np.zeros((3, 4))}, rows=3))

    def test_queue_depth_bound(self):
        b = DynamicBatcher((1,), 1, 1.0, 2)
        b.put(Request({}, rows=1))
        b.put(Request({}, rows=1))
        with pytest.raises(QueueFullError):
            b.put(Request({}, rows=1))

    def test_fifo_prefix_respects_max_rows(self):
        b = DynamicBatcher((1, 2, 4), 4, 1.0, 16)
        for rows in (2, 2, 1):
            b.put(Request({}, rows=rows))
        first = b.get_batch()
        assert [r.rows for r in first] == [2, 2]
        second = b.get_batch()
        assert [r.rows for r in second] == [1]

    def test_closed_drains_then_none(self):
        b = DynamicBatcher((1,), 1, 1.0, 16)
        b.put(Request({}, rows=1))
        b.close()
        with pytest.raises(ServerClosedError):
            b.put(Request({}, rows=1))
        assert len(b.get_batch()) == 1
        assert b.get_batch() is None


# ---------------------------------------------------------------------------
# parity: batched == single-request Predictor.forward, bit-identical
# ---------------------------------------------------------------------------
class TestParity:
    @pytest.mark.parametrize("bucket", [1, 2, 4])
    def test_bucket_bit_identical(self, bucket):
        srv, sym, params = _make_server(batch_timeout_ms=60)
        srv.start()
        try:
            rng = np.random.RandomState(bucket)
            X = rng.uniform(-1, 1, (bucket, 8)).astype(np.float32)
            reqs = [srv.submit({"data": X[i]}) for i in range(bucket)]
            got = np.concatenate([r.result(30.0)[0] for r in reqs], axis=0)
        finally:
            srv.stop()
        base = Predictor(sym.tojson(), params,
                         input_shapes={"data": (bucket, 8)})
        want = base.forward(data=X)[0].asnumpy()
        assert np.array_equal(got, want)

    @pytest.mark.parametrize("rows", [3, 5, 7])
    def test_padding_parity_non_bucket_sizes(self, rows):
        """rows not in the bucket set execute padded at the next bucket;
        the unpadded prefix must be bit-identical to an exact-size bind."""
        srv, sym, params = _make_server()
        srv.start()
        try:
            rng = np.random.RandomState(rows)
            X = rng.uniform(-1, 1, (rows, 8)).astype(np.float32)
            got = srv.predict({"data": X})
        finally:
            srv.stop()
        base = Predictor(sym.tojson(), params,
                         input_shapes={"data": (rows, 8)})
        want = base.forward(data=X)[0].asnumpy()
        assert got[0].shape == (rows, 5)
        assert np.array_equal(got[0], want)

    def test_mixed_sizes_compile_once_per_bucket(self):
        """The compile-count contract: warmup compiles exactly one forward
        program per declared bucket; arbitrary mixed-size traffic after
        warmup compiles NOTHING new (op_jit_cache_misses_total is flat)."""
        sym, params = _mlp()
        # baseline predictors run with telemetry OFF so their own (per
        # exact shape) compiles don't pollute the Executor::Forward counter
        sizes = (1, 3, 2, 8, 5, 4, 7, 6, 3, 1)
        rng = np.random.RandomState(3)
        traffic = [rng.uniform(-1, 1, (n, 8)).astype(np.float32)
                   for n in sizes]
        wants = []
        baselines = {}
        for X in traffic:
            n = X.shape[0]
            if n not in baselines:
                baselines[n] = Predictor(sym.tojson(), params,
                                         input_shapes={"data": (n, 8)})
            wants.append(baselines[n].forward(data=X)[0].asnumpy())

        telemetry.enable()
        srv = ModelServer(sym.tojson(), params,
                          example_shapes={"data": (8,)},
                          max_batch_size=8, batch_timeout_ms=20)

        def misses():
            return telemetry.value("op_jit_cache_misses_total",
                                   op="Executor::Forward")

        before = misses()
        srv.start()                       # warmup AOT-compiles all buckets
        assert misses() - before == len(srv.config.batch_buckets)
        after_warmup = misses()
        try:
            for X, want in zip(traffic, wants):
                got = srv.predict({"data": X})
                assert np.array_equal(got[0], want)
        finally:
            srv.stop()
        assert misses() == after_warmup
        assert telemetry.value("serving_padding_rows_total") > 0

    def test_multi_row_requests_coalesce(self):
        """Several multi-row requests batch together and slice apart."""
        srv, sym, params = _make_server(batch_timeout_ms=60)
        srv.start()
        try:
            rng = np.random.RandomState(0)
            X = rng.uniform(-1, 1, (6, 8)).astype(np.float32)
            r1 = srv.submit({"data": X[:2]})
            r2 = srv.submit({"data": X[2:5]})
            r3 = srv.submit({"data": X[5:]})
            got = np.concatenate(
                [r1.result(30.0)[0], r2.result(30.0)[0], r3.result(30.0)[0]],
                axis=0)
        finally:
            srv.stop()
        base = Predictor(sym.tojson(), params, input_shapes={"data": (6, 8)})
        want = base.forward(data=X)[0].asnumpy()
        assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# overload, deadlines, shutdown
# ---------------------------------------------------------------------------
class TestAdmissionAndDeadlines:
    def test_deadline_expired_dropped_before_execution(self):
        telemetry.enable()
        srv, _, _ = _make_server()
        # no worker running yet: the request must age past its deadline
        req = srv.submit({"data": np.zeros(8, np.float32)}, deadline_ms=10)
        time.sleep(0.05)
        srv.start()
        with pytest.raises(DeadlineExceededError):
            req.result(30.0)
        assert req.outcome == "deadline"
        assert telemetry.value("serving_requests_total",
                               outcome="deadline") == 1
        # the server keeps serving fresh traffic afterwards
        out = srv.predict({"data": np.zeros(8, np.float32)})
        assert out[0].shape == (1, 5)
        srv.stop()

    def test_queue_full_rejection(self):
        telemetry.enable()
        srv, _, _ = _make_server(queue_depth=2)
        x = np.zeros(8, np.float32)
        r1 = srv.submit({"data": x})
        r2 = srv.submit({"data": x})
        with pytest.raises(QueueFullError):
            srv.submit({"data": x})
        assert telemetry.value("serving_requests_total",
                               outcome="rejected") == 1
        srv.start()          # the two admitted requests still complete
        assert r1.result(30.0)[0].shape == (1, 5)
        assert r2.result(30.0)[0].shape == (1, 5)
        srv.stop()

    def test_graceful_drain(self):
        srv, _, _ = _make_server(batch_timeout_ms=200)
        srv.start()
        x = np.zeros(8, np.float32)
        reqs = [srv.submit({"data": x}) for _ in range(5)]
        srv.stop(drain=True)          # closes admission, executes the queue
        for r in reqs:
            assert r.result(5.0)[0].shape == (1, 5)
            assert r.outcome == "ok"
        with pytest.raises(ServerClosedError):
            srv.submit({"data": x})

    def test_stop_without_drain_fails_queued(self):
        srv, _, _ = _make_server()
        x = np.zeros(8, np.float32)
        reqs = [srv.submit({"data": x}) for _ in range(3)]
        srv.stop(drain=False)
        for r in reqs:
            with pytest.raises(ServerClosedError):
                r.result(5.0)

    def test_malformed_inputs_rejected(self):
        srv, _, _ = _make_server()
        with pytest.raises(ServingError, match="do not match"):
            srv.submit({"wrong": np.zeros(8, np.float32)})
        with pytest.raises(ServingError, match="shape"):
            srv.submit({"data": np.zeros((2, 9), np.float32)})
        with pytest.raises(ServingError, match="split"):
            srv.submit({"data": np.zeros((9, 8), np.float32)})


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------
class TestHotSwap:
    def test_swap_changes_outputs(self):
        sym, pa = _linear(0.5)
        _, pb = _linear(1.5)
        srv = ModelServer(sym.tojson(), pa, example_shapes={"data": (8,)},
                          max_batch_size=4, batch_timeout_ms=5)
        srv.start()
        try:
            x = np.ones(8, np.float32)
            assert np.all(srv.predict({"data": x})[0] == 4.0)
            srv.swap_params(pb)
            assert np.all(srv.predict({"data": x})[0] == 12.0)
        finally:
            srv.stop()

    @pytest.mark.parametrize("prefix", [False, True])
    def test_swap_accepts_checkpoint_prefixes(self, prefix):
        sym, pb = _linear(1.5)
        _, pa = _linear(0.5)
        srv = ModelServer(sym.tojson(), pa, example_shapes={"data": (8,)},
                          max_batch_size=2, batch_timeout_ms=5)
        srv.start()
        try:
            blob = {("arg:" + k if prefix else k): v for k, v in pb.items()}
            srv.swap_params(blob)
            assert np.all(srv.predict({"data": np.ones(8, np.float32)})[0]
                          == 12.0)
        finally:
            srv.stop()

    def test_swap_atomic_under_concurrent_load(self):
        """Requests racing a swap see EXACTLY one weight set: every
        response is uniformly old or uniformly new, never a mix."""
        telemetry.enable()
        sym, pa = _linear(0.5)
        _, pb = _linear(1.5)
        srv = ModelServer(sym.tojson(), pa, example_shapes={"data": (8,)},
                          max_batch_size=4, batch_timeout_ms=1)
        srv.start()
        x = np.ones((2, 8), np.float32)     # 2-row requests
        bad, done = [], threading.Event()

        def client():
            while not done.is_set():
                out = srv.predict({"data": x}, timeout=30.0)[0]
                vals = set(np.unique(out).tolist())
                if vals not in ({4.0}, {12.0}):
                    bad.append(vals)
                    return

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        params = [pa, pb]
        for i in range(40):
            srv.swap_params(params[i % 2])
            time.sleep(0.002)
        done.set()
        for t in threads:
            t.join(30.0)
        srv.stop()
        assert not bad, "mixed-weight responses observed: %s" % bad
        assert telemetry.value("serving_hot_swaps_total") == 40


# ---------------------------------------------------------------------------
# predictor satellites
# ---------------------------------------------------------------------------
class TestPredictorSatellites:
    def test_set_input_device_array_no_host_bounce(self, monkeypatch):
        sym, params = _mlp()
        pred = Predictor(sym.tojson(), params, input_shapes={"data": (2, 8)})
        X = nd.array(np.random.RandomState(0)
                     .uniform(-1, 1, (2, 8)).astype(np.float32))
        want = pred.forward(data=X.asnumpy())[0].asnumpy()

        def _boom(self):
            raise AssertionError("set_input bounced a device array "
                                 "through the host")

        monkeypatch.setattr(NDArray, "asnumpy", _boom)
        pred.set_input("data", X)
        # same-dtype device input is adopted without ANY copy
        assert pred._executor.arg_dict["data"]._data is X._data
        monkeypatch.undo()
        got = pred.forward()[0].asnumpy()
        assert np.array_equal(got, want)

    def test_set_input_device_shape_mismatch(self):
        sym, params = _mlp()
        pred = Predictor(sym.tojson(), params, input_shapes={"data": (2, 8)})
        with pytest.raises(MXNetError, match="bound shape"):
            pred.set_input("data", nd.array(np.zeros((3, 8), np.float32)))

    def test_reshape_shares_symbol_and_params(self):
        sym, params = _mlp()
        pred = Predictor(sym.tojson(), params, input_shapes={"data": (4, 8)})
        re = pred.reshape({"data": (2, 8)})
        assert re._symbol is pred._symbol
        assert re._arg_params is pred._arg_params
        assert re._aux_params is pred._aux_params
        X = np.random.RandomState(1).uniform(-1, 1, (2, 8)) \
            .astype(np.float32)
        want = Predictor(sym.tojson(), params,
                         input_shapes={"data": (2, 8)}) \
            .forward(data=X)[0].asnumpy()
        assert np.array_equal(re.forward(data=X)[0].asnumpy(), want)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
class TestObservability:
    def test_serving_metrics_populate(self):
        telemetry.enable()
        srv, _, _ = _make_server()
        srv.start()
        try:
            srv.predict({"data": np.zeros((3, 8), np.float32)})
        finally:
            srv.stop()
        snap = telemetry.snapshot()
        assert telemetry.value("serving_requests_total", outcome="ok") == 1
        assert telemetry.value("serving_batch_rows") == 1      # 1 batch
        assert telemetry.value("serving_padding_rows_total") == 1  # 3 -> 4
        for name in ("serving_queue_wait_seconds", "serving_execute_seconds",
                     "serving_request_seconds"):
            assert snap[name]["samples"][0]["count"] >= 1, name
        assert "serving_queue_depth" in snap

    def test_request_flow_links_into_batch_span(self):
        from mxnet_tpu import profiler
        tracing.enable()
        profiler.set_state("run")
        try:
            srv, _, _ = _make_server()
            srv.start()
            srv.predict({"data": np.zeros(8, np.float32)})
            srv.stop()
            with profiler._lock:
                ev = list(profiler._events)
        finally:
            profiler.set_state("stop")
            with profiler._lock:
                profiler._events.clear()
        submits = [e for e in ev if e.get("name") == "Serving::Submit"]
        execs = [e for e in ev if e.get("name") == "Serving::ExecuteBatch"]
        assert submits and execs
        assert execs[-1]["args"]["bucket"] == 1
        starts = {e["id"] for e in ev
                  if e.get("name") == "serving_flow" and e["ph"] == "s"}
        ends = {e["id"] for e in ev
                if e.get("name") == "serving_flow" and e["ph"] == "f"}
        # every emitted flow-start has its matching end on the batch span
        assert starts and starts == ends
        assert submits[-1]["args"]["span_id"] in starts


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------
class TestHTTP:
    def _post(self, port, doc, path="/predict"):
        body = json.dumps(doc).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:%d%s" % (port, path), data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_http_predict_and_health(self):
        srv, sym, params = _make_server()
        srv.start()
        port = serving.start_http_server(srv, port=0)
        try:
            X = np.random.RandomState(2).uniform(-1, 1, (2, 8)) \
                .astype(np.float32)
            status, doc = self._post(port, {"inputs": {"data": X.tolist()}})
            assert status == 200 and doc["rows"] == 2
            base = Predictor(sym.tojson(), params,
                             input_shapes={"data": (2, 8)})
            want = base.forward(data=X)[0].asnumpy()
            assert np.array_equal(
                np.asarray(doc["outputs"][0], np.float32), want)

            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/healthz" % port, timeout=30) as r:
                health = json.loads(r.read())
            assert health["status"] == "serving"
            assert health["buckets"] == [1, 2, 4, 8]

            status, doc = self._post(port, {"nope": 1})
            assert status == 400 and "error" in doc
            status, doc = self._post(
                port, {"inputs": {"data": [[0.0] * 9] * 2}})
            assert status == 400 and "error" in doc
        finally:
            serving.stop_http_server()
            srv.stop()

    def test_http_overload_maps_to_503(self):
        srv, _, _ = _make_server(queue_depth=1)   # tiny queue, no workers
        srv.submit({"data": np.zeros(8, np.float32)})   # fills the queue
        port = serving.start_http_server(srv, port=0)
        try:
            status, doc = self._post(
                port, {"inputs": {"data": [0.0] * 8}})
            assert status == 503 and doc["outcome"] == "rejected"
        finally:
            serving.stop_http_server()
            srv.stop(drain=False)


# ---------------------------------------------------------------------------
# load test (tier-2)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_closed_loop_load():
    """8 closed-loop clients, mixed request sizes, 400 requests total:
    everything completes ok, outputs match the serial predictor, and the
    batcher actually coalesces (mean realized batch rows > 1).

    Tolerance note: under concurrent coalescing a request's rows execute
    at whatever bucket the realized batch landed in, and XLA CPU picks a
    different matmul strategy per batch shape — the same row through the
    batch-8 program vs the batch-1 program differs by ~1 ulp of the
    softmax output.  The deterministic parity tests above pin strict
    bit-identity per bucket; here we allow that 1-ulp cross-program
    wobble."""
    telemetry.enable()
    srv, sym, params = _make_server(batch_timeout_ms=2, queue_depth=512)
    srv.start()
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
    baselines = {
        n: Predictor(sym.tojson(), params, input_shapes={"data": (n, 8)})
        for n in (1, 2, 3)}
    wants = {n: p.forward(data=X[:n])[0].asnumpy()
             for n, p in baselines.items()}
    errors = []

    def client(seed):
        r = np.random.RandomState(seed)
        for _ in range(50):
            n = int(r.choice([1, 2, 3]))
            try:
                out = srv.predict({"data": X[:n]}, timeout=60.0)
                if not np.allclose(out[0], wants[n], rtol=0, atol=1e-6):
                    errors.append("mismatch at rows=%d" % n)
                    return
            except ServingError as e:
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)
    elapsed = time.monotonic() - t0
    srv.stop()
    assert not errors, errors[:3]
    assert telemetry.value("serving_requests_total", outcome="ok") == 400
    hist = telemetry.registry().get("serving_batch_rows").get()
    assert hist["count"] > 0
    assert hist["sum"] / hist["count"] > 1.0, "no batching happened"
    assert elapsed < 120.0
