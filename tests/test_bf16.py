"""bf16 mixed-precision training (MXNET_TPU_BF16).

Contract: with the flag on, params/activations/grads are stored bf16 and
every trained weight carries a master-fp32 leaf PREPENDED to its fused
opt-state tuple.  The fused program's fp32 master trajectory must be
BIT-IDENTICAL to the eager ``update_multi_precision`` oracle (same
kernels, grad up-cast, and host-side lr folding) for every fused
optimizer; the module-level fused step must track the eager bf16 loop
within bf16 tolerance on one device and on the mesh path.  Plus the
mechanics: mixed-dtype donation genuinely frees old buffers, the env
flag is part of the jit-cache key, astype/copyto never alias across a
dtype change, and ``create_state_multi_precision`` recognizes both fp16
and bf16.
"""
import os

import numpy as np
import pytest
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import amp
from mxnet_tpu import optimizer as opt
from mxnet_tpu import fused_step as fused
from mxnet_tpu.executor import build_update_program


BF16 = amp.compute_dtype()

OPT_CONFIGS = [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.05}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-4}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
]
OPT_IDS = [c[0] + ("_c" if c[1].get("centered")
                   else ("_m" if c[1].get("momentum") else ""))
           for c in OPT_CONFIGS]


def _bf16_weight(shape, seed):
    rs = np.random.RandomState(seed)
    return mx.nd.array(rs.randn(*shape).astype(np.float32)).astype(BF16)


def _grad_stream(shape, n, seed=7):
    rs = np.random.RandomState(seed)
    return [mx.nd.array(rs.randn(*shape).astype(np.float32)).astype(BF16)
            for _ in range(n)]


class TestCreateStateMultiPrecision:
    @pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
    def test_low_precision_gets_master(self, dtype):
        o = opt.Adam(multi_precision=True)
        w = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)) \
              .astype(np.dtype(dtype))
        state = o.create_state_multi_precision(0, w)
        assert isinstance(state, tuple) and len(state) == 2
        inner, w32 = state
        assert w32.dtype == np.float32
        np.testing.assert_array_equal(w32.asnumpy(),
                                      w.asnumpy().astype(np.float32))
        mean, var = inner
        assert mean.dtype == np.float32 and var.dtype == np.float32

    @pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
    def test_sgd_low_precision(self, dtype):
        o = opt.SGD(momentum=0.9, multi_precision=True)
        w = mx.nd.ones((3,)).astype(np.dtype(dtype))
        inner, w32 = o.create_state_multi_precision(0, w)
        assert w32.dtype == np.float32 and inner.dtype == np.float32

    def test_fp32_weight_keeps_plain_state(self):
        o = opt.Adam(multi_precision=True)
        w = mx.nd.ones((3,))
        state = o.create_state_multi_precision(0, w)
        # no master for an already-fp32 weight
        assert isinstance(state, tuple) and len(state) == 2
        assert all(isinstance(s, mx.nd.NDArray) for s in state)

    def test_fused_state_leaves_mp_layouts(self):
        # SGD's mp state is flat (mom, w32); Adam's is nested
        # ((mean, var), w32) — both flatten with the master FIRST
        sgd = opt.SGD(momentum=0.9, multi_precision=True)
        adam = opt.Adam(multi_precision=True)
        w = mx.nd.ones((3,)).astype(BF16)
        st_s = sgd.create_state_multi_precision(0, w)
        st_a = adam.create_state_multi_precision(0, w)
        ls = opt.fused_state_leaves(st_s, mp=True)
        la = opt.fused_state_leaves(st_a, mp=True)
        assert len(ls) == 2 and ls[0] is st_s[1] and ls[1] is st_s[0]
        assert len(la) == 3 and la[0] is st_a[1]
        assert la[1] is st_a[0][0] and la[2] is st_a[0][1]


class TestOracleBitIdentity:
    """The fused update program's fp32 master must match the eager
    multi-precision oracle bit-for-bit over a long trajectory."""

    @pytest.mark.parametrize("name,kwargs", OPT_CONFIGS, ids=OPT_IDS)
    def test_master_trajectory(self, name, kwargs, steps=50):
        shape = (4, 5)
        grads = _grad_stream(shape, steps)

        # eager oracle
        opt_e = opt.create(name, multi_precision=True, **kwargs)
        w_e = _bf16_weight(shape, 3)
        st_e = opt_e.create_state_multi_precision(0, w_e)
        for g in grads:
            opt_e.update_multi_precision(0, w_e, g, st_e)

        # fused mp program (donated, like the module step)
        opt_f = opt.create(name, multi_precision=True, **kwargs)
        assert opt_f.supports_fused(_bf16_weight(shape, 3))
        w_f = _bf16_weight(shape, 3)
        st_f = opt_f.create_state_multi_precision(0, w_f)
        leaves = opt.fused_state_leaves(st_f, mp=True)
        assert leaves is not None
        assert len(leaves) == opt_f.fused_state_arity() + 1
        fn = build_update_program([opt_f.fused_update_mp])
        for g in grads:
            opt_f._update_count(0)
            t = opt_f._index_update_count[0]
            lr = opt_f.fused_slot_lr(opt_f._get_lr(0), t)
            new_p, new_s = fn(
                [w_f._data], [tuple(l._data for l in leaves)], [[g._data]],
                jnp.asarray([lr], jnp.float32),
                jnp.asarray([opt_f._get_wd(0)], jnp.float32),
                jnp.asarray([t], jnp.float32),
                jnp.asarray(opt_f.rescale_grad, jnp.float32))
            w_f._data = new_p[0]
            for leaf, arr in zip(leaves, new_s[0]):
                leaf._data = arr

        master_e = opt.fused_state_leaves(st_e, mp=True)[0]
        np.testing.assert_array_equal(leaves[0].asnumpy(), master_e.asnumpy())
        np.testing.assert_array_equal(w_f.asnumpy(), w_e.asnumpy())
        # inner leaves (moments) are part of the oracle contract too
        for j, (lf, le) in enumerate(zip(
                leaves[1:], opt.fused_state_leaves(st_e, mp=True)[1:])):
            np.testing.assert_array_equal(lf.asnumpy(), le.asnumpy(),
                                          err_msg="state leaf %d" % j)

    def test_mixed_dtype_donation_frees_old_buffers(self):
        o = opt.Adam(multi_precision=True)
        w = _bf16_weight((4, 5), 3)
        st = o.create_state_multi_precision(0, w)
        leaves = opt.fused_state_leaves(st, mp=True)
        fn = build_update_program([o.fused_update_mp])
        g = _grad_stream((4, 5), 1)[0]

        def step(wv, sv):
            return fn([wv], [sv], [[g._data]],
                      jnp.asarray([0.01], jnp.float32),
                      jnp.asarray([0.0], jnp.float32),
                      jnp.asarray([1.0], jnp.float32),
                      jnp.asarray(1.0, jnp.float32))

        # first call consumes host-committed arrays; the donation proof is
        # on the second call, whose inputs are device outputs of the first
        new_p, new_s = step(w._data, tuple(l._data for l in leaves))
        old_w, old_leaves = new_p[0], list(new_s[0])
        new_p, new_s = step(old_w, tuple(old_leaves))
        # the f32 master and every moment are genuinely consumed by XLA
        for buf in old_leaves:
            assert buf.is_deleted()
        # the bf16 weight only contributes its DTYPE to a pure update
        # program (the new weight is re-cast from the master), so XLA
        # cannot alias it here — it must still be readable, not corrupt
        assert not old_w.is_deleted()
        assert new_p[0].dtype == BF16
        assert new_s[0][0].dtype == jnp.float32

    def test_module_step_donates_mixed_dtype_state(self, monkeypatch):
        # full proof through the fused whole-step program, where the bf16
        # weight IS a used input (forward) and genuinely donated
        monkeypatch.setenv(amp.ENV_FLAG, "1")
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        mod = _build_module()
        mod.init_optimizer(optimizer="adam",
                           optimizer_params={"learning_rate": 0.01,
                                             "multi_precision": True})
        mod.forward_backward(_batch(0))
        mod.update()
        ex = mod._exec_group.execs[0]
        old_w = ex.arg_dict["fc1_weight"]._data
        assert old_w.dtype == BF16
        slot = mod._param_names.index("fc1_weight")
        old_leaves = [l._data for l in opt.fused_state_leaves(
            mod._updater.states[slot], mp=True)]
        assert old_leaves[0].dtype == jnp.float32
        mod.forward_backward(_batch(1))
        mod.update()
        assert old_w.is_deleted()
        for buf in old_leaves:
            assert buf.is_deleted()


# ---- module-level -------------------------------------------------------

def _build_module(ctxs=None, batch=8):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    out = mx.sym.SoftmaxOutput(fc2, label, name="softmax")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",),
                        context=ctxs or [mx.cpu()])
    mod.bind(data_shapes=[("data", (batch, 10))],
             label_shapes=[("softmax_label", (batch,))])
    mx.random.seed(42)
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    return mod


class _Batch:
    def __init__(self, x, y):
        self.data = [mx.nd.array(x)]
        self.label = [mx.nd.array(y)]


def _batch(i, batch=8):
    rs = np.random.RandomState(100 + i)
    return _Batch(rs.randn(batch, 10).astype(np.float32),
                  rs.randint(0, 4, (batch,)).astype(np.float32))


def _run_bf16(monkeypatch, fused_flag, opt_name, opt_kwargs, steps=4,
              ctxs=None, mesh=None):
    monkeypatch.setenv(amp.ENV_FLAG, "1")
    monkeypatch.setenv(fused.ENV_FLAG, fused_flag)
    if mesh is not None:
        monkeypatch.setenv(fused.MESH_ENV_FLAG, mesh)
    mod = _build_module(ctxs=ctxs)
    ex0 = mod._exec_group.execs[0]
    assert ex0.arg_dict["fc1_weight"].dtype == BF16
    assert ex0.arg_dict["softmax_label"].dtype == np.float32
    mod.init_optimizer(optimizer=opt_name,
                       optimizer_params=dict(opt_kwargs,
                                             multi_precision=True))
    for i in range(steps):
        mod.forward_backward(_batch(i))
        mod.update()
    args, _ = mod.get_params()
    masters = {}
    if mod._updater is not None:
        for slot, st in mod._updater.states.items():
            leaves = opt.fused_state_leaves(st, mp=True)
            if leaves:
                masters[slot] = leaves[0].asnumpy()
    return args, masters


class TestModuleParity:
    @pytest.mark.parametrize("name,kwargs",
                             [("sgd", {"learning_rate": 0.05,
                                       "momentum": 0.9, "wd": 1e-4}),
                              ("adam", {"learning_rate": 0.01})])
    def test_fused_vs_eager_bf16(self, monkeypatch, name, kwargs):
        f_args, f_masters = _run_bf16(monkeypatch, "1", name, kwargs)
        e_args, e_masters = _run_bf16(monkeypatch, "0", name, kwargs)
        assert sorted(f_args) == sorted(e_args)
        for k in e_args:
            np.testing.assert_allclose(
                f_args[k].asnumpy().astype(np.float32),
                e_args[k].asnumpy().astype(np.float32),
                rtol=3e-2, atol=3e-3, err_msg=k)
        assert sorted(f_masters) == sorted(e_masters)
        for slot in e_masters:
            np.testing.assert_allclose(f_masters[slot], e_masters[slot],
                                       rtol=3e-2, atol=3e-3)

    def test_mesh_step_bf16(self, monkeypatch):
        ctxs = [mx.cpu(0), mx.cpu(1)]
        kwargs = {"learning_rate": 0.05, "momentum": 0.9}
        f = _run_bf16(monkeypatch, "1", "sgd", kwargs, ctxs=ctxs, mesh="1")
        e = _run_bf16(monkeypatch, "1", "sgd", kwargs, ctxs=ctxs, mesh="0")
        for k in e[0]:
            np.testing.assert_allclose(
                f[0][k].asnumpy().astype(np.float32),
                e[0][k].asnumpy().astype(np.float32),
                rtol=3e-2, atol=3e-3, err_msg=k)

    def test_loss_head_output_is_fp32(self, monkeypatch):
        monkeypatch.setenv(amp.ENV_FLAG, "1")
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05,
                                             "multi_precision": True})
        mod.forward_backward(_batch(0))
        mod.update()
        out = mod.get_outputs()[0]
        assert out.dtype == np.float32
        p = out.asnumpy()
        np.testing.assert_allclose(p.sum(axis=-1), 1.0, rtol=1e-5)


class TestCacheKey:
    def test_env_flip_recompiles(self, monkeypatch):
        # fp32 module — the dtypes don't change, but the flag selects the
        # update_fns closure, so it MUST be part of the step-program key
        monkeypatch.setenv(fused.ENV_FLAG, "1")
        monkeypatch.delenv(amp.ENV_FLAG, raising=False)
        mod = _build_module()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05})
        mod.forward_backward(_batch(0))
        mod.update()
        ex = mod._exec_group.execs[0]
        keys0 = {k for k in ex._jitted if k[0] == "step"}
        assert len(keys0) == 1
        monkeypatch.setenv(amp.ENV_FLAG, "1")
        mod.forward_backward(_batch(1))
        mod.update()
        keys1 = {k for k in ex._jitted if k[0] == "step"}
        assert len(keys1) == 2, "flipping %s must recompile" % amp.ENV_FLAG

    def test_env_key_declared(self):
        from mxnet_tpu.executor import Executor
        assert amp.ENV_FLAG in Executor.STEP_ENV_KEYS


class TestAliasSafety:
    """bf16→fp32→bf16 round-trips must be genuine copies: donating or
    mutating one side never corrupts the other (PR 4 hazard, second
    dtype)."""

    def test_astype_round_trip_no_alias(self):
        a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)) \
              .astype(BF16)
        b = a.astype(np.float32)
        c = b.astype(BF16)
        ref_b, ref_c = b.asnumpy().copy(), c.asnumpy().copy()
        a[:] = 0.0
        np.testing.assert_array_equal(b.asnumpy(), ref_b)
        np.testing.assert_array_equal(c.asnumpy(), ref_c)
        b[:] = -1.0
        np.testing.assert_array_equal(c.asnumpy(), ref_c)
        assert a.asnumpy().max() == 0.0

    def test_copyto_cross_dtype_no_alias(self):
        a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)) \
              .astype(BF16)
        m = mx.nd.zeros((2, 3), dtype=np.float32)
        a.copyto(m)
        np.testing.assert_array_equal(m.asnumpy(),
                                      a.asnumpy().astype(np.float32))
        a[:] = 9.0
        assert m.asnumpy().max() == 5.0

    def test_master_survives_weight_donation(self):
        # the master built by astype must stay alive when the bf16 weight
        # buffer is donated into an update program
        o = opt.SGD(learning_rate=0.1, multi_precision=True)
        w = _bf16_weight((3, 3), 11)
        master = w.astype(np.float32)
        ref = master.asnumpy().copy()
        st = o.create_state_multi_precision(0, w)
        leaves = opt.fused_state_leaves(st, mp=True)
        fn = build_update_program([o.fused_update_mp])
        g = _grad_stream((3, 3), 1)[0]
        new_p, new_s = fn(
            [w._data], [tuple(l._data for l in leaves)], [[g._data]],
            jnp.asarray([0.1], jnp.float32), jnp.asarray([0.0], jnp.float32),
            jnp.asarray([1.0], jnp.float32), jnp.asarray(1.0, jnp.float32))
        assert not master._data.is_deleted()
        np.testing.assert_array_equal(master.asnumpy(), ref)


class TestServing:
    def test_predictor_accepts_bf16_params(self):
        from mxnet_tpu.predictor import Predictor
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
        out = mx.sym.softmax(fc)
        rs = np.random.RandomState(0)
        # integer-valued weights are exact in bf16 → outputs must equal
        # the fp32 reference bit-for-bit after promotion
        wv = rs.randint(-3, 4, (4, 6)).astype(np.float32)
        bv = rs.randint(-3, 4, (4,)).astype(np.float32)
        x = rs.randint(-2, 3, (2, 6)).astype(np.float32)
        p32 = Predictor(out.tojson(),
                        {"fc_weight": mx.nd.array(wv),
                         "fc_bias": mx.nd.array(bv)},
                        input_shapes={"data": (2, 6)})
        p32.forward(data=x)
        ref = p32.get_output(0).asnumpy()
        p16 = Predictor(out.tojson(),
                        {"fc_weight": mx.nd.array(wv).astype(BF16),
                         "fc_bias": mx.nd.array(bv).astype(BF16)},
                        input_shapes={"data": (2, 6)})
        p16.forward(data=x)
        got = p16.get_output(0).asnumpy()
        np.testing.assert_array_equal(got.astype(np.float32),
                                      ref.astype(np.float32))

    def test_hot_swap_bf16_no_recompile(self):
        from mxnet_tpu.predictor import Predictor
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
        out = mx.sym.softmax(fc)
        rs = np.random.RandomState(1)
        params = {"fc_weight": mx.nd.array(
                      rs.randn(4, 6).astype(np.float32)).astype(BF16),
                  "fc_bias": mx.nd.zeros((4,)).astype(BF16)}
        p = Predictor(out.tojson(), params, input_shapes={"data": (2, 6)})
        x = rs.randn(2, 6).astype(np.float32)
        p.forward(data=x)
        ex = p._executor
        before = {k for k in ex._jitted if k[0] == "fwd"}
        assert before
        # hot-swap f32 source values into the bf16-bound executor: the
        # copy casts at the boundary, dtypes (and so the program) persist
        p.copy_params_from({"fc_weight": mx.nd.array(
                                rs.randn(4, 6).astype(np.float32)),
                            "fc_bias": mx.nd.ones((4,))})
        p.forward(data=x)
        after = {k for k in ex._jitted if k[0] == "fwd"}
        assert before == after, "bf16 hot-swap must not recompile"
        assert ex.arg_dict["fc_weight"].dtype == BF16
